"""Copy-on-write prefix caching over the paged KV pool.

Under multi-user traffic most requests share a prefix — a system
prompt, a few-shot header, a conversation so far — and the engine used
to recompute that prefill for every arrival.  The paged pool already
stores KV page-granularly and the unified step already consumes an
arbitrary per-request page table, so cached pages can enter a new
request's table with ZERO kernel changes; this module adds the index
that makes the reuse safe.

**Chained page hashing** (vLLM/SGLang style).  A full page of KV at
page index ``i`` is determined by exactly ``tokens[0 : (i+1)*page_size]``
(causality: position ``j``'s K/V depends only on tokens ``<= j``).  The
index therefore keys each cached page by ``(parent_entry_id,
page_tokens)`` — the parent link chains the whole prefix into the key,
so equal keys imply equal full token prefixes (Python's tuple hash does
the chaining; the match is exact, never probabilistic).  Lookups walk
the chain page by page and stop at the first divergence: the longest
cached page-aligned prefix.

**Copy-on-write rules.**  Cached pages are READ-ONLY.  A request that
attaches a cached prefix starts its KV cursor (``pos``) at the cached
boundary, so its per-token KV write plan only ever targets freshly
allocated pages — the first partial or divergent page is always a new
allocation, never a shared one.  The pool tracks a refcount per cached
page (``1 +`` live sharers); the ``cow-page-write`` analysis rule
audits the engine's write-plan tap and fails CI if any live row writes
a cached page at all — refcount 1 (no sharers) is still read-only,
because the index serves the page to future lookups.

**Lookup cap.**  A request's match is capped at
``(len(tokens) - 1) // page_size`` pages: at least one token always
remains uncached, because the engine must still run the final prompt
position through the model to sample the first new token.  Caching is
page-aligned-only on purpose — a partial-page hit would need the tail
of the page recomputed into a *different* physical page, and stitching
two half-pages is exactly the kind of layout change that breaks the
bit-for-bit contract.  Full-page reuse reads identical page contents
through the identical kernel, so temperature-0 outputs are unchanged.

**Insertion** happens when a request FINISHES: every fully-written page
(``(i+1)*page_size <= pos``, generated tokens included — they extend
the token prefix like any other) moves from the request's ownership
into the index at refcount 0; pages whose content is already cached
are freed as duplicates; the partial tail page is freed.

**Eviction** is LRU over refcount-0 entries, leaves first.  Any
request sharing a child page also shares its parents, so
``refcount(parent) >= refcount(child)`` — a refcount-0 entry's whole
subtree is refcount-0 and leaf-first order can always reach it.  The
pool calls :meth:`evict` through its reclaim hook when the free list
runs dry, so cache reclamation happens BEFORE the scheduler falls back
to recompute preemption; a page is removed from the index before it
re-enters the free list, so the index never references a writable page.
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .kv_pool import PagedKVPool

ROOT = -1                       # parent id of a first-page entry

#: seed of the content-chained digest hashes (the "hash of the empty
#: prefix") — any fixed 64-bit value works; sharing it between
#: :func:`chain_hash` producers and consumers is what matters
ROOT_HASH = 0x9E3779B97F4A7C15


def chain_hash(parent_hash: int, page_tokens: Sequence[int]) -> int:
    """Content-chained 64-bit page hash: ``H(parent_hash, tokens)``.

    The in-process index chains by ``(parent_eid, tokens)`` tuple keys —
    exact, but entry ids are private to one cache.  The CLUSTER router
    needs a prefix key that two *different* replicas compute
    identically from token content alone, so the exported digest chains
    by hash instead: equal chain hashes imply equal full token prefixes
    up to 64-bit collision odds (~2^-32 across millions of pages —
    fine for *placement*, which is a heuristic; correctness still rides
    the exact in-replica index at admission time)."""
    h = hashlib.blake2b(digest_size=8)
    h.update(int(parent_hash).to_bytes(8, "little", signed=False))
    h.update(np.asarray(list(page_tokens), np.int64).tobytes())
    return int.from_bytes(h.digest(), "little")


def token_chain_hashes(tokens: Sequence[int], page_size: int,
                       max_pages: Optional[int] = None,
                       layout: Sequence[int] = ()) -> List[int]:
    """The chain hashes of every FULL page prefix of ``tokens`` (at most
    ``max_pages``; default caps at ``(len - 1) // page_size`` exactly
    like :meth:`PrefixCache.match` — the final prompt token must always
    run).  ``result[i]`` keys the prefix ``tokens[:(i+1)*page_size]``;
    the router probes replica digests with these.

    ``layout`` salts the chain ROOT (``PagedKVPool.layout_tag``): the
    hashes stay a pure function of token content WITHIN a layout, but a
    latent-KV replica and a full-head replica (or two different page
    layouts generally) can never cross-match — their cached page BYTES
    are incompatible even when the token prefixes agree.  Empty layout
    keeps the raw unsalted chain."""
    ps = int(page_size)
    n = max(0, len(tokens) - 1) // ps
    if max_pages is not None:
        n = min(n, int(max_pages))
    out: List[int] = []
    h = chain_hash(ROOT_HASH, layout) if len(layout) else ROOT_HASH
    for i in range(n):
        h = chain_hash(h, tokens[i * ps:(i + 1) * ps])
        out.append(h)
    return out


@dataclass
class CacheEntry:
    """One cached read-only page: a node in the prefix tree."""
    eid: int                    # unique entry id (the chain link)
    parent: int                 # parent entry id, ROOT for page 0
    tokens: Tuple[int, ...]     # this page's token content
    page: int                   # physical page in the pool
    depth: int                  # page index within its prefix
    last_use: int = 0           # LRU clock (monotonic ticks)
    refs: int = 0               # live requests sharing this page
    children: int = 0           # child entries extending this prefix


class PrefixCache:
    """Refcounted index of read-only cached pages in a PagedKVPool."""

    def __init__(self, pool: PagedKVPool):
        self.pool = pool
        self.page_size = pool.page_size
        self._index: Dict[Tuple[int, Tuple[int, ...]], CacheEntry] = {}
        self._by_id: Dict[int, CacheEntry] = {}
        # req_id -> the entries it holds references on
        self._attached: Dict[int, List[CacheEntry]] = {}
        self._next_id = 0
        self._tick = 0
        # host-tier hook (serving/slo/host_tier.py): called with
        # (entry, chain_hash) just BEFORE an evicted page returns to
        # the free list — the page is still cached (read-only) at that
        # moment, so the hook can stage its bytes to host RAM.  Leaf-
        # first eviction guarantees the entry's parent chain is still
        # indexed when the hook runs, which is what makes the chain
        # hash computable at all.
        self.on_evict = None

    # -- introspection -------------------------------------------------------

    def __len__(self) -> int:
        return len(self._index)

    @property
    def evictable_pages(self) -> int:
        """Pages an eviction sweep could reclaim right now.  Exactly the
        refcount-0 entries: a refcount-0 entry's subtree is refcount-0
        too (sharers of a child share its parents), so leaf-first
        eviction reaches every one of them."""
        return sum(1 for e in self._index.values() if e.refs == 0)

    @property
    def version(self) -> Tuple[int, int]:
        """Cheap change stamp for digest memoization: ``_next_id``
        moves on every insertion and the index size on every eviction,
        so any mutation sequence changes the pair (a dedup'd re-insert
        creates no entry and correctly leaves the digest unchanged)."""
        return (self._next_id, len(self._index))

    def digest(self) -> Dict[int, int]:
        """Compact content-chained snapshot of the cached prefix tree:
        ``{chain_hash: depth + 1}`` — one 64-bit key per cached page,
        position-stamped so a router can read "this replica holds the
        first ``depth+1`` pages of any prompt whose page-``depth`` chain
        hash is ``chain_hash``".  Entries are computed parents-first
        (sorted by depth), so each hash extends its parent's in O(1);
        the whole export is O(cached pages) — tens to hundreds of
        entries, cheap enough to refresh per routing sync.

        The chain ROOT is salted with the pool's ``layout_tag``
        (matching ``token_chain_hashes(..., layout=pool.layout_tag)``):
        digests from replicas with different KV page layouts — latent
        vs full-head, different quantization, different head geometry —
        share no keys, so the router can never place a request on a
        replica whose cached page bytes it could not actually reuse."""
        hashes: Dict[int, int] = {}        # eid -> chain hash
        out: Dict[int, int] = {}
        root = chain_hash(ROOT_HASH, self.pool.layout_tag)
        for e in sorted(self._index.values(), key=lambda e: e.depth):
            parent_h = root if e.parent == ROOT \
                else hashes[e.parent]
            h = chain_hash(parent_h, e.tokens)
            hashes[e.eid] = h
            out[h] = e.depth + 1
        return out

    # -- lookup / attach -----------------------------------------------------

    def _max_match_pages(self, tokens: Sequence[int]) -> int:
        # at least one token must stay uncached: the engine still has to
        # run the last prompt position to sample the first new token
        return max(0, len(tokens) - 1) // self.page_size

    def match(self, tokens: Sequence[int]) -> List[CacheEntry]:
        """Longest chain of cached full pages covering ``tokens`` —
        NO side effects (admission accounting peeks with this)."""
        ps = self.page_size
        out: List[CacheEntry] = []
        parent = ROOT
        for i in range(self._max_match_pages(tokens)):
            e = self._index.get((parent, tuple(tokens[i * ps:(i + 1) * ps])))
            if e is None:
                break
            out.append(e)
            parent = e.eid
        return out

    def acquire(self, req) -> List[CacheEntry]:
        """Attach the longest cached prefix to ``req``: refcount every
        matched page (they become unevictable) and touch the LRU clock.
        The caller points the request's page table at ``entry.page`` and
        starts ``pos`` at the cached boundary."""
        entries = self.match(req.tokens)
        if not entries:
            return entries
        self._tick += 1
        for e in entries:
            e.refs += 1
            e.last_use = self._tick
            self.pool.share_page(e.page)
        self._attached[req.req_id] = entries
        return entries

    def release(self, req) -> int:
        """Drop ``req``'s shared references (preemption, admission
        rollback, or the tail of :meth:`on_finish`)."""
        entries = self._attached.pop(req.req_id, [])
        for e in entries:
            e.refs -= 1
            self.pool.unshare_page(e.page)
        return len(entries)

    # -- insertion (request finish) ------------------------------------------

    def on_finish(self, req) -> Tuple[int, int]:
        """Retire a finished request's pages through the cache: insert
        every fully-written owned page, free duplicates and the partial
        tail, release shared references.  Returns
        ``(pages_inserted, pages_freed)``."""
        ps = self.page_size
        shared = self._attached.get(req.req_id, [])
        # pages fully written by the request (pos = next write index)
        full = min(len(req.pages), req.pos // ps)
        parent = shared[-1].eid if shared else ROOT
        inserted = 0
        for i in range(len(shared), full):
            key = (parent, tuple(req.tokens[i * ps:(i + 1) * ps]))
            page = req.pages[i]
            have = self._index.get(key)
            if have is not None:
                # identical content already cached: ours is a duplicate
                self.pool.free([page])
                parent = have.eid
                continue
            self.pool.cache_page(page)
            self._tick += 1
            e = CacheEntry(eid=self._next_id, parent=parent,
                           tokens=key[1], page=page, depth=i,
                           last_use=self._tick)
            self._next_id += 1
            self._index[key] = e
            self._by_id[e.eid] = e
            if parent != ROOT:
                self._by_id[parent].children += 1
            parent = e.eid
            inserted += 1
        tail = req.pages[full:]
        if tail:
            self.pool.free(tail)
        self.release(req)
        freed = (full - len(shared) - inserted) + len(tail)
        req.pages = []
        req.shared_pages = 0
        return inserted, freed

    # -- eviction ------------------------------------------------------------

    def evict(self, n: int) -> int:
        """Reclaim up to ``n`` pages: LRU refcount-0 leaves first (each
        removal may expose its parent as the next leaf).  O(entries) per
        page — pools are tens-to-hundreds of pages, and this only runs
        when the free list is already dry."""
        freed = 0
        while freed < n:
            cands = [e for e in self._index.values()
                     if e.refs == 0 and e.children == 0]
            if not cands:
                break
            victim = min(cands, key=lambda e: (e.last_use, e.eid))
            self._remove(victim)
            freed += 1
        return freed

    def chain_hash_of(self, e: CacheEntry) -> int:
        """The entry's layout-salted content chain hash — the same key
        :meth:`digest` exports and :func:`token_chain_hashes` computes
        router-side.  Walks the parent links (all still indexed while
        ``e`` is), so it is usable right up to the moment of
        eviction."""
        chain: List[Tuple[int, ...]] = []
        cur: Optional[CacheEntry] = e
        while cur is not None:
            chain.append(cur.tokens)
            cur = self._by_id.get(cur.parent) if cur.parent != ROOT \
                else None
        h = chain_hash(ROOT_HASH, self.pool.layout_tag)
        for tokens in reversed(chain):
            h = chain_hash(h, tokens)
        return h

    def _remove(self, e: CacheEntry) -> None:
        if self.on_evict is not None:
            # stage BEFORE the index/page bookkeeping: the page is
            # still read-only cached and the parent chain still hashes
            self.on_evict(e, self.chain_hash_of(e))
        del self._index[(e.parent, e.tokens)]
        del self._by_id[e.eid]
        if e.parent != ROOT:
            self._by_id[e.parent].children -= 1
        self.pool.uncache_page(e.page)

    # -- host-tier restore ---------------------------------------------------

    def restore(self, parent: int, tokens: Sequence[int], page: int,
                depth: int) -> CacheEntry:
        """Re-insert a page refetched from the host tier: ``page`` is
        freshly allocated and already holds the injected bytes; it
        becomes a refcount-0 cached entry under ``parent`` exactly as
        if :meth:`on_finish` had inserted it.  The caller guarantees
        the key is absent (it probed :meth:`match` first)."""
        key = (parent, tuple(tokens))
        if key in self._index:
            raise ValueError(f"restore of already-cached page at "
                             f"depth {depth}")
        self.pool.cache_page(page)
        self._tick += 1
        e = CacheEntry(eid=self._next_id, parent=parent, tokens=key[1],
                       page=page, depth=depth, last_use=self._tick)
        self._next_id += 1
        self._index[key] = e
        self._by_id[e.eid] = e
        if parent != ROOT:
            self._by_id[parent].children += 1
        return e

    def clear(self) -> None:
        """Evict everything evictable (attached entries survive — live
        requests still read their pages)."""
        self.evict(len(self._index))

    # -- invariants ----------------------------------------------------------

    def check_invariants(self, force: bool = False) -> None:
        """Cache-side bookkeeping invariants (the pool partition has its
        own in ``PagedKVPool.check_invariants``).  Opt-in like the
        pool's: runs only under ``pool.debug`` or ``force``."""
        if not (self.pool.debug or force):
            return
        # one implementation: the protocol verifier's snapshot predicate
        # (analysis/protocol.py) owns the invariant logic; this wrapper
        # keeps the debug/force gating and assert-style reporting every
        # existing call site relies on (imported lazily — the analysis
        # package must stay optional for serving)
        from ..analysis.protocol import cache_index_problems
        problems = cache_index_problems(self, self.pool)
        assert not problems, "; ".join(problems)
