"""Serving subsystem: paged KV-cache pool + continuous-batching engine
driving ONE unified ragged prefill+decode executable.

    from hetu_tpu.serving import Engine

    eng = Engine(state, cfg, num_pages=128, page_size=64, max_batch=8,
                 chunk_size=64, prefill_rows=1)
    req = eng.add_request(prompt_ids, max_new_tokens=64,
                          temperature=0.8, top_p=0.95, seed=7)
    outputs = eng.run()            # {req_id: generated token list}

See DESIGN.md §8 for the page-size/TP-tiling rationale, §12 for the
unified ragged step (token-budget packing, chunked prefill, on-device
temperature/top-k/top-p sampling, the one-executable compile contract),
§13 for copy-on-write prefix caching (chained page hashing, refcounted
read-only pages, LRU eviction — on by default, disable with
``Engine(..., prefix_cache=False)``), §17 for the cluster plane
(``serving.cluster.EngineCluster``: prefix-aware routing over N
replicas, disaggregated prefill/decode, priced KV-page streaming), and
§20 for draft-model speculative decoding
(``Engine(spec=SpecConfig(draft_state, draft_cfg, k=4))``: ragged
verify rows, on-device accept, temp-0 output still bit-for-bit).
"""
from .cluster import (ClusterRequest, EngineCluster, LocalPageTransport,
                      PageTransport, Replica, Router)
from .engine import Engine
from .kv_pool import PagedKVPool, TRASH_PAGE
from .prefix_cache import CacheEntry, PrefixCache
from .request import FINISHED, RUNNING, WAITING, Request, RequestQueue
from .scheduler import Scheduler
from .spec import SpecConfig, SpecDecoder

__all__ = ["Engine", "PagedKVPool", "TRASH_PAGE", "PrefixCache",
           "CacheEntry", "Request", "RequestQueue", "Scheduler",
           "WAITING", "RUNNING", "FINISHED",
           "SpecConfig", "SpecDecoder",
           "EngineCluster", "ClusterRequest", "Replica", "Router",
           "PageTransport", "LocalPageTransport"]
