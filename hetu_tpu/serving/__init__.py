"""Serving subsystem: paged KV-cache pool + continuous-batching engine.

    from hetu_tpu.serving import Engine

    eng = Engine(state, cfg, num_pages=128, page_size=64, max_batch=8)
    req = eng.add_request(prompt_ids, max_new_tokens=64)
    outputs = eng.run()            # {req_id: generated token list}

See DESIGN.md §8 for the page-size/TP-tiling rationale, the
prefill/decode executable split, and the shape-bucket policy.
"""
from .engine import Engine
from .kv_pool import PagedKVPool, TRASH_PAGE
from .request import FINISHED, RUNNING, WAITING, Request, RequestQueue
from .scheduler import Scheduler

__all__ = ["Engine", "PagedKVPool", "TRASH_PAGE", "Request",
           "RequestQueue", "Scheduler", "WAITING", "RUNNING", "FINISHED"]
