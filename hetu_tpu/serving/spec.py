"""Draft-model speculative decoding for the unified serving step.

Per-token decode latency is the serving bottleneck: every emitted token
costs one full target-model step, no matter how wide the unified
executable's token budget is.  Speculative decoding breaks the 1:1
coupling: a small **draft model** proposes ``k`` greedy tokens per
scheduled request, and the target verifies all of them in ONE unified
step — a verify row is structurally just a prefill chunk of length
``k + 1`` (the last committed token plus the proposals), so the ragged
kernel, the token-budget scheduler, the per-token KV write plan, and
the paged pool already speak exactly the right shapes.  The target's
on-device accept head (:mod:`~hetu_tpu.ops.ragged_paged_attention`)
returns the longest-accepted-prefix length plus a bonus token per row,
so a verify step emits ``accepted + 1`` tokens for one executable call
— and the host still fetches only ``[rows]`` int32s
(``host_logit_fetches`` stays 0).

This module owns the DRAFT half:

* :class:`SpecConfig` — the engine-facing knob: a draft ``state`` +
  shallow :class:`~hetu_tpu.models.gpt.GPTConfig` (same vocab; build
  one from a target checkpoint with
  :func:`hetu_tpu.models.gpt.draft_state_from`) and the proposal
  length ``k``;
* :class:`SpecDecoder` — slotted dense KV caches for up to
  ``max_batch`` concurrently-speculating requests plus exactly THREE
  jitted programs (all fixed-shape, so the draft joins the engine's
  compile-count guard):

  - ``draft_prefill``: one ``[1, max_model_len]`` padded causal
    forward that (re)builds a slot's cache — paid only when a request
    starts speculating or resumes after preemption/adoption;
  - ``draft_insert``: splices a prefilled cache into its slot;
  - ``draft_propose``: ``k`` greedy decode micro-steps batched over
    ALL speculating slots at once (per-row positions, idle rows write
    a trash position and are ignored).

**Why the draft never needs a catch-up in steady state.**  A propose
call warm-feeds the second-to-last committed token, then the last
committed token, then its own proposals — writing draft KV at
``[n - 2, n + k - 2]``.  The verify step commits the accepted prefix
``d_1..d_a`` — EXACTLY the tokens whose draft KV was just written —
plus a bonus token the draft never saw.  The next propose starts by
feeding from position ``n + a - 1``, overwriting the stale slots
before anything reads them (a decode query at position p attends only
``[0, p]``, and the write lands before the attention).  The warm-up
feed exists for the one slot this contiguity argument misses: after a
FULLY accepted burst, ``d_k`` is committed but its KV was never
written (propose only ever fed ``d_1..d_{k-1}``) — re-feeding the
committed token rewrites that slot, and is a bit-identical no-op
whenever the slot was already valid.  Rejected positions are
overwritten the same way: rewind is free on the draft side for the
same reason it is free on the target side (DESIGN.md §20).

Determinism: proposals are greedy and every propose/prefill op is
row-wise (per-slot matmuls, per-slot softmax), so a request's drafts
do not depend on which other requests share the batch — the engine's
temperature-0 bitwise contract and the sampled-mode replay determinism
both survive any traffic mix.  At temperature 0 the drafts cannot
affect OUTPUT at all (acceptance against the target argmax emits the
non-speculative sequence whatever the draft says); they only decide
how many tokens each step commits.
"""
from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..models.generate import (_act, _lm_head, _moe_mlp, _norm_apply,
                               _Params, _rotary_tables, decode_step)
from ..models.gpt import GPTConfig


@dataclass
class SpecConfig:
    """Speculative-decoding knob for ``Engine(spec=...)``.

    ``draft_state``/``draft_cfg``: the proposal model — any model with
    the TARGET's vocab (``models.gpt.draft_state_from`` builds the
    truncated self-draft).  ``k``: proposals per verify burst — each
    verify row gets its own dedicated ``k + 1``-wide slot in the token
    layout (independent of ``chunk_size``), and the engine caps the
    burst per-request at the remaining emission budget.
    """
    draft_state: Dict[str, Any]
    draft_cfg: GPTConfig
    k: int = 4

    def __post_init__(self):
        if self.k < 1:
            raise ValueError(f"spec k must be >= 1, got {self.k}")


class SpecDecoder:
    """Slotted draft-model runtime behind a speculative Engine."""

    def __init__(self, spec: SpecConfig, target_cfg: GPTConfig,
                 max_batch: int, max_model_len: int, k: int):
        dcfg = spec.draft_cfg
        if dcfg.vocab_size != target_cfg.vocab_size:
            raise ValueError(
                f"draft vocab {dcfg.vocab_size} != target vocab "
                f"{target_cfg.vocab_size}: proposals must be target "
                f"token ids")
        if dcfg.position == "learned" and \
                max_model_len > dcfg.max_seq_len:
            raise ValueError(
                f"draft learned-position table {dcfg.max_seq_len} "
                f"shorter than max_model_len {max_model_len}")
        self.cfg = dcfg
        self.k = int(k)
        self.params = _Params(spec.draft_state, dcfg).s
        self.S = int(max_batch)
        self.Lmax = int(max_model_len)
        cdt = jnp.bfloat16 if dcfg.dtype == "bfloat16" else jnp.float32
        self._cdt = cdt
        kvh, hd = dcfg.kv_heads, dcfg.head_dim
        # +1 cache row per slot: index Lmax is the TRASH position idle
        # rows scatter into (the dense-cache analogue of the pool's
        # trash page).  Layout is [slot, kv_head, position, head_dim] —
        # position INSIDE head — so the per-micro-step attention
        # contractions are transpose-free batched GEMMs; the [S, L,
        # kvh, hd] layout costs a multi-MB cache transpose per
        # micro-step on CPU, which single-handedly ate the speculative
        # speedup
        if dcfg.is_mla:
            # MLA draft (e.g. a self-draft truncated from an
            # MLA-converted target): _kc holds the single compressed
            # latent stream, _vc the shared rope stream (width 0 for
            # learned positions) — same slot/position layout
            k_shape = (self.S, 1, self.Lmax + 1, dcfg.kv_latent_dim)
            v_shape = (self.S, 1, self.Lmax + 1, dcfg.rope_dim)
        else:
            k_shape = v_shape = (self.S, kvh, self.Lmax + 1, hd)
        self._kc: List[jax.Array] = [jnp.zeros(k_shape, cdt)
                                     for _ in range(dcfg.num_layers)]
        self._vc: List[jax.Array] = [jnp.zeros(v_shape, cdt)
                                     for _ in range(dcfg.num_layers)]
        self._free: List[int] = list(range(self.S - 1, -1, -1))
        self._slot: Dict[int, int] = {}       # req_id -> slot
        self._valid: Dict[int, bool] = {}     # draft cache usable?
        # observability: how often the draft had to re-prefill (starts
        # + preemption/adoption resumes) and propose-call count
        self.prefills = 0
        self.proposals = 0
        self.compiled: Dict[str, Any] = {
            "draft_prefill": self._build_prefill(),
            "draft_propose": self._build_propose(),
            "draft_insert": self._build_insert(),
        }

    # -- jitted programs -----------------------------------------------------

    def _build_prefill(self):
        c, Lmax = self.cfg, self.Lmax
        cdt = self._cdt
        cos, sin = (_rotary_tables(c, Lmax) if c.position == "rotary"
                    else (None, None))
        kvh, hd = c.kv_heads, c.head_dim

        if c.is_mla:
            shapes = ((1, Lmax, 1, c.kv_latent_dim),
                      (1, Lmax, 1, c.rope_dim))
        else:
            shapes = ((1, Lmax, kvh, hd),) * 2

        @jax.jit
        def prefill(params, tokens):          # tokens [1, Lmax] i32
            p = _Params.__new__(_Params)
            p.s, p.cfg = params, c
            caches = [(jnp.zeros(shapes[0], cdt),
                       jnp.zeros(shapes[1], cdt))
                      for _ in range(c.num_layers)]
            _, cs = decode_step(c, p, tokens, caches, 0, cos, sin)
            return tuple(k for k, _ in cs), tuple(v for _, v in cs)

        return prefill

    def _build_insert(self):
        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def insert(kcs, vcs, pk, pv, slot):
            # prefill produces [1, L, kvh, hd]; the slot store is
            # position-inside-head ([S, kvh, L+1, hd]) — one transpose
            # here (per resume) saves one per propose micro-step
            start = (slot, jnp.int32(0), jnp.int32(0), jnp.int32(0))
            new_k = tuple(
                lax.dynamic_update_slice(
                    kc, jnp.swapaxes(k1, 1, 2).astype(kc.dtype), start)
                for kc, k1 in zip(kcs, pk))
            new_v = tuple(
                lax.dynamic_update_slice(
                    vc, jnp.swapaxes(v1, 1, 2).astype(vc.dtype), start)
                for vc, v1 in zip(vcs, pv))
            return new_k, new_v

        return insert

    def _build_propose(self):
        c, S, Lmax, K = self.cfg, self.S, self.Lmax, self.k
        cdt = self._cdt
        cos, sin = (_rotary_tables(c, Lmax + 1)
                    if c.position == "rotary" else (None, None))
        hd, nh, kvh = c.head_dim, c.num_heads, c.kv_heads
        g = nh // kvh
        d_c = c.kv_latent_dim if c.is_mla else 0
        d_r = c.rope_dim if c.is_mla else 0
        scale = ((hd + d_r) if c.is_mla else hd) ** -0.5
        rows = jnp.arange(S)

        def rope_rows(x, idx):
            # x [S, h, d]; per-row position gather (generate._rope with
            # a different position per row)
            half = x.shape[-1] // 2
            x1, x2 = x[..., :half], x[..., half:]
            rot = jnp.concatenate([-x2, x1], axis=-1)
            cg = cos[idx][:, None, :].astype(x.dtype)
            sg = sin[idx][:, None, :].astype(x.dtype)
            return x * cg + rot * sg

        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def propose(params, kcs, vcs, pre_tok, last_tok, pre_pos, pos,
                    active):
            p = _Params.__new__(_Params)
            p.s, p.cfg = params, c
            kcs, vcs = list(kcs), list(vcs)
            out = []
            # K + 1 micro-steps: a WARM-UP feed of the second-to-last
            # committed token at ``pre_pos`` (logits discarded), then
            # the K proposal steps.  The warm-up re-writes the one
            # draft-KV slot a fully-accepted burst leaves stale:
            # propose only ever feeds d_1..d_{K-1}, so d_K's KV is
            # never written — after full acceptance the next burst's
            # context would silently hold garbage at position m-2 and
            # the accept rate would decay with generation length.
            # Re-feeding a committed token rewrites the identical
            # value when the slot was already valid, so the warm-up is
            # a no-op in every other case.
            cur, cur_pos = pre_tok, pre_pos
            for step in range(K + 1):
                x = p("wte.weight")[cur].astype(cdt)           # [S, H]
                if c.position == "learned":
                    x = x + p("wpe")[jnp.clip(
                        cur_pos, 0, c.max_seq_len - 1)].astype(x.dtype)
                # idle rows (and rows proposed past the model budget)
                # scatter into the trash position Lmax
                wpos = jnp.where(active, jnp.minimum(cur_pos, Lmax),
                                 Lmax)
                for i in range(c.num_layers):
                    h = _norm_apply(c, p.layer(i, "ln_1.weight"),
                                    p.layer(i, "ln_1.bias"), x)
                    if c.is_mla:
                        # weight-absorbed latent path (DESIGN.md §21):
                        # q folded through k_up scores straight against
                        # the latent cache; the output stays latent
                        # until the per-row v_up fold — same
                        # contractions as the unified step's decode
                        # slots (drafts are greedy + row-wise either
                        # way, so batching never leaks between slots)
                        q = h @ p.layer(i, "attn.q.weight").T
                        qb = p.layer(i, "attn.q.bias")
                        if qb is not None:
                            q = q + qb
                        q = q.reshape(S, nh, hd + d_r)
                        kv = h @ p.layer(i, "attn.kv_a.weight").T
                        kb = p.layer(i, "attn.kv_a.bias")
                        if kb is not None:
                            kv = kv + kb
                        c_kv = kv[..., :d_c]
                        k_up = p.layer(i, "attn.k_up.weight")
                        v_up = p.layer(i, "attn.v_up.weight")
                        q_cat = jnp.einsum(
                            "shd,hdc->shc",
                            q[..., :hd].astype(jnp.float32),
                            k_up.astype(jnp.float32))
                        k_rope = None
                        if d_r:
                            ridx = jnp.clip(cur_pos, 0, Lmax)
                            q_rope = rope_rows(q[..., hd:], ridx)
                            k_rope = rope_rows(kv[:, None, d_c:],
                                               ridx)[:, 0]
                            q_cat = jnp.concatenate(
                                [q_cat, q_rope.astype(jnp.float32)], -1)
                        kcs[i] = kcs[i].at[rows, 0, wpos].set(
                            c_kv.astype(cdt))
                        if d_r:
                            vcs[i] = vcs[i].at[rows, 0, wpos].set(
                                k_rope.astype(cdt))
                        lat = kcs[i][:, 0].astype(jnp.float32)
                        kall = lat if not d_r else jnp.concatenate(
                            [lat, vcs[i][:, 0].astype(jnp.float32)], -1)
                        s = jnp.einsum("shc,slc->shl", q_cat,
                                       kall) * scale
                        mask = jnp.arange(Lmax + 1)[None, :] \
                            <= cur_pos[:, None]
                        s = jnp.where(mask[:, None, :], s, -jnp.inf)
                        pr = jax.nn.softmax(s, axis=-1)
                        o_lat = jnp.einsum("shl,slc->shc", pr, lat)
                        o = jnp.einsum("shc,hdc->shd", o_lat,
                                       v_up.astype(jnp.float32))
                        o = o.reshape(S, nh * hd).astype(x.dtype)
                    else:
                        qkv = h @ p.layer(i, "attn.qkv.weight").T
                        qb = p.layer(i, "attn.qkv.bias")
                        if qb is not None:
                            qkv = qkv + qb
                        qs, ks = nh * hd, kvh * hd
                        q = qkv[..., :qs].reshape(S, nh, hd)
                        kk = qkv[..., qs:qs + ks].reshape(S, kvh, hd)
                        vv = qkv[..., qs + ks:].reshape(S, kvh, hd)
                        if c.position == "rotary":
                            ridx = jnp.clip(cur_pos, 0, Lmax)
                            q = rope_rows(q, ridx)
                            kk = rope_rows(kk, ridx)
                        kcs[i] = kcs[i].at[rows, :, wpos].set(
                            kk.astype(cdt))
                        vcs[i] = vcs[i].at[rows, :, wpos].set(
                            vv.astype(cdt))
                        qg = q.reshape(S, kvh, g, hd).astype(jnp.float32)
                        s = jnp.einsum("skgd,skld->skgl", qg,
                                       kcs[i].astype(jnp.float32)) * scale
                        mask = jnp.arange(Lmax + 1)[None, :] \
                            <= cur_pos[:, None]
                        s = jnp.where(mask[:, None, None, :], s,
                                      -jnp.inf)
                        pr = jax.nn.softmax(s, axis=-1)
                        o = jnp.einsum("skgl,skld->skgd", pr,
                                       vcs[i].astype(jnp.float32))
                        o = o.reshape(S, nh * hd).astype(x.dtype)
                    o = o @ p.layer(i, "attn.out.weight").T
                    ob = p.layer(i, "attn.out.bias")
                    if ob is not None:
                        o = o + ob
                    x = x + o
                    h = _norm_apply(c, p.layer(i, "ln_2.weight"),
                                    p.layer(i, "ln_2.bias"), x)
                    if c.is_moe_layer(i):
                        h = _moe_mlp(c, p, i, h[:, None, :])[:, 0]
                    else:
                        h = _act(c, h @ p.layer(i, "mlp.up.weight").T +
                                 (p.layer(i, "mlp.up.bias")
                                  if p.layer(i, "mlp.up.bias") is not None
                                  else 0.0))
                        h = h @ p.layer(i, "mlp.down.weight").T
                        db = p.layer(i, "mlp.down.bias")
                        if db is not None:
                            h = h + db
                    x = x + h
                xf = _norm_apply(c, p("ln_f.weight"), p("ln_f.bias"), x)
                nxt = jnp.argmax(_lm_head(p, xf),
                                 axis=-1).astype(jnp.int32)
                if step == 0:              # warm-up: discard, rewind
                    cur, cur_pos = last_tok, pos
                else:
                    out.append(nxt)
                    cur = nxt
                    cur_pos = cur_pos + active.astype(jnp.int32)
            return (jnp.stack(out, axis=1), tuple(kcs), tuple(vcs))

        return propose

    # -- lifecycle -----------------------------------------------------------

    def _ensure_slot(self, req):
        """Assign (or return) the request's draft slot; ``None`` when
        the slot pool is exhausted — the caller skips the candidate
        this step rather than crash.  With ``release`` on
        preemption/finish/abort, holders are always RUNNING requests
        (≤ max_batch = slot count), so exhaustion is a defensive path,
        not an expected one."""
        slot = self._slot.get(req.req_id)
        if slot is None:
            if not self._free:
                return None
            slot = self._free.pop()
            self._slot[req.req_id] = slot
            self._valid[req.req_id] = False
        return slot

    def release(self, req) -> None:
        """Request left the engine (finish/abort): free its slot."""
        slot = self._slot.pop(req.req_id, None)
        if slot is not None:
            self._free.append(slot)
            self._valid.pop(req.req_id, None)

    def stage(self, cands, k_effs: Dict[int, int],
              tracer=None, now: float = 0.0) -> Dict[int, List[int]]:
        """Prefill stale slots, then ONE batched propose over every
        candidate: returns ``{req_id: drafts}`` with each request's
        drafts truncated to its ``k_eff``.  ``cands`` are decode-ready
        requests (``len(tokens) - pos == 1``)."""
        if not cands:
            return {}
        staged = []
        for req in cands:
            slot = self._ensure_slot(req)
            if slot is None:
                continue               # slot pool dry: plain decode
            staged.append(req)
            if not self._valid[req.req_id]:
                n = len(req.tokens)
                if n > 1:
                    toks = np.zeros((1, self.Lmax), np.int32)
                    toks[0, :n - 1] = req.tokens[:n - 1]
                    t0 = now
                    pk, pv = self.compiled["draft_prefill"](
                        self.params, jnp.asarray(toks))
                    self._kc, self._vc = self.compiled["draft_insert"](
                        tuple(self._kc), tuple(self._vc), pk, pv,
                        jnp.int32(slot))
                    self._kc, self._vc = list(self._kc), list(self._vc)
                    self.prefills += 1
                    if tracer is not None and tracer.enabled:
                        tracer.instant("draft_prefill",
                                       track=f"req {req.req_id}", ts=t0,
                                       req=req.req_id, tokens=n - 1)
                self._valid[req.req_id] = True
        cands = staged
        if not cands:
            return {}
        pre = np.zeros(self.S, np.int32)
        last = np.zeros(self.S, np.int32)
        pre_pos = np.zeros(self.S, np.int32)
        pos = np.zeros(self.S, np.int32)
        active = np.zeros(self.S, bool)
        for req in cands:
            s = self._slot[req.req_id]
            pre[s] = req.tokens[-2] if len(req.tokens) > 1 \
                else req.tokens[-1]
            last[s] = req.tokens[-1]
            pre_pos[s] = max(len(req.tokens) - 2, 0)
            pos[s] = len(req.tokens) - 1
            active[s] = True
        drafts, kcs, vcs = self.compiled["draft_propose"](
            self.params, tuple(self._kc), tuple(self._vc),
            jnp.asarray(pre), jnp.asarray(last), jnp.asarray(pre_pos),
            jnp.asarray(pos), jnp.asarray(active))
        self._kc, self._vc = list(kcs), list(vcs)
        self.proposals += 1
        d = np.asarray(drafts)
        out = {}
        for req in cands:
            k_eff = int(k_effs[req.req_id])
            out[req.req_id] = [int(t) for t in
                               d[self._slot[req.req_id], :k_eff]]
        return out

    @property
    def compile_count(self) -> int:
        n = 0
        for fn in self.compiled.values():
            try:
                n += int(fn._cache_size())
            except Exception:
                n += 1
        return n
