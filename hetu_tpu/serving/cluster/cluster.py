"""The serving cluster plane: N engine replicas behind one front door.

``EngineCluster`` scales the single-host engine out the way ROADMAP
item 1 names: N ``serving.Engine`` replicas wrapped as process-local
hosts (``replica.py``) registered through the ``rpc`` coordinator
(heartbeat → health), a prefix-aware router (``router.py``) spreading
request streams across them, and an optional **disaggregated** mode
where dedicated prefill replicas compute prompt KV and stream the pages
to dedicated decode replicas through a priced ``PageTransport``
(``transport.py``).

Two modes:

* ``"replicated"`` (default) — every replica serves prefill+decode; the
  router places each request on the replica whose prefix cache holds
  its longest prefix (digest lookup), falling back to least-loaded,
  with per-replica queue-depth backpressure.
* ``"disaggregated"`` — the first ``num_prefill`` replicas ONLY
  prefill: each request runs there with ``max_new_tokens=1`` (prefill +
  first sampled token), then its KV pages are extracted, streamed
  through the transport (priced via the planner's alpha-beta formulas),
  injected into a decode replica's pool, and the request is ADOPTED
  mid-flight (``Engine.adopt_request``) to continue decoding.  Temp-0
  output is bit-for-bit the monolithic engine's (asserted in
  tests/test_cluster.py): the decode replica reads byte-identical KV
  through the identical kernel, and the position-keyed sampler makes
  even sampled modes replay exactly.

All replicas share ONE jitted unified-step program (identical shapes →
one compile for the whole fleet), each registered for analysis under
its own name (``{name}@r{i}/unified``).  A dead replica — missed
heartbeats past the TTL, or an explicit :meth:`Replica.kill` — has its
unfinished requests pulled back into the backlog and re-placed on
survivors; no request is lost (completion-set equality asserted).

Failure/consistency contract: a re-routed or preempted request replays
from its accumulated tokens, so at temperature 0 (and under the
seeded sampler) the final output is independent of deaths, handoffs,
preemptions and placement — the same contract the single engine already
made, extended across the fleet.

Fault plane (DESIGN.md §18, ``hetu_tpu/fault``): every death verdict
bumps the replica's **fencing epoch** — placements, stream callbacks
and handoff injections all carry the epoch they were made under, so a
zombie (heartbeat stall while the engine keeps stepping), a revived
TTL-expired replica, or a duplicated wire delivery can never
double-deliver: stale completions are dropped in ``_collect_finished``
(``stale_completions_dropped``), stale stream tokens are ignored at the
callback, and handoff injection is idempotent by ``(request id,
staging epoch)``.  The bare retry loops are gone: handoff attempts back
off with a capped-exponential :class:`~hetu_tpu.fault.RetryPolicy`, a
staged handoff whose pinned destination dies mid-transfer is re-staged
to a survivor (``handoffs_restaged``), and a request that every live
replica has backpressured past its deadline is SHED with a retriable
rejection (``requests_shed``) instead of growing the backlog without
bound.  A quarantined replica rejoins only through
:meth:`readmit_replica`, which aborts its stale engine state first.
Chaos injection (``EngineCluster(chaos=ChaosController(plan))``) drives
all of it deterministically; every fault and every recovery action is a
tracer instant, so one Perfetto trace shows fail → detect → recover.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

from ...fault.backoff import RetryPolicy
from ...obs.tracer import PrefixedTracer, get_tracer
from ...utils.metrics import make_instrument, merge_prometheus_texts
from ..engine import Engine
from ..kv_pool import protocol_seq
from ..slo.backlog import ClassBacklog
from ..slo.classes import SLO_CLASSES, class_rank
from .replica import DECODE, PREFILL, UNIFIED, Replica
from .router import Router
from .transport import LocalPageTransport, PageTransport

MODES = ("replicated", "disaggregated")


@dataclass
class ClusterRequest:
    """One request as the CLUSTER sees it: stable identity across
    placements (a death re-route or a prefill→decode handoff changes
    which engine-level Request serves it, never which ClusterRequest
    it is)."""
    req_id: int
    prompt: List[int]
    max_new_tokens: int
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 0.0
    seed: int = 0
    eos_token_id: Optional[int] = None
    arrival_time: float = 0.0
    submit_time: float = 0.0
    # SLO class (serving.slo.classes): policy-only — decides who
    # waits, sheds and scales, never what a surviving request computes
    slo_class: str = "standard"

    # runtime
    out_tokens: List[int] = field(default_factory=list)
    token_times: List[float] = field(default_factory=list)
    replica: Optional[int] = None     # current owner (engine placement)
    prefill_replica: Optional[int] = None
    stage: str = ""                   # "" | prefill | final
    handoff_pending: bool = False
    n_reroutes: int = 0
    finish_time: Optional[float] = None
    # load shedding: a shed request is terminal but NOT completed — the
    # rejection is retriable (the caller may resubmit when the fleet
    # has headroom)
    rejected: bool = False
    reject_reason: str = ""

    @property
    def done(self) -> bool:
        return self.finish_time is not None

    @property
    def rank(self) -> int:
        return class_rank(self.slo_class)

    @property
    def first_token_time(self) -> Optional[float]:
        return self.token_times[0] if self.token_times else None


class _FollowTracer:
    """Resolves the cluster's effective tracer at every use (injected
    tracer, else the ambient global) — so ``obs.trace()`` around a
    cluster run captures every replica without re-wiring engines."""

    def __init__(self, cluster: "EngineCluster"):
        self._cluster = cluster

    def __getattr__(self, name):
        return getattr(self._cluster.tracer, name)

    def __len__(self) -> int:
        return len(self._cluster.tracer)


class EngineCluster:
    def __init__(self, state: Dict[str, Any], cfg,
                 num_replicas: int = 2, mode: str = "replicated",
                 num_prefill: int = 1, name: str = "cluster",
                 policy: str = "prefix",
                 max_queue_depth: Optional[int] = None,
                 heartbeat_interval: float = 0.25, ttl: float = 2.0,
                 coordinator: bool = True,
                 transport: Optional[PageTransport] = None,
                 time_fn=None, tracer=None, seed: int = 0,
                 metrics: bool = True, step_fn=None,
                 chaos=None, retry: Optional[RetryPolicy] = None,
                 request_deadline: Optional[float] = None,
                 max_backlog: Optional[int] = None,
                 autoscaler=None, **engine_kw):
        if mode not in MODES:
            raise ValueError(f"unknown mode {mode!r}; have {MODES}")
        if num_replicas < 1:
            raise ValueError("need at least one replica")
        if mode == "disaggregated":
            if num_replicas < 2:
                raise ValueError("disaggregated mode needs >= 2 replicas")
            if not (1 <= num_prefill < num_replicas):
                raise ValueError(
                    f"num_prefill must be in [1, {num_replicas - 1}], "
                    f"got {num_prefill}")
        self.name = name
        self.mode = mode
        self.cfg = cfg
        self._time = time_fn or time.monotonic
        self._tracer = tracer
        # fault plane: chaos injection + recovery policy.  The retry
        # policy governs handoff re-attempts (capped exponential,
        # deterministic jitter); request_deadline bounds how long a
        # request may wait backpressured (backlog or staged handoff)
        # before it degrades — sheds with a retriable rejection, or
        # falls back to monolithic serving; max_backlog bounds the
        # front-door queue (beyond it, arrivals shed immediately)
        self.chaos = chaos
        self.retry = retry if retry is not None else RetryPolicy()
        self.request_deadline = None if request_deadline is None \
            else float(request_deadline)
        self.max_backlog = None if max_backlog is None \
            else int(max_backlog)
        # SLO traffic plane: the autoscaler (serving.slo.Autoscaler)
        # rides the existing drain/kill/readmit lifecycle — its hook
        # runs right after the health sweep each step
        self.autoscaler = autoscaler
        follow = _FollowTracer(self)
        self.transport = transport if transport is not None \
            else LocalPageTransport()

        # -- replica plane: coordinator + N engines sharing one compile
        self.server = None
        if coordinator:
            from ...rpc.coordinator import (CoordinatorClient,
                                            CoordinatorServer)
            self.server = CoordinatorServer(world_size=num_replicas,
                                            ttl=ttl).start()
        roles = [UNIFIED] * num_replicas if mode == "replicated" else \
            [PREFILL] * num_prefill + \
            [DECODE] * (num_replicas - num_prefill)
        self.replicas: List[Replica] = []
        # one jitted program for the whole fleet: the first engine
        # builds it (or the caller injects an already-warm one — e.g.
        # a rolling restart reusing the old fleet's program)
        shared_fn = step_fn
        for i, role in enumerate(roles):
            eng = Engine(state, cfg, name=f"{name}@r{i}",
                         time_fn=self._time, metrics=metrics,
                         tracer=PrefixedTracer(follow, f"r{i}/"),
                         step_fn=shared_fn, **engine_kw)
            if shared_fn is None:
                shared_fn = eng._compiled["unified"]
            client = None
            if self.server is not None:
                client = CoordinatorClient(self.server.address,
                                           uid=f"{name}-r{i}", ttl=ttl)
            self.replicas.append(Replica(
                i, eng, role=role, client=client,
                heartbeat_interval=heartbeat_interval))
        if mode == "disaggregated":
            # expose each decode replica's handoff + adoption records to
            # the analysis plane: kv-handoff-unpriced audits that every
            # cross-replica page move carried a priced edge claim, and
            # unfenced-handoff that every move AND every mid-flight
            # adoption carried a fence token (epoch)
            from ...graph.graph import get_executable
            for r in self.replicas:
                if r.role == DECODE:
                    h = get_executable(f"{r.engine.name}/unified")
                    h.meta["kv_handoff"] = \
                        (lambda t=self.transport, d=r.idx:
                         t.records_for(d))
                    h.meta["adoptions"] = \
                        (lambda c=self, d=r.idx:
                         [a for a in c._adoptions if a["dst"] == d])
        # every replica's executable additionally sees the cluster's
        # control-plane protocol events (and the chaos audit log when a
        # controller is wired) — the protocol lifecycle rules replay
        # fences/sheds/adoptions against the engine-local planes
        from ...graph.graph import get_executable as _get_exe
        prefills = [r for r in self.replicas if r.role == PREFILL]
        for r in self.replicas:
            try:
                h = _get_exe(f"{r.engine.name}/unified")
            except KeyError:
                continue
            h.meta["protocol"] = (lambda c=self: list(c.protocol_log))
            if self.chaos is not None:
                h.meta["chaos"] = \
                    (lambda ch=self.chaos: list(ch.injected))
            if len(prefills) == 1 and r is prefills[0]:
                # page ids are pool-local: the extract log only joins
                # the stream whose pool the extracts actually read
                h.meta["extract_log"] = \
                    (lambda t=self.transport:
                     list(getattr(t, "extract_log", ())))

        self.router = Router(policy=policy,
                             max_queue_depth=max_queue_depth,
                             seed=seed, tracer=follow,
                             time_fn=self._time)
        self._next_id = 0
        self.steps = 0
        # class-aware front door: rank-major service, FIFO within a
        # class, shed pressure falls lowest-class-first
        self._backlog = ClassBacklog()
        self._pending_handoffs: List[Dict[str, Any]] = []
        # (replica idx, engine req id) -> (creq, stage, fence epoch):
        # live ownership, stamped with the epoch it was placed under
        self._placed: Dict = {}
        self.requests: Dict[int, ClusterRequest] = {}
        self.finished: Dict[int, ClusterRequest] = {}
        self.shed: Dict[int, ClusterRequest] = {}
        self._dead_handled: set = set()
        # fencing epochs: bumped at every death verdict; anything
        # stamped with an older epoch is stale and must be dropped
        self._fence: Dict[int, int] = {r.idx: 0 for r in self.replicas}
        # engine requests a fenced replica still owes us a (stale)
        # completion for: (replica idx, engine req id) -> cluster req id
        self._stale_expected: Dict = {}
        # idempotent handoff injection: (cluster req id, staging epoch)
        # pairs already landed — a duplicated delivery (retry after a
        # lost ack, chaos dup) is dropped here, never adopted twice.
        # Staging epochs come from one cluster-wide monotonic counter,
        # so a request that re-enters the disaggregated path after a
        # degrade can never collide with its own past key
        self._injected: set = set()
        self._stage_seq = 0
        # mid-flight adoption audit trail (the unfenced-handoff rule
        # reads these through the decode replicas' executable meta)
        self._adoptions: List[Dict[str, Any]] = []
        # cluster-plane protocol events (req.queued/stage/shed/finish,
        # fence.bump/complete/stale_drop) for the analysis event
        # stream — the control-plane half the engine logs can't see
        self.protocol_log: List[Dict[str, Any]] = []
        # reset-robust per-replica counter accumulation (see
        # metrics_summary): replica -> counter -> (base, last_seen)
        self._counter_acc: Dict[int, Dict[str, List[float]]] = \
            {r.idx: {} for r in self.replicas}
        m = metrics
        self.counters = {k: make_instrument("counter", k, m) for k in
                         ("requests_completed", "reroutes", "handoffs",
                          "routed",
                          # failure plane (DESIGN.md §18)
                          "replica_deaths", "handoff_retries",
                          "handoffs_restaged", "requests_shed",
                          "stale_completions_dropped",
                          "duplicate_deliveries_dropped", "readmits",
                          # SLO traffic plane (DESIGN.md §22): per-class
                          # sheds, the inversion detector (a shed or
                          # placement that favored a lower class —
                          # always 0 by construction, asserted in the
                          # bench), autoscaler actions
                          *(f"shed_{c}" for c in SLO_CLASSES),
                          "class_inversions", "scale_ups",
                          "scale_downs",
                          # drain completions deferred because a
                          # chaos-delayed handoff was still in flight
                          # TO the draining replica (the interaction
                          # bug the protocol explorer surfaced)
                          "drains_deferred_inflight")}
        self.histograms = {k: make_instrument("histogram", k, m) for k in
                           ("ttft", "tbt", "request_latency",
                            # per-class latency tails: the SLO targets
                            # are per class, so the evidence must be too
                            *(f"ttft_{c}" for c in SLO_CLASSES),
                            *(f"tbt_{c}" for c in SLO_CLASSES))}
        self.gauges = {"replicas_active":
                       make_instrument("gauge", "replicas_active", m)}

    # -- tracer --------------------------------------------------------------

    @property
    def tracer(self):
        return self._tracer if self._tracer is not None else get_tracer()

    # -- submission ----------------------------------------------------------

    def add_request(self, prompt_ids: Sequence[int], max_new_tokens: int,
                    temperature: float = 0.0, top_k: int = 0,
                    top_p: float = 0.0, seed: int = 0,
                    eos_token_id: Optional[int] = None,
                    arrival_time: Optional[float] = None,
                    slo_class: str = "standard") -> ClusterRequest:
        prompt = [int(t) for t in prompt_ids]
        if not prompt:
            raise ValueError("empty prompt")
        class_rank(slo_class)          # validate at the front door
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        # fail at the front door, not on a replica mid-route: every
        # replica shares the same engine configuration, so one pool
        # speaks for the fleet (the engines re-check at submission)
        pool = self.replicas[0].engine.pool
        total = len(prompt) + int(max_new_tokens)
        if total > self.replicas[0].engine.max_model_len:
            raise ValueError(
                f"prompt+max_new_tokens = {total} exceeds max_model_len "
                f"{self.replicas[0].engine.max_model_len}")
        if pool.pages_for(total) > pool.num_usable:
            raise ValueError(
                f"request needs {pool.pages_for(total)} pages; each "
                f"replica pool has {pool.num_usable} — it could never "
                f"run anywhere")
        now = self._time()
        creq = ClusterRequest(
            req_id=self._next_id, prompt=prompt,
            max_new_tokens=int(max_new_tokens),
            temperature=float(temperature), top_k=int(top_k),
            top_p=float(top_p), seed=int(seed),
            eos_token_id=eos_token_id,
            arrival_time=now if arrival_time is None
            else float(arrival_time), slo_class=slo_class)
        creq.submit_time = max(now, creq.arrival_time)
        self._next_id += 1
        self.requests[creq.req_id] = creq
        if self.max_backlog is not None \
                and len(self._backlog) >= self.max_backlog:
            # bounded backlog: graceful degradation instead of
            # unbounded queue growth — the rejection is retriable.
            # Class-aware: an arrival that STRICTLY outranks the
            # worst queued entry displaces it (batch sheds before
            # interactive is turned away); same-class pressure keeps
            # the old shed-the-arrival FIFO behavior
            victim = self._backlog.shed_candidate()
            if victim is not None and victim.rank > creq.rank:
                self._backlog.remove(victim)
                self._shed(victim, "displaced", now)
            else:
                self._shed(creq, "backlog_full", now)
                return creq
        self._backlog.push(creq)
        self.protocol_log.append({"ev": "req.queued",
                                  "key": f"creq:{creq.req_id}",
                                  "seq": protocol_seq()})
        tr = self.tracer
        if tr.enabled:
            tr.instant("enqueue", track="router", ts=creq.submit_time,
                       req=creq.req_id, prompt_tokens=len(prompt),
                       slo_class=creq.slo_class,
                       backlog=len(self._backlog))
        return creq

    def _shed(self, creq: ClusterRequest, reason: str,
              now: float) -> None:
        """Load shedding: mark ``creq`` terminally rejected (retriable
        — the caller may resubmit) and count it.  Sheds only ever
        happen at the front door (bounded backlog) or once the whole
        live fleet has backpressured the request past its deadline."""
        creq.rejected = True
        creq.reject_reason = reason
        creq.finish_time = now
        self.shed[creq.req_id] = creq
        self.protocol_log.append({"ev": "req.shed",
                                  "key": f"creq:{creq.req_id}",
                                  "seq": protocol_seq()})
        self.counters["requests_shed"].inc()
        self.counters[f"shed_{creq.slo_class}"].inc()
        # inversion detector: shedding this class while a LOWER class
        # sits in the backlog equally sheddable means the shed policy
        # inverted the SLO order — by construction (shed_candidate /
        # expired_head scan lowest-class-first) this never fires, and
        # the slo bench asserts the counter stays 0
        for _arr, _rid, q in self._backlog:
            if q.rank <= creq.rank:
                continue
            if reason != "backpressured_past_deadline" \
                    or (self.request_deadline is not None
                        and q.arrival_time <= now
                        and now - q.submit_time > self.request_deadline):
                self.counters["class_inversions"].inc()
                break
        tr = self.tracer
        if tr.enabled:
            tr.instant("shed", track="router", ts=now, req=creq.req_id,
                       reason=reason, retriable=True,
                       slo_class=creq.slo_class,
                       backlog=len(self._backlog))

    # -- loop ----------------------------------------------------------------

    @property
    def has_work(self) -> bool:
        return bool(self._backlog) or bool(self._pending_handoffs) \
            or any(r.alive and r.engine.has_work for r in self.replicas)

    def step(self) -> int:
        """One cluster iteration: inject due chaos, health check
        (re-route the dead replicas' work), route ready backlog, land
        pending handoffs, step every serving engine.  Returns tokens
        emitted this step (stale tokens from fenced replicas are
        excluded — a zombie's engine still steps, exactly like a real
        partitioned process, but its output is quarantined)."""
        now = self._time()
        if self.chaos is not None:
            self.chaos.on_step(self, self.steps, now)
        self._check_health()
        if self.autoscaler is not None:
            # after the health sweep: the controller must see death
            # verdicts (a drain target that died mid-drain is already
            # handled capacity, not a second kill)
            self.autoscaler.on_step(self, self.steps, now)
        self.gauges["replicas_active"].set(
            sum(1 for r in self.replicas
                if r.alive and r.serving and not r.draining))
        self._sync_counters()
        self._route_ready(now)
        self._process_handoffs(now)
        produced = 0
        for r in self.replicas:
            if not r.serving or not r.engine.has_work:
                continue
            if r.slow_until > self.steps:
                continue               # straggler: this beat is skipped
            out = r.engine.step()
            if r.alive:
                produced += out
        self._collect_finished()
        self.steps += 1
        return produced

    def run(self, max_steps: Optional[int] = None
            ) -> Dict[int, List[int]]:
        while self.has_work:
            if max_steps is not None and self.steps >= max_steps:
                break
            if not any(r.alive for r in self.replicas):
                raise RuntimeError("no live replicas but work remains")
            self.step()
        return {rid: list(c.out_tokens)
                for rid, c in self.finished.items()}

    # -- health / re-route ---------------------------------------------------

    def _check_health(self) -> None:
        dead_ranks: set = set()
        if self.server is not None:
            dead_ranks = set(self.server.dead_ranks())
        for r in self.replicas:
            if r.idx in self._dead_handled:
                continue
            # with a coordinator, death is DECLARED only by missed
            # heartbeats past the TTL (the replica may have stopped
            # serving well before the verdict lands — exactly a real
            # crash); without one, the stopped process is its own proof
            died = (r.rank is not None and r.rank in dead_ranks) \
                or (self.server is None and not r.serving) \
                or (not r.alive)
            if not died:
                continue
            r.alive = False
            self._dead_handled.add(r.idx)
            # fence the epoch: anything this replica delivers from here
            # on (it may be a zombie still stepping) is stale
            self._fence[r.idx] += 1
            self.protocol_log.append({"ev": "fence.bump",
                                      "key": f"r{r.idx}",
                                      "epoch": self._fence[r.idx],
                                      "seq": protocol_seq()})
            self.counters["replica_deaths"].inc()
            tr = self.tracer
            if tr.enabled:
                tr.instant("replica_dead", track="router",
                           ts=self._time(), replica=r.idx,
                           fence_epoch=self._fence[r.idx],
                           zombie=bool(r.serving))
            for key in [k for k in self._placed if k[0] == r.idx]:
                creq, _stage, _epoch = self._placed.pop(key)
                # the fenced engine may still finish this request: owe
                # it a stale-completion drop, never a second finish
                self._stale_expected[key] = creq.req_id
                if creq.done or creq.handoff_pending:
                    # a staged handoff survives its source's death: the
                    # pages are already extracted host-side
                    continue
                self.router.note_reroute(creq, r.idx)
                creq.n_reroutes += 1
                creq.replica = None
                creq.stage = ""
                creq.token_times = []
                self.counters["reroutes"].inc()
                self._backlog.push(creq)

    # -- routing -------------------------------------------------------------

    def _prefill_pool(self) -> List[Replica]:
        if self.mode == "disaggregated":
            pre = [r for r in self.replicas
                   if r.role == PREFILL and r.alive]
            if pre:
                return pre
            # every prefill replica died: the survivors serve requests
            # end-to-end (monolithic degradation beats a dead cluster)
        return list(self.replicas)

    def _route_ready(self, now: float) -> None:
        while True:
            # rank-major head: an arrived interactive request always
            # routes before an arrived batch one (FIFO within a class)
            creq = self._backlog.peek_ready(now)
            if creq is None:
                break
            rep = self.router.place(creq, self._prefill_pool())
            if rep is None:
                # whole fleet backpressured (placement failure is
                # fleet-wide, not request-specific — a lower class
                # could not place either).  Past the deadline requests
                # shed lowest-class-first (batch before interactive),
                # bounded wait, graceful degradation
                victim = self._backlog.expired_head(
                    now, self.request_deadline)
                if victim is not None:
                    self._backlog.remove(victim)
                    self._shed(victim, "backpressured_past_deadline",
                               now)
                    continue
                break
            self._backlog.remove(creq)
            self._submit(creq, rep, now)

    def _submit(self, creq: ClusterRequest, rep: Replica,
                now: float) -> None:
        # a prefill stage only makes sense while a decode replica is
        # alive to adopt the handoff — otherwise the placed replica
        # serves the request end-to-end (so a dead decode fleet can't
        # trap requests in a prefill→handoff→requeue loop)
        has_decode = any(r.role == DECODE and r.alive
                         for r in self.replicas)
        stage = "prefill" if (self.mode == "disaggregated"
                              and rep.role == PREFILL and has_decode
                              and creq.max_new_tokens > 1) else "final"
        mnt = 1 if stage == "prefill" else creq.max_new_tokens
        epoch = self._fence[rep.idx]

        def cb(ereq, tok, creq=creq, stage=stage, ridx=rep.idx,
               epoch=epoch):
            if self._fence[ridx] != epoch:
                return         # fenced epoch: stale stream token
            creq.token_times.append(self._time())
            if stage == "prefill":
                if creq.eos_token_id is not None \
                        and int(tok) == creq.eos_token_id:
                    return     # eos on the first token: no decode stage
                self._stage_handoff(creq, ereq, ridx, int(tok))

        ereq = rep.engine.add_request(
            creq.prompt, mnt, temperature=creq.temperature,
            top_k=creq.top_k, top_p=creq.top_p, seed=creq.seed,
            eos_token_id=creq.eos_token_id, arrival_time=now,
            stream_cb=cb, slo_class=creq.slo_class)
        creq.replica = rep.idx
        creq.stage = stage
        if stage == "prefill":
            creq.prefill_replica = rep.idx
        self._placed[(rep.idx, ereq.req_id)] = (creq, stage, epoch)
        self.counters["routed"].inc()

    # -- disaggregated handoff ----------------------------------------------

    def _stage_handoff(self, creq: ClusterRequest, ereq, src_idx: int,
                       first_tok: int) -> None:
        """Called from the prefill engine's emit path, while the pages
        are still owned: extract them NOW (the engine retires them into
        its prefix cache at finish), queue the injection."""
        pool = self.replicas[src_idx].engine.pool
        n = pool.pages_for(ereq.pos)
        staged = self.transport.extract(pool, ereq.pages[:n])
        creq.handoff_pending = True
        epoch = self._next_stage_epoch()
        self.protocol_log.append({"ev": "req.stage",
                                  "key": f"creq:{creq.req_id}",
                                  "epoch": epoch,
                                  "seq": protocol_seq()})
        self._pending_handoffs.append(
            {"creq": creq, "staged": staged, "src": src_idx,
             "first": int(first_tok), "pos": int(ereq.pos),
             # recovery state: capped-exp backoff attempts, the staging
             # epoch (fresh on every (re-)stage — the idempotency key's
             # second half), and the in-flight pin (set while a delayed
             # transfer has a destination + pages reserved)
             "attempt": 0, "not_before": float("-inf"),
             "epoch": epoch,
             "dst": None, "dst_pages": None, "lands_at": None,
             "redelivery": False})
        tr = self.tracer
        if tr.enabled:
            tr.instant("handoff_staged", track="router",
                       ts=self._time(), req=creq.req_id, src=src_idx,
                       pages=int(staged["n_pages"]),
                       payload_bytes=int(staged["payload_bytes"]))

    def _next_stage_epoch(self) -> int:
        self._stage_seq += 1
        return self._stage_seq

    def _retry_handoff(self, h: Dict[str, Any], now: float,
                       still: List[Dict[str, Any]]) -> None:
        """Schedule the next attempt: capped-exponential backoff with
        deterministic per-request jitter (no bare spin retry)."""
        self.counters["handoff_retries"].inc()
        delay = self.retry.delay(h["attempt"], key=h["creq"].req_id)
        h["attempt"] += 1
        h["not_before"] = now + delay
        tr = self.tracer
        if tr.enabled:
            tr.instant("handoff_retry", track="router", ts=now,
                       req=h["creq"].req_id, attempt=h["attempt"],
                       next_in=delay)
        still.append(h)

    def _degrade_to_local(self, creq: ClusterRequest, reason: str,
                          now: float) -> None:
        """Give up on the disaggregated path for this request: replay
        it end-to-end on whatever still lives (the backlog router
        decides — monolithic serving beats a trapped request)."""
        creq.handoff_pending = False
        creq.token_times = []
        creq.n_reroutes += 1
        self.counters["reroutes"].inc()
        self._backlog.push(creq)
        tr = self.tracer
        if tr.enabled:
            tr.instant("handoff_degraded", track="router", ts=now,
                       req=creq.req_id, reason=reason)

    def _process_handoffs(self, now: float) -> None:
        still: List[Dict[str, Any]] = []
        for h in self._pending_handoffs:
            creq: ClusterRequest = h["creq"]
            key = (creq.req_id, h["epoch"])
            # idempotent injection: this (request, staging epoch) has
            # already landed — a retried delivery whose ack was lost,
            # or a chaos-duplicated packet.  Drop, never adopt twice.
            if key in self._injected:
                self.counters["duplicate_deliveries_dropped"].inc()
                tr = self.tracer
                if tr.enabled:
                    tr.instant("duplicate_dropped", track="router",
                               ts=now, req=creq.req_id,
                               epoch=h["epoch"])
                continue
            if creq.done:
                continue               # finished through another path
            # -- in-flight (delayed) transfer: the destination is
            # pinned and may die mid-transfer
            if h["dst"] is not None:
                dst = self.replicas[h["dst"]]
                if not dst.alive:
                    # destination died mid-transfer: re-stage to a
                    # survivor.  The staged bytes are host-side, so the
                    # transfer restarts under a NEW staging epoch (the
                    # fence against the old delivery surfacing late).
                    # The reserved pages go back to the dead pool's
                    # free list — host bookkeeping, and a later
                    # readmission must not inherit leaked pages
                    if h["dst_pages"] is not None:
                        dst.engine.pool.free(h["dst_pages"])
                    h["epoch"] = self._next_stage_epoch()
                    h["dst"] = None
                    h["dst_pages"] = None
                    h["lands_at"] = None
                    h["attempt"] = 0
                    h["not_before"] = float("-inf")
                    self.counters["handoffs_restaged"].inc()
                    tr = self.tracer
                    if tr.enabled:
                        tr.instant("handoff_restaged", track="router",
                                   ts=now, req=creq.req_id,
                                   dead_dst=dst.idx, epoch=h["epoch"])
                elif now < h["lands_at"]:
                    still.append(h)    # still on the wire
                    continue
                else:
                    self._land_handoff(h, dst, h["dst_pages"], now)
                    continue
            # -- fresh attempt (possibly right after a re-stage)
            if now < h["not_before"]:
                still.append(h)        # backing off
                continue
            decode = [r for r in self.replicas
                      if r.role == DECODE and r.alive]
            if not decode:
                # every decode replica died: degrade to monolithic
                self._degrade_to_local(creq, "decode_fleet_empty", now)
                continue
            cands = self.router.candidates(decode)
            if not cands:
                # live decode fleet, all backpressured: bounded retry
                if self.request_deadline is not None \
                        and now - creq.submit_time > self.request_deadline:
                    self._degrade_to_local(
                        creq, "backpressured_past_deadline", now)
                    continue
                self._retry_handoff(h, now, still)
                continue
            rep = min(cands, key=lambda r: (r.outstanding_tokens(),
                                            r.idx))
            pool = rep.engine.pool
            n = pool.pages_for(h["pos"])
            pages = None
            if n <= pool.num_usable:
                pages = pool.alloc(n)
                if pages is None:
                    self._retry_handoff(h, now, still)  # pool full
                    continue
            # chaos seam: the wire's verdict for this attempt
            verdict, vdur = ("ok", 0.0)
            if self.chaos is not None and not h["redelivery"]:
                verdict, vdur = self.chaos.handoff_verdict()
            if verdict == "drop":
                # the wire ate it: the staged copy is still host-side,
                # release the reserved pages and back off
                if pages is not None:
                    pool.free(pages)
                self._retry_handoff(h, now, still)
                continue
            if verdict == "delay":
                # in flight: destination + pages pinned until it lands
                h["dst"] = rep.idx
                h["dst_pages"] = pages
                h["lands_at"] = now + max(vdur, 0.0)
                still.append(h)
                continue
            self._land_handoff(h, rep, pages, now)
            if verdict == "dup":
                # delivered but the ack was lost: the sender re-sends.
                # The redelivery must hit the (req_id, epoch) dedup and
                # be dropped — never adopted twice
                dup = dict(h, redelivery=True, dst=None,
                           dst_pages=None, lands_at=None)
                still.append(dup)
        self._pending_handoffs = still

    def _land_handoff(self, h: Dict[str, Any], rep: Replica,
                      pages, now: float) -> None:
        """Inject the staged pages and ADOPT the request mid-flight on
        ``rep`` — the single place a handoff becomes engine state, and
        the single place the ``(request id, epoch)`` idempotency key is
        written."""
        creq: ClusterRequest = h["creq"]
        pool = rep.engine.pool
        if pages is not None:
            rec = self.transport.inject(
                pool, h["staged"], pages, src_replica=h["src"],
                dst_replica=rep.idx, epoch=h["epoch"])
            self.counters["handoffs"].inc()
            tr = self.tracer
            if tr.enabled:
                tr.instant("handoff", track="router", ts=now,
                           req=creq.req_id, src=h["src"],
                           dst=rep.idx, pages=rec["pages"],
                           payload_bytes=rec["payload_bytes"],
                           predicted_wire_s=rec["predicted_s"],
                           epoch=h["epoch"])
            pos = h["pos"]
        else:
            # pages can NEVER fit this decode pool: degrade to a
            # full re-prefill on the decode replica (correct, just
            # not disaggregated for this one request)
            pos = 0
        fence = self._fence[rep.idx]
        ereq = rep.engine.adopt_request(
            creq.prompt, [h["first"]], creq.max_new_tokens,
            pages=pages, pos=pos, temperature=creq.temperature,
            top_k=creq.top_k, top_p=creq.top_p, seed=creq.seed,
            eos_token_id=creq.eos_token_id, arrival_time=now,
            stream_cb=self._final_cb(creq, rep.idx, fence),
            slo_class=creq.slo_class)
        self._injected.add((creq.req_id, h["epoch"]))
        self._adoptions.append({"req_id": creq.req_id,
                                "epoch": h["epoch"], "dst": rep.idx,
                                "fence_epoch": fence,
                                "seq": protocol_seq()})
        creq.handoff_pending = False
        creq.replica = rep.idx
        creq.stage = "final"
        self._placed[(rep.idx, ereq.req_id)] = (creq, "final", fence)

    def _final_cb(self, creq: ClusterRequest, ridx: int, epoch: int):
        def cb(ereq, tok, creq=creq, ridx=ridx, epoch=epoch):
            if self._fence[ridx] != epoch:
                return         # fenced epoch: stale stream token
            creq.token_times.append(self._time())
        return cb

    # -- finish collection ---------------------------------------------------

    def _collect_finished(self) -> None:
        for r in self.replicas:
            if not (r.alive or r.serving):
                continue       # fully dead process: nothing new appears
            for erid, ereq in list(r.engine.finished.items()):
                ent = self._placed.pop((r.idx, erid), None)
                if ent is None:
                    # a fenced epoch's completion surfacing late (the
                    # zombie kept stepping): drop it — the re-routed
                    # copy owns the finish.  Anything else is simply
                    # not cluster-placed (direct engine use)
                    if self._stale_expected.pop((r.idx, erid),
                                                None) is not None:
                        del r.engine.finished[erid]
                        self._drop_stale(r.idx, erid)
                    continue
                # collected: drain it from the engine so this scan
                # stays O(new finishes), not O(requests ever served)
                del r.engine.finished[erid]
                creq, stage, epoch = ent
                if epoch != self._fence[r.idx]:
                    # belt-and-braces: a placement from a fenced epoch
                    # that somehow survived the death sweep
                    self._drop_stale(r.idx, erid)
                    continue
                if stage == "prefill" and creq.handoff_pending:
                    # the decode stage owns the finish (staging always
                    # precedes the prefill finish: the stream callback
                    # runs inside the emit, before _maybe_finish)
                    continue
                if creq.done:
                    # already completed elsewhere: never finish twice
                    self._drop_stale(r.idx, erid)
                    continue
                # prefill stage without a staged handoff = eos on the
                # first sampled token: the request IS complete
                self._finish(creq, ereq)

    def _drop_stale(self, ridx: int, erid: int) -> None:
        self.protocol_log.append({"ev": "fence.stale_drop",
                                  "key": f"r{ridx}",
                                  "epoch": self._fence[ridx],
                                  "seq": protocol_seq()})
        self.counters["stale_completions_dropped"].inc()
        tr = self.tracer
        if tr.enabled:
            tr.instant("stale_completion_dropped", track="router",
                       ts=self._time(), replica=ridx, engine_req=erid,
                       fence_epoch=self._fence[ridx])

    def _finish(self, creq: ClusterRequest, ereq) -> None:
        creq.out_tokens = list(ereq.out_tokens)
        creq.finish_time = self._time()
        self.finished[creq.req_id] = creq
        if creq.replica is not None:
            # the completion was accepted under the replica's CURRENT
            # fence (_collect_finished dropped it otherwise) — record
            # the acceptance so the fence machine can audit it
            self.protocol_log.append(
                {"ev": "fence.complete", "key": f"r{creq.replica}",
                 "epoch": self._fence.get(creq.replica),
                 "replica": f"r{creq.replica}",
                 "seq": protocol_seq()})
        self.protocol_log.append({"ev": "req.finish",
                                  "key": f"creq:{creq.req_id}",
                                  "seq": protocol_seq()})
        self.counters["requests_completed"].inc()
        if creq.token_times:
            ttft = creq.token_times[0] - creq.submit_time
            self.histograms["ttft"].observe(ttft)
            self.histograms[f"ttft_{creq.slo_class}"].observe(ttft)
            for a, b in zip(creq.token_times, creq.token_times[1:]):
                self.histograms["tbt"].observe(b - a)
                self.histograms[f"tbt_{creq.slo_class}"].observe(b - a)
        self.histograms["request_latency"].observe(
            creq.finish_time - creq.submit_time)
        tr = self.tracer
        if tr.enabled:
            tr.instant("finish", track="router", ts=creq.finish_time,
                       req=creq.req_id, replica=creq.replica,
                       new_tokens=len(creq.out_tokens),
                       reroutes=creq.n_reroutes)

    # -- replica management --------------------------------------------------

    def kill_replica(self, idx: int) -> None:
        """Simulate (or administratively force) a replica death: stops
        its heartbeat and serving immediately; the next :meth:`step`
        re-routes its unfinished requests."""
        self.replicas[idx].kill()

    def readmit_replica(self, idx: int) -> None:
        """Explicitly re-admit a quarantined replica.  Quarantine is
        sticky by design: a TTL-expired replica that resumes
        heartbeating must NOT race its own replacement back into the
        candidate set — its fence epoch already advanced and its
        in-flight work was re-routed.  Re-admission aborts whatever
        stale engine state it still holds (pages freed, shared refs
        released, nothing collected), drains its stale finished set,
        restarts heartbeats, and only THEN clears the verdict; new
        placements are stamped with the current (post-death) epoch, so
        nothing it delivered from the fenced past can ever land."""
        r = self.replicas[idx]
        if r.alive:
            return
        for erid in r.engine.abort_all():
            self._stale_expected.pop((idx, erid), None)
        for erid in list(r.engine.finished):
            if self._stale_expected.pop((idx, erid), None) is not None:
                del r.engine.finished[erid]
                self._drop_stale(idx, erid)
        r.resurrect()
        self._dead_handled.discard(idx)
        self.counters["readmits"].inc()
        tr = self.tracer
        if tr.enabled:
            tr.instant("replica_readmitted", track="router",
                       ts=self._time(), replica=idx,
                       fence_epoch=self._fence[idx])

    def close(self) -> None:
        for r in self.replicas:
            r.close()
        if self.server is not None:
            self.server.stop()

    def __enter__(self) -> "EngineCluster":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- aggregate metrics ---------------------------------------------------

    def _replica_counter_total(self, r: Replica, key: str) -> float:
        """Cumulative counter across the replica's resets: a current
        value SMALLER than the last-seen one means ``reset_metrics``
        ran — bank the last-seen total and keep counting, so the
        cluster sum never double-counts nor loses a reset epoch.
        :meth:`step` snapshots every counter BEFORE the engines run
        (``_sync_counters``), so the monotonicity test can only miss a
        reset raced by same-step regrowth — and counters only grow
        inside the step, after the snapshot."""
        cur = float(r.engine.counters[key].value)
        acc = self._counter_acc[r.idx].setdefault(key, [0.0, 0.0])
        if cur < acc[1]:
            acc[0] += acc[1]
        acc[1] = cur
        return acc[0] + cur

    def _sync_counters(self) -> None:
        for r in self.replicas:
            for key in r.engine.counters:
                self._replica_counter_total(r, key)

    def metrics_summary(self) -> Dict[str, Any]:
        """Cluster-wide rollup: replica counters SUMMED (reset-robust),
        cluster-level latency histograms, per-replica hit rates."""
        out: Dict[str, Any] = {}
        counter_keys = list(self.replicas[0].engine.counters)
        for key in counter_keys:
            out[key] = sum(self._replica_counter_total(r, key)
                           for r in self.replicas)
        hits = out.get("prefix_cache_hits", 0.0)
        miss = out.get("prefix_cache_misses", 0.0)
        out["prefix_cache_hit_rate"] = hits / max(hits + miss, 1.0)
        for k, c in self.counters.items():
            out[f"cluster_{k}"] = c.value
        # failure-plane counters under their own names too (DESIGN.md
        # §18 / dashboards): requests_rerouted is the reroutes counter
        for k in ("replica_deaths", "handoff_retries",
                  "handoffs_restaged", "requests_shed",
                  "stale_completions_dropped",
                  "duplicate_deliveries_dropped", "readmits",
                  # SLO traffic plane (DESIGN.md §22)
                  *(f"shed_{c}" for c in SLO_CLASSES),
                  "class_inversions", "scale_ups", "scale_downs"):
            out[k] = self.counters[k].value
        out["requests_rerouted"] = self.counters["reroutes"].value
        out["replicas_active"] = self.gauges["replicas_active"].value
        for k, h in self.histograms.items():
            out[k] = h.summary()
        out["replicas"] = len(self.replicas)
        out["alive_replicas"] = sum(1 for r in self.replicas if r.alive)
        out["backlog"] = len(self._backlog)
        out["backlog_by_class"] = self._backlog.depth_by_class()
        out["pending_handoffs"] = len(self._pending_handoffs)
        out["shed"] = len(self.shed)
        out["per_replica"] = {
            f"r{r.idx}": {
                "alive": r.alive, "role": r.role,
                "queue_depth": r.queue_depth(),
                "outstanding_tokens": r.outstanding_tokens(),
                "cached_pages": r.engine.pool.cached_pages,
                "prefix_cache_hit_rate":
                    r.engine.metrics_summary()["prefix_cache_hit_rate"],
            } for r in self.replicas}
        out["handoff_payload_bytes"] = getattr(
            self.transport, "total_payload_bytes", 0)
        out["handoff_predicted_s"] = getattr(
            self.transport, "total_predicted_s", 0.0)
        return out

    def metrics_text(self) -> str:
        """One Prometheus exposition for the fleet: every replica's
        ``Engine.metrics_text()`` merged under a ``replica`` label
        (``utils.metrics.merge_prometheus_texts``), plus the cluster's
        own counters (routing, handoffs, and the failure plane —
        replica_deaths / handoff_retries / handoffs_restaged /
        requests_shed / stale_completions_dropped) and latency
        histograms under ``replica="router"``."""
        from ...utils.metrics import render_prometheus
        insts: Dict[str, Any] = {}
        insts.update(self.counters)
        insts.update(self.histograms)
        insts.update(self.gauges)
        texts = {f"r{r.idx}": r.engine.metrics_text()
                 for r in self.replicas}
        texts["router"] = render_prometheus(insts)
        return merge_prometheus_texts(texts, label="replica")
