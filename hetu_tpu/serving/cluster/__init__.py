"""Serving cluster plane: prefix-aware routing over N engine replicas
with disaggregated prefill/decode and priced KV-page streaming.

    from hetu_tpu.serving.cluster import EngineCluster

    # replicated: every replica serves prefill+decode, requests placed
    # on the replica whose prefix cache holds their longest prefix
    cl = EngineCluster(state, cfg, num_replicas=3, num_pages=32,
                       page_size=16, max_batch=4, chunk_size=16)
    cl.add_request(prompt_ids, max_new_tokens=32)
    outputs = cl.run()                 # {req_id: generated tokens}
    print(cl.metrics_text())           # one exposition, replica-labeled

    # disaggregated: prefill replicas stream KV pages to decode
    # replicas through a priced PageTransport
    cl = EngineCluster(state, cfg, num_replicas=2,
                       mode="disaggregated", num_prefill=1, ...)

See DESIGN.md §17: replica digests and the placement policy, handoff
pricing through the planner's alpha-beta formulas, heartbeat-driven
re-route on replica death, and why process-local hosts keep the CPU
path honest.  DESIGN.md §18 covers the fault plane layered on top:
seeded chaos injection (``EngineCluster(chaos=...)``,
``hetu_tpu.fault``), fencing epochs, backoff retries with deadlines,
destination-death re-staging, load shedding, and sticky quarantine
with explicit :meth:`EngineCluster.readmit_replica`.
"""
from .cluster import ClusterRequest, EngineCluster
from .replica import DECODE, PREFILL, UNIFIED, Replica
from .router import Router, digest_match_pages
from .transport import LocalPageTransport, PageTransport

__all__ = ["EngineCluster", "ClusterRequest", "Replica", "Router",
           "PageTransport", "LocalPageTransport", "digest_match_pages",
           "UNIFIED", "PREFILL", "DECODE"]
