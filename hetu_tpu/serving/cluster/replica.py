"""One serving replica: an Engine wrapped as a process-local "host".

The replica plane re-expresses the reference's multi-host bootstrap at
serving granularity: every replica registers with the cluster's
``rpc.CoordinatorServer`` exactly like a training worker registers with
the DeviceController (connect → rank, background heartbeat), so the
SAME liveness machinery that detects a dead training host detects a
dead serving replica — the router polls ``dead_ranks`` and re-routes a
dead replica's unfinished requests to survivors.  Process-local hosts
keep the CPU path honest (DESIGN.md §17): the control-plane protocol,
placement policy, and page-handoff pricing are all real; only the
engines happen to share one process.

Each replica exports:

* a **prefix-cache digest** (content-chained 64-bit page hashes,
  :meth:`PrefixCache.digest`) — the router's placement key;
* **load facts** — outstanding tokens (remaining prefill + decode) and
  queue depth for least-loaded placement and backpressure;
* its engine's metrics/trace planes, namespaced per replica by the
  cluster (``r{i}/…`` tracks, ``replica="r{i}"`` Prometheus label).
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..engine import Engine
from ..request import RUNNING

#: replica roles — ``unified`` serves prefill+decode (replicated mode);
#: disaggregated clusters split into dedicated ``prefill`` and
#: ``decode`` groups with KV pages streamed between them
UNIFIED = "unified"
PREFILL = "prefill"
DECODE = "decode"


class Replica:
    """An engine + its coordinator identity + liveness state."""

    def __init__(self, idx: int, engine: Engine, role: str = UNIFIED,
                 client=None, heartbeat_interval: float = 0.5):
        self.idx = int(idx)
        self.engine = engine
        self.role = role
        self.client = client
        self.rank: Optional[int] = None
        self._hb_stop = None
        self._hb_interval = float(heartbeat_interval)
        # chaos straggler window: the cluster skips this replica's
        # engine beats while its step counter is below slow_until
        self.slow_until: float = 0.0
        # ``alive`` is the cluster's health VERDICT (flipped by the
        # coordinator's missed-heartbeat detection, or directly when no
        # coordinator runs); ``serving`` is the simulated process state
        # — kill() stops serving immediately, but with a coordinator
        # the verdict only lands once the TTL lapses, exactly like a
        # real crash
        self.alive = True
        self.serving = True
        # autoscaler drain intent: a draining replica serves what it
        # already owns but takes no new placements (router skips it);
        # once empty the controller fences it through kill()
        self.draining = False
        self._digest = None      # (cache version, digest) memo
        if client is not None:
            self.rank = client.connect()
            self._hb_stop = client.start_heartbeat_thread(
                interval=heartbeat_interval)

    # -- placement facts -----------------------------------------------------

    def digest(self) -> Dict[int, int]:
        """The live prefix-cache digest ({chain_hash: pages}); empty
        when the engine runs cache-off.  Memoized on the cache's
        version stamp — the router probes every replica per placement,
        and the tree only re-hashes when the cache actually changed."""
        pc = self.engine.prefix_cache
        if pc is None:
            return {}
        ver = pc.version
        if self._digest is None or self._digest[0] != ver:
            self._digest = (ver, pc.digest())
        return self._digest[1]

    def outstanding_tokens(self) -> int:
        """Token-work this replica still owes: remaining prefill +
        remaining decode over its queue and running set — the
        least-loaded placement metric (a queue of long prompts weighs
        more than the same count of short ones)."""
        total = 0
        for req in self._all_requests():
            total += max(0, len(req.tokens) - req.pos)         # prefill
            total += max(0, req.max_new_tokens - req.n_generated)
        return total

    def queue_depth(self) -> int:
        """Requests on this replica (queued + running) — the
        backpressure gate's unit."""
        return len(self.engine.queue) + len(self.engine.running)

    def _all_requests(self) -> List:
        out = list(self.engine.queue.requests())
        out.extend(r for r in self.engine.running if r.state == RUNNING)
        return out

    # -- liveness ------------------------------------------------------------

    def kill(self) -> None:
        """Simulate a replica crash: heartbeats and serving stop NOW;
        the death *verdict* arrives through the coordinator once the
        heartbeat TTL lapses (the cluster then re-routes this replica's
        unfinished requests) — the same two-step reality a crashed
        remote host has.  Without a coordinator the cluster detects the
        stopped ``serving`` flag directly."""
        if self._hb_stop is not None:
            self._hb_stop.set()
        self.serving = False

    def pause_heartbeat(self) -> None:
        """The zombie seam: heartbeats stall while the engine keeps
        stepping — the coordinator's TTL verdict will land even though
        the 'process' is alive, and the cluster must fence it."""
        if self._hb_stop is not None:
            self._hb_stop.set()
            self._hb_stop = None

    def resume_heartbeat(self) -> None:
        """A zombie's heartbeats return.  Deliberately does NOT clear
        the quarantine: a replica the cluster already declared dead
        stays fenced until :meth:`EngineCluster.readmit_replica` — a
        revived replica racing its own replacement is the
        double-delivery hazard the fence exists for."""
        if self.client is not None and self._hb_stop is None:
            self._hb_stop = self.client.start_heartbeat_thread(
                interval=self._hb_interval)
            try:
                self.client.heartbeat()   # refresh the verdict input NOW
            except Exception:
                pass

    def resurrect(self) -> None:
        """Operator re-admission (the cluster aborts the stale engine
        state first): serving and heartbeats restart, the liveness
        verdict resets."""
        self.serving = True
        self.alive = True
        self.draining = False
        self.slow_until = 0.0
        self.resume_heartbeat()

    def close(self) -> None:
        if self._hb_stop is not None:
            self._hb_stop.set()
        if self.client is not None:
            try:
                self.client.exit()
                self.client.close()
            except Exception:
                pass
