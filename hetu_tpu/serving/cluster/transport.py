"""KV-page streaming between replica pools: the disaggregation wire.

Disaggregated serving (hydraulis-style, SURVEY.md) runs prefill and
decode on DIFFERENT engines: a prefill replica computes the prompt's KV
pages, then the pages move to a decode replica's pool and generation
resumes there.  :class:`PageTransport` is the interface that move goes
through; two phases, matching how a real wire behaves:

* :meth:`~PageTransport.extract` — serialize the source pages off the
  source pool (host staging here; a DMA ring or RDMA read on hardware).
  Extraction happens the instant the prefill finishes, while the pages
  are still owned — the source engine is then free to retire them into
  its prefix cache.
* :meth:`~PageTransport.inject` — land the staged pages into
  already-allocated destination pages and record the handoff.

:class:`LocalPageTransport` is the process-local implementation: it
really copies page contents between pools (bit-for-bit — the decode
replica reads KV identical to what a monolithic engine would hold, the
cluster tests assert temp-0 output equality), while the WIRE cost the
copy stands in for is priced through the planner's own alpha-beta
formulas (:func:`hetu_tpu.planner.cost_model.collective_time`, p2p/
ppermute rate — the same single implementation the step-time linter and
the DP solver use).  Every handoff therefore carries a **priced edge
claim**: a ``CommEdge``-shaped dict plus the predicted seconds on the
modeled interconnect.  The ``kv-handoff-unpriced`` analysis rule
(``analysis/rules.py``) fails CI for any cross-replica page move whose
record lacks that claim — the CPU-honest gate that keeps the
disaggregation design priced before TPU hardware exists.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..kv_pool import PagedKVPool, protocol_seq


class PageTransport:
    """Interface for moving KV pages between replica pools.

    Implementations must be bit-exact (the disaggregation correctness
    contract rides on it) and must append a priced handoff record per
    :meth:`inject` — see :class:`LocalPageTransport` for the record
    schema the ``kv-handoff-unpriced`` rule audits."""

    def extract(self, src_pool: PagedKVPool,
                src_pages: Sequence[int]) -> Any:
        raise NotImplementedError

    def inject(self, dst_pool: PagedKVPool, staged: Any,
               dst_pages: Sequence[int], src_replica: int = -1,
               dst_replica: int = -1,
               epoch: Optional[int] = None) -> Dict[str, Any]:
        raise NotImplementedError

    def records_for(self, dst_replica: int) -> List[Dict[str, Any]]:
        raise NotImplementedError


class LocalPageTransport(PageTransport):
    """Process-local page copy with alpha-beta wire pricing.

    ``cluster_spec`` (a :class:`~hetu_tpu.planner.cost_model.ClusterSpec`)
    models the interconnect the handoff would cross on hardware; the
    predicted seconds per handoff use the p2p/ppermute rate — a
    prefill→decode page stream is a point-to-point send, not a
    collective.  The measured host-copy wall time rides along in the
    record so the obs plane can reconcile prediction vs (CPU) reality.
    """

    def __init__(self, cluster_spec=None):
        if cluster_spec is None:
            from ...planner.cost_model import ClusterSpec
            cluster_spec = ClusterSpec()
        self.cluster_spec = cluster_spec
        self.records: List[Dict[str, Any]] = []
        # wire.extract events ``(seq, src_pages)`` for the protocol
        # verifier: extraction reads the source pages, so a page that
        # was already reclaimed at extract time ships garbage KV
        self.extract_log: List[Any] = []

    # -- the two wire phases -------------------------------------------------

    def extract(self, src_pool: PagedKVPool,
                src_pages: Sequence[int]) -> Dict[str, Any]:
        """Pull ``src_pages`` off the source pool into host staging
        buffers (one ``[n, page, kvh, hd]`` array per layer per k/v).
        ``np.asarray`` forces the device values — the staging copy is
        taken NOW, so the source engine may free/retire the pages the
        moment this returns."""
        idx = np.asarray(list(src_pages), np.int32)
        self.extract_log.append((protocol_seq(),
                                 tuple(int(p) for p in idx)))
        k = [np.asarray(p[idx]) for p in src_pool.k_pages]
        v = [np.asarray(p[idx]) for p in src_pool.v_pages]
        return {"k": k, "v": v, "n_pages": len(idx),
                # page_bytes derives from kv_pool.page_shape_bytes, so
                # a latent/quantized pool's smaller pages are priced at
                # their true wire size automatically
                "payload_bytes": len(idx) * src_pool.page_bytes,
                "layout": src_pool.layout_tag}

    def inject(self, dst_pool: PagedKVPool, staged: Dict[str, Any],
               dst_pages: Sequence[int], src_replica: int = -1,
               dst_replica: int = -1,
               epoch: Optional[int] = None) -> Dict[str, Any]:
        """Land staged pages into ``dst_pages`` (already allocated in
        ``dst_pool``) and append the priced handoff record.  ``epoch``
        is the fence token: the cluster's per-handoff staging epoch
        (fresh on every re-stage).  It deliberately has NO usable
        default — a call site that omits it records ``epoch: None``
        and the ``unfenced-handoff`` rule fails CI, which is exactly
        how a regression to the unfenced PR-11 signature gets
        caught."""
        idx = jnp.asarray(list(dst_pages), jnp.int32)
        if int(idx.shape[0]) != int(staged["n_pages"]):
            raise ValueError(
                f"staged {staged['n_pages']} pages but got "
                f"{int(idx.shape[0])} destination pages")
        src_layout = staged.get("layout")
        if src_layout is not None and \
                src_layout != dst_pool.layout_tag:
            # bit-exactness is the handoff contract: page bytes from a
            # different layout (latent vs full-head, other quant/
            # geometry) are not the destination's KV, even when shapes
            # happen to broadcast
            raise ValueError(
                f"page layout mismatch: staged {src_layout} vs "
                f"destination pool {dst_pool.layout_tag}")
        t0 = time.perf_counter()
        new_k = tuple(p.at[idx].set(jnp.asarray(s))
                      for p, s in zip(dst_pool.k_pages, staged["k"]))
        new_v = tuple(p.at[idx].set(jnp.asarray(s))
                      for p, s in zip(dst_pool.v_pages, staged["v"]))
        dst_pool.set_pages(new_k, new_v)
        wall = time.perf_counter() - t0
        rec = self._price(int(staged["n_pages"]),
                          int(staged["payload_bytes"]),
                          src_replica, dst_replica, wall)
        rec["epoch"] = None if epoch is None else int(epoch)
        rec["seq"] = protocol_seq()
        self.records.append(rec)
        return rec

    # -- pricing -------------------------------------------------------------

    def _price(self, n_pages: int, payload_bytes: int, src: int,
               dst: int, wall_s: float) -> Dict[str, Any]:
        """The priced edge claim: a CommEdge-shaped dict (the
        ``analysis/edges`` vocabulary — kind/payload/count/tag) plus
        the alpha-beta predicted seconds through the ONE
        ``collective_time`` implementation the planner and the
        step-time linter share."""
        from ...planner.cost_model import collective_time
        edge = {"kind": "ppermute", "tensor": "kv_pages",
                "producer": f"prefill r{src}",
                "consumer": f"decode r{dst}",
                "src_spec": f"pool@r{src}", "dst_spec": f"pool@r{dst}",
                "axes": ("replica",), "payload_bytes": payload_bytes,
                "count": 1, "tag": "kv_handoff", "origin": "declared"}
        predicted_s = collective_time("ppermute", float(payload_bytes),
                                      2, self.cluster_spec)
        return {"src": int(src), "dst": int(dst), "pages": n_pages,
                "payload_bytes": payload_bytes, "edge": edge,
                "predicted_s": float(predicted_s),
                "wall_s": float(wall_s)}

    def records_for(self, dst_replica: int) -> List[Dict[str, Any]]:
        """The handoff records landing on ``dst_replica`` — the decode
        engine's registration exposes exactly these to the
        ``kv-handoff-unpriced`` rule."""
        return [r for r in self.records if r["dst"] == int(dst_replica)]

    @property
    def total_payload_bytes(self) -> int:
        return sum(r["payload_bytes"] for r in self.records)

    @property
    def total_predicted_s(self) -> float:
        return sum(r["predicted_s"] for r in self.records)
