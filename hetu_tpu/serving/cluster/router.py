"""Prefix-aware request router over N engine replicas.

Placement policy (the vLLM/SGLang cache-aware trick, riding PR 7's
chained page hashes):

1. **Longest cached prefix** — every replica exports its live prefix
   cache as a compact content-chained digest
   (:meth:`hetu_tpu.serving.prefix_cache.PrefixCache.digest`); the
   router hashes the candidate request's page-aligned prefixes the same
   way (:func:`~hetu_tpu.serving.prefix_cache.token_chain_hashes`) and
   places it on the replica holding the deepest match — that replica
   skips the matched prefill entirely (copy-on-write attach), which is
   where the TTFT win comes from.
2. **Least loaded** — no replica holds any prefix (or the policy is
   ``"load"``): place on the replica with the fewest outstanding
   tokens (remaining prefill + remaining decode over its queue and
   running set).  Ties break on replica index for determinism.
3. **Backpressure** — replicas at ``max_queue_depth`` (queued + running
   requests) are not candidates; when every live replica is saturated
   the request stays in the cluster backlog and the router re-tries
   next step.  A ``"random"`` policy (seeded) exists as the bench
   baseline prefix-aware routing must beat.

Every placement emits a tracer instant on the ``router`` track carrying
the decision *and its reason* (matched pages per replica, outstanding
tokens, queue depths), so the merged Perfetto timeline shows why each
request landed where it did next to the per-replica engine rows.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..prefix_cache import token_chain_hashes

POLICIES = ("prefix", "load", "random")


def match_pages_from_hashes(hashes: Sequence[int],
                            digest: Dict[int, int]) -> int:
    """How many leading FULL pages a replica digest holds, given the
    request's precomputed chain hashes: walk page by page and stop at
    the first miss (a deeper entry without its parent chain is a
    different prefix — the chain property makes the early stop
    exact)."""
    matched = 0
    for i, h in enumerate(hashes):
        if digest.get(h) == i + 1:
            matched = i + 1
        else:
            break
    return matched


def digest_match_pages(tokens: Sequence[int], page_size: int,
                       digest: Dict[int, int],
                       layout: Sequence[int] = ()) -> int:
    """:func:`match_pages_from_hashes` over freshly-hashed ``tokens``
    (the router hashes once per placement and probes every replica
    with the same list).  ``layout`` must be the replica pool's
    ``layout_tag`` — digests are ROOT-salted by layout, so unsalted
    hashes never match a live digest."""
    return match_pages_from_hashes(
        token_chain_hashes(tokens, page_size, layout=layout), digest)


class Router:
    """Stateless-per-decision placement over live replicas; the cluster
    owns the backlog and calls :meth:`place` per ready request."""

    def __init__(self, policy: str = "prefix",
                 max_queue_depth: Optional[int] = None,
                 seed: int = 0, tracer=None, time_fn=None):
        if policy not in POLICIES:
            raise ValueError(f"unknown policy {policy!r}; have {POLICIES}")
        self.policy = policy
        self.max_queue_depth = max_queue_depth
        self._rng = np.random.RandomState(seed)
        self._tracer = tracer
        self._time = time_fn or (lambda: 0.0)
        self.decisions = 0

    # -- candidate filtering -------------------------------------------------

    def candidates(self, replicas: List[Any]) -> List[Any]:
        """Live replicas with queue headroom (the backpressure gate)."""
        out = []
        for r in replicas:
            if not r.alive:
                continue
            if getattr(r, "draining", False):
                continue       # autoscaler drain: no new placements
            if self.max_queue_depth is not None \
                    and r.queue_depth() >= self.max_queue_depth:
                continue
            out.append(r)
        return out

    # -- placement -----------------------------------------------------------

    def place(self, creq, replicas: List[Any]) -> Optional[Any]:
        """Choose a replica for ``creq`` (a cluster request), or None
        when every live replica is backpressured.  Emits the routing
        decision as a ``route`` tracer instant with the full reasoning
        payload."""
        cands = self.candidates(replicas)
        if not cands:
            return None
        matches: Dict[int, int] = {}
        if self.policy == "random":
            chosen = cands[int(self._rng.randint(len(cands)))]
            reason = "random"
        else:
            if self.policy == "prefix":
                # hash once per distinct (page_size, layout): a mixed
                # fleet — latent next to full-head replicas, or mixed
                # quantization — probes each replica with hashes salted
                # for ITS layout, so a cross-layout digest can never
                # produce a phantom prefix hit
                groups: Dict[Tuple[Any, ...], List[Any]] = {}
                for r in cands:
                    pool = r.engine.pool
                    groups.setdefault(
                        (pool.page_size, pool.layout_tag), []).append(r)
                for (page_size, tag), rs in groups.items():
                    hashes = token_chain_hashes(creq.prompt, page_size,
                                                layout=tag)
                    for r in rs:
                        matches[r.idx] = match_pages_from_hashes(
                            hashes, r.digest())
            best_depth = max(matches.values()) if matches else 0
            if best_depth > 0:
                top = [r for r in cands if matches[r.idx] == best_depth]
                chosen = min(top, key=lambda r: (r.outstanding_tokens(),
                                                 r.idx))
                reason = "prefix_hit"
            else:
                chosen = min(cands, key=lambda r: (r.outstanding_tokens(),
                                                   r.idx))
                reason = "least_loaded"
        self.decisions += 1
        tr = self._tracer
        if tr is not None and tr.enabled:
            tr.instant(
                "route", track="router", ts=self._time(),
                req=creq.req_id, replica=chosen.idx, reason=reason,
                matched_pages=matches.get(chosen.idx, 0),
                prompt_tokens=len(creq.prompt),
                per_replica_match={f"r{i}": m for i, m in matches.items()},
                per_replica_load={f"r{r.idx}": r.outstanding_tokens()
                                  for r in cands},
                per_replica_queue={f"r{r.idx}": r.queue_depth()
                                   for r in cands})
        return chosen

    def note_reroute(self, creq, dead_idx: int) -> None:
        """Trace a death-triggered re-route: the cluster pulls the
        request back into the backlog and the next :meth:`place` call
        decides its new home."""
        tr = self._tracer
        if tr is not None and tr.enabled:
            tr.instant("reroute", track="router", ts=self._time(),
                       req=creq.req_id, dead_replica=dead_idx)
