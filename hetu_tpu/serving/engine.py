"""Continuous-batching inference engine over the paged KV pool.

The serving half of the roadmap: where ``models.generate`` runs ONE
static batch to completion, the engine runs an admission loop — every
``step()`` it admits arrived requests, packs ALL live work (prefill
chunks + decode tokens) into one ragged token batch, runs the single
**unified executable** (``serving/decode.build_unified_step_fn``), and
streams each emitted token to its request, retiring/evicting under the
page budget.  Late-arriving requests join mid-flight; short requests
leave without waiting for long ones; long prompts prefill in
``chunk_size`` slices so they never stall running decodes.

One executable, compiled once (DESIGN.md §12): there is no prefill
bucket grid and no per-batch-size decode program — ``compile_count``
is 1 regardless of traffic, asserted by the CI recompile guard.

Determinism contract: at temperature 0 every request's output equals a
solo ``generate()`` run — batching, paging, chunked prefill, admission
order, and even preemption (recompute eviction) change WHEN a token is
computed, never WHAT it is.  Sampled modes (temperature / top-k /
top-p) run ON DEVICE keyed by ``(seed, position)``, so replays are
deterministic too and the engine only ever fetches ``[rows]`` int32 —
``host_logit_fetches`` stays 0 on any traffic mix.

Speculative decoding (``serving/spec.py``, DESIGN.md §20, opt-in via
``Engine(spec=SpecConfig(...))``): a shallow draft model proposes ``k``
greedy tokens per decode-ready request each step; the scheduler packs
them as dedicated ``k + 1``-token ragged VERIFY rows (structurally
prefill chunks) and the unified executable's on-device accept head
returns the longest-accepted-prefix length plus a bonus token per row
— up to ``k + 1`` tokens committed per call, temp-0 output still
bit-for-bit, ``host_logit_fetches`` still 0, and the draft's three
fixed-shape programs join the compile-count guard.

Prefix reuse (``serving/prefix_cache.py``, on by default): finished
requests' fully-written pages enter a chained-hash index; a new request
whose page-aligned token prefix is cached attaches those pages
read-only (copy-on-write — its KV write plan starts past them) and
prefills only the uncached suffix.  When the pool runs dry, an LRU
sweep over refcount-0 cached pages reclaims space BEFORE recompute
preemption.  Cache-hit and cache-cold runs are bit-for-bit identical
at temperature 0: the kernel reads identical page contents either way.

Observability (utils/metrics.py instruments): counters
``tokens_generated``/``prefill_tokens``/``requests_completed``/
``preemptions``/``decode_steps``/``prefill_chunks``/``step_calls``/
``prefix_cache_hits``/``prefix_cache_misses``/
``prefix_cache_tokens_saved``/``prefix_cache_evictions``,
gauges ``batch_occupancy``/``page_utilization``/``queue_depth``,
histograms ``ttft``/``tbt``/``tpot``/``request_latency`` (ttft/tbt are
Prometheus-bucketed for per-stage latency dashboards) — with the no-op
fallback when disabled.  ``metrics_summary()`` adds the derived
``prefix_cache_hit_rate`` and the live ``prefix_cache_pages`` count.
``metrics_text()`` renders everything as Prometheus text exposition.

Trace plane (hetu_tpu/obs, DESIGN.md §15): under an installed tracer
every request gets a complete lifecycle timeline on its own track —
``enqueue`` instant, ``queued``/``running`` state spans that tile
[submit, finish] gaplessly across preemptions, ``admit`` (page
accounting), ``prefix_cache_hit``, per-chunk ``prefill_chunk`` spans
with their token-budget slice, per-token instants, ``preempt`` and
``finish`` — plus the scheduler's ``pack`` decision per step and a
``unified_step`` span per executable call carrying the analysis plane's
predicted wire bytes / peak HBM for reconciliation.  The default tracer
is the shared no-op: every emission site guards on ``tracer.enabled``.
"""
from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.generate import _Params
from ..models.gpt import GPTConfig
from ..obs.tracer import get_tracer
from ..utils.metrics import make_instrument, render_prometheus
from .decode import build_unified_step_fn
from .kv_pool import TRASH_PAGE, PagedKVPool, protocol_seq
from .prefix_cache import PrefixCache
from .request import FINISHED, RUNNING, Request, RequestQueue
from .scheduler import Scheduler
from .spec import SpecConfig, SpecDecoder

# default Prometheus-style latency bounds (seconds) for ttft/tbt; tests
# and benches with a synthetic clock pass their own
DEFAULT_LATENCY_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                           10.0)


class Engine:
    def __init__(self, state: Dict[str, Any], cfg: GPTConfig,
                 num_pages: int = 64, page_size: int = 64,
                 max_batch: int = 8, max_model_len: Optional[int] = None,
                 chunk_size: Optional[int] = 64, prefill_rows: int = 1,
                 mesh=None, use_kernel: bool = False,
                 metrics: bool = True,
                 latency_buckets: Optional[Sequence[float]] = None,
                 time_fn: Optional[Callable[[], float]] = None,
                 name: str = "serving", analysis_tap: bool = True,
                 prefix_cache: bool = True, debug: bool = False,
                 tracer=None, step_fn: Optional[Callable] = None,
                 spec: Optional[SpecConfig] = None,
                 page_quant: Optional[str] = None,
                 host_tier=None):
        self.cfg = cfg
        self.name = name
        # runtime trace plane (hetu_tpu/obs): None follows the ambient
        # tracer (obs.install_tracer / obs.trace), which defaults to the
        # shared no-op — every emission site below guards on
        # ``tr.enabled`` so disabled tracing stays out of the hot loop
        self._tracer = tracer
        self._pred_attrs: Optional[Dict[str, Any]] = None
        # ring buffer of recent packed-step layouts (rows + page tables),
        # consumed by the trash-page-write lint (hetu_tpu/analysis)
        self.tap: Optional[deque] = deque(maxlen=128) if analysis_tap \
            else None
        # engine-plane request-lifecycle events (req.queued / req.admit
        # / req.finish) for the analysis event stream.  Preempt/rewind
        # ride the tap and adopt rides the cluster's adoption records,
        # so every transition is emitted by exactly one plane.
        self.protocol_log: List[Dict[str, Any]] = []
        # a new engine owns its analysis namespace: stale handles from a
        # discarded same-name engine would otherwise mix dead pool
        # snapshots into analyze_registered(name) — and pin that
        # engine's KV pool in the process-global registry forever
        from ..graph.graph import clear_executables
        clear_executables(f"{self.name}/")
        self.params = _Params(state, cfg).s      # normalized key view
        if max_model_len is None:
            max_model_len = (num_pages - 1) * page_size
            if cfg.position == "learned":
                # never past the wpe table: an out-of-range position
                # gather clamps silently to the last row
                max_model_len = min(max_model_len, cfg.max_seq_len)
        self.max_model_len = int(max_model_len)
        self.max_pages_per_seq = -(-self.max_model_len // page_size)
        dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
        self.debug = bool(debug)
        # MLA latent layout (DESIGN.md §21): pages hold ONE compressed
        # [latent_dim] stream per token (plus the shared rope stream /
        # quant-scale sidecar) instead of kv_heads x head_dim — the
        # whole serving stack above the pool is layout-generic
        if page_quant is not None and not cfg.is_mla:
            raise ValueError("page_quant requires an MLA config "
                             "(kv_latent_dim set)")
        self.page_quant = page_quant
        self.pool = PagedKVPool(cfg.num_layers, num_pages, page_size,
                                cfg.kv_heads, cfg.head_dim, dtype,
                                mesh=mesh, debug=debug,
                                latent_dim=cfg.kv_latent_dim,
                                rope_dim=cfg.rope_dim if cfg.is_mla
                                else 0,
                                quant=page_quant)
        # copy-on-write prefix reuse: finished requests' full pages are
        # indexed by chained token hash; _start attaches the longest
        # cached prefix so prefill skips straight to the cached boundary
        self.prefix_cache: Optional[PrefixCache] = \
            PrefixCache(self.pool) if prefix_cache else None
        if self.prefix_cache is not None:
            self.pool.set_reclaim(self._reclaim_cached_pages)
        # chunk_size=None: whole-prompt chunks (bounded by what a
        # sequence can ever hold) — the "infinite chunk" configuration
        chunk = self.max_model_len if chunk_size is None \
            else min(int(chunk_size), self.max_model_len)
        self.scheduler = Scheduler(self.pool, max_batch=max_batch,
                                   chunk=chunk,
                                   prefill_rows=prefill_rows,
                                   prefix_cache=self.prefix_cache)
        self.use_kernel = bool(use_kernel)
        self.queue = RequestQueue()
        self.running: List[Request] = []
        self.finished: Dict[int, Request] = {}
        self._time_fn = time_fn or time.monotonic
        self._next_id = 0
        self.steps = 0
        self._calls = 0
        # host logits round-trips actually paid: sampling (greedy AND
        # temperature/top-k/top-p) runs on device and moves [rows]
        # int32s per step — this stays 0 on every traffic mix
        self.host_logit_fetches = 0
        m = metrics
        self.counters = {k: make_instrument("counter", k, m) for k in
                         ("tokens_generated", "prefill_tokens",
                          "requests_completed", "preemptions",
                          "decode_steps", "prefill_chunks",
                          "step_calls",
                          # prefix cache: hits/misses count request
                          # starts with/without a cached prefix;
                          # tokens_saved = prefill tokens skipped;
                          # evictions = cached pages LRU-reclaimed
                          "prefix_cache_hits", "prefix_cache_misses",
                          "prefix_cache_tokens_saved",
                          "prefix_cache_evictions",
                          # speculative decoding: draft tokens proposed
                          # / accepted (committed), bonus tokens riding
                          # verify rows (always present so the cluster
                          # Prometheus merge sees a uniform schema;
                          # zero on non-spec engines)
                          "spec_proposed", "spec_accepted",
                          "spec_bonus_tokens",
                          # SLO traffic plane (serving/slo): per-class
                          # admission/preemption counts and the host
                          # KV tier's page moves — always present (zero
                          # without a host tier / on default-class
                          # traffic) so the cluster merge stays uniform
                          "admitted_interactive", "admitted_standard",
                          "admitted_batch", "preempted_interactive",
                          "preempted_standard", "preempted_batch",
                          "host_evictions", "host_hits",
                          "host_refetch_bytes")}
        self.gauges = {k: make_instrument("gauge", k, m) for k in
                       ("batch_occupancy", "page_utilization",
                        "queue_depth",
                        # KV footprint (satellite of DESIGN.md §21):
                        # bytes of page storage per cached token —
                        # static per layout — and bytes held by
                        # currently-allocated pages; both derive from
                        # kv_pool.page_shape_bytes so the lint /
                        # transport / metrics planes can never disagree
                        "kv_bytes_per_token", "kv_bytes_in_use",
                        # live host-tier page count (0 without one)
                        "host_pages")}
        self.gauges["kv_bytes_per_token"].set(
            self.pool.kv_bytes_per_token)
        lb = list(latency_buckets if latency_buckets is not None
                  else DEFAULT_LATENCY_BUCKETS)
        self.histograms = {
            "ttft": make_instrument("histogram", "ttft", m, buckets=lb),
            "tbt": make_instrument("histogram", "tbt", m, buckets=lb),
            "tpot": make_instrument("histogram", "tpot", m),
            "request_latency": make_instrument("histogram",
                                               "request_latency", m),
        }
        # host-RAM tier for cold prefix-cache pages (serving/slo,
        # DESIGN.md §22): pass a HostTier instance, True (defaults),
        # or an int page capacity.  Evicted refcount-0 cached pages
        # stage to host instead of dropping; a chain-hash hit refetches
        # them bit-exact through PageTransport.inject, priced.
        self.host_tier = None
        if host_tier:
            if self.prefix_cache is None:
                raise ValueError("host_tier requires prefix_cache=True")
            from .slo.host_tier import HostTier
            ht = host_tier if isinstance(host_tier, HostTier) else (
                HostTier() if host_tier is True
                else HostTier(int(host_tier)))
            ht.bind(self.pool, self.prefix_cache,
                    counters=self.counters, gauges=self.gauges,
                    tracer_fn=lambda: self.tracer,
                    time_fn=self._time_fn)
            self.host_tier = ht
        # speculative decoding (serving/spec.py, DESIGN.md §20): a
        # draft model proposes spec_k greedy tokens per decode-ready
        # request; the scheduler packs them as verify rows and the
        # unified executable's on-device accept head returns
        # accepted_len + a bonus token per row
        self.spec: Optional[SpecDecoder] = None
        self.spec_k = 0
        if spec is not None:
            self.spec_k = int(spec.k)
            self.spec = SpecDecoder(spec, cfg, self.scheduler.max_batch,
                                    self.max_model_len, self.spec_k)
            self.scheduler.verify_slots = self.scheduler.max_batch
            self.scheduler.spec_width = self.spec_k + 1
        # THE executable: fixed (max_seqs, chunk, prefill_rows) shapes,
        # compiled exactly once — no bucket grid, no per-request prefill.
        # ``step_fn`` lets N identically-shaped engines (cluster
        # replicas) share ONE jitted program: the jit cache keys on
        # argument shapes, so the whole replica fleet compiles once.
        self._compiled: Dict[str, Callable] = {
            "unified": step_fn if step_fn is not None
            else build_unified_step_fn(
                cfg, self.scheduler.max_batch, self.scheduler.chunk,
                self.scheduler.prefill_rows, self.max_pages_per_seq,
                page_size, use_kernel=self.use_kernel,
                spec_k=self.spec_k, page_quant=page_quant)}
        if self.spec is not None:
            # the draft programs join the jit-cache compile guard: a
            # silent draft retrace trips compile_count just like a
            # unified-step retrace would
            self._compiled.update(self.spec.compiled)
        # static packed-layout constants: decode slots, prefill chunk
        # slots, then (spec mode) one (k+1)-wide verify slot per
        # decode-capable request
        s, r, ck = (self.scheduler.max_batch, self.scheduler.prefill_rows,
                    self.scheduler.chunk)
        vr = s if self.spec is not None else 0
        vk = self.spec_k + 1
        self.n_rows = s + r + vr
        self.n_tokens = s + r * ck + vr * vk
        cu = np.concatenate([np.arange(s, dtype=np.int32),
                             s + ck * np.arange(r + 1, dtype=np.int32)])
        if vr:
            base = s + r * ck
            cu = np.concatenate([cu[:-1],
                                 base + vk * np.arange(vr + 1,
                                                       dtype=np.int32)])
        self._cu_q = cu                       # [rows + 1], layout-fixed
        self._register_for_analysis()

    # -- submission ----------------------------------------------------------

    def add_request(self, prompt_ids: Sequence[int], max_new_tokens: int,
                    temperature: float = 0.0, top_k: int = 0,
                    top_p: float = 0.0, seed: int = 0,
                    eos_token_id: Optional[int] = None,
                    arrival_time: Optional[float] = None,
                    stream_cb: Optional[Callable] = None,
                    slo_class: str = "standard") -> Request:
        prompt = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        total = len(prompt) + int(max_new_tokens)
        if total > self.max_model_len:
            raise ValueError(
                f"prompt+max_new_tokens = {total} exceeds max_model_len "
                f"{self.max_model_len}")
        if self.pool.pages_for(total) > self.pool.num_usable:
            raise ValueError(
                f"request needs {self.pool.pages_for(total)} pages; pool "
                f"has {self.pool.num_usable} — it could never run")
        now = self._now()
        req = Request(req_id=self._next_id, prompt=prompt,
                      max_new_tokens=int(max_new_tokens),
                      temperature=float(temperature), top_k=int(top_k),
                      top_p=float(top_p), seed=int(seed),
                      eos_token_id=eos_token_id,
                      arrival_time=now if arrival_time is None
                      else float(arrival_time), stream_cb=stream_cb,
                      slo_class=slo_class)
        req.submit_time = max(now, req.arrival_time)
        req.trace_t0 = req.submit_time      # queued segment opens here
        self._next_id += 1
        self.queue.push(req)
        self.protocol_log.append({"ev": "req.queued",
                                  "key": f"req:{req.req_id}",
                                  "seq": protocol_seq()})
        tr = self.tracer
        if tr.enabled:
            tr.instant("enqueue", track=f"req {req.req_id}",
                       ts=req.submit_time, req=req.req_id,
                       prompt_tokens=len(prompt),
                       max_new_tokens=int(max_new_tokens),
                       slo_class=req.slo_class,
                       queue_depth=len(self.queue))
        return req

    def adopt_request(self, prompt: Sequence[int],
                      generated: Sequence[int], max_new_tokens: int,
                      pages: Optional[Sequence[int]] = None,
                      pos: int = 0, temperature: float = 0.0,
                      top_k: int = 0, top_p: float = 0.0, seed: int = 0,
                      eos_token_id: Optional[int] = None,
                      arrival_time: Optional[float] = None,
                      stream_cb: Optional[Callable] = None,
                      slo_class: str = "standard") -> Request:
        """Admit a MID-FLIGHT request: ``generated`` tokens already
        sampled elsewhere and (optionally) ``pages`` in THIS engine's
        pool already holding KV for positions ``[0, pos)`` — the
        disaggregated prefill→decode handoff entry point
        (``serving/cluster``): a prefill replica finishes the prompt,
        the transport copies its pages into this pool, and decode
        resumes here from ``pos`` without recomputing the prefill.

        The adopted request rides the normal admission path (WAITING →
        ``_start`` grants any additional pages → packed steps), so
        backpressure, preemption and tracing all behave normally; a
        preemption falls back to local re-prefill of the full
        accumulated sequence, which reproduces the identical
        continuation at temperature 0 (and under the position-keyed
        sampler for every mode).  Sampling params must match the
        original request or the continuation diverges by design."""
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        generated = [int(t) for t in
                     np.asarray(generated, np.int64).reshape(-1)]
        if not prompt:
            raise ValueError("empty prompt")
        if len(generated) >= max_new_tokens:
            raise ValueError("request already finished: "
                             f"{len(generated)} >= {max_new_tokens}")
        total = len(prompt) + int(max_new_tokens)
        if total > self.max_model_len:
            raise ValueError(
                f"prompt+max_new_tokens = {total} exceeds max_model_len "
                f"{self.max_model_len}")
        if self.pool.pages_for(total) > self.pool.num_usable:
            # same guard as add_request: a request the pool can never
            # hold would otherwise defer at admission forever
            raise ValueError(
                f"request needs {self.pool.pages_for(total)} pages; pool "
                f"has {self.pool.num_usable} — it could never run")
        pages = list(pages or ())
        pos = int(pos)
        if pos > len(prompt) + len(generated):
            raise ValueError(f"pos {pos} past the accumulated tokens")
        if pos and len(pages) < self.pool.pages_for(pos):
            raise ValueError(
                f"pages cover {len(pages) * self.pool.page_size} tokens "
                f"but pos is {pos}")
        now = self._now()
        req = Request(req_id=self._next_id, prompt=prompt,
                      max_new_tokens=int(max_new_tokens),
                      temperature=float(temperature), top_k=int(top_k),
                      top_p=float(top_p), seed=int(seed),
                      eos_token_id=eos_token_id,
                      arrival_time=now if arrival_time is None
                      else float(arrival_time), stream_cb=stream_cb,
                      slo_class=slo_class)
        req.tokens = prompt + generated
        req.out_tokens = list(generated)
        req.pages = pages
        req.peak_pages = len(pages)
        req.pos = pos
        req.submit_time = max(now, req.arrival_time)
        req.trace_t0 = req.submit_time
        self._next_id += 1
        self.queue.push(req)
        self.protocol_log.append({"ev": "req.queued",
                                  "key": f"req:{req.req_id}",
                                  "seq": protocol_seq()})
        tr = self.tracer
        if tr.enabled:
            tr.instant("adopt", track=f"req {req.req_id}",
                       ts=req.submit_time, req=req.req_id,
                       prompt_tokens=len(prompt),
                       generated_tokens=len(generated), pos=pos,
                       handoff_pages=len(pages),
                       queue_depth=len(self.queue))
        return req

    # -- loop ----------------------------------------------------------------

    def _now(self) -> float:
        return self._time_fn()

    @property
    def tracer(self):
        """The effective tracer: the injected one, else the ambient
        global (usually ``NULL_TRACER`` — the no-op)."""
        return self._tracer if self._tracer is not None else get_tracer()

    def set_tracer(self, tracer) -> None:
        """Swap the engine's tracer live (None reverts to following the
        ambient global) — lets a service toggle tracing on a running
        engine, and the obs microbench A/B the same warm executable."""
        self._tracer = tracer

    @property
    def has_work(self) -> bool:
        return bool(self.queue) or bool(self.running)

    def step(self) -> int:
        """One engine iteration: admit, pack prefill chunks + decodes
        into ONE ragged batch, run the unified executable.  Returns the
        number of tokens emitted."""
        now = self._now()
        tr = self.tracer
        for req in self.scheduler.admit(self.queue, self.running, now):
            self._start(req)
        live = [r for r in self.running if r.state == RUNNING]
        if self.spec is not None:
            self._stage_spec(live)
        kept, evicted = self.scheduler.ensure_decode_pages(live)
        for req in evicted:
            self.running.remove(req)
            self.queue.push(req)
            self.counters["preemptions"].inc()
            self.counters[f"preempted_{req.slo_class}"].inc()
            if self.spec is not None:
                # a preempted request leaves the running set: free its
                # draft slot (the cache is stale anyway — resuming
                # re-prefills into a fresh slot).  Releasing, not just
                # invalidating, keeps slot holders ⊆ running, so the
                # admit-overtake path can never exhaust the slot pool
                self.spec.release(req)
            if self.tap is not None:
                # the rewind lint's validity tracking: preemption drops
                # every written KV slot (the pages themselves returned
                # to the pool)
                self.tap.append({"kind": "kv_drop", "req": req.req_id,
                                 "seq": protocol_seq()})
            t = self._now()
            if tr.enabled:
                # the running segment ends here; a fresh queued segment
                # opens at the SAME timestamp (gapless state tiling)
                tr.complete("running", req.trace_t0, t - req.trace_t0,
                            track=f"req {req.req_id}", req=req.req_id)
                tr.instant("preempt", track=f"req {req.req_id}", ts=t,
                           req=req.req_id,
                           n_preemptions=req.n_preemptions,
                           pos_lost=len(req.tokens))
            req.trace_t0 = t
        rows = self.scheduler.pack(kept)
        if tr.enabled and rows:
            tr.instant("pack", track="scheduler", ts=self._now(),
                       running=len(self.running),
                       queue_depth=len(self.queue),
                       free_pages=self.pool.free_pages,
                       **self.scheduler.slot_mix(rows))
        produced = self._run_unified(rows) if rows else 0
        if self.debug:
            self.pool.check_invariants()
            if self.prefix_cache is not None:
                self.prefix_cache.check_invariants()
        self.steps += 1
        self.gauges["batch_occupancy"].set(
            len(self.running) / self.scheduler.max_batch)
        self.gauges["page_utilization"].set(self.pool.utilization)
        self.gauges["queue_depth"].set(len(self.queue))
        self.gauges["kv_bytes_per_token"].set(
            self.pool.kv_bytes_per_token)
        self.gauges["kv_bytes_in_use"].set(
            (self.pool.num_usable - self.pool.free_pages)
            * self.pool.page_bytes)
        if self.host_tier is not None:
            self.gauges["host_pages"].set(self.host_tier.host_pages)
        return produced

    def run(self, max_steps: Optional[int] = None
            ) -> Dict[int, List[int]]:
        """Drive until idle (or ``max_steps``); returns
        {req_id: generated tokens} for everything finished so far."""
        while self.has_work:
            if max_steps is not None and self.steps >= max_steps:
                break
            self.step()
        return {rid: list(r.out_tokens)
                for rid, r in self.finished.items()}

    @property
    def compile_count(self) -> int:
        """Compiled program count, read from the REAL jit cache when the
        runtime exposes it — a silent retrace (shape/dtype/weak-type
        drift in the packed arrays) shows up here and trips the CI
        recompile guard, which a structural ``len(_compiled)`` never
        could.  Falls back to one per built executable."""
        n = 0
        for fn in self._compiled.values():
            try:
                n += int(fn._cache_size())
            except Exception:
                n += 1
        return n

    @property
    def executable_calls(self) -> int:
        """Unified-step invocations — engine state (a plain counter), so
        it stays correct under ``metrics=False``."""
        return self._calls

    # -- admission / lifecycle -----------------------------------------------

    def _reclaim_cached_pages(self, n: int) -> int:
        """The pool's reclaim hook: LRU-sweep refcount-0 cached pages
        when the free list runs dry — BEFORE the scheduler falls back to
        recompute preemption."""
        freed = self.prefix_cache.evict(n)
        if freed:
            self.counters["prefix_cache_evictions"].inc(freed)
            tr = self.tracer
            if tr.enabled:
                tr.instant("prefix_cache_evict", track="engine",
                           ts=self._now(), pages_freed=freed,
                           pages_wanted=n)
        return freed

    def _start(self, req: Request) -> None:
        """Move an admitted request to RUNNING: attach the longest
        cached prefix (copy-on-write — the shared pages enter the page
        table read-only and ``pos`` starts at the cached boundary, so
        the KV write plan and the token budget only ever see the
        uncached suffix), then grant the pages the rest of its
        accumulated tokens need.  Prefill itself is chunked over
        subsequent packed steps; there is no prefill call here."""
        looked_up = self.prefix_cache is not None and req.pos == 0 \
            and not req.pages
        if looked_up:
            if self.host_tier is not None:
                # extend the device-cache match with host-tier pages
                # FIRST: restored pages join the index, so the acquire
                # below attaches the deeper chain through the normal
                # copy-on-write path (a dry pool simply stops the
                # restore — the suffix recomputes like any miss)
                self.host_tier.refetch(req.tokens)
            entries = self.prefix_cache.acquire(req)
            if entries:
                req.pages = [e.page for e in entries]
                req.shared_pages = len(entries)
                req.pos = len(entries) * self.pool.page_size
                req.cached_tokens = req.pos
        need = self.pool.pages_for(len(req.tokens)) - len(req.pages)
        pages = self.pool.alloc(need)
        tr = self.tracer
        if pages is None:
            if tr.enabled:
                # stays queued: the open queued segment keeps running
                tr.instant("admit_defer", track=f"req {req.req_id}",
                           ts=self._now(), req=req.req_id,
                           pages_needed=need,
                           free_pages=self.pool.free_pages)
            # admission over-committed (another _start this step evicted
            # a cached page the budget counted on): roll back and retry
            # next step — never crash the loop on a page race.  Counters
            # deliberately untouched: the retried start is the SAME
            # logical start, not a second hit/miss.  Only the cache
            # attach this call made is undone — an ADOPTED request
            # (handoff pages pre-attached, pos past the prompt) keeps
            # its pages and cursor for the retry
            if looked_up:
                if self.prefix_cache is not None and req.shared_pages:
                    self.prefix_cache.release(req)
                req.pages = []
                req.shared_pages = 0
                req.cached_tokens = 0
                req.pos = 0
            self.queue.push(req)
            return
        if looked_up:
            if req.shared_pages:
                self.counters["prefix_cache_hits"].inc()
                self.counters["prefix_cache_tokens_saved"].inc(
                    req.cached_tokens)
            else:
                self.counters["prefix_cache_misses"].inc()
        req.pages = req.pages + pages
        req.peak_pages = max(req.peak_pages, len(req.pages))
        req.state = RUNNING
        self.counters[f"admitted_{req.slo_class}"].inc()
        self.running.append(req)
        self.protocol_log.append({"ev": "req.admit",
                                  "key": f"req:{req.req_id}",
                                  "seq": protocol_seq()})
        t = self._now()
        if tr.enabled:
            # close the queued segment and open running at the same
            # instant; the admission decision carries its page math
            tr.complete("queued", req.trace_t0, t - req.trace_t0,
                        track=f"req {req.req_id}", req=req.req_id,
                        preemptions=req.n_preemptions)
            tr.instant("admit", track=f"req {req.req_id}", ts=t,
                       req=req.req_id, pages_granted=need,
                       pages_total=len(req.pages),
                       cached_pages=req.shared_pages,
                       free_pages=self.pool.free_pages,
                       batch=len(self.running))
            if looked_up and req.shared_pages:
                tr.instant("prefix_cache_hit", track=f"req {req.req_id}",
                           ts=t, req=req.req_id,
                           cached_tokens=req.cached_tokens,
                           shared_pages=req.shared_pages)
        req.trace_t0 = t

    def abort_all(self) -> List[int]:
        """Abort every queued + running request: owned pages return to
        the free list, shared prefix-cache references are released,
        nothing enters ``finished``.  The re-admission path for a
        fenced cluster replica — its re-routed work already lives on
        survivors, so whatever this engine still holds is stale by
        definition.  Returns the aborted engine request ids."""
        victims = list(self.queue.requests())
        victims.extend(self.running)
        for req in victims:
            self.pool.free(req.pages[req.shared_pages:])
            if self.prefix_cache is not None and req.shared_pages:
                self.prefix_cache.release(req)
            if self.spec is not None:
                self.spec.release(req)
            req.pages = []
            req.shared_pages = 0
            req.cached_tokens = 0
            req.spec_drafts = []
            req.pos = 0
            req.state = FINISHED          # terminal, but never collected
        self.queue.clear()
        self.running.clear()
        if self.debug:
            self.pool.check_invariants()
            if self.prefix_cache is not None:
                self.prefix_cache.check_invariants()
        return [r.req_id for r in victims]

    def _stage_spec(self, live: List[Request]) -> None:
        """Draft-propose for every decode-ready request that can still
        profit from speculation (≥ 2 tokens left to emit): ONE batched
        draft call per engine step, drafts staged on the requests for
        the scheduler to pack as verify rows."""
        cands = []
        k_effs: Dict[int, int] = {}
        for r in sorted(live, key=lambda r: (r.arrival_time, r.req_id)):
            if r.state != RUNNING or r.spec_drafts or r.done:
                continue
            if len(r.tokens) - r.pos != 1:
                continue               # mid-prefill: nothing to draft
            k_eff = min(self.spec_k,
                        r.max_new_tokens - r.n_generated - 1)
            if k_eff < 1:
                continue               # last token: plain decode is it
            cands.append(r)
            k_effs[r.req_id] = k_eff
        if not cands:
            return
        tr = self.tracer
        t0 = self._now()
        drafts = self.spec.stage(cands, k_effs, tracer=tr, now=t0)
        dt = self._now() - t0
        total = 0
        for r in cands:
            r.spec_drafts = drafts.get(r.req_id, [])
            total += len(r.spec_drafts)
        self.counters["spec_proposed"].inc(total)
        if tr.enabled and total:
            tr.complete("draft", t0, dt, track="engine",
                        requests=len(cands), proposed=total,
                        k=self.spec_k)

    # -- the unified step ----------------------------------------------------

    def _pack_arrays(self, rows: List[Tuple[Request, int, int]]):
        """Host-side marshalling of the packed step: flat token arrays +
        per-row ragged descriptors + per-row sampling params.  A verify
        row's fed tokens are the committed tail plus its staged drafts
        (``qlen = 1 + spec_len``), written through the SAME trash-page-
        safe per-token KV write plan as any prefill chunk."""
        t, nr = self.n_tokens, self.n_rows
        ps = self.pool.page_size
        vbase = self.scheduler.max_batch + self.scheduler.prefill_rows
        tokens = np.zeros(t, np.int32)
        token_pos = np.zeros(t, np.int32)
        token_page = np.full(t, TRASH_PAGE, np.int32)
        token_off = np.zeros(t, np.int32)
        q_lens = np.zeros(nr, np.int32)
        page_tables = np.full((nr, self.max_pages_per_seq), TRASH_PAGE,
                              np.int32)
        ctx_lens = np.zeros(nr, np.int32)
        temps = np.zeros(nr, np.float32)
        top_ps = np.zeros(nr, np.float32)
        top_ks = np.zeros(nr, np.int32)
        seeds = np.zeros(nr, np.int32)
        spec_lens = np.zeros(nr, np.int32)
        for req, qlen, row in rows:
            start = int(self._cu_q[row])
            pos = np.arange(req.pos, req.pos + qlen)
            seq = req.tokens if not (row >= vbase and req.spec_drafts) \
                else req.tokens + req.spec_drafts
            tokens[start:start + qlen] = seq[req.pos:req.pos + qlen]
            token_pos[start:start + qlen] = pos
            pages = np.asarray(req.pages, np.int32)
            token_page[start:start + qlen] = pages[pos // ps]
            token_off[start:start + qlen] = pos % ps
            q_lens[row] = qlen
            page_tables[row, :len(req.pages)] = req.pages
            ctx_lens[row] = req.pos + qlen
            temps[row] = req.temperature
            top_ps[row] = req.top_p
            top_ks[row] = req.top_k
            seeds[row] = req.seed
            if row >= vbase and req.spec_drafts:
                spec_lens[row] = len(req.spec_drafts)
        return (tokens, token_pos, token_page, token_off, q_lens,
                page_tables, ctx_lens, temps, top_ps, top_ks, seeds,
                spec_lens)

    def _run_unified(self, rows: List[Tuple[Request, int, int]]) -> int:
        s = self.scheduler.max_batch
        vbase = s + self.scheduler.prefill_rows
        for req, qlen, row in rows:
            if row < vbase and req.spec_drafts:
                # packed outside its verify slot (defensive: with one
                # dedicated slot per sequence this shouldn't happen) —
                # this row commits a token the drafts never saw, so
                # they are stale and dropped before the step
                req.spec_drafts = []
        (tokens, token_pos, token_page, token_off, q_lens, page_tables,
         ctx_lens, temps, top_ps, top_ks, seeds,
         spec_lens) = self._pack_arrays(rows)
        if self.tap is not None:
            self.tap.append({
                "kind": "unified",
                "seq": protocol_seq(),
                "rows": [(row, req.pos, qlen) for req, qlen, row in rows],
                # per-request read extent for the spec-rewind-leak lint:
                # this step WRITES [pos, pos+qlen) and READS [0, ctx) —
                # a read past the valid-KV watermark (stale slots left
                # by a rewind, not yet re-written) is a leak
                "reads": [(req.req_id, req.pos, qlen,
                           int(ctx_lens[row]))
                          for req, qlen, row in rows],
                "page_tables": page_tables.copy(),
                # refcount snapshot of the read-only cached pages: the
                # cow-page-write lint flags any live row whose write
                # plan targets a page in this snapshot (membership =
                # cached = read-only, whatever the sharer count)
                "refcounts": {int(pg): self.pool.refcount(pg)
                              for pg in self.pool._cached}})
        t0 = self._now()
        args = (self.params, jnp.asarray(tokens), jnp.asarray(token_pos),
                jnp.asarray(token_page), jnp.asarray(token_off),
                jnp.asarray(q_lens), jnp.asarray(self._cu_q),
                jnp.asarray(page_tables), jnp.asarray(ctx_lens),
                jnp.asarray(temps), jnp.asarray(top_ps),
                jnp.asarray(top_ks), jnp.asarray(seeds))
        if self.spec is not None:
            next_tokens, accepted, new_k, new_v = \
                self._compiled["unified"](*args,
                                          jnp.asarray(spec_lens),
                                          self.pool.k_pages,
                                          self.pool.v_pages)
            accs = np.asarray(accepted)         # [rows] int32
        else:
            next_tokens, new_k, new_v = self._compiled["unified"](
                *args, self.pool.k_pages, self.pool.v_pages)
            accs = None
        self.pool.set_pages(new_k, new_v)
        toks = np.asarray(next_tokens)          # [rows] int32, ever
        dt = self._now() - t0
        self._calls += 1
        self.counters["step_calls"].inc()
        tr = self.tracer
        if tr.enabled:
            # the span every reconciliation row hangs off: exec= names
            # the registered ExecutableHandle, and the static
            # predictions ride along as attributes
            tr.complete("unified_step", t0, dt, track="engine",
                        exec=f"{self.name}/unified", rows=len(rows),
                        tokens=int(sum(q for _, q, _ in rows)),
                        **self._predicted_attrs())
        # classify by SLOT, not q_len: a chunk_size=1 prefill chunk is
        # still a prefill chunk, and a verify row is neither
        n_decode = sum(1 for _, _, row in rows if row < s)
        n_chunk = sum(1 for _, _, row in rows if s <= row < vbase)
        if n_decode:
            self.counters["decode_steps"].inc()
        self.counters["prefill_chunks"].inc(n_chunk)
        produced = 0
        for req, qlen, row in rows:
            pre = max(0, min(qlen, req.prompt_len - req.pos))
            if pre:
                self.counters["prefill_tokens"].inc(pre)
                if tr.enabled:
                    tr.complete("prefill_chunk", t0, dt,
                                track=f"req {req.req_id}",
                                req=req.req_id, q_len=qlen,
                                prefill_tokens=pre, pos=req.pos,
                                budget_slice=qlen,
                                cached_skip=req.cached_tokens)
            if row >= vbase and req.spec_drafts:
                produced += self._commit_verify(
                    req, int(accs[row]), int(toks[row]), t0, dt)
                continue
            req.pos += qlen
            if req.pos == len(req.tokens):      # row reached its tip:
                self._emit(req, int(toks[row]))  # commit the sample
                produced += 1
                self._observe_token(req, row < s, dt)
                self._maybe_finish(req)
        return produced

    def _observe_token(self, req: Request, decode_slot: bool,
                       dt: float) -> None:
        """Latency bookkeeping + trace instant for ONE emitted token."""
        tr = self.tracer
        now = self._now()
        if tr.enabled:
            tr.instant("token", track=f"req {req.req_id}", ts=now,
                       req=req.req_id, n=req.n_generated,
                       decode_slot=bool(decode_slot))
        if req.first_token_time is None:
            req.first_token_time = now
            self.histograms["ttft"].observe(now - req.submit_time)
        else:
            self.histograms["tbt"].observe(
                now - (req.last_token_time or now))
            self.histograms["tpot"].observe(dt)
        req.last_token_time = now

    def _commit_verify(self, req: Request, accepted: int,
                       bonus: int, t0: float, dt: float) -> int:
        """Commit a verify row's outcome: the accepted draft prefix
        plus the bonus token, capped by ``max_new_tokens``/EOS, then
        rewind ``pos`` to the accepted boundary.  Rejected positions'
        KV slots beyond the boundary are STALE — they are re-written by
        the next burst before anything can read them (the write plan
        covers every fed position ahead of the attention, and
        ``ctx_lens`` never reaches past the written extent; the
        ``spec-rewind-leak`` lint audits exactly this from the tap).
        Returns the number of requests that emitted (0 or 1)."""
        drafts = req.spec_drafts
        spec_len = len(drafts)
        n0 = len(req.tokens)
        committed_drafts = 0
        emitted = 0
        for i, tok in enumerate(drafts[:accepted] + [bonus]):
            if req.n_generated >= req.max_new_tokens:
                break
            self._emit(req, int(tok))
            emitted += 1
            if i < accepted:
                committed_drafts += 1
            self._observe_token(req, False, dt)
            if req.eos_token_id is not None and \
                    int(tok) == req.eos_token_id:
                break
        # rewind: the first spec_len - committed_drafts fed positions
        # past the boundary hold rejected/stale KV; the next verify
        # burst (or re-prefill) re-writes them in place
        req.pos = n0 + committed_drafts
        req.spec_drafts = []
        self.counters["spec_accepted"].inc(committed_drafts)
        if emitted > committed_drafts:
            self.counters["spec_bonus_tokens"].inc()
        tr = self.tracer
        if tr.enabled:
            tr.complete("verify", t0, dt, track=f"req {req.req_id}",
                        req=req.req_id, proposed=spec_len,
                        accepted=accepted, committed=emitted)
            tr.instant("spec_accept", track=f"req {req.req_id}",
                       ts=self._now(), req=req.req_id, n=committed_drafts,
                       bonus=int(emitted > committed_drafts))
        if self.tap is not None and committed_drafts < spec_len:
            self.tap.append({"kind": "spec_rewind", "req": req.req_id,
                             "seq": protocol_seq(),
                             "valid_upto": int(req.pos),
                             "written_upto": int(n0 + spec_len)})
        self._maybe_finish(req)
        return 1 if emitted else 0

    # -- sampling / retirement ----------------------------------------------

    def _emit(self, req: Request, token: int) -> None:
        """Commit the next token — ALWAYS sampled on device by the
        unified executable (greedy argmax bit-for-bit with solo
        ``generate()``; temperature/top-k/top-p keyed by
        ``(seed, position)`` for batching-independent replays)."""
        tok = int(token)
        req.tokens.append(tok)
        req.out_tokens.append(tok)
        self.counters["tokens_generated"].inc()
        if req.stream_cb is not None:
            req.stream_cb(req, tok)

    def _maybe_finish(self, req: Request) -> None:
        if not req.done:
            return
        if self.spec is not None:
            self.spec.release(req)
            req.spec_drafts = []
        if self.prefix_cache is not None:
            # fully-written pages enter the cache index (refcount 0,
            # LRU-evictable); duplicates and the partial tail are freed;
            # shared references released
            self.prefix_cache.on_finish(req)
        else:
            self.pool.free(req.pages)
        req.pages = []
        req.state = FINISHED
        req.finish_time = self._now()
        self.protocol_log.append({"ev": "req.finish",
                                  "key": f"req:{req.req_id}",
                                  "seq": protocol_seq()})
        tr = self.tracer
        if tr.enabled:
            tr.complete("running", req.trace_t0,
                        req.finish_time - req.trace_t0,
                        track=f"req {req.req_id}", req=req.req_id)
            tr.instant("finish", track=f"req {req.req_id}",
                       ts=req.finish_time, req=req.req_id,
                       new_tokens=req.n_generated,
                       preemptions=req.n_preemptions,
                       peak_pages=req.peak_pages)
        req.trace_t0 = req.finish_time
        if req in self.running:
            self.running.remove(req)
        self.finished[req.req_id] = req
        self.counters["requests_completed"].inc()
        self.histograms["request_latency"].observe(
            req.finish_time - req.submit_time)

    # -- analysis ------------------------------------------------------------

    def _register_for_analysis(self) -> None:
        """Expose the unified executable to the static analyzer
        (hetu_tpu/analysis): abstract arg specs are fully determined by
        the engine's fixed layout, so the handle can lower without
        running."""
        from ..graph.graph import register_executable
        sds = lambda a: jax.ShapeDtypeStruct(np.shape(a),  # noqa: E731
                                             np.asarray(a).dtype) \
            if not hasattr(a, "aval") else jax.ShapeDtypeStruct(a.shape,
                                                                a.dtype)
        params = jax.tree_util.tree_map(sds, self.params)
        # k and v page stacks differ in shape (and dtype) under the MLA
        # latent layout — build each spec from its own arrays
        k_pages = tuple(sds(p) for p in self.pool.k_pages)
        v_pages = tuple(sds(p) for p in self.pool.v_pages)
        t, nr, maxp = self.n_tokens, self.n_rows, self.max_pages_per_seq
        i32 = lambda *s: jax.ShapeDtypeStruct(s, np.int32)  # noqa: E731
        f32 = lambda *s: jax.ShapeDtypeStruct(s, np.float32)  # noqa: E731
        args = (params, i32(t), i32(t), i32(t), i32(t), i32(nr),
                i32(nr + 1), i32(nr, maxp), i32(nr), f32(nr), f32(nr),
                i32(nr), i32(nr)) \
            + ((i32(nr),) if self.spec is not None else ()) \
            + (k_pages, v_pages)
        meta = {
            "kind": "serving_unified",
            "mesh_axes": {},
            # model weights ride in as closed-over inputs: replicated by
            # design on the single-device path (trainable=False keeps
            # replicated-large-param quiet; a tp-sharded pool analysis
            # would annotate pspecs here)
            "params": [],
            # single-device (or fully explicit) program: NO collective
            # may appear that the inventory doesn't list
            "allowed_gspmd": {} if self.pool.sharding is None else None,
            "scalar_fetches": 0,
            "serving": lambda: {"pool": self.pool,
                                "page_size": self.pool.page_size,
                                "tap": list(self.tap or ()),
                                # the page + engine-request planes of
                                # the protocol event stream
                                "pool_log": list(self.pool.event_log),
                                "protocol": list(self.protocol_log)},
        }
        if self.host_tier is not None:
            # host-tier page-move records for the host-offload-unpriced
            # rule; engines without a host tier stay out of scope
            meta["host_offload"] = \
                lambda: list(self.host_tier.records)
        if self.pool.sharding is None:
            # per-edge claim: the single-device serving path predicts
            # ZERO comm edges — any emitted collective is unexplained
            # by construction (a tp-sharded pool would declare its
            # attention/head reduction edges here instead)
            meta["pspec_edges"] = []
        register_executable(f"{self.name}/unified",
                            self._compiled["unified"], args, meta)

    def unregister_analysis(self) -> None:
        """Drop this engine's executables from the analysis registry.

        Registration closes over the engine (pool snapshot hook), so a
        long-running service that retires engines must call this (or
        reuse the name — construction clears its own namespace) to let
        the pool's HBM/host arrays be collected."""
        from ..graph.graph import clear_executables
        clear_executables(f"{self.name}/")

    # -- observability -------------------------------------------------------

    def _predicted_attrs(self) -> Dict[str, Any]:
        """Static analysis-plane predictions for the unified executable,
        attached to every traced ``unified_step`` span so the trace
        alone suffices for reconciliation.  Computed once (tracing the
        registered handle) on the first TRACED step; failures degrade to
        no attrs rather than breaking serving."""
        if self._pred_attrs is None:
            from ..obs.reconcile import predicted_span_attrs
            self._pred_attrs = predicted_span_attrs(
                f"{self.name}/unified")
        return self._pred_attrs

    def metrics_text(self) -> str:
        """Prometheus text exposition of every engine instrument
        (``utils.metrics.render_prometheus``): counters and gauges
        as-is, histograms as ``_bucket``/``_sum``/``_count`` — ready
        for a /metrics scrape endpoint."""
        insts: Dict[str, Any] = {}
        insts.update(self.counters)
        insts.update(self.gauges)
        insts.update(self.histograms)
        return render_prometheus(insts)

    def reset_metrics(self) -> None:
        """Zero every counter/gauge/histogram AND the step counter (the
        compiled executable and all request state stay) — lets a bench
        separate the compile-bearing first trace from steady-state
        serving.  ``steps`` and ``executable_calls`` reset too, so
        ``run(max_steps=...)`` and the call count describe the trace
        since the reset, not the engine's lifetime (``compile_count``
        deliberately does NOT reset — compiles are lifetime state)."""
        self.steps = 0
        self._calls = 0
        for d in (self.counters, self.gauges, self.histograms):
            for k, inst in list(d.items()):
                if inst.__class__.__name__ == "_NullInstrument":
                    continue
                kw = {"buckets": list(inst.buckets)} \
                    if getattr(inst, "buckets", None) else {}
                d[k] = make_instrument(inst.__class__.__name__.lower(),
                                       k, True, **kw)
        if self.gauges["kv_bytes_per_token"].__class__.__name__ \
                != "_NullInstrument":
            # layout-static: re-seed rather than read 0 until a step
            self.gauges["kv_bytes_per_token"].set(
                self.pool.kv_bytes_per_token)

    def metrics_summary(self) -> Dict[str, Any]:
        out = {k: c.value for k, c in self.counters.items()}
        out.update({k: g.value for k, g in self.gauges.items()})
        for k, h in self.histograms.items():
            out[k] = h.summary()
        out["ttft_buckets"] = self.histograms["ttft"].bucket_counts()
        out["tbt_buckets"] = self.histograms["tbt"].bucket_counts()
        out["compile_count"] = self.compile_count
        out["executable_calls"] = self.executable_calls
        out["host_logit_fetches"] = self.host_logit_fetches
        # prefix cache: request-level hit rate since the last
        # reset_metrics (warm a shared header, reset, replay: 1.0)
        hits = self.counters["prefix_cache_hits"].value
        miss = self.counters["prefix_cache_misses"].value
        out["prefix_cache_hit_rate"] = hits / max(hits + miss, 1.0)
        out["prefix_cache_pages"] = self.pool.cached_pages
        # speculative decoding: draft hit rate + emitted tokens per
        # executable call since the last reset (non-spec engines report
        # rate 0 / plain 1-token-per-emitting-row cadence)
        prop = self.counters["spec_proposed"].value
        out["spec_accept_rate"] = \
            self.counters["spec_accepted"].value / max(prop, 1.0)
        out["accepted_per_step"] = (
            (self.counters["spec_accepted"].value +
             self.counters["spec_bonus_tokens"].value) /
            max(self.counters["step_calls"].value, 1.0))
        return out
