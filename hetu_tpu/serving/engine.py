"""Continuous-batching inference engine over the paged KV pool.

The serving half of the roadmap: where ``models.generate`` runs ONE
static batch to completion, the engine runs an admission loop — every
``step()`` it admits arrived requests (prefill, separate executable),
packs all live requests into a shape-bucketed decode batch (paged
attention through per-request page tables), streams each new token to
its request, and retires/evicts under the page budget.  Late-arriving
requests join mid-flight; short requests leave without waiting for long
ones.

Determinism contract: at temperature 0 every request's output equals a
solo ``generate()`` run — batching, paging, admission order, and even
preemption (recompute eviction) change WHEN a token is computed, never
WHAT it is.  ``tests/test_serving.py`` asserts this bit-for-bit.

Observability (utils/metrics.py instruments): counters
``tokens_generated``/``prefill_tokens``/``requests_completed``/
``preemptions``/``decode_steps``, gauges ``batch_occupancy``/
``page_utilization``/``queue_depth``, histograms ``ttft``/``tpot``/
``request_latency`` — with the no-op fallback when disabled.
"""
from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..models.generate import _Params
from ..models.gpt import GPTConfig
from ..utils.metrics import make_instrument
from .decode import build_decode_fn, build_prefill_fn
from .kv_pool import TRASH_PAGE, PagedKVPool
from .request import FINISHED, RUNNING, Request, RequestQueue
from .scheduler import Scheduler


class Engine:
    def __init__(self, state: Dict[str, Any], cfg: GPTConfig,
                 num_pages: int = 64, page_size: int = 64,
                 max_batch: int = 8, max_model_len: Optional[int] = None,
                 mesh=None, use_kernel: bool = False,
                 metrics: bool = True,
                 time_fn: Optional[Callable[[], float]] = None,
                 name: str = "serving", analysis_tap: bool = True):
        self.cfg = cfg
        self.name = name
        # ring buffer of recent prefill/decode call shapes+page tables,
        # consumed by the trash-page-write lint (hetu_tpu/analysis)
        self.tap: Optional[deque] = deque(maxlen=128) if analysis_tap \
            else None
        # a new engine owns its analysis namespace: stale handles from a
        # discarded same-name engine would otherwise mix dead pool
        # snapshots into analyze_registered(name) — and pin that
        # engine's KV pool in the process-global registry forever
        from ..graph.graph import clear_executables
        clear_executables(f"{self.name}/")
        self.params = _Params(state, cfg).s      # normalized key view
        if max_model_len is None:
            max_model_len = (num_pages - 1) * page_size
            if cfg.position == "learned":
                # never past the wpe table: an out-of-range position
                # gather clamps silently to the last row
                max_model_len = min(max_model_len, cfg.max_seq_len)
        self.max_model_len = int(max_model_len)
        self.max_pages_per_seq = -(-self.max_model_len // page_size)
        dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
        self.pool = PagedKVPool(cfg.num_layers, num_pages, page_size,
                                cfg.kv_heads, cfg.head_dim, dtype,
                                mesh=mesh)
        self.scheduler = Scheduler(self.pool, max_batch=max_batch)
        self.use_kernel = bool(use_kernel)
        self.queue = RequestQueue()
        self.running: List[Request] = []
        self.finished: Dict[int, Request] = {}
        self._compiled: Dict[Any, Callable] = {}
        self._time_fn = time_fn or time.monotonic
        self._next_id = 0
        self.steps = 0
        # host logits round-trips actually paid: greedy (temperature-0)
        # traffic samples on device and only moves B int32s per step —
        # this stays 0 unless a sampled-mode request is live
        self.host_logit_fetches = 0
        m = metrics
        self.counters = {k: make_instrument("counter", k, m) for k in
                         ("tokens_generated", "prefill_tokens",
                          "requests_completed", "preemptions",
                          "decode_steps", "prefills")}
        self.gauges = {k: make_instrument("gauge", k, m) for k in
                       ("batch_occupancy", "page_utilization",
                        "queue_depth")}
        self.histograms = {k: make_instrument("histogram", k, m) for k in
                           ("ttft", "tpot", "request_latency")}

    # -- submission ----------------------------------------------------------

    def add_request(self, prompt_ids: Sequence[int], max_new_tokens: int,
                    temperature: float = 0.0, top_k: int = 0,
                    seed: int = 0, eos_token_id: Optional[int] = None,
                    arrival_time: Optional[float] = None,
                    stream_cb: Optional[Callable] = None) -> Request:
        prompt = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        total = len(prompt) + int(max_new_tokens)
        if total > self.max_model_len:
            raise ValueError(
                f"prompt+max_new_tokens = {total} exceeds max_model_len "
                f"{self.max_model_len}")
        if self.pool.pages_for(total) > self.pool.num_usable:
            raise ValueError(
                f"request needs {self.pool.pages_for(total)} pages; pool "
                f"has {self.pool.num_usable} — it could never run")
        now = self._now()
        req = Request(req_id=self._next_id, prompt=prompt,
                      max_new_tokens=int(max_new_tokens),
                      temperature=float(temperature), top_k=int(top_k),
                      seed=int(seed), eos_token_id=eos_token_id,
                      arrival_time=now if arrival_time is None
                      else float(arrival_time), stream_cb=stream_cb)
        req.submit_time = max(now, req.arrival_time)
        self._next_id += 1
        self.queue.push(req)
        return req

    # -- loop ----------------------------------------------------------------

    def _now(self) -> float:
        return self._time_fn()

    @property
    def has_work(self) -> bool:
        return bool(self.queue) or bool(self.running)

    def step(self) -> int:
        """One engine iteration: admit+prefill, then one decode step for
        every live request.  Returns the number of tokens produced."""
        produced = 0
        now = self._now()
        for req in self.scheduler.admit(self.queue, self.running, now):
            produced += self._prefill(req)
        produced += self._decode_batch()
        self.steps += 1
        self.gauges["batch_occupancy"].set(
            len(self.running) / self.scheduler.max_batch)
        self.gauges["page_utilization"].set(self.pool.utilization)
        self.gauges["queue_depth"].set(len(self.queue))
        return produced

    def run(self, max_steps: Optional[int] = None
            ) -> Dict[int, List[int]]:
        """Drive until idle (or ``max_steps``); returns
        {req_id: generated tokens} for everything finished so far."""
        while self.has_work:
            if max_steps is not None and self.steps >= max_steps:
                break
            self.step()
        return {rid: list(r.out_tokens)
                for rid, r in self.finished.items()}

    @property
    def compile_count(self) -> int:
        """Distinct compiled executables — bounded by the shape-bucket
        grid (asserted in bench/tests), not by traffic."""
        return len(self._compiled)

    # -- prefill -------------------------------------------------------------

    def _get_fn(self, kind: str, bucket: int) -> Callable:
        key = (kind, bucket)
        fn = self._compiled.get(key)
        if fn is None:
            if kind == "prefill":
                fn = build_prefill_fn(self.cfg, bucket,
                                      self.max_pages_per_seq,
                                      self.pool.page_size)
            else:
                fn = build_decode_fn(self.cfg, bucket,
                                     self.max_pages_per_seq,
                                     self.pool.page_size,
                                     use_kernel=self.use_kernel)
            self._compiled[key] = fn
            self._register_for_analysis(kind, bucket, fn)
        return fn

    def _register_for_analysis(self, kind: str, bucket: int, fn) -> None:
        """Expose this executable to the static analyzer
        (hetu_tpu/analysis): abstract arg specs are fully determined by
        the bucket, so the handle can lower without running."""
        from ..graph.graph import register_executable
        sds = lambda a: jax.ShapeDtypeStruct(np.shape(a),  # noqa: E731
                                             np.asarray(a).dtype) \
            if not hasattr(a, "aval") else jax.ShapeDtypeStruct(a.shape,
                                                                a.dtype)
        params = jax.tree_util.tree_map(sds, self.params)
        pages = tuple(sds(p) for p in self.pool.k_pages)
        maxp = self.max_pages_per_seq
        if kind == "prefill":
            args = (params, jax.ShapeDtypeStruct((1, bucket), np.int32),
                    jax.ShapeDtypeStruct((), np.int32),
                    jax.ShapeDtypeStruct((maxp,), np.int32), pages, pages)
        else:
            args = (params, jax.ShapeDtypeStruct((bucket,), np.int32),
                    jax.ShapeDtypeStruct((bucket,), np.int32),
                    jax.ShapeDtypeStruct((bucket, maxp), np.int32),
                    pages, pages)
        meta = {
            "kind": f"serving_{kind}",
            "mesh_axes": {},
            # model weights ride in as closed-over inputs: replicated by
            # design on the single-device path (trainable=False keeps
            # replicated-large-param quiet; a tp-sharded pool analysis
            # would annotate pspecs here)
            "params": [],
            # single-device (or fully explicit) program: NO collective
            # may appear that the inventory doesn't list
            "allowed_gspmd": {} if self.pool.sharding is None else None,
            "scalar_fetches": 0,
            "serving": lambda: {"pool": self.pool,
                                "page_size": self.pool.page_size,
                                "tap": list(self.tap or ())},
        }
        if self.pool.sharding is None:
            # per-edge claim: the single-device serving path predicts
            # ZERO comm edges — any emitted collective is unexplained
            # by construction (a tp-sharded pool would declare its
            # attention/head reduction edges here instead)
            meta["pspec_edges"] = []
        register_executable(f"{self.name}/{kind}-{bucket}", fn, args, meta)

    def _pt_row(self, pages: List[int]) -> np.ndarray:
        row = np.full(self.max_pages_per_seq, TRASH_PAGE, np.int32)
        row[:len(pages)] = pages
        return row

    def _prefill(self, req: Request) -> int:
        n_tok = len(req.tokens)
        pages = self.pool.alloc(self.pool.pages_for(n_tok))
        assert pages is not None, "admission reserved these pages"
        req.pages = pages
        req.peak_pages = max(req.peak_pages, len(pages))
        s_pad = self.scheduler.prefill_bucket(n_tok)
        if self.tap is not None:
            self.tap.append({"kind": "prefill", "pages": list(pages),
                             "n_tok": n_tok})
        fn = self._get_fn("prefill", s_pad)
        prompt = np.zeros((1, s_pad), np.int32)
        prompt[0, :n_tok] = req.tokens
        logits, greedy, new_k, new_v = fn(
            self.params, jnp.asarray(prompt), jnp.int32(n_tok),
            jnp.asarray(self._pt_row(pages)),
            self.pool.k_pages, self.pool.v_pages)
        self.pool.set_pages(new_k, new_v)
        req.pos = n_tok
        req.state = RUNNING
        self.running.append(req)
        if req.temperature == 0.0:
            self._emit(req, token=int(np.asarray(greedy)))
        else:
            self.host_logit_fetches += 1
            self._emit(req, logits=np.asarray(logits))
        now = self._now()
        if req.first_token_time is None:
            req.first_token_time = now
            self.histograms["ttft"].observe(now - req.submit_time)
        self.counters["prefill_tokens"].inc(n_tok)
        self.counters["prefills"].inc()
        self._maybe_finish(req)
        return 1

    # -- decode --------------------------------------------------------------

    def _decode_batch(self) -> int:
        live = [r for r in self.running if r.state == RUNNING]
        if not live:
            return 0
        kept, evicted = self.scheduler.ensure_decode_pages(live)
        for req in evicted:
            self.running.remove(req)
            self.queue.push(req)
            self.counters["preemptions"].inc()
        if not kept:
            return 0
        bucket = self.scheduler.decode_bucket(len(kept))
        kept = kept[:bucket]               # surplus rides the next step
        fn = self._get_fn("decode", bucket)
        tokens = np.zeros(bucket, np.int32)
        pos = np.zeros(bucket, np.int32)
        pt = np.full((bucket, self.max_pages_per_seq), TRASH_PAGE,
                     np.int32)
        for i, req in enumerate(kept):
            tokens[i] = req.tokens[-1]
            pos[i] = req.pos
            pt[i, :len(req.pages)] = req.pages
        if self.tap is not None:
            self.tap.append({"kind": "decode", "n_live": len(kept),
                             "pos": pos.copy(), "page_tables": pt.copy()})
        t0 = self._now()
        logits, greedy, new_k, new_v = fn(
            self.params, jnp.asarray(tokens), jnp.asarray(pos),
            jnp.asarray(pt), self.pool.k_pages, self.pool.v_pages)
        self.pool.set_pages(new_k, new_v)
        # fetch the [B, V] logits only when a sampled-mode request is in
        # the batch; all-greedy steps move B int32s instead
        toks = np.asarray(greedy)
        logits_host = None
        if any(r.temperature != 0.0 for r in kept):
            self.host_logit_fetches += 1
            logits_host = np.asarray(logits)
        dt = self._now() - t0
        for i, req in enumerate(kept):
            req.pos += 1
            if req.temperature == 0.0:
                self._emit(req, token=int(toks[i]))
            else:
                self._emit(req, logits=logits_host[i])
            self.histograms["tpot"].observe(dt)
            self._maybe_finish(req)
        self.counters["decode_steps"].inc()
        return len(kept)

    # -- sampling / retirement ----------------------------------------------

    def _emit(self, req: Request, logits: Optional[np.ndarray] = None,
              token: Optional[int] = None) -> None:
        """Commit the next token: either ``token`` (already sampled on
        device — the greedy argmax folded into the decode/prefill jit,
        the very ``jnp.argmax`` generate() runs, so it stays bit-for-bit
        with the solo path) or sampled host-side from fp32 ``logits``
        [V] with a per-request, per-position RNG so replays are
        deterministic regardless of batching."""
        if token is not None:
            tok = int(token)
        elif req.temperature == 0.0:
            tok = int(np.argmax(logits))
        else:
            lg = logits.astype(np.float64) / req.temperature
            if req.top_k > 0:
                kth = np.sort(lg)[-req.top_k]
                lg = np.where(lg < kth, -np.inf, lg)
            lg = lg - lg.max()
            probs = np.exp(lg)
            probs /= probs.sum()
            rng = np.random.default_rng((req.seed, len(req.tokens)))
            tok = int(rng.choice(len(probs), p=probs))
        req.tokens.append(tok)
        req.out_tokens.append(tok)
        self.counters["tokens_generated"].inc()
        if req.stream_cb is not None:
            req.stream_cb(req, tok)

    def _maybe_finish(self, req: Request) -> None:
        if not req.done:
            return
        self.pool.free(req.pages)
        req.pages = []
        req.state = FINISHED
        req.finish_time = self._now()
        if req in self.running:
            self.running.remove(req)
        self.finished[req.req_id] = req
        self.counters["requests_completed"].inc()
        self.histograms["request_latency"].observe(
            req.finish_time - req.submit_time)

    def unregister_analysis(self) -> None:
        """Drop this engine's executables from the analysis registry.

        Registration closes over the engine (pool snapshot hook), so a
        long-running service that retires engines must call this (or
        reuse the name — construction clears its own namespace) to let
        the pool's HBM/host arrays be collected."""
        from ..graph.graph import clear_executables
        clear_executables(f"{self.name}/")

    # -- observability -------------------------------------------------------

    def metrics_summary(self) -> Dict[str, Any]:
        out = {k: c.value for k, c in self.counters.items()}
        out.update({k: g.value for k, g in self.gauges.items()})
        for k, h in self.histograms.items():
            out[k] = h.summary()
        out["compile_count"] = self.compile_count
        out["host_logit_fetches"] = self.host_logit_fetches
        return out
