"""Paged KV-cache pool: preallocated page storage + free-list allocator.

The dense decode cache (``models/generate.py``) holds ``[b, max_len,
kvh, hd]`` per layer — every request pays for the *longest possible*
sequence up front.  The pool instead preallocates ``num_pages`` fixed
``page_size``-token pages per layer and hands them out on demand: a
request holds ``ceil(len/page_size)`` pages, so mixed-length traffic
shares HBM proportionally to what it actually uses (the Ragged Paged
Attention storage layout, PAPERS.md arxiv 2604.15464).

Page 0 is a reserved **trash page**: every padded page-table slot (the
tail of a request's table, dummy batch slots) points at it, so the
jitted prefill/decode programs can scatter-write unconditionally with
static shapes — writes land in the trash page, reads past ``seq_len``
are masked by the attention op.  It is never allocated.

Sharding: pages are ``[num_pages, page_size, kv_heads, head_dim]`` —
the same ``kv_heads`` axis the training stack splits across ``tp``
(nn/parallel.py column-parallel QKV), so a pool built with a mesh
shards pages ``P(None, None, 'tp', None)`` and the decode executable's
per-shard pages line up with the per-shard QKV projections.

Pages live in one of THREE states (``serving/prefix_cache.py`` adds
the third): **free** (on the free list), **allocated** (owned by
exactly one request, writable), or **cached** (owned by the prefix
cache, READ-ONLY, refcounted by live sharers; refcount 0 = evictable).
``alloc`` consults an optional reclaim hook — the prefix cache's LRU
sweep — before failing, so cached pages are transparently recycled
ahead of the scheduler's recompute-preemption fallback.
"""
from __future__ import annotations

import itertools
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

TRASH_PAGE = 0

# Process-global protocol sequence counter.  Every record plane the
# analysis event stream merges (pool ops, engine tap, host-tier
# records, transport extract/inject, cluster adoptions/fences) stamps
# its records with the next value at record time, so events from
# DIFFERENT planes interleave in true causal order when
# ``analysis/events.normalize`` merges them — per-plane indices alone
# cannot order a pool free against the host-tier stage that caused it.
_PROTOCOL_SEQ = itertools.count(1)


def protocol_seq() -> int:
    """Next value of the process-global event sequence counter."""
    return next(_PROTOCOL_SEQ)

# page_quant codes for the layout tag (order is part of the tag)
_QUANT_CODES = {None: 0, "int8": 1, "nf4": 2}


def page_shape_bytes(shape: Sequence[int], dtype) -> int:
    """Bytes ONE page of a per-layer page array ``[P, ps, h, w]``
    occupies (i.e. everything but the leading page axis).  The single
    source of truth for KV page sizing: ``PagedKVPool.page_bytes``,
    ``PageTransport`` handoff pricing, engine metrics, and the
    ``analysis/memory.py`` pool predictor all derive from it, so a
    latent (MLA) pool and a full-head pool can never disagree about
    what a page costs."""
    n = 1
    for d in shape[1:]:
        n *= int(d)
    return n * jnp.dtype(dtype).itemsize


class PagedKVPool:
    """Free-list page allocator over per-layer k/v page arrays.

    Two layouts share every allocator/bookkeeping path:

    - **full-head** (default): k and v pages are both
      ``[P, ps, kv_heads, head_dim]``.
    - **latent** (MLA, ``latent_dim`` set): k_pages hold ONE compressed
      stream ``[P, ps, 1, latent_dim]`` and v_pages carry the decoupled
      rotated key ``[P, ps, 1, rope_dim]`` (width 0 for learned
      positions).  With ``quant`` set (int8/nf4, learned-position MLA
      only), k_pages store codes (int8, or packed uint8 at
      ``latent_dim // 2``) and v_pages become the per-token fp32 absmax
      sidecar ``[P, ps, 1, 1]``.

    Page-table math, the allocator, CoW refcounts, and the prefix cache
    never look inside a page, so they compose with any layout; only
    ``page_bytes`` / ``layout_tag`` observe the difference.
    """

    def __init__(self, num_layers: int, num_pages: int, page_size: int,
                 kv_heads: int, head_dim: int, dtype=jnp.float32,
                 mesh=None, kv_axis: str = "tp", debug: bool = False,
                 latent_dim: Optional[int] = None, rope_dim: int = 0,
                 quant: Optional[str] = None):
        if num_pages < 2:
            raise ValueError(f"num_pages must be >= 2 (page 0 is the "
                             f"reserved trash page), got {num_pages}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        if quant is not None:
            if quant not in ("int8", "nf4"):
                raise ValueError(f"page quant must be int8|nf4, "
                                 f"got {quant!r}")
            if latent_dim is None or rope_dim:
                raise ValueError("page quantization requires the latent "
                                 "(MLA) layout with rope_dim == 0 — the "
                                 "v-page slot carries the absmax sidecar")
            if quant == "nf4" and latent_dim % 2:
                raise ValueError(f"nf4 pages need even latent_dim, got "
                                 f"{latent_dim}")
        self.num_layers = int(num_layers)
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.kv_heads = int(kv_heads)
        self.head_dim = int(head_dim)
        self.dtype = jnp.dtype(dtype)
        self.latent_dim = None if latent_dim is None else int(latent_dim)
        self.rope_dim = int(rope_dim)
        self.quant = quant
        if latent_dim is not None:
            if quant == "int8":
                k_shape = (num_pages, page_size, 1, self.latent_dim)
                k_dtype = jnp.dtype(jnp.int8)
            elif quant == "nf4":
                k_shape = (num_pages, page_size, 1, self.latent_dim // 2)
                k_dtype = jnp.dtype(jnp.uint8)
            else:
                k_shape = (num_pages, page_size, 1, self.latent_dim)
                k_dtype = self.dtype
            # rope stream, or the per-token absmax sidecar when quantized
            v_w = 1 if quant else self.rope_dim
            v_shape = (num_pages, page_size, 1, v_w)
            v_dtype = jnp.dtype(jnp.float32) if quant else self.dtype
        else:
            k_shape = v_shape = (num_pages, page_size, kv_heads, head_dim)
            k_dtype = v_dtype = self.dtype
        self.sharding = None
        if mesh is not None and kv_axis in getattr(mesh, "axis_names", ()):
            from jax.sharding import NamedSharding, PartitionSpec as P
            tp = mesh.shape[kv_axis]
            # the latent stream has no head axis to split — replicate
            if latent_dim is None and kv_heads % tp == 0:
                self.sharding = NamedSharding(
                    mesh, P(None, None, kv_axis, None))

        def make(shape, dt):
            z = jnp.zeros(shape, dt)
            return jax.device_put(z, self.sharding) if self.sharding \
                else z

        self.k_pages: Tuple[jax.Array, ...] = tuple(
            make(k_shape, k_dtype) for _ in range(num_layers))
        self.v_pages: Tuple[jax.Array, ...] = tuple(
            make(v_shape, v_dtype) for _ in range(num_layers))
        # LIFO free list: recently-freed pages are re-issued first (their
        # HBM is hot); page 0 reserved
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        self._allocated = set()
        # read-only pages owned by the prefix cache: page -> live sharers
        # (refcount() reports 1 + sharers; 0 sharers = LRU-evictable)
        self._cached: Dict[int, int] = {}
        # invoked by alloc() when the free list can't cover a request:
        # fn(n_short) reclaims up to n_short cached pages (LRU sweep)
        self._reclaim: Optional[Callable[[int], int]] = None
        # times a reclaim hook CLAIMED more/fewer pages than actually
        # landed on the free list (alloc verifies the delta; a lying
        # hook falls through to preemption instead of IndexError)
        self.reclaim_shortfalls = 0
        # O(num_pages) invariant rebuilds are opt-in: tests/engines set
        # debug=True (or pass force=) — bench/production paths skip them
        self.debug = bool(debug)
        # append-only op log ``(seq, op, pages)`` — the page plane of
        # the analysis event stream (analysis/events.py normalizes it
        # into page.alloc/free/cache/... events).  Always on: one tuple
        # append per allocator op is noise next to the page bookkeeping
        # itself, and a conditional log would make the protocol lint
        # silently vacuous on production-configured pools.
        self.event_log: List[Tuple[int, str, List[int]]] = []

    # -- allocator -----------------------------------------------------------

    @property
    def num_usable(self) -> int:
        return self.num_pages - 1

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return len(self._allocated)

    @property
    def utilization(self) -> float:
        return self.used_pages / self.num_usable

    def pages_for(self, n_tokens: int) -> int:
        """Pages needed to hold ``n_tokens`` KV entries."""
        return -(-int(n_tokens) // self.page_size)

    def alloc(self, n: int) -> Optional[List[int]]:
        """Allocate ``n`` pages; None (no partial grant) when the pool
        can't satisfy the request — the scheduler's eviction signal.
        When a reclaim hook is installed (the prefix cache's LRU sweep),
        a dry free list triggers it BEFORE giving up: cached refcount-0
        pages are recycled ahead of recompute preemption.

        The hook's CLAIMED count is never trusted: only pages that
        actually landed on the free list satisfy the request, so a
        lying/partial sweep degrades to a clean ``None`` (the caller's
        preemption path) instead of a short grant.  A mismatch between
        claim and delivery is recorded in ``reclaim_shortfalls`` —
        it means the reclaim hook's accounting is broken."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free) and self._reclaim is not None:
            before = len(self._free)
            claimed = self._reclaim(n - before)
            delivered = len(self._free) - before
            if claimed is not None and int(claimed) != delivered:
                self.reclaim_shortfalls += 1
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        self._allocated.update(pages)
        if pages:
            self.event_log.append((protocol_seq(), "alloc", list(pages)))
        return pages

    def free(self, pages: Sequence[int]) -> None:
        for pg in pages:
            if pg not in self._allocated:
                raise ValueError(f"double free / foreign page {pg}")
            self._allocated.remove(pg)
            self._free.append(pg)
        pages = list(pages)
        if pages:
            self.event_log.append((protocol_seq(), "free", pages))

    # -- cached (read-only, refcounted) pages --------------------------------

    def set_reclaim(self, fn: Optional[Callable[[int], int]]) -> None:
        """Install the cache's LRU sweep: ``fn(n)`` frees up to ``n``
        refcount-0 cached pages; ``alloc`` calls it before failing."""
        self._reclaim = fn

    @property
    def cached_pages(self) -> int:
        return len(self._cached)

    def refcount(self, pg: int) -> int:
        """0 = free, 1 = exclusively owned (allocated, or cached with no
        sharer), 1+n = cached and shared by n live requests.  A KV write
        plan may only ever target refcount-1 ALLOCATED pages — the
        ``cow-page-write`` analysis rule audits exactly this."""
        if pg in self._cached:
            return 1 + self._cached[pg]
        return 1 if pg in self._allocated else 0

    def cache_page(self, pg: int) -> None:
        """allocated -> cached (refcount 0): the finishing request hands
        the fully-written page to the prefix cache, read-only from here."""
        if pg not in self._allocated:
            raise ValueError(f"cannot cache non-allocated page {pg}")
        self._allocated.remove(pg)
        self._cached[pg] = 0
        self.event_log.append((protocol_seq(), "cache", [pg]))

    def share_page(self, pg: int) -> None:
        """A live request attached this cached page to its page table."""
        if pg not in self._cached:
            raise ValueError(f"cannot share non-cached page {pg}")
        self._cached[pg] += 1
        self.event_log.append((protocol_seq(), "share", [pg]))

    def unshare_page(self, pg: int) -> None:
        if self._cached.get(pg, 0) < 1:
            raise ValueError(f"unshare of page {pg} with no sharers")
        self._cached[pg] -= 1
        self.event_log.append((protocol_seq(), "unshare", [pg]))

    def uncache_page(self, pg: int) -> None:
        """cached (refcount 0) -> free: the cache evicted the entry; the
        index entry must already be gone so no lookup can hand the page
        out again after it becomes writable."""
        if pg not in self._cached:
            raise ValueError(f"cannot uncache non-cached page {pg}")
        if self._cached[pg] != 0:
            raise ValueError(f"evicting cached page {pg} with "
                             f"{self._cached[pg]} live sharers")
        del self._cached[pg]
        self._free.append(pg)
        self.event_log.append((protocol_seq(), "uncache", [pg]))

    def reset(self, clear_pages: bool = False) -> None:
        """Return the pool to its post-construction allocator state.

        The rebuilt free-list must EXCLUDE the reserved trash page 0 —
        a naive ``range(num_pages)`` rebuild would hand page 0 to the
        next request and real KV writes would land in the padding sink
        (every padded page-table slot points there).  Regression-tested:
        alloc-after-reset can never return page 0.

        ``clear_pages`` additionally zeroes the page storage (off by
        default: allocator reuse does not require wiping HBM, and stale
        KV beyond ``seq_len`` is masked by the attention op anyway).
        """
        self._free = list(range(self.num_pages - 1, 0, -1))
        self._allocated = set()
        self._cached = {}
        self.event_log = [(protocol_seq(), "reset", [])]
        if clear_pages:
            self.k_pages = tuple(jnp.zeros_like(p) for p in self.k_pages)
            self.v_pages = tuple(jnp.zeros_like(p) for p in self.v_pages)

    def check_invariants(self, force: bool = False) -> None:
        """Allocator bookkeeping invariants: free/allocated/cached
        PARTITION the usable pages (pairwise disjoint, nothing leaked or
        invented), trash page never issued, cached refcounts
        non-negative.  Rebuilding the sets is O(num_pages), so the check
        is OPT-IN: a no-op unless the pool was built with ``debug=True``
        (tests, debug engines) or ``force=True`` is passed — bench and
        production paths skip it on every scheduling storm."""
        if not (self.debug or force):
            return
        # one implementation: the protocol verifier's snapshot predicate
        # (analysis/protocol.py) owns the invariant logic; this wrapper
        # keeps the debug/force gating and assert-style reporting every
        # existing call site relies on (imported lazily — the analysis
        # package must stay optional for serving)
        from ..analysis.protocol import page_partition_problems
        problems = page_partition_problems(
            self.num_pages, self._free, self._allocated, self._cached)
        assert not problems, "; ".join(problems)

    # -- accounting ----------------------------------------------------------

    @property
    def is_latent(self) -> bool:
        return self.latent_dim is not None

    def page_array_shapes(self) -> Tuple[Tuple[Tuple[int, ...], ...],
                                         Tuple[Tuple[int, ...], ...]]:
        """Actual per-layer (k, v) page-array shapes — what the jitted
        executables see, and what ``analysis/memory.py`` classifies as
        kv-page operands.  Derived from the live arrays, never from the
        constructor attrs, so it is correct for every layout."""
        return (tuple(tuple(p.shape) for p in self.k_pages),
                tuple(tuple(p.shape) for p in self.v_pages))

    @property
    def page_bytes(self) -> int:
        """HBM bytes one page holds across k+v and all layers, summed
        from the ACTUAL page arrays via :func:`page_shape_bytes` (the
        one shared helper — transport pricing and metrics read this
        property, so they can never disagree with the real layout)."""
        return sum(page_shape_bytes(p.shape, p.dtype)
                   for p in self.k_pages) + \
            sum(page_shape_bytes(p.shape, p.dtype) for p in self.v_pages)

    @property
    def kv_bytes_per_token(self) -> int:
        """KV bytes ONE cached token costs across all layers (page
        bytes amortized over the page's token slots)."""
        return self.page_bytes // self.page_size

    @property
    def layout_tag(self) -> Tuple[int, ...]:
        """Compact int tuple identifying the page LAYOUT (not contents):
        two pools agree on this iff a page extracted from one can be
        injected into the other and read back identically.  Salted into
        the prefix-cache digest so a latent replica and a full-head
        replica can never cross-match in the router."""
        if self.is_latent:
            return (1, self.latent_dim, self.rope_dim,
                    _QUANT_CODES[self.quant], self.dtype.itemsize)
        return (0, self.kv_heads, self.head_dim, 0, self.dtype.itemsize)

    def set_pages(self, k_pages, v_pages) -> None:
        """Install updated page arrays (the jitted executables return new
        arrays; the pool is the single owner of the live version)."""
        self.k_pages = tuple(k_pages)
        self.v_pages = tuple(v_pages)
