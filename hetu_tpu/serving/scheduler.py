"""Continuous-batching scheduler: admission, page budget, preemption.

Every engine step the scheduler (1) admits arrived requests while the
page budget and batch-slot budget allow, and (2) guarantees every
running request a page for its next token, preempting the
latest-arrived request (recompute-style eviction: pages freed, sequence
re-prefilled later from its accumulated tokens) when the pool runs dry.

Shape buckets (DESIGN.md §4 discipline, §8 for serving): decode batches
are padded to power-of-two sizes and prefill lengths to
power-of-two page multiples, so the number of distinct compiled
executables is bounded by ``log2(max_batch) * log2(max_pages)`` rather
than growing with traffic.
"""
from __future__ import annotations

from typing import List, Tuple

from .kv_pool import PagedKVPool
from .request import WAITING, Request, RequestQueue


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


class Scheduler:
    def __init__(self, pool: PagedKVPool, max_batch: int = 8):
        self.pool = pool
        self.max_batch = int(max_batch)

    # -- shape buckets -------------------------------------------------------

    def decode_bucket(self, n_live: int) -> int:
        """Decode batch bucket: next power of two, capped at max_batch."""
        return min(self.max_batch, _next_pow2(max(1, n_live)))

    def prefill_bucket(self, n_tokens: int) -> int:
        """Prefill length bucket: power-of-two number of pages (so the
        dense prefill cache scatters into whole pages with static
        slices)."""
        ps = self.pool.page_size
        return ps * _next_pow2(self.pool.pages_for(max(1, n_tokens)))

    # -- admission -----------------------------------------------------------

    def admit(self, queue: RequestQueue, running: List[Request],
              now: float) -> List[Request]:
        """Pop arrived requests while a batch slot AND the pages for
        prompt+first-token fit.  Stops at the first request that doesn't
        fit (FIFO — no small-request overtaking, keeps TTFT fair)."""
        admitted: List[Request] = []
        budget = self.pool.free_pages   # pages not yet claimed this step
        while len(running) + len(admitted) < self.max_batch:
            req = queue.pop_ready(now)
            if req is None:
                break
            need = self.pool.pages_for(len(req.tokens) + 1)
            if need > budget:
                queue.push(req)        # original arrival order: stays first
                break
            budget -= need
            admitted.append(req)
        return admitted

    # -- decode page budget --------------------------------------------------

    def ensure_decode_pages(self, running: List[Request]
                            ) -> Tuple[List[Request], List[Request]]:
        """Give every running request a page for its next KV write,
        evicting latest-arrived requests on exhaustion.  Returns
        (kept, evicted); evicted requests are already reset to WAITING
        with their pages freed."""
        evicted: List[Request] = []
        kept = sorted(running, key=lambda r: (r.arrival_time, r.req_id))
        for req in list(kept):
            if req in evicted:
                continue
            if len(req.pages) * self.pool.page_size >= req.pos + 1:
                continue               # current page still has room
            while True:
                got = self.pool.alloc(1)
                if got is not None:
                    req.pages.extend(got)
                    req.peak_pages = max(req.peak_pages, len(req.pages))
                    break
                victims = [r for r in kept
                           if r not in evicted and r is not req]
                victim = max(victims,
                             key=lambda r: (r.arrival_time, r.req_id)) \
                    if victims else req
                self.preempt(victim)
                evicted.append(victim)
                if victim is req:
                    break
        return [r for r in kept if r not in evicted], evicted

    def preempt(self, req: Request) -> None:
        """Recompute-style eviction: drop KV state, keep the token
        history — re-prefilling ``req.tokens`` reproduces the sequence
        exactly (asserted at temperature 0 in tests)."""
        self.pool.free(req.pages)
        req.pages = []
        req.pos = 0
        req.state = WAITING
        req.n_preemptions += 1
