"""Continuous-batching scheduler: admission, token-budget packing,
page budget, preemption.

Every engine step the scheduler (1) admits arrived requests while the
page budget and sequence-slot budget allow, (2) guarantees every
running request a page for its next KV write (preempting the
latest-arrived request — recompute-style eviction — when the pool runs
dry), and (3) **packs** the step's ragged token batch for the single
unified executable (DESIGN.md §12):

- every request one token from emitting (``remaining == 1`` — a decode,
  or the 1-token tail of a chunked prefill: the degenerate case) takes a
  single-token slot.  There are ``max_batch`` of them and at most
  ``max_batch`` live requests, so **every decode advances every step**
  — a long prompt arrival can never stall running decodes;
- remaining budget goes to prefill chunks: the earliest-arrived
  requests still mid-prompt each get one ``chunk`` slot
  (``prefill_rows`` of them per step), Sarathi-style.  A prompt longer
  than ``chunk`` prefills over several steps, interleaved with decodes
  in the SAME executable call.

There are no shape buckets and no per-request prefill executables: the
packed batch always has the same ``max_batch + prefill_rows * chunk``
token shape, so the engine compiles exactly one program no matter the
traffic mix.
"""
from __future__ import annotations

from typing import List, Tuple

from .kv_pool import PagedKVPool
from .request import RUNNING, WAITING, Request, RequestQueue


class Scheduler:
    def __init__(self, pool: PagedKVPool, max_batch: int = 8,
                 chunk: int = 64, prefill_rows: int = 1,
                 prefix_cache=None):
        if prefill_rows < 1:
            raise ValueError(f"prefill_rows must be >= 1, got "
                             f"{prefill_rows}")
        if chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        self.pool = pool
        self.max_batch = int(max_batch)
        self.chunk = int(chunk)
        self.prefill_rows = int(prefill_rows)
        # speculative mode (set by the engine): verify_slots dedicated
        # spec_width-wide rows after the chunk slots — one per
        # decode-capable request, so verify bursts NEVER compete with
        # prompt prefills for chunk slots
        self.verify_slots = 0
        self.spec_width = 0
        # optional serving.prefix_cache.PrefixCache: admission charges
        # only the UNCACHED suffix against the page budget (and counts
        # refcount-0 cached pages as reclaimable), preemption releases
        # shared pages instead of freeing them
        self.cache = prefix_cache

    @property
    def token_budget(self) -> int:
        """Tokens one packed step can carry (the executable's T)."""
        return self.max_batch + self.prefill_rows * self.chunk \
            + self.verify_slots * self.spec_width

    # -- admission -----------------------------------------------------------

    def admit(self, queue: RequestQueue, running: List[Request],
              now: float) -> List[Request]:
        """Pop arrived requests while a sequence slot AND the pages for
        prompt+first-token fit.  FRESH requests stop at the first that
        doesn't fit (FIFO — no small-request overtaking, keeps TTFT
        fair); a PAGE-HOLDING request (disaggregated-handoff adoption:
        pages already attached while WAITING) may overtake a blocked
        head.  That overtake is the deadlock breaker, not a fairness
        leak: a page-holder behind a blocked head means nothing is
        running and nothing will free pages — admitting the holder lets
        it finish and return exactly the pages the head is waiting for.

        With a prefix cache, a candidate is charged only its UNCACHED
        suffix: matched pages come for free, and refcount-0 cached pages
        count as reclaimable budget (the pool's reclaim hook evicts them
        on demand at ``_start``) — except the matched ones themselves,
        which this admission is about to pin."""
        admitted: List[Request] = []
        deferred: List[Request] = []
        # free pages + LRU-reclaimable cached pages not yet claimed
        budget = self.pool.free_pages
        if self.cache is not None:
            budget += self.cache.evictable_pages
        pinned = set()
        while len(running) + len(admitted) < self.max_batch:
            req = queue.pop_ready(now)
            if req is None:
                break
            if deferred and not req.pages:
                # fresh-FIFO behind a block: only page-holders may
                # still admit, so skip the match/pin work entirely —
                # under a deep backlog this keeps the scan O(ready),
                # not O(ready x prompt pages)
                deferred.append(req)
                continue
            # an adopted request brings its own pages — charge only
            # what it still lacks.  Cache matching mirrors _start's
            # lookup condition exactly (fresh pos-0 requests only):
            # charging a cached page the start path won't attach would
            # wedge admission the same way ignoring owned pages did
            need = self.pool.pages_for(len(req.tokens) + 1) \
                - len(req.pages)
            new_pins = []
            if self.cache is not None and req.pos == 0 and not req.pages:
                for e in self.cache.match(req.tokens):
                    need -= 1          # cached page: nothing to allocate
                    if e.refs == 0 and e.eid not in pinned:
                        budget -= 1    # ...but it is no longer evictable
                        pinned.add(e.eid)
                        new_pins.append(e.eid)
            need = max(0, need)
            if need > budget:
                # blocked: the scan continues only so page-holders
                # further back can still admit.  The pins THIS
                # candidate took are rolled back — a deferred request
                # must not shrink the budget later page-holders see, or
                # the overtake stops working exactly when nothing is
                # running to free pages
                for eid in new_pins:
                    pinned.discard(eid)
                    budget += 1
                deferred.append(req)
                continue
            budget -= need
            admitted.append(req)
        for req in deferred:
            queue.push(req)            # heap order restores FIFO
        return admitted

    # -- token-budget packing ------------------------------------------------

    def pack(self, running: List[Request]
             ) -> List[Tuple[Request, int, int]]:
        """Assign the step's rows: ``[(request, q_len, row_index)]``.

        Single-token rows (``remaining == 1``) fill slots
        ``[0, max_batch)``; mid-prompt requests fill chunk slots
        ``[max_batch, max_batch + prefill_rows)`` in class-then-arrival
        order (interactive prefills ride before batch ones) with
        ``q_len = min(remaining, chunk)`` — EXACTLY as without spec
        mode: prefill chunks are TTFT-critical and speculation never
        touches them.  In spec mode each decode-ready request with
        staged draft proposals instead takes a DEDICATED verify slot
        (``[max_batch + prefill_rows, max_batch + prefill_rows +
        verify_slots)``, width ``spec_width``) with ``q_len = 1 +
        len(spec_drafts)`` — there is one verify slot per sequence
        slot, so a staged burst always rides and the no-decode-stall
        guarantee is untouched (an unstaged or shed request still gets
        its decode slot).  Requests beyond the chunk slots simply wait
        — they are still RUNNING and keep their pages, they just don't
        ride this step."""
        live = sorted((r for r in running if r.state == RUNNING),
                      key=lambda r: (r.rank, r.arrival_time, r.req_id))
        rows: List[Tuple[Request, int, int]] = []
        verified = set()
        vrow = 0
        vbase = self.max_batch + self.prefill_rows
        for r in live:
            remaining = len(r.tokens) - r.pos
            staged = len(r.spec_drafts)
            if remaining == 1 and staged and vrow < self.verify_slots \
                    and 1 + staged <= self.spec_width:
                rows.append((r, 1 + staged, vbase + vrow))
                vrow += 1
                verified.add(r.req_id)
        slot = 0
        for r in live:
            remaining = len(r.tokens) - r.pos
            if remaining == 1 and r.req_id not in verified \
                    and slot < self.max_batch:
                rows.append((r, 1, slot))
                slot += 1
        chunk_row = 0
        for r in live:
            remaining = len(r.tokens) - r.pos
            if remaining > 1 and chunk_row < self.prefill_rows:
                rows.append((r, min(remaining, self.chunk),
                             self.max_batch + chunk_row))
                chunk_row += 1
        return rows

    def slot_mix(self, rows: List[Tuple[Request, int, int]]
                 ) -> dict:
        """The step's packing decision as a flat dict — the trace
        plane emits it as the per-step ``pack`` instant event, so a
        Perfetto timeline shows exactly how each executable call's
        token budget was split between decode slots and prefill
        chunks."""
        vbase = self.max_batch + self.prefill_rows
        n_decode = sum(1 for _, _, row in rows if row < self.max_batch)
        n_verify = sum(1 for _, _, row in rows if row >= vbase)
        return {"decode_slots": n_decode,
                "chunk_slots": len(rows) - n_decode - n_verify,
                "verify_slots": n_verify,
                "spec_tokens": int(sum(len(r.spec_drafts)
                                       for r, _, row in rows
                                       if row >= vbase)),
                "tokens": int(sum(q for _, q, _ in rows)),
                "token_budget": self.token_budget,
                "chunk": self.chunk,
                "prefill_rows": self.prefill_rows}

    # -- decode page budget --------------------------------------------------

    def ensure_decode_pages(self, running: List[Request]
                            ) -> Tuple[List[Request], List[Request]]:
        """Give every running request the pages its next KV writes
        need, evicting lowest-class latest-arrived requests on
        exhaustion.  Returns
        (kept, evicted); evicted requests are already reset to WAITING
        with their pages freed.  Mid-prefill requests were granted their
        whole prompt's pages at admission, so only emitted-token growth
        allocates here — one page per decode step, or up to
        ``ceil((1 + staged drafts) / page_size)`` for a speculative
        verify row (its burst writes ``pos .. pos + spec_len``, which
        may cross a page boundary).  A page squeeze sheds the
        requester's staged drafts FIRST — degrading a burst to a plain
        decode is free, while preempting any request costs its whole
        prefill — and only then falls back to eviction."""
        evicted: List[Request] = []
        kept = sorted(running,
                      key=lambda r: (r.rank, r.arrival_time, r.req_id))
        for req in list(kept):
            if req in evicted:
                continue
            while True:
                need_tokens = req.pos + 1 + len(req.spec_drafts)
                have = len(req.pages) * self.pool.page_size
                if have >= need_tokens:
                    break              # current pages still have room
                got = self.pool.alloc(self.pool.pages_for(need_tokens)
                                      - len(req.pages))
                if got is not None:
                    req.pages.extend(got)
                    req.peak_pages = max(req.peak_pages, len(req.pages))
                    break
                if req.spec_drafts:
                    req.spec_drafts = []   # shed the burst, keep running
                    continue
                # lowest class first, then latest arrival: a batch
                # straggler is always evicted before any interactive
                # request loses its prefill.  The requester ITSELF is a
                # candidate — a batch request squeezing for a decode
                # page must self-preempt rather than take a page from a
                # higher class (that would be an SLO-class inversion)
                victims = [r for r in kept if r not in evicted]
                victim = max(victims,
                             key=lambda r: (r.rank, r.arrival_time,
                                            r.req_id))
                self.preempt(victim)
                evicted.append(victim)
                if victim is req:
                    break
        return [r for r in kept if r not in evicted], evicted

    def preempt(self, req: Request) -> None:
        """Recompute-style eviction: drop KV state, keep the token
        history — re-prefilling ``req.tokens`` (chunked like any other
        prompt) reproduces the sequence exactly (asserted at
        temperature 0 in tests).  Shared prefix-cache pages are
        RELEASED (refcount drop), never freed — other requests and the
        cache index still hold them; only exclusively-owned pages return
        to the free list."""
        self.pool.free(req.pages[req.shared_pages:])
        if self.cache is not None and req.shared_pages:
            self.cache.release(req)
        req.pages = []
        req.shared_pages = 0
        req.cached_tokens = 0
        req.spec_drafts = []
        req.pos = 0
        req.state = WAITING
        req.n_preemptions += 1
