"""The unified serving executable: one jit for ragged prefill + decode.

v1 (PR 2) compiled a GRID of programs — one bucketed prefill executable
per power-of-two prompt length, one decode executable per power-of-two
batch size — and ran every admitted request's prefill as its own call.
That bounded compiles logarithmically but still paid
O(prefill buckets x batch buckets) compiles and serialized prefills,
which is exactly where the v1 bench lost (15.5 tok/s paged vs 25.6
dense, TTFT p90 6.3 s, BENCH_SERVING.json v1).

``build_unified_step_fn`` replaces the whole grid with ONE executable
over a fixed-shape **ragged token batch** (DESIGN.md §12):

- the token axis ``[T]`` = ``max_seqs`` single-token slots (decode — the
  degenerate 1-query-token case) followed by ``prefill_rows`` chunk
  slots of ``chunk_size`` tokens each (Sarathi-style prefill chunks);
- raggedness is described per row by ``(q_lens, cu_q, page_tables,
  ctx_lens)`` — the same scalar arrays the
  :mod:`~hetu_tpu.ops.ragged_paged_attention` kernel prefetches;
- every layer runs the projections/MLP over the WHOLE token axis (one
  MXU-shaped matmul for mixed prefill+decode, the core RPA win),
  scatter-writes each token's k/v into its page at ``(token_page,
  token_off)`` (padding tokens land in the trash page), and attends
  raggedly: the Pallas kernel on TPU, or — off TPU — a split dense
  fallback whose decode half IS ``paged_attention_reference`` (the
  bit-for-bit-proven v1 decode math) and whose chunk half is the same
  gather+masked-dense attention with a causal in-row mask;
- sampling is ON DEVICE for every mode: greedy argmax (bit-for-bit the
  ``jnp.argmax`` solo ``generate()`` runs), or temperature / top-k /
  top-p (nucleus) from a per-row params vector, keyed by
  ``fold_in(PRNGKey(seed), ctx_len)`` so a request's sample at token
  position ``n`` is identical regardless of batching, chunking or
  preemption.  The engine fetches ``[rows]`` int32 — never a ``[B, V]``
  logits matrix (``host_logit_fetches`` stays 0 on mixed traffic).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..models.generate import (_act, _lm_head, _moe_mlp, _norm_apply,
                               _Params, _rotary_tables)
from ..models.gpt import GPTConfig
from ..ops.paged_attention import paged_attention_reference
from ..ops.quantization import quantize_rows
from ..ops.ragged_paged_attention import (_dequant_latent,
                                          latent_paged_attention_reference,
                                          latent_ragged_paged_attention_pallas,
                                          ragged_paged_attention_pallas,
                                          sample_row, sample_rows,
                                          speculative_verify_head)

def _params_view(cfg: GPTConfig, params) -> _Params:
    p = _Params.__new__(_Params)
    p.s, p.cfg = params, cfg
    return p


def _rope_tok(x, cos_g, sin_g):
    """Rotary embedding at per-token positions: x [T, h, d], cos_g/sin_g
    [T, d] (already position-gathered).  Same arithmetic as
    ``generate._rope`` — the flat token axis just has a DIFFERENT
    position per row, so the table gather happens outside."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    rot = jnp.concatenate([-x2, x1], axis=-1)
    c = cos_g[:, None, :].astype(x.dtype)
    s = sin_g[:, None, :].astype(x.dtype)
    return x * c + rot * s


def _chunk_slots(max_seqs: int, prefill_rows: int, chunk: int,
                 spec_k: int):
    """The multi-token slot layout shared by the region map, the
    split-attention fallback and the engine's ``cu_q``: a list of
    ``(row_index, token_start, width)``.  Plain prefill chunk slots
    come first; in spec mode (``spec_k > 0``) every decode-capable
    request additionally owns a DEDICATED verify slot of width
    ``spec_k + 1`` — a verify row is structurally a prefill chunk, but
    giving it its own narrow slot means verifying k drafts prices
    ``k + 1`` tokens of compute, not a whole ``chunk``-wide slot, and
    verify traffic never competes with prompt prefills for slots."""
    slots = [(max_seqs + r, max_seqs + r * chunk, chunk)
             for r in range(prefill_rows)]
    if spec_k:
        base = max_seqs + prefill_rows * chunk
        vk = spec_k + 1
        slots += [(max_seqs + prefill_rows + j, base + j * vk, vk)
                  for j in range(max_seqs)]
    return slots


def _split_ragged_attention(cfg: GPTConfig, q, kp, vp, q_lens,
                            page_tables, ctx_lens, max_seqs: int,
                            prefill_rows: int, chunk: int,
                            spec_k: int = 0):
    """Off-TPU ragged attention over the structured serving layout.

    The flat batch's FIRST ``max_seqs`` tokens are the single-token
    decode slots: they run through :func:`paged_attention_reference` —
    literally the v1 decode math, so temperature-0 decode stays
    bit-for-bit with solo ``generate()``.  Each multi-token slot
    (prefill chunk or — spec mode — verify row) then runs
    gather+masked-dense attention over its own page table with the
    causal in-row mask (query j at absolute position
    ``ctx - q_len + j``).  Padding decode slots attend one trash-page
    slot (``max(ctx, 1)``) and padding chunk rows attend trash pages —
    finite junk, never NaN, discarded by the engine."""
    c = cfg
    hd, nh, kvh = c.head_dim, c.num_heads, c.kv_heads
    g = nh // kvh
    maxp = page_tables.shape[1]
    ps = kp.shape[1]
    scale = hd ** -0.5
    # decode slots: [S] one-token rows (v1 math, bitwise-proven)
    outs = [paged_attention_reference(
        q[:max_seqs], kp, vp, page_tables[:max_seqs],
        jnp.maximum(ctx_lens[:max_seqs], 1))]
    # power-of-two page-window levels: a chunk whose context spans n
    # pages attends only the first level >= n pages of its table.  The
    # dropped tail slots are exactly the ones the causal mask would zero
    # (trailing exact-zero softmax terms — removing them is the same
    # width-invariance the decode path already relies on, so chunk
    # numerics stay bit-for-bit with the full-width form).  Level 0 is
    # the idle slot: decode-only steps skip the chunk region entirely —
    # the CPU analogue of the Pallas kernel's pl.when page skipping.
    levels = [0]
    n = 1
    while n < maxp:
        levels.append(n)
        n *= 2
    levels.append(maxp)
    levels_arr = jnp.asarray(levels, jnp.int32)

    def make_chunk_attn(npages, width_q):
        if npages == 0:
            return lambda qc, pt_row, ctx, qlen: jnp.zeros(
                (width_q, nh, hd), q.dtype)

        # near-twin of ops.ragged_paged_attention_reference's per-row
        # body, but NOT shared on purpose: this path masks with -inf
        # (exact-zero softmax terms — the bit-for-bit-vs-solo contract),
        # while the ops reference mirrors the kernel's finite
        # DEFAULT_MASK_VALUE for interpret-mode parity
        def attn(qc, pt_row, ctx, qlen):
            width = npages * ps
            qg = qc.reshape(width_q, kvh, g, hd).astype(jnp.float32)
            k = kp[pt_row[:npages]].reshape(width, kvh, hd)
            v = vp[pt_row[:npages]].reshape(width, kvh, hd)
            s = jnp.einsum("qhgd,khd->qhgk", qg,
                           k.astype(jnp.float32)) * scale
            qpos = (ctx - qlen) + jnp.arange(width_q)
            valid = jnp.arange(width)[None, :] <= qpos[:, None]
            s = jnp.where(valid[:, None, None, :], s, -jnp.inf)
            pr = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("qhgk,khd->qhgd", pr, v.astype(jnp.float32))
            return o.reshape(width_q, nh, hd).astype(q.dtype)

        return attn

    branch_sets = {}                     # per slot width
    for row, start, width_q in _chunk_slots(max_seqs, prefill_rows,
                                            chunk, spec_k):
        if width_q not in branch_sets:
            branch_sets[width_q] = [make_chunk_attn(npages, width_q)
                                    for npages in levels]
        qc = q[start: start + width_q]
        need = -(-ctx_lens[row] // ps)              # pages ctx spans
        lvl = jnp.searchsorted(levels_arr, need)
        lvl = jnp.where(q_lens[row] > 0, lvl, 0)    # idle -> level 0
        outs.append(lax.switch(lvl, branch_sets[width_q], qc,
                               page_tables[row], ctx_lens[row],
                               q_lens[row]))
    return jnp.concatenate(outs, axis=0)


def _split_latent_ragged_attention(cfg: GPTConfig, q_cat, cp, rp, q_lens,
                                   page_tables, ctx_lens, max_seqs: int,
                                   prefill_rows: int, chunk: int,
                                   spec_k: int = 0, scale_pages=None,
                                   quant=None):
    """Latent (MLA) twin of :func:`_split_ragged_attention`: absorbed
    ``q_cat [T, nh, d_c+d_r]`` against the single latent stream ``cp``
    (+ optional rope stream ``rp`` / absmax sidecar ``scale_pages``),
    returning the LATENT attention output ``[T, nh, d_c]`` fp32 — the
    caller applies the ``v_up`` fold.  Decode slots run
    :func:`latent_paged_attention_reference` and chunk/verify slots run
    the same pow2 page-window ``lax.switch`` with ``-inf`` masking, so
    temp-0 latent serving stays bit-for-bit with the solo MLA oracle
    (``models.generate._mla_attn_step``)."""
    c = cfg
    hd, nh = c.head_dim, c.num_heads
    d_c, d_r = c.kv_latent_dim, c.rope_dim
    maxp = page_tables.shape[1]
    ps = cp.shape[1]
    scale = (hd + d_r) ** -0.5
    outs = [latent_paged_attention_reference(
        q_cat[:max_seqs], cp, rp, page_tables[:max_seqs],
        jnp.maximum(ctx_lens[:max_seqs], 1), softmax_scale=scale,
        scale_pages=scale_pages, quant=quant, latent_dim=d_c)]
    levels = [0]
    n = 1
    while n < maxp:
        levels.append(n)
        n *= 2
    levels.append(maxp)
    levels_arr = jnp.asarray(levels, jnp.int32)

    def make_chunk_attn(npages, width_q):
        if npages == 0:
            return lambda qc, pt_row, ctx, qlen: jnp.zeros(
                (width_q, nh, d_c), jnp.float32)

        def attn(qc, pt_row, ctx, qlen):
            width = npages * ps
            qf = qc.astype(jnp.float32)
            cw = cp[pt_row[:npages]].reshape(width, cp.shape[-1])
            sw = None if scale_pages is None else \
                scale_pages[pt_row[:npages]].reshape(width, 1)
            cd = _dequant_latent(cw, sw, quant, d_c)   # [width, d_c]
            if d_r:
                r = rp[pt_row[:npages]].reshape(width, d_r)
                k = jnp.concatenate([cd, r.astype(jnp.float32)], -1)
            else:
                k = cd
            s = jnp.einsum("qhc,kc->qhk", qf, k) * scale
            qpos = (ctx - qlen) + jnp.arange(width_q)
            valid = jnp.arange(width)[None, :] <= qpos[:, None]
            s = jnp.where(valid[:, None, :], s, -jnp.inf)
            pr = jax.nn.softmax(s, axis=-1)
            return jnp.einsum("qhk,kc->qhc", pr, cd)

        return attn

    branch_sets = {}
    for row, start, width_q in _chunk_slots(max_seqs, prefill_rows,
                                            chunk, spec_k):
        if width_q not in branch_sets:
            branch_sets[width_q] = [make_chunk_attn(npages, width_q)
                                    for npages in levels]
        qc = q_cat[start: start + width_q]
        need = -(-ctx_lens[row] // ps)
        lvl = jnp.searchsorted(levels_arr, need)
        lvl = jnp.where(q_lens[row] > 0, lvl, 0)
        outs.append(lax.switch(lvl, branch_sets[width_q], qc,
                               page_tables[row], ctx_lens[row],
                               q_lens[row]))
    return jnp.concatenate(outs, axis=0)


# the on-device per-row sampler lives next to the verify head in
# ops/ragged_paged_attention.py (ONE implementation: the speculative
# accept rule is "the draft matches this sampler's keyed choice", which
# is only sound if verify and non-verify rows draw identically); the
# old name stays importable here
_sample_row = sample_row


def build_unified_step_fn(cfg: GPTConfig, max_seqs: int, chunk: int,
                          prefill_rows: int, max_pages: int,
                          page_size: int, use_kernel: bool = False,
                          spec_k: int = 0, page_quant=None):
    """Compile THE serving executable: one ragged prefill+decode step.

    Token-axis layout (static)::

        [0 .. max_seqs)                    decode slots, 1 token each
        [max_seqs .. max_seqs + R*chunk)   R = prefill_rows chunk slots

    fn(params,
       tokens [T] i32, token_pos [T] i32,
       token_page [T] i32, token_off [T] i32,   # KV write plan (trash
                                                # page for padding)
       q_lens [rows] i32, cu_q [rows+1] i32,
       page_tables [rows, max_pages] i32, ctx_lens [rows] i32,
       temps [rows] f32, top_ps [rows] f32,
       top_ks [rows] i32, seeds [rows] i32,
       k_pages, v_pages)
      -> (next_tokens [rows] i32, new k_pages, new v_pages)

    where ``rows = max_seqs + prefill_rows`` and ``T = max_seqs +
    prefill_rows * chunk``.  Every row gets a next-token sample at its
    LAST query token; the engine commits it only when the row reached
    the end of its accumulated sequence (``pos + q_len == len(tokens)``
    — i.e. the final prefill chunk or a decode step).  ALL shapes are
    fixed: the engine compiles this exactly once.

    ``spec_k > 0`` (speculative serving, DESIGN.md §20) grows BOTH the
    layout and the signature.  The token axis gains ``max_seqs``
    dedicated VERIFY slots of ``spec_k + 1`` tokens each (after the
    prefill chunk slots), so every decode-capable request can verify a
    draft burst every step — structurally a prefill chunk, but priced
    at ``k + 1`` tokens of compute instead of a ``chunk``-wide slot,
    and never competing with prompt prefills for chunk slots.  An
    extra ``spec_lens [rows] i32`` input after ``seeds`` marks live
    verify rows (feeding the last committed token plus the drafts),
    and the outputs gain ``accepted [rows] i32`` — the
    longest-accepted-prefix length from the on-device verify head
    (:func:`~hetu_tpu.ops.ragged_paged_attention.speculative_verify_head`).
    For rows with ``spec_len == 0`` (every decode slot, every plain
    prefill chunk, every idle verify slot) ``accepted`` is 0 and
    ``next_tokens`` is computed by the IDENTICAL per-row sampler as
    the non-speculative build — mixed spec/non-spec traffic shares the
    one executable.
    """
    if prefill_rows < 1:
        raise ValueError(f"prefill_rows must be >= 1, got {prefill_rows}")
    if chunk < 1:
        raise ValueError(f"chunk must be >= 1, got {chunk}")
    if spec_k < 0:
        raise ValueError(f"spec_k must be >= 0, got {spec_k}")
    c = cfg
    if page_quant is not None and (not c.is_mla or c.rope_dim):
        raise ValueError("page_quant requires the latent (MLA) layout "
                         "with rope_dim == 0")
    verify_rows = max_seqs if spec_k else 0
    t_tokens = max_seqs + prefill_rows * chunk \
        + verify_rows * (spec_k + 1)
    n_rows = max_seqs + prefill_rows + verify_rows
    max_len = max_pages * page_size
    cdt = jnp.bfloat16 if c.dtype == "bfloat16" else jnp.float32
    cos, sin = (_rotary_tables(c, max_len) if c.position == "rotary"
                else (None, None))
    hd, nh, nkv = c.head_dim, c.num_heads, c.kv_heads

    def region_map(f, h, q_lens, f_chunk=None):
        """Apply a row-wise map ``f`` per region: unconditionally over
        the decode slots, under ``lax.cond`` per chunk slot — an idle
        chunk slot (no prompt in flight) contributes zeros without
        paying its ``[chunk, ...]`` matmul.  Row-wise means per-token
        results are unchanged by the split (bit-for-bit).  ``f_chunk``
        overrides ``f`` for the chunk slots (MoE keeps v1's per-phase
        expert paths: dense per-token mix for decode, dispatched
        group-GEMM for prefill chunks).  The spec-mode VERIFY region
        (``max_seqs`` rows of ``spec_k + 1`` tokens) runs
        unconditionally like the decode slots: the whole region is a
        few dozen tokens, cheaper than the per-slot conditional thunks
        would be, and idle verify tokens are trash-page padding the
        engine discards."""
        fc = f_chunk or f
        parts = [f(h[:max_seqs])]
        for row, start, width in _chunk_slots(max_seqs, prefill_rows,
                                              chunk, 0)[:prefill_rows]:
            sl = h[start: start + width]
            zero = jax.eval_shape(fc, sl)
            parts.append(lax.cond(
                q_lens[row] > 0, fc,
                lambda s, z=zero: jnp.zeros(z.shape, z.dtype), sl))
        if spec_k:
            parts.append(f(h[max_seqs + prefill_rows * chunk:]))
        return jnp.concatenate(parts, axis=0)

    # pages are donated (the pool replaces them wholesale every call, so
    # XLA scatters in place); seeds is donated so the [rows] int32
    # next-token output can alias it instead of tripping donation-miss
    # (spec mode additionally donates spec_lens to back the [rows]
    # accepted output)
    def run_impl(params, tokens, token_pos, token_page, token_off,
                 q_lens, cu_q, page_tables, ctx_lens, temps, top_ps,
                 top_ks, seeds, spec_lens, k_pages, v_pages):
        p = _params_view(c, params)
        x = p("wte.weight")[tokens].astype(cdt)            # [T, H]
        if c.position == "learned":
            x = x + p("wpe")[token_pos].astype(x.dtype)
        new_k, new_v = [], []
        for i in range(c.num_layers):
            h = _norm_apply(c, p.layer(i, "ln_1.weight"),
                            p.layer(i, "ln_1.bias"), x)

            if c.is_mla:
                d_c, d_r = c.kv_latent_dim, c.rope_dim

                def q_proj(hh, i=i):
                    out = hh @ p.layer(i, "attn.q.weight").T
                    qb = p.layer(i, "attn.q.bias")
                    return out + qb if qb is not None else out

                def kv_proj(hh, i=i):
                    out = hh @ p.layer(i, "attn.kv_a.weight").T
                    kb = p.layer(i, "attn.kv_a.bias")
                    return out + kb if kb is not None else out

                qh = region_map(q_proj, h, q_lens).reshape(
                    t_tokens, nh, hd + d_r)
                kv = region_map(kv_proj, h, q_lens)    # [T, d_c + d_r]
                c_kv = kv[..., :d_c]
                k_up = p.layer(i, "attn.k_up.weight")  # [nh, hd, d_c]
                v_up = p.layer(i, "attn.v_up.weight")
                # FlashMLA-ETAP absorption: fold W_UK into q so scores
                # are MQA dot products against the latent stream
                q_abs = jnp.einsum("thd,hdc->thc",
                                   qh[..., :hd].astype(jnp.float32),
                                   k_up.astype(jnp.float32))
                if d_r:
                    q_rope = _rope_tok(qh[..., hd:], cos[token_pos],
                                       sin[token_pos])
                    k_rope = _rope_tok(kv[..., d_c:][:, None, :],
                                       cos[token_pos],
                                       sin[token_pos])[:, 0]
                    q_cat = jnp.concatenate(
                        [q_abs, q_rope.astype(jnp.float32)], -1)
                else:
                    q_cat = q_abs
                with jax.named_scope("kv_page_scatter"):
                    if page_quant:
                        codes, am = quantize_rows(c_kv, page_quant)
                        kp = k_pages[i].at[token_page, token_off].set(
                            codes[:, None, :])
                        vp = v_pages[i].at[token_page, token_off].set(
                            am[:, None, :])
                    else:
                        kp = k_pages[i].at[token_page, token_off].set(
                            c_kv[:, None, :].astype(cdt))
                        if d_r:
                            vp = v_pages[i].at[
                                token_page, token_off].set(
                                k_rope[:, None, :].astype(cdt))
                        else:
                            vp = v_pages[i]        # width-0 rope stream
                rp = None if (page_quant or not d_r) else vp
                sp = vp if page_quant else None
                if use_kernel:
                    o_lat = latent_ragged_paged_attention_pallas(
                        q_cat, kp, rp, q_lens, cu_q, page_tables,
                        ctx_lens, max_q=max(chunk, spec_k + 1),
                        softmax_scale=(hd + d_r) ** -0.5,
                        scale_pages=sp, quant=page_quant,
                        latent_dim=d_c)
                else:
                    o_lat = _split_latent_ragged_attention(
                        c, q_cat, kp, rp, q_lens, page_tables, ctx_lens,
                        max_seqs, prefill_rows, chunk, spec_k=spec_k,
                        scale_pages=sp, quant=page_quant)
                # the W_UV fold: one up-projection per QUERY token —
                # cached tokens are never decompressed
                attn = jnp.einsum("thc,hdc->thd", o_lat,
                                  v_up.astype(jnp.float32))
                attn = attn.reshape(t_tokens, nh * hd).astype(x.dtype)
            else:
                def qkv_proj(hh, i=i):
                    out = hh @ p.layer(i, "attn.qkv.weight").T
                    qb = p.layer(i, "attn.qkv.bias")
                    return out + qb if qb is not None else out

                qkv = region_map(qkv_proj, h, q_lens)
                q_size, kv_size = nh * hd, nkv * hd
                q = qkv[..., :q_size].reshape(t_tokens, nh, hd)
                k = qkv[..., q_size:q_size + kv_size].reshape(
                    t_tokens, nkv, hd)
                v = qkv[..., q_size + kv_size:].reshape(t_tokens, nkv,
                                                        hd)
                if c.position == "rotary":
                    q = _rope_tok(q, cos[token_pos], sin[token_pos])
                    k = _rope_tok(k, cos[token_pos], sin[token_pos])
                with jax.named_scope("kv_page_scatter"):
                    kp = k_pages[i].at[token_page, token_off].set(
                        k.astype(cdt))
                    vp = v_pages[i].at[token_page, token_off].set(
                        v.astype(cdt))
                if use_kernel:
                    attn = ragged_paged_attention_pallas(
                        q, kp, vp, q_lens, cu_q, page_tables, ctx_lens,
                        max_q=max(chunk, spec_k + 1))
                else:
                    attn = _split_ragged_attention(
                        c, q, kp, vp, q_lens, page_tables, ctx_lens,
                        max_seqs, prefill_rows, chunk, spec_k=spec_k)
                attn = attn.reshape(t_tokens, nh * hd).astype(x.dtype)

            def out_proj(aa, i=i):
                out = aa @ p.layer(i, "attn.out.weight").T
                ob = p.layer(i, "attn.out.bias")
                return out + ob if ob is not None else out

            x = x + region_map(out_proj, attn, q_lens)
            h = _norm_apply(c, p.layer(i, "ln_2.weight"),
                            p.layer(i, "ln_2.bias"), x)
            if c.is_moe_layer(i):
                # decode slots: [T', 1, H] -> s=1 dense per-token mix
                # (v1 decode path); chunk slots: [1, C, H] -> dispatched
                # blocked group-GEMM (v1 prefill path) — both exactly
                # equivalent, each matching its v1 phase
                mlp = lambda hh, i=i: _moe_mlp(c, p, i,  # noqa: E731
                                               hh[:, None, :])[:, 0]
                mlp_chunk = lambda hh, i=i: _moe_mlp(c, p, i,  # noqa: E731
                                                     hh[None])[0]
            else:
                mlp_chunk = None

                def mlp(hh, i=i):
                    hh = _act(c, hh @ p.layer(i, "mlp.up.weight").T +
                              (p.layer(i, "mlp.up.bias")
                               if p.layer(i, "mlp.up.bias") is not None
                               else 0.0))
                    hh = hh @ p.layer(i, "mlp.down.weight").T
                    db = p.layer(i, "mlp.down.bias")
                    return hh + db if db is not None else hh

            x = x + region_map(mlp, h, q_lens, f_chunk=mlp_chunk)
            new_k.append(kp)
            new_v.append(vp)
        x = _norm_apply(c, p("ln_f.weight"), p("ln_f.bias"), x)
        # per-row last TRUE query token -> [rows, V] fp32 logits
        last = jnp.clip(cu_q[:n_rows] + jnp.maximum(q_lens, 1) - 1, 0,
                        t_tokens - 1)
        logits = _lm_head(p, x[last])
        # batched sampler: the sort-based sampled path runs under ONE
        # any(temps > 0) branch — all-greedy steps (the temp-0 bitwise
        # contract's case) never pay a vocab argsort per row
        next_tokens = sample_rows(logits, temps, top_ps, top_ks,
                                  seeds, ctx_lens)
        if spec_k == 0:
            return next_tokens, tuple(new_k), tuple(new_v)
        # -- verify head (dedicated verify slots only: decode slots and
        # prefill chunks never stage drafts).  Verify position j of a
        # row starting at cu sits at token cu + j and its logits verify
        # the draft fed at cu + j + 1 (all K windows are computed —
        # fixed shapes — and masked by spec_lens; a spec_len of 0
        # yields accepted == 0 and the per-row sample above stands,
        # which is exactly the non-spec path, bit-for-bit)
        v0 = max_seqs + prefill_rows             # first verify row
        starts = cu_q[v0:n_rows]                 # [R = verify_rows]
        widx = jnp.clip(starts[:, None] + jnp.arange(spec_k)[None, :],
                        0, t_tokens - 1)                   # [R, K]
        vlogits = _lm_head(p, x[widx.reshape(-1)]).reshape(
            verify_rows, spec_k, -1)
        draft_next = tokens[jnp.clip(widx + 1, 0, t_tokens - 1)]
        acc_v, alt_v = speculative_verify_head(
            vlogits, draft_next, spec_lens[v0:], temps[v0:],
            top_ps[v0:], top_ks[v0:], seeds[v0:], ctx_lens[v0:])
        # bonus token: first-rejection alternative, or — on full
        # acceptance — the last-position per-row sample (whose sampling
        # index ctx_lens[r] is exactly the emitted token's index)
        spec_v = spec_lens[v0:]
        bonus_alt = jnp.take_along_axis(
            alt_v, jnp.minimum(acc_v, spec_k - 1)[:, None], axis=1)[:, 0]
        verify_next = jnp.where(acc_v < spec_v, bonus_alt,
                                next_tokens[v0:])
        next_tokens = jnp.concatenate(
            [next_tokens[:v0], verify_next])
        accepted = jnp.concatenate(
            [jnp.zeros(v0, jnp.int32), acc_v])
        return next_tokens, accepted, tuple(new_k), tuple(new_v)

    if spec_k == 0:
        @functools.partial(jax.jit, donate_argnums=(12, 13, 14))
        def run(params, tokens, token_pos, token_page, token_off,
                q_lens, cu_q, page_tables, ctx_lens, temps, top_ps,
                top_ks, seeds, k_pages, v_pages):
            return run_impl(params, tokens, token_pos, token_page,
                            token_off, q_lens, cu_q, page_tables,
                            ctx_lens, temps, top_ps, top_ks, seeds,
                            None, k_pages, v_pages)
    else:
        @functools.partial(jax.jit, donate_argnums=(12, 13, 14, 15))
        def run(params, tokens, token_pos, token_page, token_off,
                q_lens, cu_q, page_tables, ctx_lens, temps, top_ps,
                top_ks, seeds, spec_lens, k_pages, v_pages):
            return run_impl(params, tokens, token_pos, token_page,
                            token_off, q_lens, cu_q, page_tables,
                            ctx_lens, temps, top_ps, top_ks, seeds,
                            spec_lens, k_pages, v_pages)

    return run
