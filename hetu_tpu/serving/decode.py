"""Jitted serving executables: bucketed prefill + paged decode step.

Prefill and decode are SEPARATE compiled programs (DESIGN.md §8): a
prefill is one big [1, s_pad] forward whose arithmetic intensity keeps
the MXU busy, while a decode step is a [B, 1] forward that lives or
dies by HBM bandwidth — fusing them into one executable would force the
decode batch to retrace whenever prefill shapes change and drag
padding-FLOPs into every step.

- ``build_prefill_fn``: dense-cache forward over the padded prompt via
  the same :func:`~hetu_tpu.models.generate.decode_step` that
  ``generate()`` scans (shared layer math, one source of truth), then
  scatters the dense caches into the request's KV pages and projects
  logits at the last TRUE token.
- ``build_decode_fn``: single-token batched step that scatter-writes
  each request's new k/v into its current page and attends through the
  page table with ``ops.paged_attention``.

Both are cached per shape bucket by the engine, so compile count is
bounded by the bucket grid, not the traffic mix.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..models.generate import (_act, _lm_head, _moe_mlp, _norm_apply,
                               _Params, _rotary_tables, decode_step)
from ..models.gpt import GPTConfig
from ..ops.paged_attention import paged_attention_decode


def _params_view(cfg: GPTConfig, params) -> _Params:
    p = _Params.__new__(_Params)
    p.s, p.cfg = params, cfg
    return p


def _rope_at(x, cos_g, sin_g):
    """Rotary embedding at per-request positions: x [B, 1, h, d],
    cos_g/sin_g [B, d] (already position-gathered).  Same arithmetic as
    generate._rope, which takes a shared [s, d] table — decode batches
    have a DIFFERENT position per row, so the gather happens outside."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    rot = jnp.concatenate([-x2, x1], axis=-1)
    c = cos_g[:, None, None, :].astype(x.dtype)
    s = sin_g[:, None, None, :].astype(x.dtype)
    return x * c + rot * s


def build_prefill_fn(cfg: GPTConfig, s_pad: int, max_pages: int,
                     page_size: int):
    """Compile a prefill executable for prompt-length bucket ``s_pad``
    (a multiple of ``page_size``).

    fn(params, prompt [1, s_pad], true_len, pt_row [max_pages],
       k_pages, v_pages) -> (logits [V], greedy token [], new k_pages,
       new v_pages)

    The greedy (temperature-0) argmax is folded into the jit so the
    engine can skip the host logits round-trip entirely — the same
    ``jnp.argmax`` ``generate()`` runs, so on-device sampling stays
    bit-for-bit with the solo path.

    Padded prompt tail tokens only influence positions >= true_len
    (causal mask), whose KV entries are masked by ``seq_len`` until
    decode overwrites them; padded page-table slots point at the trash
    page, so the static per-page scatter loop never writes real pages it
    doesn't own.
    """
    if s_pad % page_size != 0:
        raise ValueError(f"prefill bucket {s_pad} not a multiple of "
                         f"page_size {page_size}")
    cdt = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
    cos, sin = (_rotary_tables(cfg, s_pad) if cfg.position == "rotary"
                else (None, None))
    # the power-of-two bucket can exceed the page-table width when
    # max_pages is not itself a power of two; positions past
    # max_pages*page_size are guaranteed padding (admission bounds real
    # length by max_model_len), so those pages are simply not written —
    # an unclamped pt_row[j] gather would clamp to the LAST REAL page
    # and corrupt it with padding KV
    n_pack = min(s_pad // page_size, max_pages)

    # page arrays are donated: the pool replaces them wholesale every
    # call (Engine.set_pages), so XLA may scatter in place instead of
    # holding live+new copies of the whole KV pool.  true_len is donated
    # too — the engine builds it fresh per call, and the on-device
    # greedy token output would otherwise alias its shape/dtype and trip
    # donation-miss
    @functools.partial(jax.jit, donate_argnums=(2, 4, 5))
    def run(params, prompt, true_len, pt_row, k_pages, v_pages):
        p = _params_view(cfg, params)
        caches = [(jnp.zeros((1, s_pad, cfg.kv_heads, cfg.head_dim), cdt),
                   jnp.zeros((1, s_pad, cfg.kv_heads, cfg.head_dim), cdt))
                  for _ in range(cfg.num_layers)]
        _, cs, x = decode_step(cfg, p, prompt, caches, 0, cos, sin,
                               return_hidden=True)
        logits = _lm_head(p, x[0, true_len - 1][None])[0]      # [V]
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        new_k, new_v = [], []
        with jax.named_scope("kv_page_scatter"):
            for i in range(cfg.num_layers):
                kc, vc = cs[i]
                kp, vp = k_pages[i], v_pages[i]
                for j in range(n_pack):
                    kp = kp.at[pt_row[j]].set(
                        kc[0, j * page_size:(j + 1) * page_size])
                    vp = vp.at[pt_row[j]].set(
                        vc[0, j * page_size:(j + 1) * page_size])
                new_k.append(kp)
                new_v.append(vp)
        return logits, greedy, tuple(new_k), tuple(new_v)

    return run


def build_decode_fn(cfg: GPTConfig, batch: int, max_pages: int,
                    page_size: int, use_kernel: bool = False):
    """Compile a paged decode step for batch bucket ``batch``.

    fn(params, tokens [B], pos [B], page_tables [B, max_pages],
       k_pages, v_pages) -> (logits [B, V], greedy tokens [B],
       new k_pages, new v_pages)

    The on-device greedy argmax lets the engine fetch B int32s instead
    of a [B, V] fp32 logits matrix when every live request decodes at
    temperature 0 — the host round-trip that dominates small-model
    decode (ROADMAP serving item).

    ``pos[b]`` is the KV write index for this token (== tokens already
    committed); dummy batch slots carry pos=0 and an all-trash page
    table, so their writes land in the trash page and their outputs are
    discarded by the engine.  Layer math mirrors
    ``models.generate._attn_step`` exactly, with the dense
    update+attend swapped for page scatter + ``paged_attention``.
    """
    max_len = max_pages * page_size
    cdt = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
    cos, sin = (_rotary_tables(cfg, max_len) if cfg.position == "rotary"
                else (None, None))
    c = cfg
    hd, nh, nkv = c.head_dim, c.num_heads, c.kv_heads
    batch_idx = jnp.arange(batch)

    # tokens is rebuilt by the engine every step: donating it lets XLA
    # alias the on-device greedy-token output instead of holding a dead
    # copy (pos, the same shape, stays un-donated — the single [B] int32
    # output slot is already claimed)
    @functools.partial(jax.jit, donate_argnums=(1, 4, 5))
    def run(params, tokens, pos, page_tables, k_pages, v_pages):
        p = _params_view(cfg, params)
        x = p("wte.weight")[tokens][:, None].astype(cdt)       # [B, 1, H]
        if c.position == "learned":
            x = x + p("wpe")[pos][:, None].astype(x.dtype)
        page_idx = page_tables[batch_idx, pos // page_size]    # [B]
        offset = pos % page_size                               # [B]
        seq_lens = pos + 1
        new_k, new_v = [], []
        for i in range(c.num_layers):
            h = _norm_apply(c, p.layer(i, "ln_1.weight"),
                            p.layer(i, "ln_1.bias"), x)
            qkv = h @ p.layer(i, "attn.qkv.weight").T
            qb = p.layer(i, "attn.qkv.bias")
            if qb is not None:
                qkv = qkv + qb
            q_size, kv_size = nh * hd, nkv * hd
            q = qkv[..., :q_size].reshape(batch, 1, nh, hd)
            k = qkv[..., q_size:q_size + kv_size].reshape(batch, 1, nkv,
                                                          hd)
            v = qkv[..., q_size + kv_size:].reshape(batch, 1, nkv, hd)
            if c.position == "rotary":
                q = _rope_at(q, cos[pos], sin[pos])
                k = _rope_at(k, cos[pos], sin[pos])
            with jax.named_scope("kv_page_scatter"):
                kp = k_pages[i].at[page_idx, offset].set(
                    k[:, 0].astype(cdt))
                vp = v_pages[i].at[page_idx, offset].set(
                    v[:, 0].astype(cdt))
            attn = paged_attention_decode(q[:, 0], kp, vp, page_tables,
                                          seq_lens,
                                          use_kernel=use_kernel)
            attn = attn.reshape(batch, 1, nh * hd).astype(x.dtype)
            out = attn @ p.layer(i, "attn.out.weight").T
            ob = p.layer(i, "attn.out.bias")
            if ob is not None:
                out = out + ob
            x = x + out
            h = _norm_apply(c, p.layer(i, "ln_2.weight"),
                            p.layer(i, "ln_2.bias"), x)
            if c.is_moe_layer(i):
                h = _moe_mlp(c, p, i, h)
            else:
                h = _act(c, h @ p.layer(i, "mlp.up.weight").T +
                         (p.layer(i, "mlp.up.bias")
                          if p.layer(i, "mlp.up.bias") is not None
                          else 0.0))
                h = h @ p.layer(i, "mlp.down.weight").T
                db = p.layer(i, "mlp.down.bias")
                if db is not None:
                    h = h + db
            x = x + h
            new_k.append(kp)
            new_v.append(vp)
        x = _norm_apply(c, p("ln_f.weight"), p("ln_f.bias"), x)
        logits = _lm_head(p, x[:, 0])                          # [B, V]
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return logits, greedy, tuple(new_k), tuple(new_v)

    return run
