"""Data type system.

TPU-native re-expression of the reference's ``DataType`` enum
(``hetu/core/dtype.h``): fp32/fp16/bf16/integer types plus the 4-bit
quantization formats (fp4/nf4) the reference implements via bitsandbytes
(``hetu/impl/kernel/Quantization.cu``).  On TPU the storage types map onto
jnp dtypes; fp4/nf4 are *codebook* formats used by the quantized
checkpoint/save path (see ``hetu_tpu.utils.quantization``) — they are stored
as packed uint8 with a per-block absmax, exactly like the reference's
bitsandbytes path, but implemented with pure XLA ops.
"""
from __future__ import annotations

import enum
from typing import Union

import jax.numpy as jnp
import numpy as np


class DataType(enum.Enum):
    UINT8 = "uint8"
    UINT16 = "uint16"
    UINT32 = "uint32"
    UINT64 = "uint64"
    INT8 = "int8"
    INT16 = "int16"
    INT32 = "int32"
    INT64 = "int64"
    FLOAT16 = "float16"
    FLOAT32 = "float32"
    FLOAT64 = "float64"
    BFLOAT16 = "bfloat16"
    BOOL = "bool"
    # 4-bit quantization codebook formats (packed storage, not compute types).
    FLOAT4 = "float4"
    NFLOAT4 = "nfloat4"

    @property
    def is_floating_point(self) -> bool:
        return self in (DataType.FLOAT16, DataType.FLOAT32, DataType.FLOAT64,
                        DataType.BFLOAT16, DataType.FLOAT4, DataType.NFLOAT4)

    @property
    def is_quantized(self) -> bool:
        return self in (DataType.FLOAT4, DataType.NFLOAT4)

    def to_jnp(self):
        """Map to the jnp dtype used for device compute/storage."""
        if self.is_quantized:
            # Packed 4-bit codes live in uint8 (2 codes per byte).
            return jnp.uint8
        import jax
        if not jax.config.jax_enable_x64:
            if self == DataType.INT64:
                return jnp.int32
            if self == DataType.FLOAT64:
                return jnp.float32
        return _TO_JNP[self]

    @property
    def itemsize(self) -> float:
        """Bytes per element (reference ``DataType2Size``)."""
        if self.is_quantized:
            return 0.5
        return np.dtype(_TO_JNP[self]).itemsize


_TO_JNP = {
    DataType.UINT8: jnp.uint8,
    DataType.UINT16: jnp.uint16,
    DataType.UINT32: jnp.uint32,
    DataType.UINT64: jnp.uint64,
    DataType.INT8: jnp.int8,
    DataType.INT16: jnp.int16,
    DataType.INT32: jnp.int32,
    DataType.INT64: jnp.int64,
    DataType.FLOAT16: jnp.float16,
    DataType.FLOAT32: jnp.float32,
    DataType.FLOAT64: jnp.float64,
    DataType.BFLOAT16: jnp.bfloat16,
    DataType.BOOL: jnp.bool_,
}

_FROM_STR = {dt.value: dt for dt in DataType}
_ALIASES = {
    "fp16": DataType.FLOAT16,
    "fp32": DataType.FLOAT32,
    "fp64": DataType.FLOAT64,
    "bf16": DataType.BFLOAT16,
    "half": DataType.FLOAT16,
    "float": DataType.FLOAT32,
    "double": DataType.FLOAT64,
    "fp4": DataType.FLOAT4,
    "nf4": DataType.NFLOAT4,
    "int": DataType.INT32,
    "long": DataType.INT64,
}

DTypeLike = Union[DataType, str, type, np.dtype, None]


def canonicalize_dtype(dtype: DTypeLike) -> DataType:
    """Accept DataType / str / numpy / jnp dtypes and return a DataType."""
    if dtype is None:
        return DataType.FLOAT32
    if isinstance(dtype, DataType):
        return dtype
    if isinstance(dtype, str):
        if dtype in _FROM_STR:
            return _FROM_STR[dtype]
        if dtype in _ALIASES:
            return _ALIASES[dtype]
        raise ValueError(f"unknown dtype string: {dtype!r}")
    name = np.dtype(dtype).name
    if name in _FROM_STR:
        return _FROM_STR[name]
    raise ValueError(f"cannot canonicalize dtype: {dtype!r}")


def to_jnp_dtype(dtype: DTypeLike):
    return canonicalize_dtype(dtype).to_jnp()


# Module-level convenience names mirroring ``hetu.float32`` etc.
uint8 = DataType.UINT8
int8 = DataType.INT8
int16 = DataType.INT16
int32 = DataType.INT32
int64 = DataType.INT64
float16 = DataType.FLOAT16
float32 = DataType.FLOAT32
float64 = DataType.FLOAT64
bfloat16 = DataType.BFLOAT16
bool_ = DataType.BOOL
float4 = DataType.FLOAT4
nfloat4 = DataType.NFLOAT4
