from .dtype import (DataType, canonicalize_dtype, to_jnp_dtype,
                    uint8, int8, int16, int32, int64,
                    float16, float32, float64, bfloat16, bool_,
                    float4, nfloat4)
from .device import (Device, DeviceGroup, DeviceGroupUnion, DeviceType,
                     local_device, global_device_group)

__all__ = [
    "DataType", "canonicalize_dtype", "to_jnp_dtype",
    "uint8", "int8", "int16", "int32", "int64",
    "float16", "float32", "float64", "bfloat16", "bool_", "float4", "nfloat4",
    "Device", "DeviceGroup", "DeviceGroupUnion", "DeviceType",
    "local_device", "global_device_group",
]
