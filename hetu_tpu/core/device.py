"""Device identity and device groups.

TPU-native equivalent of the reference's ``Device``/``DeviceGroup``
(``hetu/core/device.h``).  A :class:`Device` identifies one chip (or host
CPU) by type/index/hostname; a :class:`DeviceGroup` is an *ordered* set of
devices.  Unlike the CUDA reference, devices here are thin descriptors that
resolve to ``jax.Device`` objects; placement/compute is delegated to XLA via
`jax.sharding` meshes (see ``hetu_tpu.parallel.mesh``).

Global-rank bookkeeping (the reference's world-rank <-> device mapping set up
by ``SetUpDeviceMappingAndAssignLocalDeviceOnce``, ``comm_group.h:223``) maps
onto ``jax.process_index()`` / flat device ids.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Iterable, List, Optional, Sequence, Tuple

import jax


class DeviceType(enum.Enum):
    CPU = "cpu"
    TPU = "tpu"
    GPU = "gpu"  # accepted for interop; not a compute target in this build
    UNDETERMINED = "undetermined"


@dataclass(frozen=True, order=True)
class Device:
    """A single device descriptor (reference ``Device``, ``core/device.h``)."""
    type: DeviceType = DeviceType.UNDETERMINED
    index: int = 0
    hostname: str = ""
    multiplex: int = 0  # reference supports multiplexing several ranks per card

    @staticmethod
    def parse(spec: "Device | str") -> "Device":
        """Parse 'cpu', 'tpu:3', 'host1/tpu:0' style strings."""
        if isinstance(spec, Device):
            return spec
        hostname = ""
        body = spec
        if "/" in spec:
            hostname, body = spec.split("/", 1)
        if ":" in body:
            type_str, idx_str = body.split(":", 1)
            index = int(idx_str)
        else:
            type_str, index = body, 0
        return Device(DeviceType(type_str.lower()), index, hostname)

    @property
    def is_cpu(self) -> bool:
        return self.type == DeviceType.CPU

    @property
    def is_tpu(self) -> bool:
        return self.type == DeviceType.TPU

    def local(self) -> bool:
        return self.hostname in ("", "localhost")

    def __str__(self) -> str:
        prefix = f"{self.hostname}/" if self.hostname else ""
        return f"{prefix}{self.type.value}:{self.index}"

    def to_jax(self) -> jax.Device:
        """Resolve to a concrete jax.Device on this process."""
        backend = "cpu" if self.is_cpu else None
        devs = jax.devices(backend) if backend else jax.devices()
        for d in devs:
            if d.id == self.index:
                return d
        raise RuntimeError(f"no local jax device for {self}")


class DeviceGroup:
    """Ordered set of devices (reference ``DeviceGroup``)."""

    def __init__(self, devices: Iterable["Device | str"] = ()):
        self._devices: Tuple[Device, ...] = tuple(Device.parse(d) for d in devices)

    @property
    def devices(self) -> Tuple[Device, ...]:
        return self._devices

    @property
    def num_devices(self) -> int:
        return len(self._devices)

    def empty(self) -> bool:
        return not self._devices

    def contains(self, device: "Device | str") -> bool:
        return Device.parse(device) in self._devices

    def get_index(self, device: "Device | str") -> int:
        return self._devices.index(Device.parse(device))

    def get(self, index: int) -> Device:
        return self._devices[index]

    def __len__(self) -> int:
        return self.num_devices

    def __iter__(self):
        return iter(self._devices)

    def __eq__(self, other) -> bool:
        return isinstance(other, DeviceGroup) and self._devices == other._devices

    def __hash__(self) -> int:
        return hash(self._devices)

    def __repr__(self) -> str:
        return f"DeviceGroup([{', '.join(map(str, self._devices))}])"


class DeviceGroupUnion:
    """Union of device groups — one group per (hetero) pipeline slot.

    Mirrors the reference's ``DeviceGroupUnion`` used for heterogeneous
    pipeline placement (``hetu/graph/distributed_states.h``).
    """

    def __init__(self, groups: Sequence[DeviceGroup]):
        self._groups: Tuple[DeviceGroup, ...] = tuple(groups)

    @property
    def groups(self) -> Tuple[DeviceGroup, ...]:
        return self._groups

    def size(self) -> int:
        return len(self._groups)

    def get(self, i: int) -> DeviceGroup:
        return self._groups[i]

    def all_devices(self) -> DeviceGroup:
        seen: List[Device] = []
        for g in self._groups:
            for d in g:
                if d not in seen:
                    seen.append(d)
        return DeviceGroup(seen)

    def __eq__(self, other) -> bool:
        return isinstance(other, DeviceGroupUnion) and self._groups == other._groups

    def __hash__(self) -> int:
        return hash(self._groups)

    def __repr__(self) -> str:
        return f"DeviceGroupUnion({list(self._groups)!r})"


def local_device() -> Device:
    """The device this process computes on (first addressable device)."""
    d = jax.local_devices()[0]
    dtype = DeviceType.TPU if d.platform == "tpu" else DeviceType.CPU
    return Device(dtype, d.id, "")


def global_device_group(device_type: Optional[DeviceType] = None) -> DeviceGroup:
    """All devices visible to jax, as an ordered DeviceGroup."""
    devs = []
    for d in jax.devices():
        dt = DeviceType.TPU if d.platform == "tpu" else DeviceType.CPU
        if device_type is not None and dt != device_type:
            continue
        devs.append(Device(dt, d.id, ""))
    return DeviceGroup(devs)
