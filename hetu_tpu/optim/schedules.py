"""Learning-rate schedules.

The reference trains with a fixed ``lr`` (``python/hetu/optim/
optimizer.py``); real pretraining recipes need warmup + decay, so this
is a beyond-parity addition.  A schedule is a callable ``step -> lr``
over jnp scalars (the optimizer's step counter is traced — schedules
compile into the update program, changing the lr costs no retrace).
Pass one anywhere an optimizer takes ``lr``::

    optim.AdamOptimizer(lr=optim.cosine_schedule(3e-4, 2000, 100_000))

``step`` is 1-based (the value used for the step that is being applied).
"""
from __future__ import annotations

import jax.numpy as jnp


def constant_schedule(lr: float):
    """Fixed lr as a schedule (identity wrapper)."""
    return lambda step: jnp.float32(lr)


def cosine_schedule(peak_lr: float, warmup_steps: int, total_steps: int,
                    min_lr: float = 0.0):
    """Linear warmup to ``peak_lr`` over ``warmup_steps``, then cosine
    decay to ``min_lr`` at ``total_steps`` (the GPT-3/LLaMA recipe)."""
    if total_steps <= warmup_steps:
        raise ValueError(f"total_steps {total_steps} must exceed "
                         f"warmup_steps {warmup_steps}")

    def lr(step):
        s = jnp.asarray(step, jnp.float32)
        warm = peak_lr * s / jnp.maximum(1.0, float(warmup_steps))
        frac = jnp.clip((s - warmup_steps) / (total_steps - warmup_steps),
                        0.0, 1.0)
        decay = min_lr + 0.5 * (peak_lr - min_lr) * (
            1.0 + jnp.cos(jnp.pi * frac))
        return jnp.where(s <= warmup_steps, warm, decay)
    return lr


def linear_schedule(peak_lr: float, warmup_steps: int, total_steps: int,
                    min_lr: float = 0.0):
    """Linear warmup then linear decay to ``min_lr`` (the BERT recipe)."""
    if total_steps <= warmup_steps:
        raise ValueError(f"total_steps {total_steps} must exceed "
                         f"warmup_steps {warmup_steps}")

    def lr(step):
        s = jnp.asarray(step, jnp.float32)
        warm = peak_lr * s / jnp.maximum(1.0, float(warmup_steps))
        frac = jnp.clip((s - warmup_steps) / (total_steps - warmup_steps),
                        0.0, 1.0)
        return jnp.where(s <= warmup_steps, warm,
                         peak_lr + (min_lr - peak_lr) * frac)
    return lr


def step_decay_schedule(lr0: float, decay_rate: float, every: int):
    """lr0 * decay_rate ** (step // every)."""
    def lr(step):
        s = jnp.asarray(step, jnp.float32)
        return lr0 * jnp.power(decay_rate, jnp.floor(s / float(every)))
    return lr
