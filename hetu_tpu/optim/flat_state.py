"""Flat dp-sharded optimizer state (ZeRO-2 reduce-scatter-only sync).

The reference's ZeRO path (``SplitReduceScatter`` under the ``zero`` DS
flag, ``Communication.h:583``) syncs gradients with a single
reduce-scatter and updates only the locally-owned shard.  Doing the same
through the explicit coalesced grad-comm path (PR 1) needs the optimizer
state laid out to match the *bucket chunk* geometry of
:func:`hetu_tpu.parallel.comm.reduce_scatter_coalesced`: chunk
boundaries do NOT align with parameter rows, so per-parameter state
arrays cannot express "rank r owns bytes [r*chunk, (r+1)*chunk) of
bucket b".  This module packs the per-parameter fp32 master /
momentum / variance state into contiguous per-bucket flat buffers whose
geometry is exactly the reduce-scatter's:

* bucket planning reuses :func:`~hetu_tpu.parallel.comm.plan_buckets`
  over the tid-sorted parameter set (same-dtype, size-capped — identical
  inputs, identical buckets);
* each bucket's flat buffer holds ``device_num * chunk`` fp32 elements
  with ``chunk = quantized_chunk(numel, n, block)`` (a block multiple,
  so int8 absmax blocks never straddle rank boundaries), zero-padded
  past the packed parameters;
* sharded ``P(dp)`` each rank owns a contiguous equal chunk — the very
  shard :func:`reduce_scatter_coalesced` hands it, so the optimizer
  update is pure local elementwise math with no regather.

``index`` maps ``param key -> (bucket, offset, numel, shape)`` — the
view used by checkpointing (per-parameter keyed safetensors entries,
interchangeable between ``flat_state=True/False`` and across dp sizes)
and by the static analyzer.
"""
from __future__ import annotations

from typing import Any, Dict, List, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..parallel.comm import INT8_BLOCK, plan_buckets, quantized_chunk


def sync_order(xs):
    """The ONE gradient-sync ordering: ascending tensor id.  jax
    flattens the grad dict by sorted key, so every consumer of the flat
    geometry — layout construction, state packing, the update itself,
    and the analyzer's registered entries — must sort exactly this way
    or chunk boundaries disagree with the reduce-scatter shards."""
    return sorted(xs, key=lambda t: t.id)


class FlatStateLayout:
    """Static geometry of a flat dp-sharded optimizer-state set."""

    def __init__(self, entries: Sequence[Tuple[Any, Sequence[int], Any]],
                 device_num: int, bucket_mb: float = 4.0,
                 block: int = INT8_BLOCK):
        self.entries = [(k, tuple(int(d) for d in shape),
                         np.dtype(dt).name) for k, shape, dt in entries]
        self.device_num = int(device_num)
        self.block = int(block)
        self.bucket_mb = float(bucket_mb)
        self.buckets = tuple(plan_buckets(self.entries, bucket_mb))
        self.chunks = tuple(
            quantized_chunk(sum(b.numels), self.device_num, self.block)
            for b in self.buckets)
        # param key -> (bucket index, offset into the bucket's flat
        # buffer, numel, original shape)
        self.index: Dict[Any, Tuple[int, int, int, Tuple[int, ...]]] = {}
        for bi, b in enumerate(self.buckets):
            off = 0
            for k, shape, numel in zip(b.keys, b.shapes, b.numels):
                self.index[k] = (bi, off, numel, shape)
                off += numel

    @property
    def padded_sizes(self) -> Tuple[int, ...]:
        """Global flat length per bucket (``device_num * chunk``)."""
        return tuple(self.device_num * c for c in self.chunks)

    def comm_layout(self):
        """The :class:`~hetu_tpu.parallel.comm.CoalescedLayout` view of
        this state geometry — the very layout
        ``reduce_scatter_coalesced`` would return for the same entries,
        buildable WITHOUT running a reduce-scatter first.  ZeRO-3 uses it
        to all-gather the working parameters just-in-time from the flat
        master chunks (``all_gather_coalesced`` rides the bucket's weight
        dtype) before any gradient collective has run this step."""
        from ..parallel.comm import CoalescedLayout
        return CoalescedLayout(tuple(self.buckets), tuple(self.chunks),
                               False)

    def same_geometry(self, other: "FlatStateLayout") -> bool:
        return (other is not None and self.entries == other.entries
                and self.device_num == other.device_num
                and self.block == other.block
                and self.bucket_mb == other.bucket_mb)

    def matches(self, entries, device_num: int, bucket_mb: float = 4.0,
                block: int = INT8_BLOCK) -> bool:
        """Cheap geometry check against raw (normalized) entries — lets
        the steady-state training step skip rebuilding bucket plans and
        the param index entirely."""
        norm = [(k, tuple(int(d) for d in shape), np.dtype(dt).name)
                for k, shape, dt in entries]
        return (self.entries == norm
                and self.device_num == int(device_num)
                and self.block == int(block)
                and self.bucket_mb == float(bucket_mb))

    def pack(self, values: Dict[Any, Any],
             dtype=jnp.float32) -> List[jnp.ndarray]:
        """``{key: array}`` -> per-bucket flat buffers, zero-padded to
        ``device_num * chunk`` (padding lanes never receive gradient —
        the reduce-scatter pads with zeros too — so they stay inert
        through any elementwise update)."""
        flats = []
        for b, size in zip(self.buckets, self.padded_sizes):
            parts = [jnp.ravel(jnp.asarray(values[k])).astype(dtype)
                     for k in b.keys]
            flat = jnp.concatenate(parts)
            flats.append(jnp.pad(flat, (0, size - flat.shape[0])))
        return flats

    def unpack(self, flats: Sequence[Any],
               dtypes: Dict[Any, Any] = None) -> Dict[Any, Any]:
        """Per-bucket flat buffers -> ``{key: array}`` in the original
        shapes, through the param index (padding dropped)."""
        out: Dict[Any, Any] = {}
        for b, flat in zip(self.buckets, flats):
            arr = jnp.asarray(flat)
            off = 0
            for k, shape, numel in zip(b.keys, b.shapes, b.numels):
                piece = arr[off:off + numel].reshape(shape)
                if dtypes is not None and k in dtypes:
                    piece = piece.astype(dtypes[k])
                out[k] = piece
                off += numel
        return out
