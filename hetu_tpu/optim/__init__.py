from .optimizer import Optimizer, SGDOptimizer, AdamOptimizer, SGD, Adam, AdamW

__all__ = ["Optimizer", "SGDOptimizer", "AdamOptimizer", "SGD", "Adam", "AdamW"]
