from .flat_state import FlatStateLayout
from .optimizer import (Optimizer, SGDOptimizer, AdamOptimizer,
                        AdamWOptimizer, AdafactorOptimizer,
                        SGD, Adam, AdamW)
from .schedules import (constant_schedule, cosine_schedule, linear_schedule,
                        step_decay_schedule)

__all__ = ["FlatStateLayout", "Optimizer", "SGDOptimizer", "AdamOptimizer",
           "AdamWOptimizer", "AdafactorOptimizer", "SGD", "Adam", "AdamW",
           "constant_schedule", "cosine_schedule", "linear_schedule",
           "step_decay_schedule"]
