from .optimizer import (Optimizer, SGDOptimizer, AdamOptimizer,
                        AdamWOptimizer, SGD, Adam, AdamW)

__all__ = ["Optimizer", "SGDOptimizer", "AdamOptimizer", "AdamWOptimizer",
           "SGD", "Adam", "AdamW"]
