"""Optimizers.

Reference: ``hetu/graph/optim/optimizer.h:9-100`` (SGD w/ momentum, Adam,
``Minimize = ComputeGradients + ApplyDense``, ``MakeStates`` per-param
optimizer-state variables, multi-zero awareness) and the Python wrappers
(``python/hetu/optim/optimizer.py:43``).

``minimize(loss)`` builds a symbolic update node executed by
``DefineAndRunGraph.run``; under jit the whole fwd+bwd+update is one XLA
program with donated parameter/state buffers (the analogue of the
reference's fused param/grad buffers + fused Optimizers.cu kernels).
ZeRO levels (reference ``zero`` DS flag, ``distributed_states.h:69``,
grad reduce-scatter / param allgather comm ops ``Communication.h:583``),
expressed as GSPMD sharding annotations instead of explicit collectives —
the XLA partitioner then emits the reduce-scatter/all-gather pairs:

- ``zero=1`` — optimizer states sharded over the dp axis.
- ``zero=2`` — + gradients constrained to the same dp-sharded spec inside
  the update (XLA turns the dp grad all-reduce into reduce-scatter and
  gathers the updated params back).
- ``zero=3`` — + parameters stored dp-sharded at rest (FSDP); forward /
  backward all-gathers are inserted by the partitioner on demand.

``zero=True`` keeps its historical meaning of level 1.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..graph.graph import DefineAndRunGraph, Graph, OpNode, get_default_graph
from ..graph.tensor import Tensor


class Optimizer:
    def __init__(self, params: Optional[Sequence[Tensor]] = None,
                 lr=0.01, zero: int = 0, dp_axis: str = "dp",
                 max_grad_norm: Optional[float] = None,
                 grad_comm: Optional[str] = None,
                 bucket_mb: float = 4.0):
        # lr: float, or a schedule callable step -> lr (optim.schedules)
        self.lr = lr
        self.params = list(params) if params is not None else None
        self.zero = int(zero)     # ZeRO level 0-3 (True -> 1)
        if not 0 <= self.zero <= 3:
            raise ValueError(f"zero level must be 0..3, got {zero}")
        self.dp_axis = dp_axis
        # global-norm gradient clipping (Megatron-style; applied inside
        # the jitted update, before any optimizer math)
        self.max_grad_norm = max_grad_norm
        # explicit gradient-communication transport (reference
        # AllReduceCoalesce + EQuARX quantized collectives): None keeps
        # the implicit GSPMD per-tensor sync; "fp32"/"bf16"/"int8"
        # switches the dp gradient sync to coalesced buckets over the
        # selected wire format (parallel/comm.py, graph explicit path).
        # Sync uses the data-parallel MEAN convention (torch-DDP
        # semantics) and therefore assumes a mean-normalized loss; a
        # literally sum-reduced loss makes the graph fall back to the
        # implicit path (graph._grad_comm_fallback records why).
        from ..parallel.comm import GRAD_COMM_TRANSPORTS
        if grad_comm is not None and grad_comm not in GRAD_COMM_TRANSPORTS:
            raise ValueError(f"grad_comm must be None or one of "
                             f"{GRAD_COMM_TRANSPORTS}, got {grad_comm!r}")
        self.grad_comm = grad_comm
        self.bucket_mb = float(bucket_mb)
        if self.bucket_mb <= 0:
            raise ValueError(f"bucket_mb must be > 0, got {bucket_mb}")
        self._state: Dict[str, Any] = {}
        self._shardings: Dict[int, Any] = {}  # tid -> NamedSharding of states
        self._param_shardings: Dict[int, Any] = {}  # tid -> zero-3 sharding
        self._param_base_shardings: Dict[int, Any] = {}  # tid -> own spec

    # -- graph API (reference Optimizer::Minimize) ---------------------------

    def minimize(self, loss: Tensor,
                 var_list: Optional[Sequence[Tensor]] = None,
                 grad_scaler=None) -> Tensor:
        g = loss.graph or get_default_graph()
        xs = list(var_list or self.params or g.trainable_variables)
        assert xs, "no trainable variables to optimize"
        grad_node_outputs = g.make_gradients(loss, xs)
        grad_node = grad_node_outputs[0].producer
        node = OpNode("update", None, grad_node_outputs,
                      {"optimizer": self, "grad_node": grad_node, "xs": xs,
                       "grad_scaler": grad_scaler},
                      f"update_{loss.name}")
        t = Tensor((), "float32", producer=node, name=node.name, graph=g)
        node.outputs = [t]
        g.ops.append(node)
        return t

    # -- state management (reference MakeStates) -----------------------------

    def _state_sharding(self, t: Tensor, arr, graph: Graph):
        """Sharding for a per-param optimizer state: the param's own
        sharding, plus ZeRO dp-sharding of dim 0 when enabled (reference
        `zero` ds flag, distributed_states.h:69)."""
        from jax.sharding import NamedSharding, PartitionSpec
        mesh = graph.mesh
        if mesh is None:
            return None
        base = graph._pspec_for(t)
        spec = list(base) if base is not None else []
        spec += [None] * (arr.ndim - len(spec))
        if self.zero and self.dp_axis in mesh.axis_names and arr.ndim > 0:
            dp = mesh.shape[self.dp_axis]
            used = {a for entry in spec if entry
                    for a in (entry if isinstance(entry, tuple) else (entry,))}
            if (self.dp_axis not in used and arr.shape[0] % dp == 0
                    and spec[0] is None):
                spec[0] = self.dp_axis
        if not any(spec):
            return None
        return NamedSharding(mesh, PartitionSpec(*spec))

    def _ensure_state(self, var_state: Dict[int, jax.Array],
                      xs: Sequence[Tensor], graph: Graph) -> Dict[str, Any]:
        just_inited = False
        if not self._state:
            self._state = self._init_state(var_state, xs)
            just_inited = True
            for key, tree in self._state.items():
                if isinstance(tree, dict):
                    for tid, arr in tree.items():
                        t = next((x for x in xs if x.id == tid), None)
                        if t is None or not hasattr(arr, "shape") \
                                or arr.shape != var_state[tid].shape:
                            continue
                        sharding = self._state_sharding(t, arr, graph)
                        if sharding is not None:
                            tree[tid] = jax.device_put(arr, sharding)
                            self._shardings[tid] = sharding
        if getattr(self, "_pending_tree_state", None):
            # structured state loaded from a checkpoint as ordered leaves
            # (safetensors_io "@@leaf" entries): graft into the freshly
            # initialized structure, validating leaf count + shapes.
            # just-initialized state IS a fresh template; only rebuild
            # one when stepping had already populated self._state
            fresh = self._state if just_inited \
                else self._init_state(var_state, xs)
            for slot, leaves in self._pending_tree_state.items():
                if slot not in fresh or isinstance(fresh[slot], dict):
                    raise ValueError(
                        f"checkpoint carries structured optimizer state "
                        f"{slot!r} that this optimizer does not define — "
                        f"restoring into a different optimizer type?")
                tdef = jax.tree_util.tree_structure(fresh[slot])
                ref = jax.tree_util.tree_leaves(fresh[slot])
                if len(ref) != len(leaves) or any(
                        getattr(a, "shape", None) != getattr(b, "shape", None)
                        for a, b in zip(ref, leaves)):
                    raise ValueError(
                        f"checkpointed optimizer state {slot!r} does not "
                        f"match this optimizer/model (leaf count/shapes)")
                self._state[slot] = jax.tree_util.tree_unflatten(
                    tdef, [jnp.asarray(l, r.dtype)
                           for l, r in zip(leaves, ref)])
            self._pending_tree_state = None
        if self.zero in (1, 2) and graph.mesh is not None \
                and not self._param_base_shardings:
            # pin updated params to their OWN spec (replicated over dp):
            # with dp-sharded states XLA would otherwise freely emit
            # dp-sharded params, silently turning zero-1/2 into FSDP
            from jax.sharding import NamedSharding, PartitionSpec
            for t in xs:
                arr = var_state.get(t.id)
                if arr is None or not hasattr(arr, "ndim"):
                    continue
                base = graph._pspec_for(t)
                spec = list(base) if base is not None else []
                spec += [None] * (arr.ndim - len(spec))
                self._param_base_shardings[t.id] = NamedSharding(
                    graph.mesh, PartitionSpec(*spec))
        if self.zero >= 3:
            # FSDP: parameters live dp-sharded at rest.  Re-assert every
            # step (device_put on an already-sharded array is a no-op) so
            # checkpoint loads / hot switches can't silently unshard.
            for t in xs:
                arr = var_state.get(t.id)
                if arr is None or not hasattr(arr, "shape"):
                    continue
                sh = self._param_shardings.get(t.id)
                if sh is None:
                    sh = self._state_sharding(t, arr, graph)
                    if sh is None:
                        continue
                    self._param_shardings[t.id] = sh
                var_state[t.id] = jax.device_put(arr, sh)
                graph._var_data[t.id] = var_state[t.id]
        return self._state

    def _c(self, tid: int, arr):
        """Re-assert the optimizer-state sharding inside the jitted update
        (XLA would otherwise choose output shardings freely)."""
        sh = self._shardings.get(tid)
        return jax.lax.with_sharding_constraint(arr, sh) if sh is not None else arr

    def _c_grad(self, tid: int, g):
        """ZeRO>=2: constrain the gradient to the dp-sharded state spec —
        the partitioner then reduce-scatters the dp gradient sum instead
        of all-reducing it (reference SplitReduceScatter under zero,
        Communication.h:583).  Under the explicit grad-comm path the
        gradient arrives already reduced (coalesced collectives), so this
        constraint degrades to a local slice — the correct ZeRO-2 layout
        either way."""
        return self._c(tid, g) if self.zero >= 2 else g

    def sync_gradients(self, grads: Dict[int, jax.Array], axis: str):
        """Explicit DP gradient sync: coalesced (optionally quantized)
        mean-allreduce of the micro-batch-accumulated gradient dict —
        one collective chain per bucket instead of one psum per
        parameter.  Must run inside a manual (shard_map) region with
        ``axis`` in scope; the graph executor arranges that
        (DefineAndRunGraph._build_executable explicit path)."""
        from ..parallel import comm
        return comm.all_reduce_coalesced(
            grads, axis, op="mean", bucket_mb=self.bucket_mb,
            transport=self.grad_comm or "fp32")

    def _c_param(self, tid: int, p):
        """ZeRO-3: keep the updated parameter dp-sharded at rest;
        ZeRO-1/2: pin it to its own (dp-replicated) spec — the param
        allgather of the reference's zero pairing."""
        sh = self._param_shardings.get(tid) if self.zero >= 3 \
            else self._param_base_shardings.get(tid)
        if sh is not None:
            return jax.lax.with_sharding_constraint(p, sh)
        return p

    def _store_state(self, state: Dict[str, Any]) -> None:
        self._state = dict(state)

    def reset_state_rows(self, param: Tensor, rows) -> None:
        """Zero the leading-dim rows of every per-param state array for
        ``param`` (momentum, Adam m/v).  Used by cache-backed embeddings
        when a slot's occupant changes (hetu_tpu/embedding/cached.py);
        subclasses with non-standard state layouts must override."""
        import numpy as np
        rows = np.asarray(rows)
        if rows.size == 0:
            return
        tid = param.id
        nrows = param.shape[0] if param.shape else 0
        rows_dev = jnp.asarray(rows)
        for state in self._state.values():
            if isinstance(state, dict) and tid in state:
                arr = state[tid]
                if hasattr(arr, "ndim") and arr.ndim >= 1 \
                        and arr.shape[0] == nrows:
                    # device-side masked update: preserves the array's
                    # sharding/placement (a numpy round-trip would gather
                    # and fail on non-fully-addressable arrays)
                    arr = jnp.asarray(arr)
                    state[tid] = arr.at[rows_dev].set(0)

    def _init_state(self, var_state, xs) -> Dict[str, Any]:
        return {}

    def _lr_at(self, step):
        """Resolve lr: plain float, or a schedule called with the
        (1-based, traced) step — see optim/schedules.py."""
        return self.lr(step) if callable(self.lr) else self.lr

    def _clip_grads(self, grads: Dict[int, jax.Array],
                    xs: Sequence[Tensor]) -> Dict[int, jax.Array]:
        """Global-norm clip across ALL parameter grads (fp32 norm)."""
        if self.max_grad_norm is None:
            return grads
        sq = sum(jnp.sum(jnp.square(grads[t.id].astype(jnp.float32)))
                 for t in xs)
        norm = jnp.sqrt(sq)
        scale = jnp.minimum(1.0, self.max_grad_norm / (norm + 1e-6))
        return {t.id: (grads[t.id].astype(jnp.float32) * scale)
                .astype(grads[t.id].dtype) for t in xs}

    def _apply_updates(self, var_state: Dict[int, jax.Array],
                       opt_state: Dict[str, Any],
                       grads: Dict[int, jax.Array],
                       xs: Sequence[Tensor]):
        raise NotImplementedError

    # -- eager API (torch-style step) ----------------------------------------

    def step(self, grads: Dict[int, jax.Array]) -> None:
        assert self.params is not None, "eager step needs params list"
        g = self.params[0].graph
        var_state = {p.id: g.get_tensor_value(p) for p in self.params}
        opt_state = self._ensure_state(var_state, self.params, g)
        new_vars, new_opt = self._apply_updates(var_state, opt_state, grads,
                                                self.params)
        for p in self.params:
            g._var_data[p.id] = new_vars[p.id]
        self._store_state(new_opt)


class SGDOptimizer(Optimizer):
    def __init__(self, params=None, lr: float = 0.01, momentum: float = 0.0,
                 nesterov: bool = False, **kw):
        super().__init__(params, lr, **kw)
        self.momentum = momentum
        self.nesterov = nesterov

    def _init_state(self, var_state, xs):
        state = {"step": jnp.zeros((), jnp.int32)}
        if self.momentum != 0.0:
            state["velocity"] = {t.id: jnp.zeros_like(var_state[t.id])
                                 for t in xs}
        return state

    def _apply_updates(self, var_state, opt_state, grads, xs):
        grads = self._clip_grads(grads, xs)
        new_vars = dict(var_state)
        new_opt = dict(opt_state)
        # .get: checkpoints from before SGD carried a step counter have
        # no "step" entry — backfill instead of KeyError on restore
        step = opt_state.get("step", jnp.zeros((), jnp.int32)) + 1
        new_opt["step"] = step
        lr = self._lr_at(step)
        def apply(p, upd):
            # fp32 update math, cast back (a scheduled lr is an fp32
            # scalar; don't let promotion change the stored param dtype)
            return (p.astype(jnp.float32)
                    - lr * upd.astype(jnp.float32)).astype(p.dtype)

        if self.momentum == 0.0:
            for t in xs:
                g = self._c_grad(t.id, grads[t.id].astype(var_state[t.id].dtype))
                new_vars[t.id] = self._c_param(t.id, apply(var_state[t.id], g))
            return new_vars, new_opt
        vel = dict(opt_state["velocity"])
        for t in xs:
            g = self._c_grad(t.id, grads[t.id].astype(var_state[t.id].dtype))
            v = self._c(t.id, self.momentum * vel[t.id] + g)
            vel[t.id] = v
            upd = g + self.momentum * v if self.nesterov else v
            new_vars[t.id] = self._c_param(t.id, apply(var_state[t.id], upd))
        new_opt["velocity"] = vel
        return new_vars, new_opt


class AdamOptimizer(Optimizer):
    """Adam/AdamW (reference AdamOptimizer, optimizer.h:60; fused kernel
    impl/kernel/Optimizers.cu).  States kept in fp32 regardless of param
    dtype (mixed-precision master states)."""

    decoupled_weight_decay = False  # True in AdamW (decoupled, torch-style)

    def __init__(self, params=None, lr: float = 1e-3, beta1: float = 0.9,
                 beta2: float = 0.999, eps: float = 1e-8,
                 weight_decay: float = 0.0, **kw):
        super().__init__(params, lr, **kw)
        self.beta1, self.beta2, self.eps = beta1, beta2, eps
        self.weight_decay = weight_decay

    def _init_state(self, var_state, xs):
        return {
            "step": jnp.zeros((), jnp.int32),
            "m": {t.id: jnp.zeros(var_state[t.id].shape, jnp.float32)
                  for t in xs},
            "v": {t.id: jnp.zeros(var_state[t.id].shape, jnp.float32)
                  for t in xs},
        }

    def _apply_updates(self, var_state, opt_state, grads, xs):
        grads = self._clip_grads(grads, xs)
        new_vars = dict(var_state)
        step = opt_state["step"] + 1
        m = dict(opt_state["m"])
        v = dict(opt_state["v"])
        b1, b2 = self.beta1, self.beta2
        lr = self._lr_at(step)
        bc1 = 1.0 - b1 ** step.astype(jnp.float32)
        bc2 = 1.0 - b2 ** step.astype(jnp.float32)
        for t in xs:
            g = self._c_grad(t.id, grads[t.id].astype(jnp.float32))
            p = var_state[t.id]
            if self.weight_decay and not self.decoupled_weight_decay:
                g = g + self.weight_decay * p.astype(jnp.float32)  # Adam-L2
            m[t.id] = self._c(t.id, b1 * m[t.id] + (1 - b1) * g)
            v[t.id] = self._c(t.id, b2 * v[t.id] + (1 - b2) * (g * g))
            m_hat = m[t.id] / bc1
            v_hat = v[t.id] / bc2
            upd = lr * m_hat / (jnp.sqrt(v_hat) + self.eps)
            if self.weight_decay and self.decoupled_weight_decay:
                upd = upd + lr * self.weight_decay * p.astype(jnp.float32)
            new_vars[t.id] = self._c_param(
                t.id, (p.astype(jnp.float32) - upd).astype(p.dtype))
        return new_vars, {"step": step, "m": m, "v": v}


class AdamWOptimizer(AdamOptimizer):
    """AdamW: decoupled weight decay (torch.optim.AdamW semantics)."""
    decoupled_weight_decay = True


class AdafactorOptimizer(Optimizer):
    """Adafactor (Shazeer & Stern 2018) — the memory-efficient TPU
    pretraining optimizer (T5 recipe): second moments factored into
    row/col EMAs, so optimizer state is O(rows+cols) per matrix instead
    of O(rows*cols).  Beyond the reference (SGD/Adam only).

    Delegates the update math to ``optax.adafactor`` (public, baked-in)
    under this framework's graph-update machinery, so it composes with
    define-and-run graphs, donation, and checkpointing like the native
    optimizers.  ZeRO state sharding is intentionally not applied — the
    factored state is the memory win already.  ``lr`` may be a float or
    an ``optim.schedules`` callable (1-based steps, adapted to optax's
    0-based count).
    """

    def __init__(self, params=None, lr=None, min_dim_size_to_factor=128,
                 decay_rate: float = 0.8, clipping_threshold: float = 1.0,
                 momentum: Optional[float] = None,
                 weight_decay_rate: Optional[float] = None,
                 multiply_by_parameter_scale: bool = True,
                 max_grad_norm: Optional[float] = None, **kw):
        super().__init__(params, lr, max_grad_norm=max_grad_norm, **kw)
        import optax
        if callable(lr):
            schedule = lambda count: lr(count + 1)  # noqa: E731
        else:
            schedule = lr
        self._tx = optax.adafactor(
            learning_rate=schedule,
            min_dim_size_to_factor=min_dim_size_to_factor,
            decay_rate=decay_rate,
            clipping_threshold=clipping_threshold,
            momentum=momentum,
            weight_decay_rate=weight_decay_rate,
            multiply_by_parameter_scale=multiply_by_parameter_scale)

    def _init_state(self, var_state, xs):
        params = {t.id: var_state[t.id].astype(jnp.float32) for t in xs}
        return {"optax": self._tx.init(params)}

    def _apply_updates(self, var_state, opt_state, grads, xs):
        grads = self._clip_grads(grads, xs)
        params = {t.id: var_state[t.id].astype(jnp.float32) for t in xs}
        gdict = {t.id: grads[t.id].astype(jnp.float32) for t in xs}
        updates, new_opt = self._tx.update(gdict, opt_state["optax"], params)
        new_vars = dict(var_state)
        for t in xs:
            p = var_state[t.id]
            new_vars[t.id] = self._c_param(
                t.id, (params[t.id] + updates[t.id]).astype(p.dtype))
        return new_vars, {"optax": new_opt}


# torch-style aliases
SGD = SGDOptimizer
Adam = AdamOptimizer
AdamW = AdamWOptimizer
