"""Optimizers.

Reference: ``hetu/graph/optim/optimizer.h:9-100`` (SGD w/ momentum, Adam,
``Minimize = ComputeGradients + ApplyDense``, ``MakeStates`` per-param
optimizer-state variables, multi-zero awareness) and the Python wrappers
(``python/hetu/optim/optimizer.py:43``).

``minimize(loss)`` builds a symbolic update node executed by
``DefineAndRunGraph.run``; under jit the whole fwd+bwd+update is one XLA
program with donated parameter/state buffers (the analogue of the
reference's fused param/grad buffers + fused Optimizers.cu kernels).
ZeRO levels (reference ``zero`` DS flag, ``distributed_states.h:69``,
grad reduce-scatter / param allgather comm ops ``Communication.h:583``),
expressed as GSPMD sharding annotations instead of explicit collectives —
the XLA partitioner then emits the reduce-scatter/all-gather pairs:

- ``zero=1`` — optimizer states sharded over the dp axis.
- ``zero=2`` — + gradients constrained to the same dp-sharded spec inside
  the update (XLA turns the dp grad all-reduce into reduce-scatter and
  gathers the updated params back).
- ``zero=3`` — + parameters stored dp-sharded at rest (FSDP); forward /
  backward all-gathers are inserted by the partitioner on demand.

``zero=True`` keeps its historical meaning of level 1.

``flat_state=True`` (with ``grad_comm=`` and ``zero`` 1/2) swaps the
per-parameter state arrays for flat dp-sharded buffers matching the
coalesced reduce-scatter geometry (optim/flat_state.py), turning the
explicit gradient sync into the reference's reduce-scatter-only ZeRO-2
pairing: RS -> local-chunk update -> weight-dtype param all-gather —
half the gradient wire bytes of the all-reduce path (DESIGN.md §10).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..graph.graph import DefineAndRunGraph, Graph, OpNode, get_default_graph
from ..graph.tensor import Tensor


class Optimizer:
    def __init__(self, params: Optional[Sequence[Tensor]] = None,
                 lr=0.01, zero: int = 0, dp_axis: str = "dp",
                 max_grad_norm: Optional[float] = None,
                 grad_comm: Optional[str] = None,
                 bucket_mb: float = 4.0,
                 flat_state: bool = False,
                 sentry=None):
        # lr: float, or a schedule callable step -> lr (optim.schedules)
        self.lr = lr
        self.params = list(params) if params is not None else None
        self.zero = int(zero)     # ZeRO level 0-3 (True -> 1)
        if not 0 <= self.zero <= 3:
            raise ValueError(f"zero level must be 0..3, got {zero}")
        self.dp_axis = dp_axis
        # global-norm gradient clipping (Megatron-style; applied inside
        # the jitted update, before any optimizer math)
        self.max_grad_norm = max_grad_norm
        # explicit gradient-communication transport (reference
        # AllReduceCoalesce + EQuARX quantized collectives): None keeps
        # the implicit GSPMD per-tensor sync; "fp32"/"bf16"/"int8"
        # switches the dp gradient sync to coalesced buckets over the
        # selected wire format (parallel/comm.py, graph explicit path).
        # Sync uses the data-parallel MEAN convention (torch-DDP
        # semantics) and therefore assumes a mean-normalized loss; a
        # literally sum-reduced loss makes the graph fall back to the
        # implicit path (graph._grad_comm_fallback records why).
        from ..parallel.comm import GRAD_COMM_TRANSPORTS
        if grad_comm is not None and grad_comm not in GRAD_COMM_TRANSPORTS:
            raise ValueError(f"grad_comm must be None or one of "
                             f"{GRAD_COMM_TRANSPORTS}, got {grad_comm!r}")
        self.grad_comm = grad_comm
        self.bucket_mb = float(bucket_mb)
        if self.bucket_mb <= 0:
            raise ValueError(f"bucket_mb must be > 0, got {bucket_mb}")
        # flat dp-sharded optimizer state (reduce-scatter-only ZeRO-2
        # gradient sync, reference SplitReduceScatter under zero): master
        # fp32 params + momentum/variance packed into per-bucket flat
        # buffers sharded P(dp) in equal per-rank chunks.  Requires the
        # explicit grad-comm path (the chunks ARE reduce_scatter_coalesced
        # shards) and ZeRO 1/2 semantics (params replicated at rest,
        # state sharded).
        self.flat_state = bool(flat_state)
        if self.flat_state:
            if grad_comm is None:
                raise ValueError(
                    "flat_state=True needs the explicit grad-comm path: "
                    "pass grad_comm='fp32'|'bf16'|'int8'")
            if self.zero not in (1, 2, 3):
                raise ValueError(
                    f"flat_state=True needs dp-sharded state (ZeRO "
                    f"1/2) or fully sharded params (ZeRO 3); got "
                    f"zero={self.zero}")
        # numeric sentry (resilience/sentry.py): on-device finite/spike
        # verdict fused into every UPDATE-level step, anomalous updates
        # skipped with bitwise-zero residue.  True / SentryConfig /
        # NumericSentry all accepted; None disables.
        if sentry:
            from ..resilience.sentry import NumericSentry, SentryConfig
            if sentry is True:
                sentry = NumericSentry()
            elif isinstance(sentry, SentryConfig):
                sentry = NumericSentry(sentry)
            elif not isinstance(sentry, NumericSentry):
                raise ValueError(
                    f"sentry must be True, a SentryConfig or a "
                    f"NumericSentry, got {sentry!r}")
        self.sentry = sentry or None
        self._flat_layout = None        # FlatStateLayout when flat+active
        self._packed_var_writes = -1    # graph._var_writes at last pack
        self._state: Dict[str, Any] = {}
        self._shardings: Dict[int, Any] = {}  # tid -> NamedSharding of states
        self._param_shardings: Dict[int, Any] = {}  # tid -> zero-3 sharding
        self._param_base_shardings: Dict[int, Any] = {}  # tid -> own spec

    # -- graph API (reference Optimizer::Minimize) ---------------------------

    def minimize(self, loss: Tensor,
                 var_list: Optional[Sequence[Tensor]] = None,
                 grad_scaler=None) -> Tensor:
        g = loss.graph or get_default_graph()
        xs = list(var_list or self.params or g.trainable_variables)
        assert xs, "no trainable variables to optimize"
        grad_node_outputs = g.make_gradients(loss, xs)
        grad_node = grad_node_outputs[0].producer
        node = OpNode("update", None, grad_node_outputs,
                      {"optimizer": self, "grad_node": grad_node, "xs": xs,
                       "grad_scaler": grad_scaler},
                      f"update_{loss.name}")
        t = Tensor((), "float32", producer=node, name=node.name, graph=g)
        node.outputs = [t]
        g.ops.append(node)
        return t

    # -- state management (reference MakeStates) -----------------------------

    def _state_sharding(self, t: Tensor, arr, graph: Graph):
        """Sharding for a per-param optimizer state: the param's own
        sharding, plus ZeRO dp-sharding of dim 0 when enabled (reference
        `zero` ds flag, distributed_states.h:69)."""
        from jax.sharding import NamedSharding, PartitionSpec
        mesh = graph.mesh
        if mesh is None:
            return None
        base = graph._pspec_for(t)
        spec = list(base) if base is not None else []
        spec += [None] * (arr.ndim - len(spec))
        if self.zero and self.dp_axis in mesh.axis_names and arr.ndim > 0:
            dp = mesh.shape[self.dp_axis]
            used = {a for entry in spec if entry
                    for a in (entry if isinstance(entry, tuple) else (entry,))}
            if (self.dp_axis not in used and arr.shape[0] % dp == 0
                    and spec[0] is None):
                spec[0] = self.dp_axis
        if not any(spec):
            return None
        return NamedSharding(mesh, PartitionSpec(*spec))

    def _ensure_state(self, var_state: Dict[int, jax.Array],
                      xs: Sequence[Tensor], graph: Graph) -> Dict[str, Any]:
        # a flat checkpoint's fp32 master copy is meaningful only to
        # _ensure_flat_state; per-param math has no such slot, and
        # letting it ride along (SGD's dict(opt_state) carry) would
        # re-save a STALE master that a later flat restore prefers over
        # the trained params — silently reverting the weights
        self._state.pop("master", None)
        just_inited = False
        if not self._state:
            self._state = self._init_state(var_state, xs)
            just_inited = True
            for key, tree in self._state.items():
                if isinstance(tree, dict):
                    for tid, arr in tree.items():
                        t = next((x for x in xs if x.id == tid), None)
                        if t is None or not hasattr(arr, "shape") \
                                or arr.shape != var_state[tid].shape:
                            continue
                        sharding = self._state_sharding(t, arr, graph)
                        if sharding is not None:
                            tree[tid] = jax.device_put(arr, sharding)
                            self._shardings[tid] = sharding
        if getattr(self, "_pending_tree_state", None):
            # structured state loaded from a checkpoint as ordered leaves
            # (safetensors_io "@@leaf" entries): graft into the freshly
            # initialized structure, validating leaf count + shapes.
            # just-initialized state IS a fresh template; only rebuild
            # one when stepping had already populated self._state
            fresh = self._state if just_inited \
                else self._init_state(var_state, xs)
            for slot, leaves in self._pending_tree_state.items():
                if slot not in fresh or isinstance(fresh[slot], dict):
                    raise ValueError(
                        f"checkpoint carries structured optimizer state "
                        f"{slot!r} that this optimizer does not define — "
                        f"restoring into a different optimizer type?")
                tdef = jax.tree_util.tree_structure(fresh[slot])
                ref = jax.tree_util.tree_leaves(fresh[slot])
                if len(ref) != len(leaves) or any(
                        getattr(a, "shape", None) != getattr(b, "shape", None)
                        for a, b in zip(ref, leaves)):
                    raise ValueError(
                        f"checkpointed optimizer state {slot!r} does not "
                        f"match this optimizer/model (leaf count/shapes)")
                self._state[slot] = jax.tree_util.tree_unflatten(
                    tdef, [jnp.asarray(l, r.dtype)
                           for l, r in zip(leaves, ref)])
            self._pending_tree_state = None
        if self.zero in (1, 2) and graph.mesh is not None \
                and not self._param_base_shardings:
            # pin updated params to their OWN spec (replicated over dp):
            # with dp-sharded states XLA would otherwise freely emit
            # dp-sharded params, silently turning zero-1/2 into FSDP
            from jax.sharding import NamedSharding, PartitionSpec
            for t in xs:
                arr = var_state.get(t.id)
                if arr is None or not hasattr(arr, "ndim"):
                    continue
                base = graph._pspec_for(t)
                spec = list(base) if base is not None else []
                spec += [None] * (arr.ndim - len(spec))
                self._param_base_shardings[t.id] = NamedSharding(
                    graph.mesh, PartitionSpec(*spec))
        if self.zero >= 3:
            # FSDP: parameters live dp-sharded at rest.  Re-assert every
            # step (device_put on an already-sharded array is a no-op) so
            # checkpoint loads / hot switches can't silently unshard.
            for t in xs:
                arr = var_state.get(t.id)
                if arr is None or not hasattr(arr, "shape"):
                    continue
                sh = self._param_shardings.get(t.id)
                if sh is None:
                    sh = self._state_sharding(t, arr, graph)
                    if sh is None:
                        continue
                    self._param_shardings[t.id] = sh
                var_state[t.id] = jax.device_put(arr, sh)
                graph._var_data[t.id] = var_state[t.id]
        return self._state

    def _c(self, tid: int, arr):
        """Re-assert the optimizer-state sharding inside the jitted update
        (XLA would otherwise choose output shardings freely)."""
        sh = self._shardings.get(tid)
        return jax.lax.with_sharding_constraint(arr, sh) if sh is not None else arr

    def _c_grad(self, tid: int, g):
        """ZeRO>=2: constrain the gradient to the dp-sharded state spec —
        the partitioner then reduce-scatters the dp gradient sum instead
        of all-reducing it (reference SplitReduceScatter under zero,
        Communication.h:583).  Under the explicit grad-comm path the
        gradient arrives already reduced (coalesced collectives), so this
        constraint degrades to a local slice — the correct ZeRO-2 layout
        either way."""
        return self._c(tid, g) if self.zero >= 2 else g

    def sync_gradients(self, grads: Dict[int, jax.Array], axis: str):
        """Explicit DP gradient sync: coalesced (optionally quantized)
        mean-allreduce of the micro-batch-accumulated gradient dict —
        one collective chain per bucket instead of one psum per
        parameter.  Must run inside a manual (shard_map) region with
        ``axis`` in scope; the graph executor arranges that
        (DefineAndRunGraph._build_executable explicit path)."""
        from ..parallel import comm
        return comm.all_reduce_coalesced(
            grads, axis, op="mean", bucket_mb=self.bucket_mb,
            transport=self.grad_comm or "fp32")

    # -- flat dp-sharded state (ZeRO-2 reduce-scatter-only sync) -------------
    #
    # State geometry mirrors comm.reduce_scatter_coalesced exactly
    # (optim/flat_state.py): each rank's P(dp) shard of every flat buffer
    # IS its reduce-scattered gradient chunk, so the update is pure local
    # elementwise math and the only collectives per step are one
    # reduce-scatter chain plus one param-dtype all-gather per bucket.

    def _flat_slots(self):
        """Per-param state slots packed into flat buffers (beyond the
        fp32 master copy); subclasses that support flat_state override."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support flat_state=True")

    def _flat_update(self, p, slots, g, step, lr, **ctx):
        """Elementwise update on local fp32 chunks: (master, {slot:
        chunk}, grad, step, lr) -> (new master, {slot: new chunk}).
        ``ctx`` carries ``bucket`` (index), ``axis`` (the manual dp axis)
        and ``fstate`` (the full local flat state) for optimizers whose
        update needs cross-chunk reductions (Adafactor's factored
        stats); plain elementwise optimizers ignore it."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support flat_state=True")

    def _flat_extra_update(self, fstate) -> Dict[str, Any]:
        """New values for non-chunk state entries (anything outside the
        ``flat_*`` slots, e.g. Adafactor's replicated factored stats),
        collected after the per-bucket update loop.  Base: none."""
        return {}

    def _flat_repack_extra(self, key: str, val, old_lay, new_lay):
        """Hot-switch repack of one non-chunk state entry across a flat
        geometry change (dp resize).  Base: pass through unchanged —
        right for geometry-independent extras like the step counter AND
        for per-bucket extras like Adafactor's factored stats (bucket
        planning depends only on the entry set and bucket_mb, not on dp,
        so a dp resize leaves the bucket partition — and with it every
        row/col slot — untouched)."""
        return val

    def _flat_extra_init(self, lay, st: Dict[str, Any]) -> Dict[str, Any]:
        """Initial values for non-chunk state entries when the flat
        state is (re)built under layout ``lay`` (``st`` is the per-param
        starting point — a checkpoint or the unpacked previous state).
        Base: none."""
        return {}

    def _flat_comm_extra(self) -> Dict[str, int]:
        """Collectives the flat update emits in-region BEYOND the
        predicted grad/param chains, as ``{kind: count}`` per step
        (Adafactor's factored-stat psums).  Registered as the plan's
        ``grad_comm.opt_extra`` and folded into the emission
        predictor's/edge pass's ``extra``.  Base: none — the
        registration stays strict."""
        return {}

    def predicted_step_collectives(self, entries, device_num: int,
                                   scalar_fetches: int = 1):
        """The exact collective sequence ONE update step of this
        optimizer (as configured: transport, bucket size, clipping,
        ZeRO level, flat extras) emits over ``device_num`` dp shards —
        ``(predictions, extra)`` per
        ``dstates.predict_update_step_collectives``.

        Single source of truth for every consumer of the optimizer's
        comm contract: the graph's ``grad_comm`` registration, the edge
        pass that prices it, and the cross-rank schedule verifier
        (``analysis/schedule``) that checks it for rank consistency —
        so a config change here cannot drift from what the analysis
        plane verifies."""
        from ..parallel.dstates import predict_update_step_collectives
        return predict_update_step_collectives(
            list(entries), int(device_num),
            transport=self.grad_comm or "fp32",
            bucket_mb=self.bucket_mb,
            scalar_fetches=int(scalar_fetches),
            flat=self.flat_state,
            clip=self.max_grad_norm is not None,
            zero=self.zero,
            opt_extra=self._flat_comm_extra() if self.flat_state
            else None)

    def _flat_entries(self, xs: Sequence[Tensor], var_state):
        """(key, shape, dtype) of the gradient set in SYNC order
        (flat_state.sync_order — the one ordering every flat-geometry
        consumer shares)."""
        from .flat_state import sync_order
        return [(t.id, np.shape(var_state[t.id]),
                 np.dtype(jnp.result_type(var_state[t.id])).name)
                for t in sync_order(xs)]

    def _ensure_flat_state(self, var_state: Dict[int, jax.Array],
                           xs: Sequence[Tensor], graph: Graph
                           ) -> Dict[str, Any]:
        """Build (or graft a restored checkpoint into) the flat state.

        Accepts three starting points: empty (fresh init), per-parameter
        dicts (a checkpoint written by either the flat or the per-param
        path — checkpoints are always per-parameter keyed), or an
        existing flat state whose geometry changed (dp resize / hot
        switch), which is unpacked through the old index and repacked.
        """
        from jax.sharding import NamedSharding, PartitionSpec
        from .flat_state import FlatStateLayout, sync_order
        mesh = graph.mesh
        assert mesh is not None and self.dp_axis in mesh.axis_names, \
            "flat_state needs a mesh with the dp axis (explicit path)"
        slots = self._flat_slots()
        entries = self._flat_entries(xs, var_state)
        dp = mesh.shape[self.dp_axis]
        st = dict(self._state)
        # restored-but-ungrafted non-param state (a checkpoint's
        # ``@@leaf`` entries — Adafactor's per-bucket factored EMAs)
        # joins the starting point so _flat_extra_init can reuse it
        for k, v in (getattr(self, "_pending_tree_state", None)
                     or {}).items():
            st.setdefault(k, v)
        is_flat = any(k.startswith("flat_") for k in st)
        writes = getattr(graph, "_var_writes", 0)

        def _written_since_pack():
            # ONLY the params actually written since the last pack
            # (graph._var_write_log): refreshing every master from the
            # (possibly bf16) live values would throw away the fp32
            # precision of untouched params
            log = getattr(graph, "_var_write_log", {})
            return [t for t in sync_order(xs)
                    if log.get(t.id, -1) > self._packed_var_writes]

        if is_flat and self._flat_layout is not None \
                and self._flat_layout.matches(entries, dp,
                                              self.bucket_mb):
            # steady state: no bucket replanning.  But params written
            # OUTSIDE the update loop (reset_variable / load_model)
            # supersede their packed fp32 master slices, or the next
            # all-gather would silently revert the external write
            if writes != self._packed_var_writes:
                stale = _written_since_pack()
                if stale:
                    lay = self._flat_layout
                    masters = list(self._state["flat_master"])
                    touched = set()
                    for t in stale:
                        bi, off, numel, _shape = lay.index[t.id]
                        flat = jnp.asarray(masters[bi])
                        masters[bi] = flat.at[off:off + numel].set(
                            jnp.ravel(var_state[t.id])
                            .astype(jnp.float32))
                        touched.add(bi)
                    sh_m = NamedSharding(mesh,
                                         PartitionSpec(self.dp_axis))
                    self._state["flat_master"] = [
                        jax.device_put(m, sh_m) if i in touched else m
                        for i, m in enumerate(masters)]
                self._packed_var_writes = writes
            return self._state
        new_lay = FlatStateLayout(entries, dp, bucket_mb=self.bucket_mb)
        if is_flat:
            # geometry changed (dp size / param set): go through the
            # per-param view and repack under the new index; params
            # written since the last pack supersede their old master
            old = self._flat_layout
            per: Dict[str, Any] = {"step": st.get("step")}
            per["master"] = old.unpack(st["flat_master"])
            for t in _written_since_pack():
                per["master"][t.id] = var_state[t.id]
            for s in slots:
                per[s] = old.unpack(st[f"flat_{s}"])
            st = per
        xs_sorted = sync_order(xs)
        params = {t.id: var_state[t.id] for t in xs_sorted}

        def _per_param(tree, default):
            if not isinstance(tree, dict) or not tree:
                return {t.id: default(t) for t in xs_sorted}
            vals = {}
            for t in xs_sorted:
                arr = tree.get(t.id)
                if arr is not None and np.shape(arr) != np.shape(
                        var_state[t.id]):
                    raise ValueError(
                        f"checkpointed flat-state entry for {t.name} has "
                        f"shape {np.shape(arr)}, param is "
                        f"{np.shape(var_state[t.id])}")
                vals[t.id] = arr if arr is not None else default(t)
            return vals

        zeros = lambda t: jnp.zeros(  # noqa: E731
            np.shape(var_state[t.id]), jnp.float32)
        # master defaults to the current (possibly bf16) param values —
        # exactly what a flat_state=False checkpoint implies
        master = _per_param(st.get("master"), lambda t: var_state[t.id])
        flat: Dict[str, Any] = {
            "step": jnp.asarray(st.get("step")
                                if st.get("step") is not None else 0,
                                jnp.int32),
            "flat_master": new_lay.pack(master),
        }
        for s in slots:
            flat[f"flat_{s}"] = new_lay.pack(_per_param(st.get(s), zeros))
        flat.update(self._flat_extra_init(new_lay, st))
        sh = NamedSharding(mesh, PartitionSpec(self.dp_axis))
        for key, bufs in flat.items():
            if key.startswith("flat_"):
                flat[key] = [jax.device_put(a, sh) for a in bufs]
        self._flat_layout = new_lay
        self._state = flat
        self._pending_tree_state = None
        self._packed_var_writes = writes
        if self.zero >= 3:
            # ZeRO-3 at rest: the flat fp32 master IS the authoritative
            # parameter storage; the per-param working copies stay
            # dp-sharded (dim-0 when divisible) so nothing replicated
            # remains resident between steps
            for t in sync_order(xs):
                arr = var_state.get(t.id)
                if arr is None or not hasattr(arr, "shape"):
                    continue
                psh = self._param_shardings.get(t.id)
                if psh is None:
                    psh = self._state_sharding(t, arr, graph)
                    if psh is None:
                        continue
                    self._param_shardings[t.id] = psh
                var_state[t.id] = jax.device_put(arr, psh)
                graph._var_data[t.id] = var_state[t.id]
        return self._state

    def _flat_state_pspecs(self, opt_state: Dict[str, Any]):
        """shard_map specs matching ``opt_state``'s structure: flat
        buffers ride P(dp), everything else replicated."""
        from jax.sharding import PartitionSpec
        return {k: ([PartitionSpec(self.dp_axis)] * len(v)
                    if k.startswith("flat_") else PartitionSpec())
                for k, v in opt_state.items()}

    def _flat_gather_params(self, fstate, xs: Sequence[Tensor], axis: str):
        """ZeRO-3 just-in-time parameter materialization: all-gather
        every bucket of the flat fp32 master in the bucket's WEIGHT
        dtype (``all_gather_coalesced`` casts the chunk before the
        collective), tagged ``param_gather`` so parameter-gather traffic
        stays separable from gradient and param_comm traffic.  Returns
        ``{tid: full param}`` — bitwise the arrays ZeRO-2's post-update
        all-gather produced, since the chunks ARE the same fp32 master.
        Must run inside the shard_map manual region."""
        from ..parallel import comm
        lay = self._flat_layout
        return comm.all_gather_coalesced(
            list(fstate["flat_master"]), lay.comm_layout(), axis,
            tag="param_gather")

    def materialize_flat_params(self, graph: Graph,
                                xs: Sequence[Tensor]) -> None:
        """Refresh the per-param working copies from the flat fp32
        master (ZeRO-3's authoritative storage).  Called lazily when a
        consumer outside the flat update loop needs parameter VALUES —
        eval plans, checkpoint saves, hot switches — and stored back
        dp-sharded so the at-rest footprint stays 1/dp.  The cast
        fp32 -> weight dtype is exactly the in-region gather's, so a
        continuation from the materialized copies is bitwise."""
        lay = self._flat_layout
        if lay is None or "flat_master" not in self._state:
            return
        per = lay.unpack(self._state["flat_master"])
        for t in xs:
            if t.id not in per:
                continue
            arr = jnp.asarray(per[t.id]).astype(t.dtype.to_jnp())
            sh = self._param_shardings.get(t.id)
            if sh is None and self.zero >= 3:
                sh = self._state_sharding(t, arr, graph)
                if sh is not None:
                    self._param_shardings[t.id] = sh
            if sh is not None:
                arr = jax.device_put(arr, sh)
            graph._var_data[t.id] = arr

    def _flat_sync_and_update(self, var_state, fstate, grads,
                              xs: Sequence[Tensor], axis: str,
                              want_sq_norm: bool = False):
        """Reduce-scatter -> local-chunk update -> param-dtype all-gather
        (the reference's zero pairing, Communication.h:583, without ever
        materializing a full gradient).  Must run inside the shard_map
        manual region; ``fstate`` leaves arrive as LOCAL chunks.
        Returns (new param dict, new flat buffers, global grad sq-norm
        or None).  The sq-norm (``want_sq_norm`` or clipping) is the
        psum-reduced fp32 sum of squares of the SYNCED gradient — the
        quantity the clip and the numeric sentry share; psum on its
        def-chain keeps it legal to return from the region.  The step
        counter is NOT among the outputs: it is replicated arithmetic
        the caller increments outside the region (a scalar leaving a
        manual region with no reduction on its def-chain would —
        rightly — trip the unreduced-psum-scalar lint)."""
        from ..parallel import comm
        from .flat_state import sync_order
        lay = self._flat_layout
        xs_sorted = sync_order(xs)
        gdict = {t.id: grads[t.id] for t in xs_sorted}
        chunks, rs_layout = comm.reduce_scatter_coalesced(
            gdict, axis, op="mean", bucket_mb=self.bucket_mb,
            transport=self.grad_comm or "fp32")
        assert tuple(rs_layout.chunks) == tuple(lay.chunks), \
            "flat-state layout drifted from the reduce-scatter geometry"
        sq_norm = None
        if self.max_grad_norm is not None or want_sq_norm:
            # global sum of squares over the scattered chunks: local
            # partial sums + one psum (padding lanes contribute exact
            # zeros) — pre-clip, shared by clip and sentry
            sq = sum(jnp.sum(jnp.square(c)) for c in chunks)
            sq_norm = jax.lax.psum(sq, axis)
        if self.max_grad_norm is not None:
            norm = jnp.sqrt(sq_norm)
            scale = jnp.minimum(1.0, self.max_grad_norm / (norm + 1e-6))
            chunks = [c * scale for c in chunks]
        step = fstate["step"] + 1
        lr = self._lr_at(step)
        slots = self._flat_slots()
        new_master: list = []
        new_slots: Dict[str, list] = {s: [] for s in slots}
        for bi, g in enumerate(chunks):
            p = fstate["flat_master"][bi]
            cur = {s: fstate[f"flat_{s}"][bi] for s in slots}
            p_new, cur_new = self._flat_update(p, cur, g, step, lr,
                                               bucket=bi, axis=axis,
                                               fstate=fstate)
            new_master.append(p_new)
            for s in slots:
                new_slots[s].append(cur_new[s])
        out: Dict[str, Any] = {"flat_master": new_master}
        for s in slots:
            out[f"flat_{s}"] = new_slots[s]
        for k, v in self._flat_extra_update(fstate).items():
            out[k] = v
        if self.zero >= 3:
            # ZeRO-3: nothing but the 1/dp master chunks survives the
            # step — the next step's forward re-gathers just-in-time
            # (param_gather), so there is no post-update all-gather and
            # the trainables drop out of the returned var set entirely
            xs_ids = {t.id for t in xs_sorted}
            new_vars = {k: v for k, v in var_state.items()
                        if k not in xs_ids}
            return new_vars, out, sq_norm
        # updated params ride the WEIGHT dtype across the wire (bucket
        # dtype == param dtype), tagged param_comm — gradient bytes and
        # parameter bytes stay separable in the accounting
        gathered = comm.all_gather_coalesced(new_master, rs_layout, axis,
                                             tag="param_comm")
        new_vars = dict(var_state)
        for t in xs_sorted:
            new_vars[t.id] = gathered[t.id]
        return new_vars, out, sq_norm

    def _c_param(self, tid: int, p):
        """ZeRO-3: keep the updated parameter dp-sharded at rest;
        ZeRO-1/2: pin it to its own (dp-replicated) spec — the param
        allgather of the reference's zero pairing."""
        sh = self._param_shardings.get(tid) if self.zero >= 3 \
            else self._param_base_shardings.get(tid)
        if sh is not None:
            return jax.lax.with_sharding_constraint(p, sh)
        return p

    def _store_state(self, state: Dict[str, Any]) -> None:
        self._state = dict(state)

    def reset_state_rows(self, param: Tensor, rows) -> None:
        """Zero the leading-dim rows of every per-param state array for
        ``param`` (momentum, Adam m/v).  Used by cache-backed embeddings
        when a slot's occupant changes (hetu_tpu/embedding/cached.py);
        subclasses with non-standard state layouts must override."""
        if self._flat_layout is not None:
            # rows of one param live at arbitrary offsets inside shared
            # flat buffers; silently skipping would corrupt cache-backed
            # embeddings — refuse loudly instead
            raise NotImplementedError(
                "reset_state_rows is not supported with flat_state=True "
                "(cache-backed embeddings need per-param state)")
        rows = np.asarray(rows)
        if rows.size == 0:
            return
        tid = param.id
        nrows = param.shape[0] if param.shape else 0
        rows_dev = jnp.asarray(rows)
        for state in self._state.values():
            if isinstance(state, dict) and tid in state:
                arr = state[tid]
                if hasattr(arr, "ndim") and arr.ndim >= 1 \
                        and arr.shape[0] == nrows:
                    # device-side masked update: preserves the array's
                    # sharding/placement (a numpy round-trip would gather
                    # and fail on non-fully-addressable arrays)
                    arr = jnp.asarray(arr)
                    state[tid] = arr.at[rows_dev].set(0)

    def _init_state(self, var_state, xs) -> Dict[str, Any]:
        return {}

    def _lr_at(self, step):
        """Resolve lr: plain float, or a schedule called with the
        (1-based, traced) step — see optim/schedules.py."""
        return self.lr(step) if callable(self.lr) else self.lr

    def _grad_sq_norm(self, grads: Dict[int, jax.Array],
                      xs: Sequence[Tensor]):
        """fp32 global sum of squared gradients — the ONE quantity the
        global-norm clip and the numeric sentry both read (shared here
        so XLA CSE makes the reuse literal).  Nonfinite iff any
        gradient lane is nonfinite."""
        return sum(jnp.sum(jnp.square(grads[t.id].astype(jnp.float32)))
                   for t in xs)

    def _clip_grads(self, grads: Dict[int, jax.Array],
                    xs: Sequence[Tensor]) -> Dict[int, jax.Array]:
        """Global-norm clip across ALL parameter grads (fp32 norm)."""
        if self.max_grad_norm is None:
            return grads
        norm = jnp.sqrt(self._grad_sq_norm(grads, xs))
        scale = jnp.minimum(1.0, self.max_grad_norm / (norm + 1e-6))
        return {t.id: (grads[t.id].astype(jnp.float32) * scale)
                .astype(grads[t.id].dtype) for t in xs}

    def _apply_updates(self, var_state: Dict[int, jax.Array],
                       opt_state: Dict[str, Any],
                       grads: Dict[int, jax.Array],
                       xs: Sequence[Tensor]):
        raise NotImplementedError

    # -- eager API (torch-style step) ----------------------------------------

    def step(self, grads: Dict[int, jax.Array]) -> None:
        assert self.params is not None, "eager step needs params list"
        assert not self.flat_state, \
            "eager step() has no manual dp region; flat_state needs the " \
            "graph explicit path (DefineAndRunGraph.run)"
        g = self.params[0].graph
        var_state = {p.id: g.get_tensor_value(p) for p in self.params}
        opt_state = self._ensure_state(var_state, self.params, g)
        new_vars, new_opt = self._apply_updates(var_state, opt_state, grads,
                                                self.params)
        for p in self.params:
            g._var_data[p.id] = new_vars[p.id]
        self._store_state(new_opt)


class SGDOptimizer(Optimizer):
    def __init__(self, params=None, lr: float = 0.01, momentum: float = 0.0,
                 nesterov: bool = False, **kw):
        super().__init__(params, lr, **kw)
        self.momentum = momentum
        self.nesterov = nesterov

    def _init_state(self, var_state, xs):
        state = {"step": jnp.zeros((), jnp.int32)}
        if self.momentum != 0.0:
            state["velocity"] = {t.id: jnp.zeros_like(var_state[t.id])
                                 for t in xs}
        return state

    def _flat_slots(self):
        return ("velocity",) if self.momentum != 0.0 else ()

    def _flat_update(self, p, slots, g, step, lr, **ctx):
        if self.momentum == 0.0:
            return p - lr * g, {}
        v = self.momentum * slots["velocity"] + g
        upd = g + self.momentum * v if self.nesterov else v
        return p - lr * upd, {"velocity": v}

    def _apply_updates(self, var_state, opt_state, grads, xs):
        grads = self._clip_grads(grads, xs)
        new_vars = dict(var_state)
        new_opt = dict(opt_state)
        # .get: checkpoints from before SGD carried a step counter have
        # no "step" entry — backfill instead of KeyError on restore
        step = opt_state.get("step", jnp.zeros((), jnp.int32)) + 1
        new_opt["step"] = step
        lr = self._lr_at(step)
        def apply(p, upd):
            # fp32 update math, cast back (a scheduled lr is an fp32
            # scalar; don't let promotion change the stored param dtype)
            return (p.astype(jnp.float32)
                    - lr * upd.astype(jnp.float32)).astype(p.dtype)

        if self.momentum == 0.0:
            for t in xs:
                g = self._c_grad(t.id, grads[t.id].astype(var_state[t.id].dtype))
                new_vars[t.id] = self._c_param(t.id, apply(var_state[t.id], g))
            return new_vars, new_opt
        vel = dict(opt_state["velocity"])
        for t in xs:
            g = self._c_grad(t.id, grads[t.id].astype(var_state[t.id].dtype))
            v = self._c(t.id, self.momentum * vel[t.id] + g)
            vel[t.id] = v
            upd = g + self.momentum * v if self.nesterov else v
            new_vars[t.id] = self._c_param(t.id, apply(var_state[t.id], upd))
        new_opt["velocity"] = vel
        return new_vars, new_opt


class AdamOptimizer(Optimizer):
    """Adam/AdamW (reference AdamOptimizer, optimizer.h:60; fused kernel
    impl/kernel/Optimizers.cu).  States kept in fp32 regardless of param
    dtype (mixed-precision master states)."""

    decoupled_weight_decay = False  # True in AdamW (decoupled, torch-style)

    def __init__(self, params=None, lr: float = 1e-3, beta1: float = 0.9,
                 beta2: float = 0.999, eps: float = 1e-8,
                 weight_decay: float = 0.0, **kw):
        super().__init__(params, lr, **kw)
        self.beta1, self.beta2, self.eps = beta1, beta2, eps
        self.weight_decay = weight_decay

    def _init_state(self, var_state, xs):
        return {
            "step": jnp.zeros((), jnp.int32),
            "m": {t.id: jnp.zeros(var_state[t.id].shape, jnp.float32)
                  for t in xs},
            "v": {t.id: jnp.zeros(var_state[t.id].shape, jnp.float32)
                  for t in xs},
        }

    def _flat_slots(self):
        return ("m", "v")

    def _flat_update(self, p, slots, g, step, lr, **ctx):
        # same math as _apply_updates on fp32 chunks; padding lanes have
        # g == 0 and p == 0, so every term stays exactly 0 there
        b1, b2 = self.beta1, self.beta2
        if self.weight_decay and not self.decoupled_weight_decay:
            g = g + self.weight_decay * p                      # Adam-L2
        m = b1 * slots["m"] + (1 - b1) * g
        v = b2 * slots["v"] + (1 - b2) * (g * g)
        bc1 = 1.0 - b1 ** step.astype(jnp.float32)
        bc2 = 1.0 - b2 ** step.astype(jnp.float32)
        upd = lr * (m / bc1) / (jnp.sqrt(v / bc2) + self.eps)
        if self.weight_decay and self.decoupled_weight_decay:
            upd = upd + lr * self.weight_decay * p
        return p - upd, {"m": m, "v": v}

    def _apply_updates(self, var_state, opt_state, grads, xs):
        grads = self._clip_grads(grads, xs)
        new_vars = dict(var_state)
        step = opt_state["step"] + 1
        m = dict(opt_state["m"])
        v = dict(opt_state["v"])
        b1, b2 = self.beta1, self.beta2
        lr = self._lr_at(step)
        bc1 = 1.0 - b1 ** step.astype(jnp.float32)
        bc2 = 1.0 - b2 ** step.astype(jnp.float32)
        for t in xs:
            g = self._c_grad(t.id, grads[t.id].astype(jnp.float32))
            p = var_state[t.id]
            if self.weight_decay and not self.decoupled_weight_decay:
                g = g + self.weight_decay * p.astype(jnp.float32)  # Adam-L2
            m[t.id] = self._c(t.id, b1 * m[t.id] + (1 - b1) * g)
            v[t.id] = self._c(t.id, b2 * v[t.id] + (1 - b2) * (g * g))
            m_hat = m[t.id] / bc1
            v_hat = v[t.id] / bc2
            upd = lr * m_hat / (jnp.sqrt(v_hat) + self.eps)
            if self.weight_decay and self.decoupled_weight_decay:
                upd = upd + lr * self.weight_decay * p.astype(jnp.float32)
            new_vars[t.id] = self._c_param(
                t.id, (p.astype(jnp.float32) - upd).astype(p.dtype))
        return new_vars, {"step": step, "m": m, "v": v}


class AdamWOptimizer(AdamOptimizer):
    """AdamW: decoupled weight decay (torch.optim.AdamW semantics)."""
    decoupled_weight_decay = True


class AdafactorOptimizer(Optimizer):
    """Adafactor (Shazeer & Stern 2018) — the memory-efficient TPU
    pretraining optimizer (T5 recipe): second moments factored into
    row/col EMAs, so optimizer state is O(rows+cols) per matrix instead
    of O(rows*cols).  Beyond the reference (SGD/Adam only).

    The per-param path delegates the update math to ``optax.adafactor``
    (public, baked-in) under this framework's graph-update machinery, so
    it composes with define-and-run graphs, donation, and checkpointing
    like the native optimizers.  ``lr`` may be a float or an
    ``optim.schedules`` callable (1-based steps, adapted to optax's
    0-based count).

    ``flat_state=True`` is supported natively (same optax semantics,
    reimplemented on bucket chunks): the full second moment rides the
    flat dp-sharded ``v`` slot ONLY for parameters too small to factor;
    factored parameters keep row/col EMA vectors packed per-bucket in
    replicated ``fac_row``/``fac_col`` state (O(rows+cols) — tiny), and
    their lanes of ``v`` stay zero.  The factored stats need global
    row/col means of the squared gradient, which each rank computes from
    its chunk via static segment-sum plans plus ONE fp32 psum per bucket
    (a second when ``clipping_threshold`` adds the per-block update-RMS
    reduction); those extra collectives are declared through
    ``_flat_comm_extra`` so the strict emission verifier still holds
    exactly.  Deviation from optax: only 2-D parameters factor (ndim>2
    falls back to the full second moment).
    """

    def __init__(self, params=None, lr=None, min_dim_size_to_factor=128,
                 decay_rate: float = 0.8, clipping_threshold: float = 1.0,
                 momentum: Optional[float] = None,
                 weight_decay_rate: Optional[float] = None,
                 multiply_by_parameter_scale: bool = True,
                 max_grad_norm: Optional[float] = None, **kw):
        super().__init__(params, lr, max_grad_norm=max_grad_norm, **kw)
        import optax
        self.min_dim_size_to_factor = int(min_dim_size_to_factor)
        self.decay_rate = float(decay_rate)
        self.clipping_threshold = clipping_threshold
        self.momentum = momentum
        self.weight_decay_rate = weight_decay_rate
        self.multiply_by_parameter_scale = multiply_by_parameter_scale
        self.eps = 1e-30            # optax factorized epsilon[0]
        self._fac_cache = None      # (layout, per-bucket segment plans)
        self._pending_fac = None
        if callable(lr):
            schedule = lambda count: lr(count + 1)  # noqa: E731
        else:
            schedule = lr
        self._tx = optax.adafactor(
            learning_rate=schedule,
            min_dim_size_to_factor=min_dim_size_to_factor,
            decay_rate=decay_rate,
            clipping_threshold=clipping_threshold,
            momentum=momentum,
            weight_decay_rate=weight_decay_rate,
            multiply_by_parameter_scale=multiply_by_parameter_scale)

    def _init_state(self, var_state, xs):
        params = {t.id: var_state[t.id].astype(jnp.float32) for t in xs}
        return {"optax": self._tx.init(params)}

    # -- flat_state support ---------------------------------------------------

    def _factored_dims(self, shape):
        """(d1, d0) = (second-largest, largest) dim index when ``shape``
        factors — optax's rule restricted to ndim==2 (the flat plans
        index rows/cols of matrices; higher-rank tensors keep the full
        second moment)."""
        if len(shape) != 2 or min(shape) < self.min_dim_size_to_factor:
            return None
        order = np.argsort(shape)     # stable: square -> d1=0, d0=1
        return int(order[-2]), int(order[-1])

    def _flat_slots(self):
        return ("v",) + (("m",) if self.momentum else ())

    def _fac_plan(self, lay):
        """Per-bucket static segment plans mapping every flat-buffer
        lane to its factored row/col slot and owning param.  Pure numpy
        from the layout index (cached per layout object); rank-local
        views are sliced inside the update by ``axis_index``.

        Slot spaces per bucket (each with one trailing TRASH slot that
        absorbs padding lanes and non-factored params):
        ``row``  — concatenated per-factored-param vectors of length
        ``shape[d1]`` (the axis that survives the mean over d0);
        ``col``  — same with d0/d1 swapped; ``pid`` — one slot per
        param (clip blocks + parameter-scale RMS)."""
        if self._fac_cache is not None and self._fac_cache[0] is lay:
            return self._fac_cache[1]
        plans = []
        n = lay.device_num
        for bi, b in enumerate(lay.buckets):
            size = n * lay.chunks[bi]
            nparams = len(b.keys)
            row_div, rowslot_pid, col_div = [], [], []
            p_nrows = np.ones(nparams + 1, np.float32)
            p_numel = np.ones(nparams + 1, np.float32)
            # first pass: count row/col slots so trash ids are known
            n_rows = n_cols = 0
            facd = []
            for shape in b.shapes:
                fd = self._factored_dims(shape)
                facd.append(fd)
                if fd is not None:
                    d1, d0 = fd
                    n_rows += shape[d1]
                    n_cols += shape[d0]
            n_rows += 1               # trash slots
            n_cols += 1
            pid = np.full(size, nparams, np.int32)
            row_id = np.full(size, n_rows - 1, np.int32)
            col_id = np.full(size, n_cols - 1, np.int32)
            fac = np.zeros(size, np.float32)
            real = np.zeros(size, np.float32)
            off = row_base = col_base = 0
            for idx, (shape, numel, fd) in enumerate(
                    zip(b.shapes, b.numels, facd)):
                sl = slice(off, off + numel)
                real[sl] = 1.0
                pid[sl] = idx
                p_numel[idx] = numel
                if fd is not None:
                    d1, d0 = fd
                    q = np.arange(numel)
                    i, j = q // shape[1], q % shape[1]
                    row_id[sl] = row_base + (i if d0 == 1 else j)
                    col_id[sl] = col_base + (j if d0 == 1 else i)
                    fac[sl] = 1.0
                    row_div.extend([shape[d0]] * shape[d1])
                    rowslot_pid.extend([idx] * shape[d1])
                    col_div.extend([shape[d1]] * shape[d0])
                    p_nrows[idx] = shape[d1]
                    row_base += shape[d1]
                    col_base += shape[d0]
                off += numel
            row_div.append(1)
            rowslot_pid.append(nparams)
            col_div.append(1)
            plans.append({
                "pid": pid, "row_id": row_id, "col_id": col_id,
                "fac": fac, "real": real,
                "n_rows": n_rows, "n_cols": n_cols,
                "nparams": nparams,
                "row_div": np.asarray(row_div, np.float32),
                "rowslot_pid": np.asarray(rowslot_pid, np.int32),
                "col_div": np.asarray(col_div, np.float32),
                "p_nrows": p_nrows, "p_numel": p_numel,
            })
        self._fac_cache = (lay, plans)
        return plans

    def _flat_update(self, p, slots, g, step, lr, **ctx):
        """optax.adafactor's exact chain on one bucket's local chunk —
        factored stats via segment sums + one psum (two with clipping):
        scale_by_factored_rms -> clip_by_block_rms -> lr ->
        scale_by_param_block_rms -> ema(momentum) ->
        add_decayed_weights -> descent."""
        import jax.ops
        bi, axis = ctx["bucket"], ctx["axis"]
        fstate = ctx["fstate"]
        lay = self._flat_layout
        plan = self._fac_plan(lay)[bi]
        if bi == 0:
            self._pending_fac = ([], [])
        chunk = lay.chunks[bi]
        r = jax.lax.axis_index(axis)

        def local(arr):
            return jax.lax.dynamic_slice_in_dim(
                jnp.asarray(arr), r * chunk, chunk)

        pid_l = local(plan["pid"])
        row_l = local(plan["row_id"])
        col_l = local(plan["col_id"])
        fac_l = local(plan["fac"])
        real_l = local(plan["real"])
        n_rows, n_cols = plan["n_rows"], plan["n_cols"]
        nseg = plan["nparams"] + 1
        t = step.astype(jnp.float32)
        d = 1.0 - t ** (-self.decay_rate)      # decay_rate_t, 1-based t
        gsq = g * g + self.eps
        # round 1: rank-local segment sums -> ONE fp32 psum (row sums,
        # col sums, and pre-update param sq-norms ride one buffer)
        row_s = jax.ops.segment_sum(gsq, row_l, num_segments=n_rows)
        col_s = jax.ops.segment_sum(gsq, col_l, num_segments=n_cols)
        psq = jax.ops.segment_sum(p * p, pid_l, num_segments=nseg)
        stats = jax.lax.psum(jnp.concatenate([row_s, col_s, psq]), axis)
        row_s = stats[:n_rows]
        col_s = stats[n_rows:n_rows + n_cols]
        psq = stats[n_rows + n_cols:]
        # factored row/col EMAs (replicated — every rank computed the
        # same psum) and the factored update
        vr = d * fstate["fac_row"][bi] + (1 - d) * (row_s / plan["row_div"])
        vc = d * fstate["fac_col"][bi] + (1 - d) * (col_s / plan["col_div"])
        self._pending_fac[0].append(vr)
        self._pending_fac[1].append(vc)
        rsum = jax.ops.segment_sum(vr, jnp.asarray(plan["rowslot_pid"]),
                                   num_segments=nseg)
        rmean = rsum / plan["p_nrows"]
        rf = (jnp.maximum(vr, self.eps)
              / jnp.maximum(rmean[plan["rowslot_pid"]], self.eps)) ** -0.5
        cf = jnp.maximum(vc, self.eps) ** -0.5
        u_fac = g * rf[row_l] * cf[col_l]
        # non-factored lanes: full second moment on the flat v slot
        # (kept exactly zero on factored/padding lanes)
        vfull = d * slots["v"] + (1 - d) * gsq
        u_nf = g * jax.lax.rsqrt(jnp.maximum(vfull, self.eps))
        u = jnp.where(fac_l > 0, u_fac, u_nf)
        out = {"v": vfull * real_l * (1.0 - fac_l)}
        if self.clipping_threshold is not None:
            # round 2: per-param block RMS of the update
            usq = jax.lax.psum(
                jax.ops.segment_sum(u * u, pid_l, num_segments=nseg), axis)
            rms_u = jnp.sqrt(usq / plan["p_numel"])
            u = u / jnp.maximum(
                1.0, rms_u / self.clipping_threshold)[pid_l]
        if lr is not None:
            u = u * lr
        if self.multiply_by_parameter_scale:
            pscale = jnp.maximum(jnp.sqrt(psq / plan["p_numel"]), 1e-3)
            u = u * pscale[pid_l]
        if self.momentum:
            m = self.momentum * slots["m"] + (1 - self.momentum) * u
            u = m
            out["m"] = m * real_l
        if self.weight_decay_rate:
            u = u + self.weight_decay_rate * p
        u = u * real_l
        return p - u, out

    def _flat_extra_update(self, fstate):
        fr, fc = self._pending_fac
        self._pending_fac = None
        return {"fac_row": fr, "fac_col": fc}

    def _flat_extra_init(self, lay, st):
        """Zero row/col EMA vectors per bucket (reusing shape-matching
        vectors from ``st`` when a rebuild preserved them)."""
        plans = self._fac_plan(lay)
        out = {}
        for key, n_key in (("fac_row", "n_rows"), ("fac_col", "n_cols")):
            old = st.get(key)
            vecs = []
            for bi, plan in enumerate(plans):
                want = plan[n_key]
                if (isinstance(old, (list, tuple)) and bi < len(old)
                        and np.shape(old[bi]) == (want,)):
                    vecs.append(jnp.asarray(old[bi], jnp.float32))
                else:
                    vecs.append(jnp.zeros((want,), jnp.float32))
            out[key] = vecs
        return out

    def _flat_comm_extra(self):
        lay = self._flat_layout
        nb = len(lay.buckets) if lay is not None else 0
        per_bucket = 2 if self.clipping_threshold is not None else 1
        return {"all_reduce": nb * per_bucket} if nb else {}

    def _apply_updates(self, var_state, opt_state, grads, xs):
        grads = self._clip_grads(grads, xs)
        params = {t.id: var_state[t.id].astype(jnp.float32) for t in xs}
        gdict = {t.id: grads[t.id].astype(jnp.float32) for t in xs}
        updates, new_opt = self._tx.update(gdict, opt_state["optax"], params)
        new_vars = dict(var_state)
        for t in xs:
            p = var_state[t.id]
            new_vars[t.id] = self._c_param(
                t.id, (params[t.id] + updates[t.id]).astype(p.dtype))
        return new_vars, {"optax": new_opt}


# torch-style aliases
SGD = SGDOptimizer
Adam = AdamOptimizer
AdamW = AdamWOptimizer
