"""Dataloader with native background prefetching and dp-rank sharding.

Counterpart of the reference's C++ prefetching loader
(``hetu/graph/data/dataloader.h:18`` — worker queue, shuffle, drop_last,
``set_dp_rank`` dp sharding at ``dataloader.h:116``) and its Python
wrappers (``python/hetu/utils/data/``).

Two paths:
- **native**: fixed-stride sample matrices (contiguous 2-D numpy arrays)
  stream through the C++ core (``hetu_tpu/csrc/dataloader.cc``) which
  assembles batches on a background thread;
- **python**: arbitrary map-style datasets batched in-process.

Both yield numpy batches; dp sharding hands each rank a disjoint
``rank::nrank`` slice of the sample set.
"""
from __future__ import annotations

import ctypes
from typing import Iterator, Optional, Sequence, Union

import numpy as np

from ..csrc.build import load_dataloader_core
from .dataset import Dataset, TensorDataset


class Dataloader:
    def __init__(self, dataset: Union[Dataset, np.ndarray],
                 batch_size: int, shuffle: bool = False,
                 drop_last: bool = True, seed: int = 0,
                 queue_size: int = 2, use_native: Optional[bool] = None):
        if isinstance(dataset, np.ndarray):
            dataset = TensorDataset(dataset)
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed
        self.queue_size = queue_size
        self._dp_rank, self._dp_nrank = 0, 1
        self._epoch = 0

        self._native_mat: Optional[np.ndarray] = None
        self._lib = None
        self._handle = None
        self._handle_key = None
        if use_native is not False:
            lib = load_dataloader_core()  # probe before materializing
            if lib is not None:
                mat = self._native_matrix(dataset)
                if mat is not None:
                    self._native_mat = mat
                    self._lib = lib
        if use_native is True and self._lib is None:
            raise RuntimeError("native dataloader requested but "
                               "unavailable (need a contiguous 2-D array "
                               "dataset and a working g++)")

    @staticmethod
    def _native_matrix(dataset) -> Optional[np.ndarray]:
        """The native path needs one contiguous fixed-stride matrix."""
        if isinstance(dataset, TensorDataset) and len(dataset.arrays) == 1:
            a = dataset.arrays[0]
            if a.ndim == 2 and a.flags["C_CONTIGUOUS"]:
                return a
        if hasattr(dataset, "as_matrix"):
            return np.ascontiguousarray(dataset.as_matrix())
        return None

    # -- reference API: dp sharding (dataloader.h set_dp_rank) -------------

    def set_dp_rank(self, dp_rank: int, dp_nrank: int) -> "Dataloader":
        assert 0 <= dp_rank < dp_nrank
        self._dp_rank, self._dp_nrank = dp_rank, dp_nrank
        return self

    @property
    def num_samples(self) -> int:
        n = len(self.dataset)
        return (n - self._dp_rank + self._dp_nrank - 1) // self._dp_nrank

    def __len__(self) -> int:
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    # -- iteration ---------------------------------------------------------

    def __iter__(self) -> Iterator:
        self._epoch += 1
        seed = self.seed + self._epoch
        if self._lib is not None:
            yield from self._iter_native(seed)
        else:
            yield from self._iter_python(seed)

    def _iter_native(self, seed):
        mat = self._native_mat
        # one persistent handle; epochs restart via the core's reset (dp
        # sharding changes require a rebuild)
        key = (self._dp_rank, self._dp_nrank)
        if self._handle is not None and self._handle_key != key:
            self._lib.hetu_loader_destroy(self._handle)
            self._handle = None
        if self._handle is None:
            self._handle = self._lib.hetu_loader_create(
                mat.ctypes.data_as(ctypes.c_void_p), mat.shape[0],
                mat.strides[0], self.batch_size, self.queue_size,
                int(self.shuffle), seed, int(self.drop_last),
                self._dp_rank, self._dp_nrank)
            self._handle_key = key
        else:
            self._lib.hetu_loader_reset(self._handle, seed)
        out = np.empty((self.batch_size, mat.shape[1]), mat.dtype)
        while True:
            rows = self._lib.hetu_loader_next(
                self._handle, out.ctypes.data_as(ctypes.c_void_p))
            if rows == 0:
                return
            yield out[:rows].copy()

    def __del__(self):
        if getattr(self, "_handle", None) is not None and \
                self._lib is not None:
            self._lib.hetu_loader_destroy(self._handle)
            self._handle = None

    def _iter_python(self, seed):
        idx = np.arange(self._dp_rank, len(self.dataset), self._dp_nrank)
        if self.shuffle:
            np.random.RandomState(seed).shuffle(idx)
        bs = self.batch_size
        for s in range(0, len(idx), bs):
            chunk = idx[s:s + bs]
            if len(chunk) < bs and self.drop_last:
                return
            samples = [self.dataset[int(i)] for i in chunk]
            if isinstance(samples[0], tuple):
                yield tuple(np.stack([s[j] for s in samples])
                            for j in range(len(samples[0])))
            else:
                yield np.stack(samples)
