"""Variable-sequence-length buckets: padding and packing with cu_seqlens.

Counterpart of the reference's Hydraulis bucket utilities
(``examples/hydraulis/data_utils/bucket.py``: ``Bucket.pad_data`` /
``pack_data`` building padded or packed batches + per-row ``cu_seqlens``
for varlen flash attention, ``get_sorted_batch_and_len``,
``get_input_and_label_buckets``).

Packed rows feed :func:`hetu_tpu.ops.attention` varlen kernels; alignment
keeps row lengths on TPU-friendly multiples (static shape buckets).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np


def _align_up(x: int, a: int) -> int:
    return (x + a - 1) // a * a


def ffd_pack(seqlens: Sequence[int], max_seqlen: int, alignment: int
             ) -> List[List[int]]:
    """First-fit-decreasing packing of (aligned) sequence lengths into
    rows of ``max_seqlen``; returns per-row index groups.  Shared by
    :meth:`Bucket.pack_data` and the dispatcher's
    :func:`hetu_tpu.planner.dispatch.batching_strategy`."""
    order = sorted(range(len(seqlens)), key=lambda i: -seqlens[i])
    groups: List[List[int]] = []
    room: List[int] = []
    for i in order:
        n = _align_up(int(seqlens[i]), alignment)
        assert n <= max_seqlen, \
            f"sequence {i} (aligned {n}) exceeds max_seqlen {max_seqlen}"
        for gi, g in enumerate(groups):
            if room[gi] >= n:
                g.append(i)
                room[gi] -= n
                break
        else:
            groups.append([i])
            room.append(max_seqlen - n)
    return groups


class Bucket:
    """Collects variable-length sequences, then materializes either a
    padded batch (one row per sequence) or a packed batch (greedy
    first-fit-decreasing into rows of ``max_seqlen``) with cu_seqlens."""

    def __init__(self, pad_token: int, max_seqlen: int, alignment: int = 128):
        self.pad_token = pad_token
        self.max_seqlen = _align_up(max_seqlen, alignment)
        self.alignment = alignment
        self._seqs: List[np.ndarray] = []      # valid tokens only
        self._padded: Optional[np.ndarray] = None
        self._padded_cu: List[np.ndarray] = []
        self._packed: Optional[np.ndarray] = None
        self._packed_cu: List[np.ndarray] = []
        self._packed_lens: List[np.ndarray] = []

    def add_data(self, sequence: np.ndarray, valid_tokens: int) -> None:
        seq = np.asarray(sequence).reshape(-1)[:valid_tokens]
        assert len(seq) <= self.max_seqlen, \
            f"sequence of {len(seq)} tokens exceeds bucket max " \
            f"{self.max_seqlen}"
        self._seqs.append(seq.astype(np.int64))

    # -- padded layout -----------------------------------------------------

    def pad_data(self) -> None:
        """One sequence per row, padded to the aligned max length."""
        rows, cus = [], []
        for seq in self._seqs:
            row = np.full(self.max_seqlen, self.pad_token, np.int64)
            row[:len(seq)] = seq
            rows.append(row)
            cus.append(np.asarray([0, len(seq)], np.int32))
        self._padded = np.stack(rows) if rows else \
            np.zeros((0, self.max_seqlen), np.int64)
        self._padded_cu = cus

    # -- packed layout -----------------------------------------------------

    def pack_data(self, batching_option_matrix: Optional[np.ndarray] = None
                  ) -> None:
        """Pack sequences into rows of ``max_seqlen``.

        With ``batching_option_matrix`` [num_rows, num_seqs] (0/1: row
        assignment, e.g. from the Hydraulis ILP dispatcher), rows follow
        the matrix; otherwise greedy first-fit-decreasing.
        """
        if batching_option_matrix is not None:
            mat = np.asarray(batching_option_matrix)
            if mat.shape[1] != len(self._seqs):
                raise ValueError(
                    f"batching_option_matrix has {mat.shape[1]} columns "
                    f"for {len(self._seqs)} sequences")
            cover = mat.sum(axis=0)
            bad = np.nonzero(cover != 1)[0]
            if bad.size:
                raise ValueError(
                    f"batching_option_matrix must assign each sequence to "
                    f"exactly one row; sequences {bad.tolist()[:8]} are "
                    f"covered {cover[bad].tolist()[:8]} times")
            groups = [[j for j in range(mat.shape[1]) if mat[i, j]]
                      for i in range(mat.shape[0])]
            groups = [g for g in groups if g]
        else:
            groups = ffd_pack([len(s) for s in self._seqs],
                              self.max_seqlen, self.alignment)
        # validate capacity before writing anything (matters for
        # caller-provided assignment matrices)
        for gi, g in enumerate(groups):
            need = sum(_align_up(len(self._seqs[i]), self.alignment)
                       for i in g)
            if need > self.max_seqlen:
                raise ValueError(
                    f"packed row {gi} needs {need} aligned tokens, exceeds "
                    f"max_seqlen {self.max_seqlen}")
        rows, cus, lens = [], [], []
        for g in groups:
            row = np.full(self.max_seqlen, self.pad_token, np.int64)
            cu = [0]
            ln = []
            off = 0
            for i in g:
                seq = self._seqs[i]
                row[off:off + len(seq)] = seq
                off = _align_up(off + len(seq), self.alignment)
                cu.append(off)
                ln.append(len(seq))
            rows.append(row)
            cus.append(np.asarray(cu, np.int32))
            lens.append(np.asarray(ln, np.int32))
        self._packed = np.stack(rows) if rows else \
            np.zeros((0, self.max_seqlen), np.int64)
        self._packed_cu = cus
        self._packed_lens = lens

    # -- accessors (reference property surface) ----------------------------

    @property
    def original_batch_size(self) -> int:
        return len(self._seqs)

    @property
    def padded_batch_size(self) -> int:
        assert self._padded is not None, "call pad_data() first"
        return len(self._padded)

    @property
    def packed_batch_size(self) -> int:
        assert self._packed is not None, "call pack_data() first"
        return len(self._packed)

    @property
    def padded_batch(self) -> np.ndarray:
        assert self._padded is not None, "call pad_data() first"
        return self._padded

    @property
    def padded_cu_seqlens_list(self) -> List[np.ndarray]:
        return self._padded_cu

    @property
    def packed_batch(self) -> np.ndarray:
        assert self._packed is not None, "call pack_data() first"
        return self._packed

    @property
    def packed_cu_seqlens_list(self) -> List[np.ndarray]:
        return self._packed_cu

    @property
    def packed_valid_lens_list(self) -> List[np.ndarray]:
        """Per packed row: each doc's VALID token count (cu offsets are
        alignment-padded; doc k occupies [cu[k], cu[k]+lens[k]))."""
        return self._packed_lens


def _valid_lens(batch: np.ndarray, pad_token: int) -> np.ndarray:
    """Per-row valid length = non-pad PREFIX length (position after the
    last non-pad token), so a legitimate in-vocab token equal to
    pad_token mid-sequence doesn't shrink the count."""
    S = batch.shape[1]
    nonpad = batch != pad_token
    has_any = nonpad.any(axis=1)
    last = S - np.argmax(nonpad[:, ::-1], axis=1)
    return np.where(has_any, last, 0)


def get_sorted_batch_and_len(global_batch: np.ndarray, pad_token: int
                             ) -> Tuple[np.ndarray, np.ndarray]:
    """Sort a padded [B, S] batch by valid length ascending; returns
    (sorted_batch, sorted_valid_lens) (reference bucket.py:119)."""
    batch = np.asarray(global_batch)
    valid = _valid_lens(batch, pad_token)
    order = np.argsort(valid, kind="stable")
    return batch[order], valid[order]


def build_fake_batch_and_len(fake_seqlens: Sequence[int], pad_token: int,
                             vocab_size: int = 100, seed: int = 0
                             ) -> Tuple[np.ndarray, np.ndarray]:
    """Synthetic padded batch with the given valid lengths (reference
    bucket.py:128 — used for dispatcher testing/profiling)."""
    rng = np.random.RandomState(seed)
    S = max(fake_seqlens)
    rows = []
    for n in fake_seqlens:
        row = np.full(S, pad_token, np.int64)
        row[:n] = rng.randint(1, vocab_size, n)
        rows.append(row)
    batch = np.stack(rows)
    return batch, np.asarray(fake_seqlens)


def get_input_and_label_buckets(global_batch: np.ndarray, pad_token: int,
                                batch_indices: Sequence[int],
                                max_seqlen: int, alignment: int = 128
                                ) -> Tuple[Bucket, Bucket]:
    """Build (input, label) buckets for the selected rows: labels are the
    inputs shifted by one (reference bucket.py:142)."""
    batch = np.asarray(global_batch)
    valid = _valid_lens(batch, pad_token)
    in_bucket = Bucket(pad_token, max_seqlen, alignment)
    lb_bucket = Bucket(pad_token, max_seqlen, alignment)
    for i in batch_indices:
        n = int(valid[i])
        seq = batch[i, :n]
        in_bucket.add_data(seq[:-1], n - 1)
        lb_bucket.add_data(seq[1:], n - 1)
    return in_bucket, lb_bucket
