"""Data subsystem: datasets, prefetching dataloader (native core), and
variable-seq-len buckets.

Covers the reference's C++ dataloader (``hetu/graph/data/dataloader.h``),
Python data utils (``python/hetu/utils/data/``), GPT datasets
(``examples/gpt/data_utils/``) and Hydraulis buckets
(``examples/hydraulis/data_utils/bucket.py``).
"""
from .bucket import (Bucket, build_fake_batch_and_len,
                     get_input_and_label_buckets, get_sorted_batch_and_len)
from .dataloader import Dataloader
from .dataset import Dataset, GPTJsonDataset, GPTSeqDataset, TensorDataset

__all__ = [
    "Bucket", "build_fake_batch_and_len", "get_input_and_label_buckets",
    "get_sorted_batch_and_len", "Dataloader", "Dataset", "GPTJsonDataset",
    "GPTSeqDataset", "TensorDataset",
]
