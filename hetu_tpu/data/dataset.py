"""Datasets: torch-style map datasets + the GPT token datasets.

Counterparts of the reference's data utilities
(``python/hetu/utils/data/``, ``examples/gpt/data_utils/gpt_seq_dataset.py``
json+tokenizer GPT dataset, ``examples/hydraulis/data_utils/llama_dataset.py``).
"""
from __future__ import annotations

import json
import os
from typing import Any, Callable, List, Optional, Sequence

import numpy as np


class Dataset:
    """Map-style dataset."""

    def __len__(self) -> int:
        raise NotImplementedError

    def __getitem__(self, idx: int):
        raise NotImplementedError


class TensorDataset(Dataset):
    """Tuple-of-arrays dataset (rows indexed together)."""

    def __init__(self, *arrays: np.ndarray):
        assert arrays and all(len(a) == len(arrays[0]) for a in arrays)
        self.arrays = [np.asarray(a) for a in arrays]

    def __len__(self):
        return len(self.arrays[0])

    def __getitem__(self, idx):
        out = tuple(a[idx] for a in self.arrays)
        return out[0] if len(out) == 1 else out


class GPTSeqDataset(Dataset):
    """Fixed-length causal-LM windows over a flat token stream
    (reference GPTSeqDataset pattern: doc tokens -> seq_len windows with
    next-token labels)."""

    def __init__(self, tokens: np.ndarray, seq_len: int,
                 stride: Optional[int] = None):
        self.tokens = np.asarray(tokens, np.int32).reshape(-1)
        self.seq_len = seq_len
        self.stride = stride or seq_len
        n = (len(self.tokens) - 1 - seq_len)
        self.num = max(0, n // self.stride + 1)

    def __len__(self):
        return self.num

    def __getitem__(self, idx):
        s = idx * self.stride
        x = self.tokens[s:s + self.seq_len]
        y = self.tokens[s + 1:s + self.seq_len + 1]
        return x, y

    def as_matrix(self) -> np.ndarray:
        """All (input, label) rows as one [N, 2*seq_len] int32 matrix —
        the fixed-stride layout the native prefetch loader consumes."""
        out = np.empty((self.num, 2 * self.seq_len), np.int32)
        for i in range(self.num):
            x, y = self[i]
            out[i, :self.seq_len] = x
            out[i, self.seq_len:] = y
        return out


class GPTJsonDataset(Dataset):
    """JSON-lines text corpus tokenized to fixed-length rows (reference
    ``examples/gpt/data_utils/gpt_seq_dataset.py``: web json docs ->
    tokenize -> pad/concat to seq_len).

    ``tokenizer`` is any callable text -> list[int]; pass e.g. a
    HuggingFace tokenizer's ``encode``.
    """

    def __init__(self, json_file: str, key: str, seq_len: int,
                 tokenizer: Callable[[str], List[int]],
                 pad_id: int = 0, cache_path: Optional[str] = None):
        self.seq_len = seq_len
        self.pad_id = pad_id
        if cache_path is not None and not cache_path.endswith(".npy"):
            cache_path += ".npy"  # np.save appends it; keep paths in sync
        if cache_path is not None and os.path.exists(cache_path):
            self.data = np.load(cache_path)
        else:
            rows = []
            with open(json_file) as f:
                for line in f:
                    if not line.strip():
                        continue
                    doc = json.loads(line)[key]
                    ids = list(tokenizer(doc))[:seq_len]
                    ids = ids + [pad_id] * (seq_len - len(ids))
                    rows.append(ids)
            self.data = np.asarray(rows, np.int32)
            if cache_path is not None:
                np.save(cache_path, self.data)

    def __len__(self):
        return len(self.data)

    def __getitem__(self, idx):
        return self.data[idx]
