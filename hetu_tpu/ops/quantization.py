"""Blockwise quantization ops: fp4 / nf4 / int8.

TPU-native re-expression of the reference's bitsandbytes-backed quantize /
dequantize ops (``hetu/graph/ops/Quantization.h:15,79`` and the fp4/nf4
kernels it links from ``third_party/bitsandbytes``): absmax blockwise
quantization with 4-bit packed storage plus a per-block absmax sidecar —
the layout the checkpoint quantized-save path
(``python/hetu/utils/checkpoint/ht_safetensors.py:18-35``) writes.

Everything here is pure jnp so it fuses under jit on TPU; 4-bit packing is
two codes per uint8.  The fp4/nf4 codebooks are the standard public
16-entry tables (fp4 = 1-bit sign x 2-bit exponent x 1-bit mantissa;
nf4 = normal-float quantiles from the QLoRA paper).
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

# 16-entry codebooks, index = 4-bit code.
FP4_CODE = np.array(
    [0.0, 0.0052083333, 0.6666666667, 1.0, 0.3333333333, 0.5,
     0.1666666667, 0.25,
     -0.0, -0.0052083333, -0.6666666667, -1.0, -0.3333333333, -0.5,
     -0.1666666667, -0.25], dtype=np.float32)

NF4_CODE = np.array(
    [-1.0, -0.6961928009986877, -0.5250730514526367, -0.39491748809814453,
     -0.28444138169288635, -0.18477343022823334, -0.09105003625154495, 0.0,
     0.07958029955625534, 0.16093020141124725, 0.24611230194568634,
     0.33791524171829224, 0.44070982933044434, 0.5626170039176941,
     0.7229568362236023, 1.0], dtype=np.float32)

_CODES = {"fp4": FP4_CODE, "nf4": NF4_CODE}


def _blocked(x: jnp.ndarray, blocksize: int) -> Tuple[jnp.ndarray, int]:
    flat = x.reshape(-1)
    pad = (-flat.shape[0]) % blocksize
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat.reshape(-1, blocksize), pad


def quantize_4bit(x, quant_type: str = "nf4", blocksize: int = 64
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Blockwise 4-bit quantize.  Returns (packed uint8 of length
    ceil(n/2), absmax per block as float32)."""
    code = jnp.asarray(_CODES[quant_type])
    x = jnp.asarray(x, jnp.float32)
    blocks, _pad = _blocked(x, blocksize)
    absmax = jnp.max(jnp.abs(blocks), axis=1)
    scale = jnp.where(absmax > 0, absmax, 1.0)
    normed = blocks / scale[:, None]
    # nearest codebook entry
    idx = jnp.argmin(jnp.abs(normed[..., None] - code[None, None, :]),
                     axis=-1).astype(jnp.uint8)
    flat_idx = idx.reshape(-1)
    packed = (flat_idx[0::2] << 4) | flat_idx[1::2]
    return packed, absmax.astype(jnp.float32)


def dequantize_4bit(packed, absmax, shape, quant_type: str = "nf4",
                    blocksize: int = 64, dtype=jnp.float32) -> jnp.ndarray:
    """Inverse of :func:`quantize_4bit` (original ``shape`` required)."""
    code = jnp.asarray(_CODES[quant_type])
    hi = (packed >> 4).astype(jnp.int32)
    lo = (packed & 0xF).astype(jnp.int32)
    idx = jnp.stack([hi, lo], axis=1).reshape(-1)
    vals = code[idx]
    scale = jnp.where(absmax > 0, absmax, 1.0)
    vals = vals.reshape(-1, blocksize) * scale[:, None]
    n = int(np.prod(shape)) if len(shape) else 1
    return vals.reshape(-1)[:n].reshape(shape).astype(dtype)


def quantize_rows(x, quant: str) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-ROW absmax quantize of ``[..., d]`` vectors — blockwise
    quantization with ``blocksize == d`` and the block axis kept in
    place, so a paged KV pool can store one scale per cached token
    (``serving`` latent-page quantization).  Bitwise identical to the
    flat :func:`quantize_int8` / :func:`quantize_4bit` math.

    Returns ``(codes, absmax)``: codes are int8 ``[..., d]`` for
    ``"int8"`` or packed uint8 ``[..., d//2]`` for ``"nf4"`` (d must be
    even); absmax is float32 ``[..., 1]``."""
    x = jnp.asarray(x, jnp.float32)
    absmax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.where(absmax > 0, absmax, 1.0)
    if quant == "int8":
        q = jnp.clip(jnp.round(x / scale * 127.0), -127, 127)
        return q.astype(jnp.int8), absmax
    if quant in ("nf4", "fp4"):
        if x.shape[-1] % 2:
            raise ValueError(f"4-bit rows need even width, got "
                             f"{x.shape[-1]}")
        code = jnp.asarray(_CODES[quant])
        idx = jnp.argmin(jnp.abs((x / scale)[..., None] - code),
                         axis=-1).astype(jnp.uint8)
        packed = (idx[..., 0::2] << 4) | idx[..., 1::2]
        return packed, absmax
    raise ValueError(f"unknown row quant {quant!r}")


def dequantize_rows(codes, absmax, quant: str, d: int,
                    dtype=jnp.float32) -> jnp.ndarray:
    """Inverse of :func:`quantize_rows`: codes ``[..., w]`` + absmax
    ``[..., 1]`` -> ``[..., d]``."""
    scale = jnp.where(absmax > 0, absmax, 1.0).astype(jnp.float32)
    if quant == "int8":
        return (codes.astype(jnp.float32) / 127.0 * scale).astype(dtype)
    if quant in ("nf4", "fp4"):
        code = jnp.asarray(_CODES[quant])
        hi = (codes >> 4).astype(jnp.int32)
        lo = (codes & 0xF).astype(jnp.int32)
        idx = jnp.stack([hi, lo], axis=-1).reshape(*codes.shape[:-1], d)
        return (code[idx] * scale).astype(dtype)
    raise ValueError(f"unknown row quant {quant!r}")


def quantize_int8(x, blocksize: int = 256
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Blockwise symmetric int8 absmax quantize -> (int8 codes, absmax)."""
    x = jnp.asarray(x, jnp.float32)
    blocks, _pad = _blocked(x, blocksize)
    absmax = jnp.max(jnp.abs(blocks), axis=1)
    scale = jnp.where(absmax > 0, absmax, 1.0)
    q = jnp.clip(jnp.round(blocks / scale[:, None] * 127.0), -127, 127)
    return q.astype(jnp.int8).reshape(-1), absmax.astype(jnp.float32)


def dequantize_int8(q, absmax, shape, blocksize: int = 256,
                    dtype=jnp.float32) -> jnp.ndarray:
    q = jnp.asarray(q, jnp.float32).reshape(-1, blocksize)
    scale = jnp.where(absmax > 0, absmax, 1.0)
    vals = q / 127.0 * scale[:, None]
    n = int(np.prod(shape)) if len(shape) else 1
    return vals.reshape(-1)[:n].reshape(shape).astype(dtype)
