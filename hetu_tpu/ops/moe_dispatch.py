"""Capacity-free MoE dispatch: blocked group-GEMM.

Shared core of the dropless expert-compute path (reference
``moe_layer.py:45`` reaches the same dataflow with layout_transform +
AllToAll but *drops* over-capacity tokens; this path drops none).

Mechanics: (token, expert) assignments are sorted by expert and each
expert's group padded to a block multiple, so every ``[B, d]`` token
block multiplies exactly ONE expert's weights — three einsums over
``G = ceil(N_pad / B)`` blocks with ``N_pad <= T*k + E*(B-1)``, i.e.
~``k/E`` of the dense all-experts FLOPs, with static shapes throughout
(runs under jit).  Gradients flow through the gathers/scatter-adds and
the gate-weight multiply; the integer sort/offset plumbing carries no
cotangent.

Used by both the generation engine's prefill (``models/generate.py``)
and the training MoE layer's ``dispatch_mode="dropless"``
(``nn/moe.py``).
"""
from __future__ import annotations

import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp


def capacity_tokens(num_tokens: int, num_experts: int, k: int,
                    capacity_factor: float) -> int:
    """Tokens-per-expert capacity the GShard routing math requires:
    ``k * ceil(T/E * cf)``.  Single source of truth shared by the gating
    impls (nn/moe.py) and the analyzer's ``moe-capacity-overprovision``
    rule — a dispatch tensor sized beyond this moves zero-padded bytes
    through the EP all-to-alls."""
    return int(k) * math.ceil(num_tokens / num_experts
                              * float(capacity_factor))


def pick_block_size(n_assign: int, num_experts: int) -> int:
    """Group-GEMM block: large enough to keep the MXU busy, small enough
    that per-expert padding (< E blocks of waste) stays a minor fraction
    of the T*k real assignments."""
    for cand in (512, 256, 128, 64, 32, 16, 8):
        if n_assign >= num_experts * cand:
            return cand
    return 8


def blocked_group_gemm(xt: jax.Array, topi: jax.Array, topv: jax.Array,
                       w1: jax.Array, b1: jax.Array,
                       w2: jax.Array, b2: jax.Array,
                       act: Callable[[jax.Array], jax.Array],
                       block: Optional[int] = None) -> jax.Array:
    """Dropless top-k expert FFN.

    xt: [T, d] tokens; topi/topv: [T, k] expert ids / fp32 gate weights;
    w1: [E, d, f], b1: [E, 1, f], w2: [E, f, d], b2: [E, 1, d].
    Returns the combined output [T, d] in fp32.
    """
    T, d = xt.shape
    E = w1.shape[0]
    k = topi.shape[-1]
    n = T * k
    B = block or pick_block_size(n, E)
    e_flat = topi.reshape(-1)
    t_flat = jnp.repeat(jnp.arange(T, dtype=jnp.int32), k)
    w_flat = topv.reshape(-1).astype(jnp.float32)
    # stable sort by expert keeps token order inside each group
    order = jnp.argsort(e_flat, stable=True)
    e_sorted = e_flat[order]
    t_sorted = t_flat[order]
    w_sorted = w_flat[order]
    counts = jnp.bincount(e_flat, length=E)          # [E] tokens/expert
    padded = ((counts + B - 1) // B) * B
    src_off = jnp.cumsum(counts) - counts            # group starts, sorted
    dst_off = jnp.cumsum(padded) - padded            # block-aligned starts
    pos_in_e = jnp.arange(n, dtype=jnp.int32) - src_off[e_sorted]
    dst = (dst_off[e_sorted] + pos_in_e).astype(jnp.int32)
    n_pad = ((n + E * (B - 1)) // B + 1) * B         # static upper bound
    slot_tok = jnp.full((n_pad,), -1, jnp.int32).at[dst].set(t_sorted)
    slot_w = jnp.zeros((n_pad,), jnp.float32).at[dst].set(w_sorted)
    G = n_pad // B
    # each block lies inside one expert's padded region: its expert is
    # the first e whose region end exceeds the block start
    blk_start = jnp.arange(G, dtype=jnp.int32) * B
    blk_e = jnp.clip(jnp.searchsorted(jnp.cumsum(padded), blk_start,
                                      side="right"), 0, E - 1)
    live = slot_tok >= 0
    xg = jnp.where(live[:, None], xt[jnp.clip(slot_tok, 0)], 0.0)
    xg = xg.reshape(G, B, d)
    h = act(jnp.einsum("gbd,gdf->gbf", xg, w1[blk_e]) + b1[blk_e])
    y = jnp.einsum("gbf,gfd->gbd", h, w2[blk_e]) + b2[blk_e]
    y = y.reshape(n_pad, d).astype(jnp.float32) * slot_w[:, None]
    return jnp.zeros((T, d), jnp.float32).at[jnp.clip(slot_tok, 0)].add(
        jnp.where(live[:, None], y, 0.0))
