from .attention import sdpa, sdpa_reference
from .paged_attention import (paged_attention_decode,
                              paged_attention_reference)
from .ragged_paged_attention import (ragged_paged_attention,
                                     ragged_paged_attention_reference)
from .functional import *  # noqa: F401,F403
# NB: importing the .attention submodule binds `ops.attention` to the module;
# rebind the op function explicitly (it must win).
from .functional import attention  # noqa: F401
