"""Ragged paged attention: mixed prefill chunks + decode in ONE kernel.

The serving engine's v1 split (``ops/paged_attention.py`` decode kernel
+ a dense bucketed prefill) pays a compile-grid tax: every prompt-length
bucket and every decode-batch bucket is its own executable, and each
admitted request runs its own prefill call.  This op collapses the two
phases into one program over a **ragged batch** — the Ragged Paged
Attention recipe (PAPERS.md, arxiv 2604.15464):

- the query side is a flat token axis ``q [T, nh, hd]`` holding every
  scheduled token this step: prefill *chunks* (Sarathi-style slices of a
  long prompt) and decode tokens side by side;
- raggedness is described by four per-sequence int32 arrays that ride
  in as **scalar prefetch** on TPU:

  ===============  =======================================================
  ``q_lens   [S]``  query tokens this step (0 = padding row)
  ``cu_q   [S+1]``  cumulative query offsets: row i owns
                    ``q[cu_q[i] : cu_q[i] + q_lens[i]]``
  ``page_tables``   ``[S, maxp]`` physical KV page ids (padding slots
                    point at the reserved trash page)
  ``ctx_lens [S]``  total KV length *including* this step's tokens
  ===============  =======================================================

- a decode row is simply the degenerate ``q_lens[i] == 1`` case — no
  separate code path, no separate executable;
- causal masking is *within* each row's query span: query j of row i
  sits at absolute position ``ctx_lens[i] - q_lens[i] + j`` and attends
  every KV position at or before it.

Two implementations with the same contract:

- ``ragged_paged_attention_reference`` — per-row gather of the page
  table into a contiguous ``[maxp*ps, kvh, hd]`` view + masked dense
  attention over a static ``max_q``-wide query window (CPU oracle).
- ``ragged_paged_attention_pallas`` — Pallas TPU kernel, grid
  ``(kvh, S, maxp)`` with pages innermost.  The k/v BlockSpec index
  maps read the prefetched page table (one physical-page DMA per grid
  step), ``pl.when`` skips pages past ``ctx_lens`` and whole padding
  rows, and the online-softmax state is carried in VMEM scratch.  The
  query window is loaded with a dynamic ``pl.ds`` slice at ``cu_q[i]``
  and the output window is committed read-modify-write so ragged row
  boundaries never clobber a neighbour.  Runs in interpret mode off-TPU.

``max_q`` (the static query-window bound) is the scheduler's prefill
chunk size: every row owns at most ``max_q`` query tokens.  Inputs are
padded by ``max_q`` rows internally so the window slide never reads out
of bounds.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .paged_attention import (DEFAULT_MASK_VALUE, LANES, SUBLANES, _on_tpu)


def _check_ragged_shapes(q, k_pages, v_pages, q_lens, cu_q, page_tables,
                         ctx_lens, max_q):
    t, nh, hd = q.shape
    p_, ps, kvh, hd2 = k_pages.shape
    if v_pages.shape != k_pages.shape:
        raise ValueError(f"k_pages {k_pages.shape} != v_pages "
                         f"{v_pages.shape}")
    if hd != hd2:
        raise ValueError(f"head_dim mismatch: q {hd} vs pages {hd2}")
    if nh % kvh != 0:
        raise ValueError(f"num_heads {nh} not divisible by kv_heads {kvh}")
    s = q_lens.shape[0]
    if cu_q.shape != (s + 1,):
        raise ValueError(f"cu_q must be [S+1]={s + 1}, got {cu_q.shape}")
    if page_tables.ndim != 2 or page_tables.shape[0] != s:
        raise ValueError(f"page_tables must be [S, maxp], got "
                         f"{page_tables.shape}")
    if ctx_lens.shape != (s,):
        raise ValueError(f"ctx_lens must be [S], got {ctx_lens.shape}")
    if not 1 <= int(max_q):
        raise ValueError(f"max_q must be >= 1, got {max_q}")
    return t, nh, hd, ps, kvh, s


# ---------------------------------------------------------------------------
# reference path (CPU / oracle)
# ---------------------------------------------------------------------------

def ragged_paged_attention_reference(q: jax.Array, k_pages: jax.Array,
                                     v_pages: jax.Array, q_lens: jax.Array,
                                     cu_q: jax.Array,
                                     page_tables: jax.Array,
                                     ctx_lens: jax.Array, *, max_q: int,
                                     softmax_scale: Optional[float] = None
                                     ) -> jax.Array:
    """Dense oracle for the ragged contract: per row, gather its pages
    in position order and run masked fp32 attention over a static
    ``max_q`` query window at ``cu_q[i]``.  Returns ``[T, nh, hd]``;
    rows' padding windows never leak into neighbouring rows (masked
    read-modify-write, mirroring the kernel)."""
    t, nh, hd, ps, kvh, s = _check_ragged_shapes(
        q, k_pages, v_pages, q_lens, cu_q, page_tables, ctx_lens, max_q)
    maxp = page_tables.shape[1]
    g = nh // kvh
    scale = softmax_scale if softmax_scale is not None else hd ** -0.5
    kk = maxp * ps
    kv_pos = jnp.arange(kk)
    qp = jnp.pad(q, ((0, max_q), (0, 0), (0, 0)))
    out = jnp.zeros_like(qp)
    with jax.named_scope("ragged_paged_attention"):
        for i in range(s):
            start, qlen, ctx = cu_q[i], q_lens[i], ctx_lens[i]
            qi = lax.dynamic_slice(qp, (start, 0, 0), (max_q, nh, hd))
            qg = qi.reshape(max_q, kvh, g, hd).astype(jnp.float32)
            k = k_pages[page_tables[i]].reshape(kk, kvh, hd)
            v = v_pages[page_tables[i]].reshape(kk, kvh, hd)
            sc = jnp.einsum("qhgd,khd->qhgk", qg,
                            k.astype(jnp.float32)) * scale
            qpos = (ctx - qlen) + jnp.arange(max_q)       # absolute pos
            valid = kv_pos[None, :] <= qpos[:, None]      # causal in-row
            sc = jnp.where(valid[:, None, None, :], sc,
                           DEFAULT_MASK_VALUE)
            pr = jax.nn.softmax(sc, axis=-1)
            o = jnp.einsum("qhgk,khd->qhgd", pr,
                           v.astype(jnp.float32))
            o = o.reshape(max_q, nh, hd).astype(q.dtype)
            rowv = jnp.arange(max_q) < qlen
            cur = lax.dynamic_slice(out, (start, 0, 0), (max_q, nh, hd))
            out = lax.dynamic_update_slice(
                out, jnp.where(rowv[:, None, None], o, cur),
                (start, 0, 0))
    return out[:t]


# ---------------------------------------------------------------------------
# Pallas TPU kernel
# ---------------------------------------------------------------------------

def _ragged_kernel(ql_ref, cu_ref, pt_ref, cl_ref,    # scalar prefetch
                   q_ref, k_ref, v_ref,               # inputs
                   o_ref,                             # output
                   m_scr, l_scr, acc_scr,             # scratch
                   *, scale: float, ps: int, maxp: int, max_q: int,
                   gp: int):
    i = pl.program_id(1)
    p = pl.program_id(2)
    qlen = ql_ref[i]
    start = cu_ref[i]
    ctx = cl_ref[i]
    mqg = max_q * gp

    @pl.when(p == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, DEFAULT_MASK_VALUE)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    @pl.when(jnp.logical_and(qlen > 0, p * ps < ctx))
    def _page():
        q = q_ref[pl.ds(start, max_q), 0].astype(jnp.float32)
        q2 = q.reshape(mqg, q.shape[-1])               # [max_q*gp, hd]
        k = k_ref[0, :, 0, :].astype(jnp.float32)      # [ps, hd]
        v = v_ref[0, :, 0, :].astype(jnp.float32)
        s = lax.dot_general(q2, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        row_q = lax.broadcasted_iota(jnp.int32, (mqg, ps), 0) // gp
        cols = p * ps + lax.broadcasted_iota(jnp.int32, (mqg, ps), 1)
        qpos = (ctx - qlen) + row_q                    # absolute position
        s = jnp.where(cols <= qpos, s, DEFAULT_MASK_VALUE)
        m_prev = m_scr[:, 0]                           # [mqg]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
        alpha = jnp.exp(m_prev - m_cur)
        pexp = jnp.exp(s - m_cur[:, None])             # [mqg, ps]
        l_cur = l_scr[:, 0] * alpha + jnp.sum(pexp, axis=1)
        acc_scr[...] = acc_scr[...] * alpha[:, None] + lax.dot_general(
            pexp, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_cur[:, None], m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_cur[:, None], l_scr.shape)

    @pl.when(p == maxp - 1)
    def _finalize():
        l = l_scr[:, 0]
        l = jnp.where(l == 0.0, 1.0, l)                # empty rows -> 0
        o = (acc_scr[...] / l[:, None]).reshape(max_q, gp,
                                                acc_scr.shape[-1])
        # ragged row boundaries are not block-aligned: commit the window
        # read-modify-write so the padded tail of this row's window never
        # clobbers the next row's (already- or not-yet-written) tokens
        prev = o_ref[pl.ds(start, max_q), 0]
        rowv = lax.broadcasted_iota(jnp.int32, (max_q, 1, 1), 0) < qlen
        o_ref[pl.ds(start, max_q), 0] = jnp.where(
            rowv, o.astype(o_ref.dtype), prev)


def ragged_paged_attention_pallas(q: jax.Array, k_pages: jax.Array,
                                  v_pages: jax.Array, q_lens: jax.Array,
                                  cu_q: jax.Array, page_tables: jax.Array,
                                  ctx_lens: jax.Array, *, max_q: int,
                                  softmax_scale: Optional[float] = None,
                                  interpret: Optional[bool] = None
                                  ) -> jax.Array:
    """Pallas ragged paged attention (same contract as the reference).

    Grid is ``(kvh, S, maxp)`` with pages innermost (sequential on TPU);
    the query/output windows live in a full-token-axis VMEM block while
    k/v index maps read the prefetched page table so each grid step DMAs
    exactly one physical page — pages past ``ctx_lens[i]`` and whole
    padding rows are skipped with ``pl.when``.
    """
    t, nh, hd, ps, kvh, s = _check_ragged_shapes(
        q, k_pages, v_pages, q_lens, cu_q, page_tables, ctx_lens, max_q)
    maxp = page_tables.shape[1]
    scale = softmax_scale if softmax_scale is not None else hd ** -0.5
    if interpret is None:
        interpret = not _on_tpu()
    g = nh // kvh
    gp = max(SUBLANES, ((g + SUBLANES - 1) // SUBLANES) * SUBLANES)
    t_pad = t + max_q                       # window slide never OOB
    qg = q.reshape(t, kvh, g, hd)
    qg = jnp.pad(qg, ((0, max_q), (0, 0), (0, gp - g), (0, 0)))
    kernel = functools.partial(_ragged_kernel, scale=float(scale), ps=ps,
                               maxp=maxp, max_q=int(max_q), gp=gp)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(kvh, s, maxp),
        in_specs=[
            pl.BlockSpec((t_pad, 1, gp, hd),
                         lambda h, i, p, ql, cu, pt, cl: (0, h, 0, 0)),
            pl.BlockSpec((1, ps, 1, hd),
                         lambda h, i, p, ql, cu, pt, cl: (pt[i, p], 0, h,
                                                          0)),
            pl.BlockSpec((1, ps, 1, hd),
                         lambda h, i, p, ql, cu, pt, cl: (pt[i, p], 0, h,
                                                          0)),
        ],
        out_specs=pl.BlockSpec(
            (t_pad, 1, gp, hd),
            lambda h, i, p, ql, cu, pt, cl: (0, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((max_q * gp, LANES), jnp.float32),
            pltpu.VMEM((max_q * gp, LANES), jnp.float32),
            pltpu.VMEM((max_q * gp, hd), jnp.float32),
        ],
    )
    with jax.named_scope("ragged_paged_attention"):
        out = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((t_pad, kvh, gp, hd), q.dtype),
            interpret=interpret,
        )(q_lens.astype(jnp.int32), cu_q.astype(jnp.int32),
          page_tables.astype(jnp.int32), ctx_lens.astype(jnp.int32),
          qg, k_pages, v_pages)
    return out[:t, :, :g, :].reshape(t, nh, hd)


def ragged_paged_attention(q: jax.Array, k_pages: jax.Array,
                           v_pages: jax.Array, q_lens: jax.Array,
                           cu_q: jax.Array, page_tables: jax.Array,
                           ctx_lens: jax.Array, *, max_q: int,
                           softmax_scale: Optional[float] = None,
                           use_kernel: Optional[bool] = None) -> jax.Array:
    """Dispatching entry point: Pallas kernel on TPU, gather-dense
    reference elsewhere (``ops.sdpa`` / ``paged_attention_decode``
    dispatch discipline)."""
    if use_kernel is None:
        use_kernel = _on_tpu()
    if use_kernel:
        try:
            return ragged_paged_attention_pallas(
                q, k_pages, v_pages, q_lens, cu_q, page_tables, ctx_lens,
                max_q=max_q, softmax_scale=softmax_scale)
        except Exception:
            pass
    return ragged_paged_attention_reference(
        q, k_pages, v_pages, q_lens, cu_q, page_tables, ctx_lens,
        max_q=max_q, softmax_scale=softmax_scale)


# ---------------------------------------------------------------------------
# MLA latent path (FlashMLA-ETAP, arxiv 2506.01969; DESIGN.md §21)
# ---------------------------------------------------------------------------
#
# The latent variants run attention directly against ONE compressed KV
# stream per layer: ``c_pages [P, ps, 1, d_c]`` (or int8/packed-nf4
# codes plus a per-token absmax sidecar) and an optional decoupled-rope
# key stream ``r_pages [P, ps, 1, d_r]``.  The query side arrives
# ALREADY weight-absorbed — ``q [*, nh, d_c + d_r]`` is
# ``concat(q_nope @ k_up, rope(q_rope))`` per head — so scores are MQA
# dot products in latent space and the attention output STAYS latent
# (``[*, nh, d_c]``); the caller applies the ``v_up`` fold per query
# token.  No cached token is ever decompressed.


def _dequant_latent(codes, scales, quant, latent_dim):
    """fp32 view of a gathered latent window: identity cast when
    ``quant`` is None, else per-token absmax dequant (codes ``[..., w]``
    + scales ``[..., 1]`` -> ``[..., latent_dim]``)."""
    if quant is None:
        return codes.astype(jnp.float32)
    from .quantization import dequantize_rows
    return dequantize_rows(codes, scales, quant, latent_dim)


def _check_latent_shapes(q, c_pages, r_pages, quant, latent_dim):
    nh, dq = q.shape[-2], q.shape[-1]
    p_, ps, one, wc = c_pages.shape
    if one != 1:
        raise ValueError(f"latent c_pages carry ONE shared stream, got "
                         f"{c_pages.shape}")
    d_c = int(latent_dim) if latent_dim is not None else wc
    if quant == "nf4":
        if wc * 2 != d_c:
            raise ValueError(f"nf4 codes width {wc} != latent_dim/2 "
                             f"({d_c})")
    elif wc != d_c:
        raise ValueError(f"c_pages width {wc} != latent_dim {d_c}")
    d_r = 0
    if r_pages is not None and r_pages.shape[-1] > 0:
        if r_pages.shape[:2] != (p_, ps) or r_pages.shape[2] != 1:
            raise ValueError(f"r_pages {r_pages.shape} incompatible with "
                             f"c_pages {c_pages.shape}")
        d_r = r_pages.shape[-1]
    if dq != d_c + d_r:
        raise ValueError(f"absorbed q width {dq} != d_c + d_r "
                         f"({d_c}+{d_r})")
    return nh, ps, d_c, d_r


def latent_paged_attention_reference(q: jax.Array, c_pages: jax.Array,
                                     r_pages: Optional[jax.Array],
                                     page_tables: jax.Array,
                                     seq_lens: jax.Array, *,
                                     softmax_scale: float,
                                     scale_pages: Optional[jax.Array] = None,
                                     quant: Optional[str] = None,
                                     latent_dim: Optional[int] = None
                                     ) -> jax.Array:
    """Decode-slot oracle over latent pages: absorbed ``q [B, nh,
    d_c+d_r]`` (one token per request), ``seq_lens`` counting the token
    just written -> latent output ``[B, nh, d_c]``.  Mirrors
    ``paged_attention_reference``'s gather + ``-inf`` masking so the
    serving step stays bitwise vs the solo MLA oracle."""
    nh, ps, d_c, d_r = _check_latent_shapes(q, c_pages, r_pages, quant,
                                            latent_dim)
    b = q.shape[0]
    maxp = page_tables.shape[1]
    kk = maxp * ps
    with jax.named_scope("latent_paged_attention"):
        c = c_pages[page_tables].reshape(b, kk, c_pages.shape[-1])
        sc = None if scale_pages is None else \
            scale_pages[page_tables].reshape(b, kk, 1)
        cd = _dequant_latent(c, sc, quant, d_c)        # [B, kk, d_c]
        if d_r:
            r = r_pages[page_tables].reshape(b, kk, d_r)
            k = jnp.concatenate([cd, r.astype(jnp.float32)], -1)
        else:
            k = cd
        s = jnp.einsum("bhc,bkc->bhk", q.astype(jnp.float32),
                       k) * softmax_scale
        valid = (jnp.arange(kk)[None] < seq_lens[:, None])[:, None, :]
        s = jnp.where(valid, s, -jnp.inf)
        pr = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhk,bkc->bhc", pr, cd)      # latent, fp32


def latent_ragged_paged_attention_reference(
        q: jax.Array, c_pages: jax.Array, r_pages: Optional[jax.Array],
        q_lens: jax.Array, cu_q: jax.Array, page_tables: jax.Array,
        ctx_lens: jax.Array, *, max_q: int, softmax_scale: float,
        scale_pages: Optional[jax.Array] = None,
        quant: Optional[str] = None,
        latent_dim: Optional[int] = None) -> jax.Array:
    """Latent twin of :func:`ragged_paged_attention_reference` (same
    ragged contract, ``DEFAULT_MASK_VALUE`` masking — the kernel
    oracle): absorbed ``q [T, nh, d_c+d_r]`` -> latent ``[T, nh,
    d_c]``."""
    nh, ps, d_c, d_r = _check_latent_shapes(q, c_pages, r_pages, quant,
                                            latent_dim)
    t = q.shape[0]
    s_rows = q_lens.shape[0]
    maxp = page_tables.shape[1]
    kk = maxp * ps
    kv_pos = jnp.arange(kk)
    qp = jnp.pad(q, ((0, max_q), (0, 0), (0, 0)))
    out = jnp.zeros((t + max_q, nh, d_c), jnp.float32)
    with jax.named_scope("latent_ragged_paged_attention"):
        for i in range(s_rows):
            start, qlen, ctx = cu_q[i], q_lens[i], ctx_lens[i]
            qi = lax.dynamic_slice(
                qp, (start, 0, 0),
                (max_q, nh, d_c + d_r)).astype(jnp.float32)
            c = c_pages[page_tables[i]].reshape(kk, c_pages.shape[-1])
            sc = None if scale_pages is None else \
                scale_pages[page_tables[i]].reshape(kk, 1)
            cd = _dequant_latent(c, sc, quant, d_c)    # [kk, d_c]
            if d_r:
                r = r_pages[page_tables[i]].reshape(kk, d_r)
                k = jnp.concatenate([cd, r.astype(jnp.float32)], -1)
            else:
                k = cd
            s = jnp.einsum("qhc,kc->qhk", qi, k) * softmax_scale
            qpos = (ctx - qlen) + jnp.arange(max_q)
            valid = kv_pos[None, :] <= qpos[:, None]
            s = jnp.where(valid[:, None, :], s, DEFAULT_MASK_VALUE)
            pr = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("qhk,kc->qhc", pr, cd)
            rowv = jnp.arange(max_q) < qlen
            cur = lax.dynamic_slice(out, (start, 0, 0), (max_q, nh, d_c))
            out = lax.dynamic_update_slice(
                out, jnp.where(rowv[:, None, None], o, cur),
                (start, 0, 0))
    return out[:t]


def _make_latent_kernel(scale: float, ps: int, maxp: int, max_q: int,
                        gp: int, d_c: int, quant: Optional[str],
                        has_rope: bool, has_scales: bool,
                        has_code: bool = False):
    """Latent twin of :func:`_ragged_kernel`: grid ``(S, maxp)`` (one
    shared KV stream, so no kv-head grid dim), q/out blocks span the
    padded token axis, c/r/scale blocks are one physical page each via
    the prefetched page table; online softmax in VMEM scratch."""

    def kernel(ql_ref, cu_ref, pt_ref, cl_ref, q_ref, c_ref, *rest):
        n = 0
        r_ref = rest[n] if has_rope else None
        n += int(has_rope)
        s_ref = rest[n] if has_scales else None
        n += int(has_scales)
        code_ref = rest[n] if has_code else None
        n += int(has_code)
        o_ref, m_scr, l_scr, acc_scr = rest[n:n + 4]
        i = pl.program_id(0)
        p = pl.program_id(1)
        qlen = ql_ref[i]
        start = cu_ref[i]
        ctx = cl_ref[i]
        mqg = max_q * gp

        @pl.when(p == 0)
        def _init():
            m_scr[...] = jnp.full_like(m_scr, DEFAULT_MASK_VALUE)
            l_scr[...] = jnp.zeros_like(l_scr)
            acc_scr[...] = jnp.zeros_like(acc_scr)

        @pl.when(jnp.logical_and(qlen > 0, p * ps < ctx))
        def _page():
            q = q_ref[pl.ds(start, max_q)].astype(jnp.float32)
            q2 = q.reshape(mqg, q.shape[-1])           # [mqg, d_c+d_r]
            raw = c_ref[0, :, 0, :]                    # [ps, w]
            if quant is None:
                c = raw.astype(jnp.float32)
            else:
                sc = s_ref[0, :, 0, :].astype(jnp.float32)     # [ps, 1]
                sc = jnp.where(sc > 0, sc, 1.0)
                if quant == "int8":
                    c = raw.astype(jnp.float32) / 127.0 * sc
                else:                                  # packed 4-bit
                    hi = (raw >> 4).astype(jnp.int32)
                    lo = (raw & 0xF).astype(jnp.int32)
                    idx = jnp.stack([hi, lo], axis=-1).reshape(ps, d_c)
                    c = code_ref[...][idx] * sc
            if has_rope:
                k = jnp.concatenate(
                    [c, r_ref[0, :, 0, :].astype(jnp.float32)], -1)
            else:
                k = c
            s = lax.dot_general(q2, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
            row_q = lax.broadcasted_iota(jnp.int32, (mqg, ps), 0) // gp
            cols = p * ps + lax.broadcasted_iota(jnp.int32, (mqg, ps), 1)
            qpos = (ctx - qlen) + row_q
            s = jnp.where(cols <= qpos, s, DEFAULT_MASK_VALUE)
            m_prev = m_scr[:, 0]
            m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
            alpha = jnp.exp(m_prev - m_cur)
            pexp = jnp.exp(s - m_cur[:, None])
            l_cur = l_scr[:, 0] * alpha + jnp.sum(pexp, axis=1)
            acc_scr[...] = acc_scr[...] * alpha[:, None] + lax.dot_general(
                pexp, c, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_scr[...] = jnp.broadcast_to(m_cur[:, None], m_scr.shape)
            l_scr[...] = jnp.broadcast_to(l_cur[:, None], l_scr.shape)

        @pl.when(p == maxp - 1)
        def _finalize():
            l = l_scr[:, 0]
            l = jnp.where(l == 0.0, 1.0, l)
            o = (acc_scr[...] / l[:, None]).reshape(max_q, gp, d_c)
            prev = o_ref[pl.ds(start, max_q)]
            rowv = lax.broadcasted_iota(jnp.int32, (max_q, 1, 1), 0) < qlen
            o_ref[pl.ds(start, max_q)] = jnp.where(
                rowv, o.astype(o_ref.dtype), prev)

    return kernel


def latent_ragged_paged_attention_pallas(
        q: jax.Array, c_pages: jax.Array, r_pages: Optional[jax.Array],
        q_lens: jax.Array, cu_q: jax.Array, page_tables: jax.Array,
        ctx_lens: jax.Array, *, max_q: int, softmax_scale: float,
        scale_pages: Optional[jax.Array] = None,
        quant: Optional[str] = None, latent_dim: Optional[int] = None,
        interpret: Optional[bool] = None) -> jax.Array:
    """Pallas latent ragged paged attention (same contract as
    :func:`latent_ragged_paged_attention_reference`)."""
    nh, ps, d_c, d_r = _check_latent_shapes(q, c_pages, r_pages, quant,
                                            latent_dim)
    t = q.shape[0]
    s_rows = q_lens.shape[0]
    maxp = page_tables.shape[1]
    if interpret is None:
        interpret = not _on_tpu()
    gp = max(SUBLANES, ((nh + SUBLANES - 1) // SUBLANES) * SUBLANES)
    t_pad = t + max_q
    qg = jnp.pad(q, ((0, max_q), (0, gp - nh), (0, 0)))
    has_rope, has_scales = d_r > 0, scale_pages is not None
    if quant is not None and not has_scales:
        raise ValueError("quantized latent pages need scale_pages")
    has_code = quant in ("nf4", "fp4")
    kernel = _make_latent_kernel(float(softmax_scale), ps, maxp,
                                 int(max_q), gp, d_c, quant, has_rope,
                                 has_scales, has_code)
    in_specs = [
        pl.BlockSpec((t_pad, gp, d_c + d_r),
                     lambda i, p, ql, cu, pt, cl: (0, 0, 0)),
        pl.BlockSpec((1, ps, 1, c_pages.shape[-1]),
                     lambda i, p, ql, cu, pt, cl: (pt[i, p], 0, 0, 0)),
    ]
    operands = [qg, c_pages]
    if has_rope:
        in_specs.append(pl.BlockSpec(
            (1, ps, 1, d_r),
            lambda i, p, ql, cu, pt, cl: (pt[i, p], 0, 0, 0)))
        operands.append(r_pages)
    if has_scales:
        in_specs.append(pl.BlockSpec(
            (1, ps, 1, 1),
            lambda i, p, ql, cu, pt, cl: (pt[i, p], 0, 0, 0)))
        operands.append(scale_pages)
    if has_code:
        from .quantization import _CODES
        in_specs.append(pl.BlockSpec(
            (16,), lambda i, p, ql, cu, pt, cl: (0,)))
        operands.append(jnp.asarray(_CODES[quant]))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(s_rows, maxp),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((t_pad, gp, d_c),
                               lambda i, p, ql, cu, pt, cl: (0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((max_q * gp, LANES), jnp.float32),
            pltpu.VMEM((max_q * gp, LANES), jnp.float32),
            pltpu.VMEM((max_q * gp, d_c), jnp.float32),
        ],
    )
    with jax.named_scope("latent_ragged_paged_attention"):
        out = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((t_pad, gp, d_c), jnp.float32),
            interpret=interpret,
        )(q_lens.astype(jnp.int32), cu_q.astype(jnp.int32),
          page_tables.astype(jnp.int32), ctx_lens.astype(jnp.int32),
          *operands)
    return out[:t, :nh, :]


def latent_ragged_paged_attention(
        q: jax.Array, c_pages: jax.Array, r_pages: Optional[jax.Array],
        q_lens: jax.Array, cu_q: jax.Array, page_tables: jax.Array,
        ctx_lens: jax.Array, *, max_q: int, softmax_scale: float,
        scale_pages: Optional[jax.Array] = None,
        quant: Optional[str] = None, latent_dim: Optional[int] = None,
        use_kernel: Optional[bool] = None) -> jax.Array:
    """Dispatching entry point for the latent path (kernel on TPU,
    gather-dense oracle elsewhere)."""
    if use_kernel is None:
        use_kernel = _on_tpu()
    if use_kernel:
        try:
            return latent_ragged_paged_attention_pallas(
                q, c_pages, r_pages, q_lens, cu_q, page_tables, ctx_lens,
                max_q=max_q, softmax_scale=softmax_scale,
                scale_pages=scale_pages, quant=quant,
                latent_dim=latent_dim)
        except Exception:
            pass
    return latent_ragged_paged_attention_reference(
        q, c_pages, r_pages, q_lens, cu_q, page_tables, ctx_lens,
        max_q=max_q, softmax_scale=softmax_scale, scale_pages=scale_pages,
        quant=quant, latent_dim=latent_dim)


# ---------------------------------------------------------------------------
# verify-row sampling head (speculative decoding, DESIGN.md §20)
# ---------------------------------------------------------------------------
#
# A speculative **verify row** is structurally a prefill chunk: the row
# feeds ``[last committed token, d_1, ..., d_K]`` (K greedy draft
# proposals) through the unified step, so the kernel above already
# produces per-position attention for it.  What a verify row needs ON
# TOP is a per-position accept/reject decision next to the engine's
# per-row sampler — this head provides it, entirely on device, so the
# host still fetches only ``[rows]``-shaped int32s per step
# (``host_logit_fetches`` stays 0).
#
# Acceptance rule per in-row verify position j (absolute sequence index
# of the token it emits is ``ctx - spec_len + j``):
#
# * temperature 0: accept ``d_{j+1}`` iff it equals ``argmax(logits_j)``
#   — the very argmax a non-speculative decode step would commit, so the
#   longest-prefix accepted tokens plus the first-mismatch bonus token
#   reproduce the non-speculative greedy sequence EXACTLY (bit-for-bit,
#   test-pinned);
# * temperature > 0: leftover-distribution rejection sampling for a
#   deterministic (greedy) draft, in COUPLED form.  The draft's
#   proposal distribution is a point mass ``q = δ_d``, so the generic
#   speculative-sampling accept probability ``min(1, p/q)`` reduces to
#   ``p(d)`` and the leftover distribution ``norm(max(p - q, 0))``
#   reduces to ``p`` with ``d`` removed and renormalized.  Instead of
#   burning two independent draws (an accept coin and a leftover
#   sample), the head draws ONE categorical sample ``X ~ p`` from the
#   truncated distribution with the position's own key and accepts iff
#   ``X == d``: the accept probability is exactly ``p(d)``, and the law
#   of ``X`` conditioned on rejection (``X != d``) is exactly the
#   leftover distribution — the same accept/leftover semantics, one
#   draw.  The payoff of the coupling is replay stability: ``X`` is the
#   IDENTICAL ``(seed, index)``-keyed draw the per-row sampler makes,
#   so the emitted token at a given sequence index is the same whether
#   that index was covered by a verify burst, a plain decode step, or a
#   replay under different batching/chunking/k — sampled-mode spec
#   serving reproduces non-speculative sampled serving bit-for-bit,
#   the same way temperature 0 does.  ``p`` here is the same
#   temperature/top-k/top-p-truncated distribution the per-row sampler
#   draws from.


def _sampled_draw(logits, temp, top_p, top_k, seed, ctx):
    """The sort-based keyed categorical draw for ONE sampled row: fp32
    logits [V], temperature-scaled, top-k/top-p truncated, keyed by
    ``(seed, ctx)``."""
    v = logits.shape[0]
    key = jax.random.fold_in(jax.random.PRNGKey(seed), ctx)
    lg = logits / jnp.where(temp > 0, temp, 1.0)
    order = jnp.argsort(-lg)
    lg_s = lg[order]                                 # descending
    probs = jax.nn.softmax(lg_s)
    csum = jnp.cumsum(probs)
    idxs = jnp.arange(v)
    # nucleus: drop tokens once the mass BEFORE them reaches top_p (the
    # smallest prefix whose mass >= top_p always survives; the argmax
    # token is never cut)
    cut = (csum - probs > top_p) & (top_p > 0.0) & (top_p < 1.0)
    cut = cut | ((idxs >= top_k) & (top_k > 0))
    return order[jax.random.categorical(
        key, jnp.where(cut, -jnp.inf, lg_s))].astype(jnp.int32)


def sample_row(logits, temp, top_p, top_k, seed, ctx):
    """On-device next-token choice for one row, fp32 logits [V].

    Greedy rows take the jit'd argmax (the very ``jnp.argmax`` solo
    ``generate()`` runs — bit-for-bit at temperature 0).  Sampled rows
    draw from temperature-scaled logits with optional top-k truncation
    and top-p (nucleus) truncation, keyed by ``(seed, ctx)`` — ``ctx``
    equals the sampled token's index in the sequence, so replays are
    deterministic regardless of batching/chunking/preemption.  (Moved
    here from ``serving/decode.py`` so the speculative verify head and
    the per-row sampler are one implementation — the coupling above is
    only sound if they draw identically.)"""
    greedy = jnp.argmax(logits).astype(jnp.int32)
    samp = _sampled_draw(logits, temp, top_p, top_k, seed, ctx)
    return jnp.where(temp == 0.0, greedy, samp)


def sample_rows(logits, temps, top_ps, top_ks, seeds, ctxs):
    """Batched :func:`sample_row` over ``[N, V]`` logits with one
    payoff a per-row vmap cannot have: the ENTIRE sort-based sampled
    path hides behind a single ``lax.cond(any(temps > 0))``.  XLA CPU
    sorts are slow enough that N unconditional 50k-vocab argsorts
    dominate a serving step, and the verify head multiplies N by
    ``spec_k`` — on all-greedy traffic (the common serving case and
    the temp-0 bitwise gate) this computes N argmaxes and nothing
    else.  Per-row values are IDENTICAL to :func:`sample_row` either
    way: a batched ``lax.cond`` under vmap would degrade to a
    both-branches select, which is why the predicate is batch-global
    rather than per-row."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def sampled(_):
        return jax.vmap(_sampled_draw)(logits, temps, top_ps, top_ks,
                                       seeds, ctxs)

    samp = lax.cond(jnp.any(temps > 0.0), sampled,
                    lambda _: greedy, None)
    return jnp.where(temps == 0.0, greedy, samp)


def speculative_verify_head(vlogits, draft_next, spec_lens, temps,
                            top_ps, top_ks, seeds, ctx_lens):
    """Batched accept/reject head over verify rows.

    Args (R = verify rows, K = static max draft length):
      vlogits    [R, K, V] fp32 — logits at the row's first K query
                 positions (position j predicts the token the draft
                 proposed at j+1)
      draft_next [R, K] i32 — the draft token fed at in-row position
                 j+1 (i.e. the proposal position j's logits verify)
      spec_lens  [R] i32 — staged draft count per row (0 = not a verify
                 row: accepted comes back 0 and the caller's per-row
                 sampler result stands)
      temps/top_ps/top_ks/seeds [R] — the row's sampling params
      ctx_lens   [R] i32 — total context including this step's tokens

    Returns ``(accepted [R] i32, alt [R, K] i32)``: ``accepted`` is the
    longest-accepted-prefix length (≤ spec_len) and ``alt[r, a]`` is the
    bonus token to emit when ``accepted < spec_len`` (first rejection);
    on full acceptance the caller's last-position sample IS the bonus.
    Each position's choice comes from the ONE row sampler keyed by its
    absolute sequence index — accept iff the draft matches it — so the
    emitted tokens are bitwise what non-speculative serving emits.
    """
    r, k, v = vlogits.shape
    # absolute sequence index of the token emitted at verify position j
    idx = (ctx_lens[:, None] - spec_lens[:, None]
           + jnp.arange(k)[None, :])                       # [R, K]
    rep = lambda a: jnp.repeat(a, k)                       # noqa: E731
    choice = sample_rows(vlogits.reshape(r * k, v), rep(temps),
                         rep(top_ps), rep(top_ks), rep(seeds),
                         idx.reshape(-1)).reshape(r, k)
    accept = choice == draft_next
    live = jnp.arange(k)[None, :] < spec_lens[:, None]     # [R, K]
    accepted = jnp.sum(jnp.cumprod((accept & live).astype(jnp.int32),
                                   axis=1), axis=1)
    return accepted.astype(jnp.int32), choice.astype(jnp.int32)
