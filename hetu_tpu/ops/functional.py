"""Op library — the graph-level operator surface.

TPU-native re-expression of the reference's op library
(``hetu/graph/ops/*`` — 188 files of ``XxxOpImpl`` + ``MakeXxxOp``
factories, backed by 172 CUDA kernel files in ``hetu/impl/kernel/``).
Here every op is a thin symbolic wrapper over jnp/lax: XLA fuses
elementwise chains into matmuls (replacing hand-written fused CUDA
kernels), and the handful of genuinely custom kernels (flash attention,
ring attention) live in ``hetu_tpu/ops/pallas``.

Ops accept graph ``Tensor`` handles or raw arrays; results are Tensors on
the current graph (eager graph executes immediately).
"""
from __future__ import annotations

import math
from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from ..core.dtype import canonicalize_dtype
from ..graph import amp
from ..graph.graph import Graph, get_default_graph
from ..graph.tensor import Tensor

TensorLike = Union[Tensor, jnp.ndarray, float, int]


def _graph_of(*xs) -> Graph:
    for x in xs:
        if isinstance(x, Tensor) and x.graph is not None:
            return x.graph
    return get_default_graph()


def _op(op_type: str, impl, inputs: Sequence[Any], attrs=None, name="",
        num_outputs: int = 1):
    if amp._autocast_stack:
        impl = amp.wrap_impl(op_type, impl)
    g = _graph_of(*inputs)
    return g.make_op(op_type, impl, inputs, attrs or {}, name,
                     num_outputs=num_outputs)


# ---------------------------------------------------------------------------
# arithmetic / unary / binary  (ops/Arithmetics.cc, ops/Unary*.cc)
# ---------------------------------------------------------------------------

def add(a, b):       return _op("add", jnp.add, [a, b])
def sub(a, b):       return _op("sub", jnp.subtract, [a, b])
def mul(a, b):       return _op("mul", jnp.multiply, [a, b])
def div(a, b):       return _op("div", jnp.divide, [a, b])
def neg(a):          return _op("neg", jnp.negative, [a])
def reciprocal(a):   return _op("reciprocal", jnp.reciprocal, [a])
def abs(a):          return _op("abs", jnp.abs, [a])  # noqa: A001
def exp(a):          return _op("exp", jnp.exp, [a])
def log(a):          return _op("log", jnp.log, [a])
def sqrt(a):         return _op("sqrt", jnp.sqrt, [a])
def rsqrt(a):        return _op("rsqrt", lax.rsqrt, [a])
def ceil(a):         return _op("ceil", jnp.ceil, [a])
def floor(a):        return _op("floor", jnp.floor, [a])
def round(a):        return _op("round", jnp.round, [a])  # noqa: A001
def sin(a):          return _op("sin", jnp.sin, [a])
def cos(a):          return _op("cos", jnp.cos, [a])
def tanh(a):         return _op("tanh", jnp.tanh, [a])
def sigmoid(a):      return _op("sigmoid", jax.nn.sigmoid, [a])
def maximum(a, b):   return _op("maximum", jnp.maximum, [a, b])
def minimum(a, b):   return _op("minimum", jnp.minimum, [a, b])


def pow(a, exponent):  # noqa: A001
    return _op("pow", lambda x, e=None: jnp.power(x, e), [a],
               {"e": exponent})


def clamp(a, min=None, max=None):  # noqa: A002
    return _op("clamp", lambda x, lo=None, hi=None: jnp.clip(x, lo, hi),
               [a], {"lo": min, "hi": max})


def where(cond, a, b):
    return _op("where", jnp.where, [cond, a, b])


def cast(a, dtype):
    jdt = canonicalize_dtype(dtype).to_jnp()
    return _op("cast", lambda x, dt=None: x.astype(dt), [a], {"dt": jdt})


# ---------------------------------------------------------------------------
# activations (ops/Relu.cc, Gelu.cc, SwiGLU kernel, ...)
# ---------------------------------------------------------------------------

def relu(a):         return _op("relu", jax.nn.relu, [a])
def leaky_relu(a, alpha=0.01):
    return _op("leaky_relu",
               lambda x, alpha=0.01: jax.nn.leaky_relu(x, alpha),
               [a], {"alpha": alpha})
def gelu(a, approximate=True):
    return _op("gelu",
               lambda x, approximate=True: jax.nn.gelu(x, approximate=approximate),
               [a], {"approximate": approximate})
def silu(a):         return _op("silu", jax.nn.silu, [a])
swish = silu
def elu(a):          return _op("elu", jax.nn.elu, [a])
def softplus(a):     return _op("softplus", jax.nn.softplus, [a])


def swiglu(a):
    """SwiGLU fused activation (reference ``impl/kernel/SwiGLU.cu``):
    input is [..., 2H]; out = silu(x1) * x2.  XLA fuses this chain."""
    def _impl(x):
        x1, x2 = jnp.split(x, 2, axis=-1)
        return jax.nn.silu(x1) * x2
    return _op("swiglu", _impl, [a])


# ---------------------------------------------------------------------------
# matmul family (ops/MatMul.cc, Linear.cc, BatchMatMul.cc) — MXU ops
# ---------------------------------------------------------------------------

def matmul(a, b, trans_a=False, trans_b=False):
    def _impl(x, y, trans_a=False, trans_b=False):
        if trans_a:
            x = jnp.swapaxes(x, -1, -2)
        if trans_b:
            y = jnp.swapaxes(y, -1, -2)
        return jnp.matmul(x, y)
    return _op("matmul", _impl, [a, b],
               {"trans_a": trans_a, "trans_b": trans_b})


batch_matmul = matmul


def linear(x, w, bias=None, trans_b=True):
    """y = x @ w^T + b (reference ops/Linear.cc convention)."""
    if bias is None:
        return matmul(x, w, trans_b=trans_b)
    def _impl(x, w, b, trans_b=True):
        if trans_b:
            w = jnp.swapaxes(w, -1, -2)
        return jnp.matmul(x, w) + b
    return _op("linear", _impl, [x, w, bias], {"trans_b": trans_b})


def einsum(equation: str, *operands):
    return _op("einsum",
               lambda *xs, eq=None: jnp.einsum(eq, *xs),
               list(operands), {"eq": equation})


# ---------------------------------------------------------------------------
# reductions (ops/Reduce*.cc)
# ---------------------------------------------------------------------------

def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(axis)
    return (axis,)


def reduce_sum(a, axis=None, keepdims=False):
    return _op("reduce_sum",
               lambda x, axis=None, keepdims=False: jnp.sum(x, axis=axis, keepdims=keepdims),
               [a], {"axis": _norm_axis(axis), "keepdims": keepdims})


def reduce_mean(a, axis=None, keepdims=False):
    return _op("reduce_mean",
               lambda x, axis=None, keepdims=False: jnp.mean(x, axis=axis, keepdims=keepdims),
               [a], {"axis": _norm_axis(axis), "keepdims": keepdims})


def reduce_max(a, axis=None, keepdims=False):
    return _op("reduce_max",
               lambda x, axis=None, keepdims=False: jnp.max(x, axis=axis, keepdims=keepdims),
               [a], {"axis": _norm_axis(axis), "keepdims": keepdims})


def reduce_min(a, axis=None, keepdims=False):
    return _op("reduce_min",
               lambda x, axis=None, keepdims=False: jnp.min(x, axis=axis, keepdims=keepdims),
               [a], {"axis": _norm_axis(axis), "keepdims": keepdims})


def argmax(a, axis=-1):
    return _op("argmax", lambda x, axis=-1: jnp.argmax(x, axis=axis),
               [a], {"axis": axis})


def cumsum(a, axis=-1):
    return _op("cumsum", lambda x, axis=-1: jnp.cumsum(x, axis=axis),
               [a], {"axis": axis})


def topk(a, k, axis=-1):
    def _impl(x, k=1, axis=-1):
        if axis in (-1, x.ndim - 1):
            return lax.top_k(x, k)
        xm = jnp.moveaxis(x, axis, -1)
        vals, idx = lax.top_k(xm, k)
        return jnp.moveaxis(vals, -1, axis), jnp.moveaxis(idx, -1, axis)
    return _op("topk", _impl, [a], {"k": k, "axis": axis}, num_outputs=2)


# ---------------------------------------------------------------------------
# shape/view ops (ops/Views.h, Reshape/Transpose/Slice/Split/Concat)
# ---------------------------------------------------------------------------

def reshape(a, shape):
    return _op("reshape", lambda x, shape=None: jnp.reshape(x, shape),
               [a], {"shape": tuple(shape)})


def transpose(a, perm=None):
    return _op("transpose", lambda x, perm=None: jnp.transpose(x, perm),
               [a], {"perm": tuple(perm) if perm is not None else None})


def getitem(a, idx):
    return _op("getitem", lambda x, idx=None: x[idx], [a], {"idx": idx})


def slice(a, begin, size):  # noqa: A001
    """Static slice (reference ops/Slice.cc)."""
    return _op("slice",
               lambda x, begin=None, size=None: lax.slice(
                   x, begin, [b + s for b, s in zip(begin, size)]),
               [a], {"begin": tuple(begin), "size": tuple(size)})


def as_strided(a, shape, strides, storage_offset=0):
    """Strided view over ``a``'s flattened storage (reference
    ``ops/Views.h`` AsStrided / ``impl/kernel`` AsStrided).  ``strides``
    are element strides into the flattened input, as in torch.  XLA has
    no aliasing views, so this materializes a gather — overlapping
    windows are supported (the reference's main AsStrided use case)."""
    ash = a.concrete_shape() if hasattr(a, "concrete_shape") else a.shape
    size = 1
    for d in ash:
        size *= int(d)
    lo = int(storage_offset) + sum(
        (d - 1) * st for d, st in zip(shape, strides) if st < 0)
    hi = int(storage_offset) + sum(
        (d - 1) * st for d, st in zip(shape, strides) if st > 0)
    if lo < 0 or hi >= size:
        raise ValueError(
            f"as_strided window [{lo}, {hi}] exceeds storage of {size} "
            f"elements (shape={tuple(shape)}, strides={tuple(strides)}, "
            f"storage_offset={storage_offset})")

    def _impl(x, shape=None, strides=None, offset=0):
        flat = x.reshape(-1)
        idx = jnp.asarray(offset, jnp.int32)
        for dim, st in zip(shape, strides):
            idx = idx[..., None] + jnp.arange(dim, dtype=jnp.int32) * st
        return flat[idx.reshape(shape)]
    return _op("as_strided", _impl, [a],
               {"shape": tuple(shape), "strides": tuple(strides),
                "offset": int(storage_offset)})


def split(a, num_chunks, axis=0):
    return _op("split",
               lambda x, n=2, axis=0: tuple(jnp.split(x, n, axis=axis)),
               [a], {"n": num_chunks, "axis": axis}, num_outputs=num_chunks)


def concat(tensors, axis=0):
    return _op("concat",
               lambda *xs, axis=0: jnp.concatenate(xs, axis=axis),
               list(tensors), {"axis": axis})


concatenate = concat


def stack(tensors, axis=0):
    return _op("stack", lambda *xs, axis=0: jnp.stack(xs, axis=axis),
               list(tensors), {"axis": axis})


def pad(a, paddings, value=0.0):
    return _op("pad",
               lambda x, paddings=None, value=0.0: jnp.pad(
                   x, paddings, constant_values=value),
               [a], {"paddings": tuple(map(tuple, paddings)), "value": value})


def broadcast_to(a, shape):
    return _op("broadcast_to",
               lambda x, shape=None: jnp.broadcast_to(x, shape),
               [a], {"shape": tuple(shape)})


def triu(a, k=0):
    return _op("triu", lambda x, k=0: jnp.triu(x, k), [a], {"k": k})


def tril(a, k=0):
    return _op("tril", lambda x, k=0: jnp.tril(x, k), [a], {"k": k})


# ---------------------------------------------------------------------------
# indexing (ops/Gather.cc, Scatter, Embedding*)
# ---------------------------------------------------------------------------

def gather(a, indices, axis=0):
    return _op("gather",
               lambda x, idx, axis=0: jnp.take_along_axis(x, idx, axis=axis),
               [a, indices], {"axis": axis})


def index_select(a, indices, axis=0):
    return _op("index_select",
               lambda x, idx, axis=0: jnp.take(x, idx, axis=axis),
               [a, indices], {"axis": axis})


def embedding_lookup(table, ids):
    """Embedding (reference ops/EmbeddingLookup.cc); grads are dense on TPU
    (XLA scatter-add), matching the reference's dense embedding grad."""
    return _op("embedding_lookup", lambda t, i: jnp.take(t, i, axis=0),
               [table, ids])


def one_hot(ids, num_classes, dtype=jnp.float32):
    return _op("one_hot",
               lambda i, n=None, dt=None: jax.nn.one_hot(i, n, dtype=dt),
               [ids], {"n": num_classes, "dt": dtype})


# ---------------------------------------------------------------------------
# softmax & losses (ops/Softmax.cc, *Loss.cc)
# ---------------------------------------------------------------------------

def softmax(a, axis=-1):
    return _op("softmax", lambda x, axis=-1: jax.nn.softmax(x, axis=axis),
               [a], {"axis": axis})


def log_softmax(a, axis=-1):
    return _op("log_softmax",
               lambda x, axis=-1: jax.nn.log_softmax(x, axis=axis),
               [a], {"axis": axis})


def _reduce_loss(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def nll_loss(log_probs, target, reduction="mean"):
    def _impl(lp, t, reduction="mean"):
        picked = jnp.take_along_axis(lp, t[..., None].astype(jnp.int32),
                                     axis=-1)[..., 0]
        return _reduce_loss(-picked, reduction)
    return _op("nll_loss", _impl, [log_probs, target],
               {"reduction": reduction})


def softmax_cross_entropy(logits, target, reduction="mean",
                          ignore_index: Optional[int] = None):
    """Dense-label or sparse-label softmax CE
    (ops/SoftmaxCrossEntropy[Sparse].cc)."""
    def _impl(lg, t, reduction="mean", ignore_index=None):
        lp = jax.nn.log_softmax(lg, axis=-1)
        if t.dtype in (jnp.int32, jnp.int64):
            picked = jnp.take_along_axis(
                lp, t[..., None].astype(jnp.int32), axis=-1)[..., 0]
            loss = -picked
            if ignore_index is not None:
                mask = (t != ignore_index)
                loss = loss * mask
                if reduction == "mean":
                    return jnp.sum(loss) / jnp.maximum(jnp.sum(mask), 1)
        else:
            loss = -jnp.sum(t * lp, axis=-1)
        return _reduce_loss(loss, reduction)
    return _op("softmax_cross_entropy", _impl, [logits, target],
               {"reduction": reduction, "ignore_index": ignore_index})


sparse_softmax_cross_entropy = softmax_cross_entropy


def mse_loss(pred, target, reduction="mean"):
    return _op("mse_loss",
               lambda p, t, reduction="mean": _reduce_loss((p - t) ** 2, reduction),
               [pred, target], {"reduction": reduction})


def binary_cross_entropy(pred, target, reduction="mean", with_logits=False):
    def _impl(p, t, reduction="mean", with_logits=False):
        if with_logits:
            loss = jnp.maximum(p, 0) - p * t + jnp.log1p(jnp.exp(-jnp.abs(p)))
        else:
            eps = 1e-12
            loss = -(t * jnp.log(p + eps) + (1 - t) * jnp.log(1 - p + eps))
        return _reduce_loss(loss, reduction)
    return _op("bce", _impl, [pred, target],
               {"reduction": reduction, "with_logits": with_logits})


def kl_div(log_probs, target, reduction="mean"):
    def _impl(lp, t, reduction="mean"):
        loss = t * (jnp.log(jnp.maximum(t, 1e-12)) - lp)
        return _reduce_loss(loss, reduction)
    return _op("kl_div", _impl, [log_probs, target], {"reduction": reduction})


# ---------------------------------------------------------------------------
# normalization (ops/LayerNorm.cc, RMSNorm kernel, BatchNorm, InstanceNorm)
# ---------------------------------------------------------------------------

def layer_norm(x, scale, bias, eps=1e-5):
    """LayerNorm over the last dim (reference FusedLayerNorm.cu — XLA fuses
    the reduction+normalize chain on TPU)."""
    def _impl(x, s, b, eps=1e-5):
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.mean((x - mean) ** 2, axis=-1, keepdims=True)
        inv = lax.rsqrt(var + eps)
        return (x - mean) * inv * s + b
    return _op("layer_norm", _impl, [x, scale, bias], {"eps": eps})


def rms_norm(x, scale, eps=1e-6):
    """RMSNorm (reference impl/kernel/RMSNorm.cu)."""
    def _impl(x, s, eps=1e-6):
        # compute in fp32 for stability, cast back (matches fused kernel)
        xf = x.astype(jnp.float32)
        var = jnp.mean(xf * xf, axis=-1, keepdims=True)
        out = xf * lax.rsqrt(var + eps)
        return (out * s.astype(jnp.float32)).astype(x.dtype)
    return _op("rms_norm", _impl, [x, scale], {"eps": eps})


def batch_norm(x, scale, bias, running_mean=None, running_var=None,
               training=True, eps=1e-5):
    """BatchNorm over NCHW/NC (reference ops/BatchNorm.cc).

    Training (or no stats provided): normalize with batch statistics.
    Eval with stats: normalize with running_mean/running_var.  Running-stat
    *updates* are handled by the nn.BatchNorm2d layer (see
    ``batch_norm_stats``), not here — this op is pure.
    """
    use_batch_stats = training or running_mean is None

    def _norm(x, s, b, mean, var, eps):
        shape = [1, -1] + [1] * (x.ndim - 2)
        inv = lax.rsqrt(var.reshape(shape) + eps)
        return (x - mean.reshape(shape)) * inv * s.reshape(shape) \
            + b.reshape(shape)

    if use_batch_stats:
        def _impl(x, s, b, eps=1e-5):
            axes = (0,) + tuple(range(2, x.ndim))
            return _norm(x, s, b, jnp.mean(x, axis=axes),
                         jnp.var(x, axis=axes), eps)
        return _op("batch_norm", _impl, [x, scale, bias], {"eps": eps})

    def _impl(x, s, b, rm, rv, eps=1e-5):
        return _norm(x, s, b, rm, rv, eps)
    return _op("batch_norm", _impl, [x, scale, bias, running_mean,
                                     running_var], {"eps": eps})


def batch_norm_stats(x):
    """Batch mean/var over the non-channel axes of NCHW/NC input — used by
    nn.BatchNorm2d to maintain running statistics."""
    def _impl(x):
        axes = (0,) + tuple(range(2, x.ndim))
        return jnp.mean(x, axis=axes), jnp.var(x, axis=axes)
    return _op("batch_norm_stats", _impl, [x], num_outputs=2)


def instance_norm(x, eps=1e-7):
    def _impl(x, eps=1e-7):
        axes = tuple(range(2, x.ndim))
        mean = jnp.mean(x, axis=axes, keepdims=True)
        var = jnp.var(x, axis=axes, keepdims=True)
        return (x - mean) * lax.rsqrt(var + eps)
    return _op("instance_norm", _impl, [x], {"eps": eps})


# ---------------------------------------------------------------------------
# conv / pool (ops/Conv2d.cc, MaxPool.cc, AvgPool.cc) — MXU convs
# ---------------------------------------------------------------------------

def conv2d(x, w, bias=None, stride=1, padding=0):
    """NCHW conv2d (reference ops/Conv2d.cc / cuDNN)."""
    strides = (stride, stride) if isinstance(stride, int) else tuple(stride)
    if isinstance(padding, int):
        pads = [(padding, padding), (padding, padding)]
    else:
        pads = [tuple(p) if isinstance(p, (list, tuple)) else (p, p)
                for p in padding]

    def _impl(x, w, b=None, strides=None, pads=None):
        out = lax.conv_general_dilated(
            x, w, window_strides=strides, padding=pads,
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        if b is not None:
            out = out + b.reshape(1, -1, 1, 1)
        return out
    inputs = [x, w] if bias is None else [x, w, bias]
    if bias is None:
        return _op("conv2d",
                   lambda x, w, strides=None, pads=None: _impl(
                       x, w, None, strides, pads),
                   inputs, {"strides": strides, "pads": tuple(map(tuple, pads))})
    return _op("conv2d", _impl, inputs,
               {"strides": strides, "pads": tuple(map(tuple, pads))})


def max_pool(x, kernel_size, stride=None, padding=0):
    k = (kernel_size, kernel_size) if isinstance(kernel_size, int) else tuple(kernel_size)
    s = k if stride is None else ((stride, stride) if isinstance(stride, int) else tuple(stride))
    p = (padding, padding) if isinstance(padding, int) else tuple(padding)

    def _impl(x, k=None, s=None, p=None):
        return lax.reduce_window(
            x, -jnp.inf, lax.max, (1, 1) + k, (1, 1) + s,
            [(0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])])
    return _op("max_pool", _impl, [x], {"k": k, "s": s, "p": p})


def avg_pool(x, kernel_size, stride=None, padding=0):
    k = (kernel_size, kernel_size) if isinstance(kernel_size, int) else tuple(kernel_size)
    s = k if stride is None else ((stride, stride) if isinstance(stride, int) else tuple(stride))
    p = (padding, padding) if isinstance(padding, int) else tuple(padding)

    def _impl(x, k=None, s=None, p=None):
        summed = lax.reduce_window(
            x, 0.0, lax.add, (1, 1) + k, (1, 1) + s,
            [(0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])])
        counts = lax.reduce_window(
            jnp.ones_like(x), 0.0, lax.add, (1, 1) + k, (1, 1) + s,
            [(0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])])
        return summed / counts
    return _op("avg_pool", _impl, [x], {"k": k, "s": s, "p": p})


# ---------------------------------------------------------------------------
# dropout (ops/Dropout.cc) — stateless RNG via graph-fed key
# ---------------------------------------------------------------------------

_dropout_salt = [0]


def dropout(x, p=0.5, training=True, rng_key=None):
    if not training or p == 0.0:
        return x if isinstance(x, Tensor) else _op("identity", lambda v: v, [x])
    g = _graph_of(x)
    if rng_key is None:
        rng_key = g.next_rng_tensor()
    _dropout_salt[0] += 1

    def _impl(x, key, p=0.5, salt=0):
        keep = 1.0 - p
        key = jax.random.fold_in(key, salt)  # distinct mask per dropout op
        mask = jax.random.bernoulli(key, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)

    return _op("dropout", _impl, [x, rng_key],
               {"p": p, "salt": _dropout_salt[0]})


def repeat_kv(x, n_rep: int):
    """Repeat KV heads for GQA: [b, s, kv_heads, d] -> [b, s, kv_heads*n_rep, d]."""
    if n_rep == 1:
        return x
    def _impl(x, n=1):
        b, s, h, d = x.shape
        return jnp.broadcast_to(x[:, :, :, None, :],
                                (b, s, h, n, d)).reshape(b, s, h * n, d)
    return _op("repeat_kv", _impl, [x], {"n": n_rep})


# ---------------------------------------------------------------------------
# rotary embedding (impl/kernel/Rotary.cu)
# ---------------------------------------------------------------------------

def rotary_embed(x, cos, sin, interleaved=False):
    """Apply rotary position embedding to [..., seq, heads, dim] or
    [..., seq, dim] tensors."""
    def _impl(x, cos, sin, interleaved=False):
        if interleaved:
            x1 = x[..., ::2]
            x2 = x[..., 1::2]
            rot = jnp.stack([-x2, x1], axis=-1).reshape(x.shape)
        else:
            half = x.shape[-1] // 2
            x1, x2 = x[..., :half], x[..., half:]
            rot = jnp.concatenate([-x2, x1], axis=-1)
        return x * cos + rot * sin
    return _op("rotary", _impl, [x, cos, sin], {"interleaved": interleaved})


# ---------------------------------------------------------------------------
# attention (ops/Attention.cc; pallas flash kernel on TPU)
# ---------------------------------------------------------------------------

def attention(q, k, v, causal=True, softmax_scale=None, use_flash=None,
              segment_ids=None):
    """Scaled-dot-product attention on [batch, seq, heads, head_dim]
    (reference ops/Attention.cc wrapping flash-attn2).

    On TPU, dispatches to the Pallas flash-attention kernel when available;
    the jnp fallback is used on CPU/simulation (XLA still fuses well).
    ``segment_ids`` ([b, s] int, -1 pad) gives packed/varlen masking —
    the reference's cu_seqlens path (ops/Attention.h:286).
    """
    from .attention import sdpa  # local import to avoid cycle
    if segment_ids is None:
        def _impl(q, k, v, causal=True, softmax_scale=None):
            return sdpa(q, k, v, causal=causal, softmax_scale=softmax_scale,
                        use_flash=use_flash)
        return _op("attention", _impl, [q, k, v],
                   {"causal": causal, "softmax_scale": softmax_scale})

    def _impl(q, k, v, segs, causal=True, softmax_scale=None):
        return sdpa(q, k, v, causal=causal, softmax_scale=softmax_scale,
                    use_flash=use_flash, segment_ids=segs)
    return _op("attention", _impl, [q, k, v, segment_ids],
               {"causal": causal, "softmax_scale": softmax_scale})


def parallel_attention(q, k, v, causal=True, softmax_scale=None,
                       cp_axis: str = "cp", batch_axis: str = "dp",
                       head_axis: str = "tp", segment_ids=None,
                       cp_impl: str = "ring"):
    """Context-parallel attention op (reference ParallelAttentionOp,
    ops/ParallelAttention.h:425): sequence sharded over ``cp_axis``.
    Requires the owning graph to carry a mesh with the cp axis; otherwise
    falls back to plain attention.
    ``segment_ids`` ([b, s] global doc ids, -1 pad) rides the KV ring —
    the reference's packed/varlen path (``ParallelAttention.cc:1061``).

    ``cp_impl``: "ring" (KV ring via ppermute + online LSE correction,
    the reference's AttnCommRing) or "ulysses" (all-to-all head scatter;
    no reference counterpart — TPU-native extension; indivisible head
    counts are zero-padded up to the cp(x tp) multiple).
    """
    g = _graph_of(q, k, v)
    mesh = getattr(g, "mesh", None)
    if mesh is None or cp_axis not in mesh.axis_names:
        raise ValueError(
            f"parallel_attention requires a graph mesh with axis "
            f"{cp_axis!r}; got mesh={mesh}. Use ops.attention for non-CP "
            f"runs instead of silently dropping context parallelism.")
    if cp_impl not in ("ring", "ulysses"):
        raise ValueError(f"cp_impl must be 'ring' or 'ulysses', "
                         f"got {cp_impl!r}")
    if mesh.shape[cp_axis] == 1:
        # degenerate ring: identical semantics, skip the shard_map
        return attention(q, k, v, causal=causal, softmax_scale=softmax_scale,
                         segment_ids=segment_ids)
    from ..parallel.ring_attention import ring_attention_sharded
    from ..parallel.ulysses import ulysses_attention_sharded
    sharded_attn = ring_attention_sharded if cp_impl == "ring" \
        else ulysses_attention_sharded

    def _impl(q, k, v, segment_ids=None, causal=True, softmax_scale=None):
        return sharded_attn(q, k, v, mesh, axis_name=cp_axis,
                            causal=causal,
                            softmax_scale=softmax_scale,
                            batch_axis=batch_axis,
                            head_axis=head_axis,
                            segment_ids=segment_ids)
    inputs = [q, k, v] if segment_ids is None else [q, k, v, segment_ids]
    if segment_ids is None:
        impl = lambda q, k, v, causal=True, softmax_scale=None: _impl(
            q, k, v, None, causal, softmax_scale)
    else:
        impl = _impl
    return _op("parallel_attention", impl, inputs,
               {"causal": causal, "softmax_scale": softmax_scale})


def fused_lm_cross_entropy(x, weight, labels, ignore_index=-100,
                           num_chunks: int = 8, reduction: str = "mean"):
    """LM-head matmul + CE fused, logits never materialized whole (the
    reference's VocabParallelCrossEntropyLoss pipeline collapsed into one
    chunked op — see ops/fused_ce.py).  x: [b, s, h] or [n, h];
    weight: [vocab, h]; labels match x's leading dims."""
    from .fused_ce import fused_linear_cross_entropy

    def _impl(x, w, lbl, ignore_index=-100, num_chunks=8,
              reduction="mean"):
        n = 1
        for d in x.shape[:-1]:
            n *= d
        return fused_linear_cross_entropy(
            x.reshape(n, x.shape[-1]), w, lbl.reshape(n),
            ignore_index, num_chunks, reduction)

    return _op("fused_lm_cross_entropy", _impl, [x, weight, labels],
               {"ignore_index": ignore_index, "num_chunks": num_chunks,
                "reduction": reduction})


# ---------------------------------------------------------------------------
# AMP helpers (ops/CheckFinite, update_scale)
# ---------------------------------------------------------------------------

def check_finite(x):
    return _op("check_finite",
               lambda v: jnp.all(jnp.isfinite(v)).astype(jnp.float32), [x])


def arange(start, stop=None, step=1, dtype=jnp.int32):
    g = get_default_graph()
    if stop is None:
        start, stop = 0, start
    return _op("arange",
               lambda start=0, stop=None, step=1, dt=None: jnp.arange(
                   start, stop, step, dtype=dt),
               [], {"start": start, "stop": stop, "step": step, "dt": dtype})


def full(shape, fill_value, dtype=jnp.float32):
    return _op("full",
               lambda shape=None, v=0, dt=None: jnp.full(shape, v, dtype=dt),
               [], {"shape": tuple(shape), "v": fill_value, "dt": dtype})


def zeros(shape, dtype=jnp.float32):
    return full(shape, 0.0, dtype)


def ones(shape, dtype=jnp.float32):
    return full(shape, 1.0, dtype)
