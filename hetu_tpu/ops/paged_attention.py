"""Paged decode attention: GQA decode against a block-paged KV pool.

Serving keeps KV state in a preallocated page pool
(``hetu_tpu/serving/kv_pool.py``): per layer, ``k_pages``/``v_pages``
of shape ``[num_pages, page_size, kv_heads, head_dim]``, with each
request owning a list of pages through an int32 page table.  Decode
attention then reads *ragged* per-request histories through the page
table instead of a padded dense ``[B, max_len, ...]`` cache — the
Ragged Paged Attention recipe (PAPERS.md, arxiv 2604.15464) that lets
mixed-length requests share one pool with no padding HBM.

Two implementations, numerically interchangeable:

- ``paged_attention_reference`` — gather pages via the page table into a
  contiguous ``[B, maxp*ps, kvh, hd]`` view and run masked dense
  attention.  This is the CPU/simulation path and the oracle the kernel
  is tested against.
- ``paged_attention_pallas`` — Pallas TPU kernel.  The page table and
  sequence lengths ride in as **scalar-prefetch** operands
  (``PrefetchScalarGridSpec``), so the kernel's k/v BlockSpec index maps
  translate grid position -> physical page id and Mosaic DMAs exactly
  the pages a request owns; pages past ``seq_len`` are skipped with
  ``pl.when`` (no gather materialization, no padding FLOPs beyond the
  last partial page).  Runs in interpret mode off-TPU so the whole path
  is testable on the simulated mesh.

Layout notes (DESIGN.md §8): ``head_dim`` fills the 128-lane tile;
``page_size`` is the sublane dim of the per-(page, kv-head) ``[ps, hd]``
tile and must be a multiple of 8 (f32 sublanes) — multiples of 128
additionally make one page exactly one MXU-shaped block.  The GQA group
dim is padded to 8 sublanes for the q/out tiles.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128
SUBLANES = 8
DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def _check_shapes(q, k_pages, v_pages, page_tables, seq_lens):
    b, nh, hd = q.shape
    p_, ps, kvh, hd2 = k_pages.shape
    if v_pages.shape != k_pages.shape:
        raise ValueError(f"k_pages {k_pages.shape} != v_pages "
                         f"{v_pages.shape}")
    if hd != hd2:
        raise ValueError(f"head_dim mismatch: q {hd} vs pages {hd2}")
    if nh % kvh != 0:
        raise ValueError(f"num_heads {nh} not divisible by kv_heads {kvh}")
    if page_tables.ndim != 2 or page_tables.shape[0] != b:
        raise ValueError(f"page_tables must be [B, max_pages], got "
                         f"{page_tables.shape}")
    if seq_lens.shape != (b,):
        raise ValueError(f"seq_lens must be [B], got {seq_lens.shape}")
    return b, nh, hd, ps, kvh


# ---------------------------------------------------------------------------
# reference path (CPU / oracle): gather-via-page-table + masked dense attn
# ---------------------------------------------------------------------------

def paged_attention_reference(q: jax.Array, k_pages: jax.Array,
                              v_pages: jax.Array, page_tables: jax.Array,
                              seq_lens: jax.Array,
                              softmax_scale: Optional[float] = None
                              ) -> jax.Array:
    """q [B, nh, hd] (one decode token per request), pages
    [P, ps, kvh, hd], page_tables [B, maxp] int32, seq_lens [B] int32
    (tokens valid, *including* the one just written) -> out [B, nh, hd].
    """
    b, nh, hd, ps, kvh = _check_shapes(q, k_pages, v_pages, page_tables,
                                       seq_lens)
    maxp = page_tables.shape[1]
    scale = softmax_scale if softmax_scale is not None else hd ** -0.5
    # named scope: the static analyzer (hetu_tpu/analysis) attributes
    # eqns to this op through the jaxpr name stack
    with jax.named_scope("paged_attention"):
        # [B, maxp, ps, kvh, hd] -> [B, maxp*ps, kvh, hd]
        k = k_pages[page_tables].reshape(b, maxp * ps, kvh, hd)
        v = v_pages[page_tables].reshape(b, maxp * ps, kvh, hd)
        g = nh // kvh
        qg = q.reshape(b, kvh, g, hd).astype(jnp.float32)
        s = jnp.einsum("bhgd,bshd->bhgs", qg,
                       k.astype(jnp.float32)) * scale   # [B, kvh, g, S]
        valid = (jnp.arange(maxp * ps)[None] <
                 seq_lens[:, None])[:, None, None, :]   # [B, 1, 1, S]
        s = jnp.where(valid, s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bhgs,bshd->bhgd", p, v.astype(jnp.float32))
        return out.reshape(b, nh, hd).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas TPU kernel
# ---------------------------------------------------------------------------

def _paged_kernel(sl_ref, pt_ref,            # scalar prefetch
                  q_ref, k_ref, v_ref,       # inputs
                  o_ref,                     # output
                  m_scr, l_scr, acc_scr,     # scratch
                  *, scale: float, ps: int, maxp: int, gp: int):
    bi = pl.program_id(0)
    p = pl.program_id(2)
    seqlen = sl_ref[bi]

    @pl.when(p == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, DEFAULT_MASK_VALUE)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    @pl.when(p * ps < seqlen)
    def _page():
        q = q_ref[0, 0].astype(jnp.float32)            # [gp, hd]
        k = k_ref[0, :, 0, :].astype(jnp.float32)      # [ps, hd]
        v = v_ref[0, :, 0, :].astype(jnp.float32)
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        cols = p * ps + lax.broadcasted_iota(jnp.int32, (gp, ps), 1)
        s = jnp.where(cols < seqlen, s, DEFAULT_MASK_VALUE)
        m_prev = m_scr[:, 0]                           # [gp]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
        alpha = jnp.exp(m_prev - m_cur)
        pexp = jnp.exp(s - m_cur[:, None])             # [gp, ps]
        l_cur = l_scr[:, 0] * alpha + jnp.sum(pexp, axis=1)
        acc_scr[...] = acc_scr[...] * alpha[:, None] + lax.dot_general(
            pexp, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_cur[:, None], m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_cur[:, None], l_scr.shape)

    @pl.when(p == maxp - 1)
    def _finalize():
        l = l_scr[:, 0]
        l = jnp.where(l == 0.0, 1.0, l)                # empty rows -> 0
        o_ref[0, 0] = (acc_scr[...] / l[:, None]).astype(o_ref.dtype)


def paged_attention_pallas(q: jax.Array, k_pages: jax.Array,
                           v_pages: jax.Array, page_tables: jax.Array,
                           seq_lens: jax.Array,
                           softmax_scale: Optional[float] = None,
                           interpret: Optional[bool] = None) -> jax.Array:
    """Pallas paged decode attention (same contract as the reference).

    Grid is ``(B, kvh, maxp)`` with pages innermost (sequential on TPU);
    the online-softmax state is carried across the page loop in VMEM
    scratch exactly like the flash forward.  k/v index maps read the
    prefetched page table, so each grid step DMAs one physical page.
    """
    b, nh, hd, ps, kvh = _check_shapes(q, k_pages, v_pages, page_tables,
                                       seq_lens)
    maxp = page_tables.shape[1]
    scale = softmax_scale if softmax_scale is not None else hd ** -0.5
    if interpret is None:
        interpret = not _on_tpu()
    g = nh // kvh
    gp = max(SUBLANES, ((g + SUBLANES - 1) // SUBLANES) * SUBLANES)
    qg = q.reshape(b, kvh, g, hd)
    if gp != g:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, gp - g), (0, 0)))
    pt = page_tables.astype(jnp.int32)
    sl = seq_lens.astype(jnp.int32)

    kernel = functools.partial(_paged_kernel, scale=float(scale), ps=ps,
                               maxp=maxp, gp=gp)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, kvh, maxp),
        in_specs=[
            pl.BlockSpec((1, 1, gp, hd),
                         lambda bi, h, p, sl_r, pt_r: (bi, h, 0, 0)),
            pl.BlockSpec((1, ps, 1, hd),
                         lambda bi, h, p, sl_r, pt_r: (pt_r[bi, p], 0, h,
                                                       0)),
            pl.BlockSpec((1, ps, 1, hd),
                         lambda bi, h, p, sl_r, pt_r: (pt_r[bi, p], 0, h,
                                                       0)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, gp, hd), lambda bi, h, p, sl_r, pt_r: (bi, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((gp, LANES), jnp.float32),
            pltpu.VMEM((gp, LANES), jnp.float32),
            pltpu.VMEM((gp, hd), jnp.float32),
        ],
    )
    with jax.named_scope("paged_attention"):
        out = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((b, kvh, gp, hd), q.dtype),
            interpret=interpret,
        )(sl, pt, qg, k_pages, v_pages)
    return out[:, :, :g, :].reshape(b, nh, hd)


def paged_attention_decode(q: jax.Array, k_pages: jax.Array,
                           v_pages: jax.Array, page_tables: jax.Array,
                           seq_lens: jax.Array,
                           softmax_scale: Optional[float] = None,
                           use_kernel: Optional[bool] = None) -> jax.Array:
    """Dispatching entry point: Pallas kernel on TPU, gather-dense
    reference elsewhere (mirrors ``ops.sdpa``'s dispatch discipline)."""
    if use_kernel is None:
        use_kernel = _on_tpu()
    if use_kernel:
        try:
            return paged_attention_pallas(q, k_pages, v_pages, page_tables,
                                          seq_lens,
                                          softmax_scale=softmax_scale)
        except Exception:
            pass
    return paged_attention_reference(q, k_pages, v_pages, page_tables,
                                     seq_lens, softmax_scale=softmax_scale)
