"""Fused LM-head + cross-entropy: logits are never materialized whole.

The reference computes ``logits = lm_head(x)`` then CE
(``ops/VocabParallelCrossEntropyLoss.cc``) — on TPU the [B*S, V] logits
tensor (3-7 GB for GPT-2-class configs) dominates HBM traffic because XLA
keeps it alive as the backward residual.  This op chunks the token dim:
each chunk's logits are computed, reduced to (lse, picked-logit) and
discarded; the backward RECOMPUTES chunk logits and accumulates dx/dw —
the round-3 ``scratch/purejax.py`` "fusedce" variant, landed.

Pure-jax with a custom VJP; shards transparently under GSPMD (tp-sharded
``w`` keeps the chunk matmuls vocab-parallel).
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def _num_chunks(n: int, want: int) -> int:
    want = max(1, min(want, n))
    while n % want:
        want -= 1
    return want


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def fused_linear_cross_entropy(x, w, labels, ignore_index: int = -100,
                               num_chunks: int = 8,
                               reduction: str = "mean"):
    """mean/sum CE of ``x @ w.T`` against ``labels`` without storing the
    logits.  x: [N, H]; w: [V, H]; labels: [N] (ignore_index masked)."""
    loss, _ = _fce_fwd_impl(x, w, labels, ignore_index, num_chunks,
                            reduction)
    return loss


def _fce_fwd_impl(x, w, labels, ignore_index, num_chunks, reduction):
    if reduction not in ("mean", "sum"):
        raise ValueError(
            f"fused_linear_cross_entropy supports reduction 'mean'/'sum', "
            f"got {reduction!r} (use the unfused softmax_cross_entropy "
            f"for 'none')")
    n, h = x.shape
    c = _num_chunks(n, num_chunks)
    xs = x.reshape(c, n // c, h)
    ls = labels.reshape(c, n // c)

    def chunk(carry, xl):
        xc, lc = xl
        logits = jax.lax.dot_general(
            xc, w, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [nc, V]
        m = jnp.max(logits, axis=-1)
        lse = m + jnp.log(jnp.sum(jnp.exp(logits - m[:, None]), axis=-1))
        safe = jnp.clip(lc, 0, w.shape[0] - 1)
        picked = jnp.take_along_axis(logits, safe[:, None], 1)[:, 0]
        valid = lc != ignore_index
        losses = jnp.where(valid, lse - picked, 0.0)
        return carry + jnp.sum(losses), (lse, valid)

    total, (lses, valids) = lax.scan(chunk, jnp.float32(0.0), (xs, ls))
    n_valid = jnp.maximum(jnp.sum(valids.astype(jnp.float32)), 1.0)
    loss = total / n_valid if reduction == "mean" else total
    return loss, (lses.reshape(n), n_valid)


def _fce_fwd_rule(x, w, labels, ignore_index, num_chunks, reduction):
    loss, (lse, n_valid) = _fce_fwd_impl(x, w, labels, ignore_index,
                                         num_chunks, reduction)
    return loss, (x, w, labels, lse, n_valid)


def _fce_bwd_rule(ignore_index, num_chunks, reduction, res, g):
    x, w, labels, lse, n_valid = res
    n, h = x.shape
    v = w.shape[0]
    c = _num_chunks(n, num_chunks)
    xs = x.reshape(c, n // c, h)
    ls = labels.reshape(c, n // c)
    lses = lse.reshape(c, n // c)
    scale = g / n_valid if reduction == "mean" else g

    def chunk(dw_acc, xl):
        xc, lc, lse_c = xl
        logits = jax.lax.dot_general(
            xc, w, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # recompute
        p = jnp.exp(logits - lse_c[:, None])           # softmax
        safe = jnp.clip(lc, 0, v - 1)
        onehot = jax.nn.one_hot(safe, v, dtype=p.dtype)
        valid = (lc != ignore_index).astype(p.dtype)[:, None]
        dlogits = (p - onehot) * valid * scale         # [nc, V] fp32
        dxc = jax.lax.dot_general(
            dlogits.astype(w.dtype), w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dw_c = jax.lax.dot_general(
            dlogits.astype(xc.dtype), xc, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dw_acc + dw_c, dxc

    dw, dxs = lax.scan(chunk, jnp.zeros((v, h), jnp.float32),
                       (xs, ls, lses))
    dx = dxs.reshape(n, h).astype(x.dtype)
    dlabels = np.zeros(labels.shape, jax.dtypes.float0)
    return dx, dw.astype(w.dtype), dlabels


fused_linear_cross_entropy.defvjp(_fce_fwd_rule, _fce_bwd_rule)
