"""Attention kernels: jnp reference path + Pallas flash dispatch.

Reference: ``hetu/graph/ops/Attention.cc`` (wrapping vendored flash-attn2
CUDA, varlen via cu_seqlens at ``impl/kernel/FlashAttention.cu:48-56``).
On TPU the flash kernel is Pallas (``hetu_tpu/ops/pallas/flash_attention.py``);
on CPU/simulation we use the jnp path (XLA fuses it adequately for tests).

Layout convention follows the reference: [batch, seq, num_heads, head_dim].
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def sdpa_reference(q: jax.Array, k: jax.Array, v: jax.Array,
                   causal: bool = True,
                   softmax_scale: Optional[float] = None,
                   bias: Optional[jax.Array] = None,
                   segment_ids: Optional[jax.Array] = None) -> jax.Array:
    """Plain scaled-dot-product attention, numerically standard.

    ``segment_ids`` ([batch, seq] int) implements packed/varlen attention —
    tokens attend only within their segment, the TPU-native equivalent of
    the reference's cu_seqlens varlen path (ops/Attention.h:286,371).
    """
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = softmax_scale if softmax_scale is not None else 1.0 / math.sqrt(d)
    # [b, h, sq, sk]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    logits = logits.astype(jnp.float32)
    if bias is not None:
        logits = logits + bias
    mask = None
    if causal:
        qi = lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        ki = lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        mask = (ki <= qi + (sk - sq))
    if segment_ids is not None:
        seg_mask = (segment_ids[:, :, None] == segment_ids[:, None, :])
        seg_mask = seg_mask[:, None, :, :]
        mask = seg_mask if mask is None else (mask[None, None] & seg_mask)
    if mask is not None:
        if mask.ndim == 2:
            mask = mask[None, None]
        logits = jnp.where(mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1)
    probs = jnp.where(jnp.isnan(probs), 0.0, probs)  # fully-masked rows
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
    return out


def sdpa(q, k, v, causal: bool = True, softmax_scale: Optional[float] = None,
         bias: Optional[jax.Array] = None,
         segment_ids: Optional[jax.Array] = None,
         use_flash: Optional[bool] = None) -> jax.Array:
    """Dispatching attention entry point."""
    if use_flash is None:
        use_flash = _on_tpu()
    if use_flash:
        try:
            from .pallas.flash_attention import flash_attention
            if bias is None:
                return flash_attention(q, k, v, causal=causal,
                                       softmax_scale=softmax_scale,
                                       segment_ids=segment_ids)
        except Exception:
            pass
    return sdpa_reference(q, k, v, causal=causal,
                          softmax_scale=softmax_scale, bias=bias,
                          segment_ids=segment_ids)
