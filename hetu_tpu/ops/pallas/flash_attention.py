"""Flash attention — Pallas TPU kernel (fwd + bwd), LSE-returning.

TPU-native replacement for the reference's vendored flash-attn2 CUDA kernels
(``hetu/impl/kernel/FlashAttention.cu``, ``hetu/graph/ops/Attention.cc``).
Design follows the FlashAttention-2 online-softmax algorithm, blocked for
the MXU: the kv loop is the innermost grid dimension with VMEM scratch
accumulators carried across it (TPU grid iterations are sequential).

Returns (out, lse); the log-sum-exp output is what ring attention's online
correction needs (reference ``AttnCommRing::ExecCorr``,
``ops/ParallelAttention.h:361``) and what the backward recompute uses.

Backward is a single fused kernel (dq, dk, dv in one grid pass): grid
(bh, q, kv) with kv innermost; dq accumulates in a per-q-block VMEM
scratch, dk/dv accumulate in full-sequence VMEM scratch written out once
per bh, and delta = rowsum(do*o) is computed in-kernel at kv==0 — so the
score matrix is materialized once per (q, kv) block pair instead of twice
(the split dq / dkv formulation).  Sequences whose dk/dv scratch would
exceed the VMEM budget fall back to the split two-kernel path.

Layout: [batch, seq, heads, head_dim] (reference convention).  Internally
[b*h, s, d].  Causal masking is block-skipped (fully-masked kv blocks are
not computed).  ``segment_ids`` gives packed/varlen semantics (the
cu_seqlens path of the reference, ``ops/Attention.h:286``).  Narrow
(8-lane) layouts are used for the lse / delta / q-segment operands — not
full 128-lane broadcasts.

On CPU the kernel runs in interpret mode so the whole path is testable on
the simulated mesh (SURVEY.md §4 takeaway).
"""
from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


def _empty_rows(m):
    """Rows whose max score is the mask fill value saw no valid kv
    position (ring varlen padding) — real logits can't get near it.
    Shared by the fast path and the accumulate finalize so the
    out=0/lse=-inf empty-row contract can't desynchronize."""
    return m <= DEFAULT_MASK_VALUE * 0.5

# Scores are computed as base-2 logits: the softmax scale AND log2(e) are
# folded into the q operand (one [s, d] multiply outside the kernel
# instead of a [s, s] multiply per block inside), and exp/log become
# exp2/log2 — the VPU-native transcendentals.  LSE stays natural-log at
# every API boundary (ring correction, backward, tests).
LOG2E = 1.4426950408889634
LN2 = 0.6931471805599453


def _interpret() -> bool:
    return jax.devices()[0].platform != "tpu"


LANES = 128      # last-dim tile width
SUBLANES = 8     # second-to-last tile width (f32/int32)

# dk/dv full-sequence fp32 scratch budget for the fused backward; above
# this the split two-kernel path is used (e.g. d=64 -> sk <= 8192).
_FUSED_DKV_VMEM_BYTES = 4 * 1024 * 1024


def _padded_segs(segment_ids, b, h, sq, sk):
    """Broadcast segment ids into TPU-tileable layouts: q side
    [bh, sq, SUBLANES] (narrow lanes), kv side [bh, SUBLANES, sk].

    ``segment_ids`` is either a [b, sq] array (shared q/kv — requires
    sq == sk) or a tuple ``(q_ids [b, sq], kv_ids [b, sk])`` — the ring
    attention case where the visiting KV block carries its own ids.
    """
    if segment_ids is None:
        q_segs = jnp.zeros((b * h, sq, SUBLANES), jnp.int32)
        kv_segs = jnp.zeros((b * h, SUBLANES, sk), jnp.int32)
        return q_segs, kv_segs
    if isinstance(segment_ids, (tuple, list)):
        q_ids, kv_ids = segment_ids
    else:
        if sq != sk:
            raise NotImplementedError(
                "segment_ids with sq != sk needs a (q_ids, kv_ids) tuple")
        q_ids = kv_ids = segment_ids
    flat_q = jnp.repeat(q_ids[:, None, :], h, axis=1).reshape(b * h, sq)
    q_segs = jnp.broadcast_to(flat_q[:, :, None], (b * h, sq, SUBLANES))
    flat_kv = jnp.repeat(kv_ids[:, None, :], h, axis=1).reshape(b * h, sk)
    kv_segs = jnp.broadcast_to(flat_kv[:, None, :], (b * h, SUBLANES, sk))
    return q_segs, kv_segs


def _seg_operands(segment_ids, b, h, sq, sk, bq, bk):
    """(in_specs, operands) for the segment-id streams — empty when
    segments are unused, so the common no-packing case pays zero extra
    HBM traffic for them."""
    if segment_ids is None:
        return [], []
    q_segs, kv_segs = _padded_segs(segment_ids, b, h, sq, sk)
    specs = [
        pl.BlockSpec((1, bq, SUBLANES), lambda bh, i, j: (bh, i, 0)),
        pl.BlockSpec((1, SUBLANES, bk), lambda bh, i, j: (bh, 0, j)),
    ]
    return specs, [q_segs, kv_segs]


def _dim_semantics(*sem):
    """Mosaic dimension semantics (parallel dims may split across
    TensorCores); None on toolchains without CompilerParams."""
    try:
        return pltpu.CompilerParams(dimension_semantics=sem)
    except (AttributeError, TypeError):
        return None


def _nosegs_kernel(kernel, *refs, **kw):
    """Adapter: invoke a seg-aware kernel with no segment operands
    (use_segs=False guarantees the seg refs are never read)."""
    return kernel(None, None, *refs, **kw)


def _causal_mask(s, q_idx, kv_idx, bq, bk, offset):
    """Apply the causal mask to a score block — diag-specialized (fa2
    sweep): blocks fully below the diagonal skip the iota mask entirely,
    so half the causal blocks pay zero masking VPU work.  Shared by all
    four kernels so fwd/bwd masking can never desynchronize."""
    def _masked(sv):
        rows = q_idx * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cols = kv_idx * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        return jnp.where(cols <= rows + offset, sv, DEFAULT_MASK_VALUE)
    is_diag = kv_idx * bk + bk - 1 > q_idx * bq + offset
    return lax.cond(is_diag, _masked, lambda sv: sv, s)


def _block_sizes(s: int, d: int, dtype, role: str = "fwd"
                 ) -> Tuple[int, int]:
    """Pick q/kv block sizes.  Blocks must divide s AND satisfy TPU tiling
    (last-two-dims rule); a block equal to the full dim is always legal, so
    sequences with no nice divisor fall back to a single block.

    Forward prefers 1024 blocks (fp32 score tile 4MB — the measured sweet
    spot of the round-3 fa3 prototype); the backward passes carry more
    scratch per block, so they cap at 512.  ``HETU_TPU_FLASH_BLOCK_FWD``
    / ``HETU_TPU_FLASH_BLOCK_BWD`` override the preference for sweeps."""
    import os
    cands = (1024, 512, 256, 128) if role == "fwd" and d <= 128 \
        else (512, 256, 128)
    env = os.environ.get(f"HETU_TPU_FLASH_BLOCK_{role.upper()}")
    if env:
        want = int(env)
        # want == s (single block) is always legal, at any size — the
        # fallback path emits exactly that for divisor-less sequences
        if s % want == 0 and (128 <= want <= cands[0] or want == s):
            return want, want
        import warnings
        warnings.warn(
            f"HETU_TPU_FLASH_BLOCK_{role.upper()}={want} ignored: must "
            f"divide s={s} and lie in [128, {cands[0]}] (or equal s) "
            f"for role={role}")
    for cand in cands:
        if s % cand == 0:
            return cand, cand
    return s, s


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_seg_ref, kv_seg_ref, q_ref, k_ref, v_ref,  # inputs
                o_ref, lse_ref,                              # outputs
                acc_ref, m_ref, l_ref,                       # scratch
                *, causal: bool, offset: int, bq: int,
                bk: int, num_kv: int, use_segs: bool):
    # q arrives pre-scaled by softmax_scale * LOG2E: scores are base-2
    # logits and all exps are exp2 (see module constant note).
    kv_idx = pl.program_id(2)
    q_idx = pl.program_id(1)

    def _scores():
        q = q_ref[0]                       # [bq, d]
        k = k_ref[0]                       # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bq, bk] base-2
        if causal:
            s = _causal_mask(s, q_idx, kv_idx, bq, bk, offset)
        if use_segs:
            qs = q_seg_ref[0, :, 0]        # [bq] (narrow-lane layout)
            ks = kv_seg_ref[0, 0, :]       # [bk] (sublane-padded layout)
            seg_ok = qs[:, None] == ks[None, :]
            s = jnp.where(seg_ok, s, DEFAULT_MASK_VALUE)
        return s

    if num_kv == 1 and (not causal or offset == 0):
        # single-kv-block fast path (the whole kv sequence is one block,
        # and the block is never fully skipped): no online-softmax carry,
        # no scratch traffic, outputs written directly
        s = _scores()
        m = jnp.max(s, axis=1)
        p = jnp.exp2(s - m[:, None])
        l = jnp.sum(p, axis=1)             # >= 1: exp2(0) at the max
        o = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) / l[:, None]
        lse = (m + jnp.log2(l)) * LN2
        if use_segs:
            # rows whose every position is seg-masked honor the empty-row
            # contract — out=0, lse=-inf — instead of averaging V through
            # exp2(0)=1 at the mask fill value
            empty = _empty_rows(m)
            o = jnp.where(empty[:, None], 0.0, o)
            lse = jnp.where(empty, -jnp.inf, lse)
        o_ref[0] = o.astype(o_ref.dtype)
        lse_ref[0] = jnp.broadcast_to(lse[:, None], lse_ref.shape[1:])
        return

    @pl.when(kv_idx == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)

    # block-level causal skip: kv block strictly after q block -> no
    # work (offset shifts the diagonal right: rows are offset global
    # positions ahead of cols — the SYM tail-half case)
    run = True
    if causal:
        run = kv_idx * bk <= q_idx * bq + bq - 1 + offset

    @pl.when(run)
    def _compute():
        s = _scores()
        m_prev = m_ref[:, 0]               # [bq]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
        p = jnp.exp2(s - m_cur[:, None])
        alpha = jnp.exp2(m_prev - m_cur)
        l_new = l_ref[:, 0] * alpha + jnp.sum(p, axis=1)
        l_ref[:] = jnp.broadcast_to(l_new[:, None], l_ref.shape)
        acc_ref[:] = acc_ref[:] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_cur[:, None], m_ref.shape)

    @pl.when(kv_idx == num_kv - 1)
    def _finalize():
        l = l_ref[:, 0]
        m = m_ref[:, 0]
        empty = l == 0.0
        if use_segs:
            # blocks ran but every position was seg-masked: m is the mask
            # fill value, not a real logit — same empty-row contract
            empty = jnp.logical_or(empty, _empty_rows(m))
        safe_l = jnp.where(empty, 1.0, l)
        o = acc_ref[:] / safe_l[:, None]
        o_ref[0] = jnp.where(empty[:, None], 0.0, o).astype(o_ref.dtype)
        lse = jnp.where(empty, -jnp.inf, (m + jnp.log2(safe_l)) * LN2)
        lse_ref[0] = jnp.broadcast_to(lse[:, None], lse_ref.shape[1:])


def _flash_fwd(q, k, v, scale, causal, segment_ids, causal_offset=0):
    b, sq, h, d = q.shape
    sk = k.shape[1]
    # fold softmax scale + log2(e) into q (one [s, d] multiply; scores
    # come out of the kernel's matmul as base-2 logits)
    qr = (q * (scale * LOG2E)).astype(q.dtype) \
        .transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kr = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vr = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    bq, _ = _block_sizes(sq, d, q.dtype)
    _, bk = _block_sizes(sk, d, q.dtype)
    num_q, num_kv = sq // bq, sk // bk

    use_segs = segment_ids is not None
    seg_specs, seg_args = _seg_operands(segment_ids, b, h, sq, sk, bq, bk)

    kernel = functools.partial(
        _fwd_kernel, causal=causal, offset=causal_offset,
        bq=bq, bk=bk, num_kv=num_kv, use_segs=use_segs)
    if not use_segs:
        kernel = functools.partial(_nosegs_kernel, kernel)

    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, num_q, num_kv),
        in_specs=[
            *seg_specs,
            pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, bq, SUBLANES), lambda bh, i, j: (bh, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sq, SUBLANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, LANES), jnp.float32),
            pltpu.VMEM((bq, LANES), jnp.float32),
        ],
        compiler_params=_dim_semantics("parallel", "parallel", "arbitrary"),
        interpret=_interpret(),
    )(*seg_args, qr, kr, vr)
    out = out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    lse = lse[:, :, 0].reshape(b, h, sq)
    return out, lse


# ---------------------------------------------------------------------------
# backward — fused single kernel (dq + dk + dv)
# ---------------------------------------------------------------------------

def _bwd_fused_kernel(q_seg_ref, kv_seg_ref, q_ref, k_ref, v_ref, do_ref,
                      o_ref, lse_ref,
                      dq_ref, dk_ref, dv_ref,
                      dq_acc, dk_acc, dv_acc, delta_scr,
                      *, scale, causal, offset, bq, bk, num_q, num_kv,
                      use_segs):
    # q and lse arrive pre-scaled by LOG2E (q also by softmax_scale), so
    # p = exp2(s2 - lse2) with no per-element scale multiplies; the
    # deferred scales land on the [*, d] accumulators at finalize:
    # dq *= scale, dk /= LOG2E (dk was accumulated against the scaled q).
    q_idx = pl.program_id(1)
    kv_idx = pl.program_id(2)

    @pl.when(jnp.logical_and(q_idx == 0, kv_idx == 0))
    def _init_kv():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @pl.when(kv_idx == 0)
    def _init_q():
        dq_acc[:] = jnp.zeros_like(dq_acc)
        do = do_ref[0].astype(jnp.float32)
        o = o_ref[0].astype(jnp.float32)
        delta = jnp.sum(do * o, axis=1)          # rowsum(do*o), in-kernel
        delta_scr[:] = jnp.broadcast_to(delta[:, None], delta_scr.shape)

    # fully-masked (q, kv) block pairs contribute to none of dq/dk/dv
    run = True
    if causal:
        run = kv_idx * bk <= q_idx * bq + bq - 1 + offset

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, q_idx, kv_idx, bq, bk, offset)
        if use_segs:
            seg_ok = (q_seg_ref[0, :, 0][:, None]
                      == kv_seg_ref[0, 0, :][None, :])
            s = jnp.where(seg_ok, s, DEFAULT_MASK_VALUE)
        lse = lse_ref[0, :, 0]
        p = jnp.exp2(s - lse[:, None])
        if use_segs or offset != 0:
            # fully-skipped q rows carry lse == -inf (never occurs in the
            # plain causal path — every row sees its diagonal)
            p = jnp.where(jnp.isfinite(lse)[:, None], p, 0.0)
        dv_acc[pl.dslice(kv_idx * bk, bk), :] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        delta = delta_scr[:, 0]
        ds = p * (dp - delta[:, None])
        dsl = ds.astype(q.dtype)
        dq_acc[:] += jax.lax.dot_general(
            dsl, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_acc[pl.dslice(kv_idx * bk, bk), :] += jax.lax.dot_general(
            dsl, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kv_idx == num_kv - 1)
    def _fin_q():
        dq_ref[0] = (dq_acc[:] * scale).astype(dq_ref.dtype)

    @pl.when(jnp.logical_and(q_idx == num_q - 1, kv_idx == num_kv - 1))
    def _fin_kv():
        dk_ref[0] = (dk_acc[:] * (1.0 / LOG2E)).astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd_fused(scale, causal, segment_ids, res, do, causal_offset):
    q, k, v, out, lse = res
    b, sq, h, d = q.shape
    sk = k.shape[1]
    qr = (q * (scale * LOG2E)).astype(q.dtype) \
        .transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kr = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vr = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    dor = do.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    outr = out.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    lser = jnp.broadcast_to((lse * LOG2E).reshape(b * h, sq)[:, :, None],
                            (b * h, sq, SUBLANES))
    bq, _ = _block_sizes(sq, d, q.dtype, role="bwd")
    _, bk = _block_sizes(sk, d, q.dtype, role="bwd")
    num_q, num_kv = sq // bq, sk // bk

    use_segs = segment_ids is not None
    seg_specs, seg_args = _seg_operands(segment_ids, b, h, sq, sk, bq, bk)

    kernel = functools.partial(
        _bwd_fused_kernel, scale=scale, causal=causal, offset=causal_offset,
        bq=bq, bk=bk, num_q=num_q, num_kv=num_kv, use_segs=use_segs)
    if not use_segs:
        kernel = functools.partial(_nosegs_kernel, kernel)
    dq, dk, dv = pl.pallas_call(
        kernel,
        grid=(b * h, num_q, num_kv),
        in_specs=[
            *seg_specs,
            pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, bq, SUBLANES), lambda bh, i, j: (bh, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, sk, d), lambda bh, i, j: (bh, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda bh, i, j: (bh, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((sk, d), jnp.float32),
            pltpu.VMEM((sk, d), jnp.float32),
            pltpu.VMEM((bq, LANES), jnp.float32),
        ],
        compiler_params=_dim_semantics("parallel", "arbitrary", "arbitrary"),
        interpret=_interpret(),
    )(*seg_args, qr, kr, vr, dor, outr, lser)
    dq = dq.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    dk = dk.reshape(b, h, sk, d).transpose(0, 2, 1, 3)
    dv = dv.reshape(b, h, sk, d).transpose(0, 2, 1, 3)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# backward — split two-kernel fallback (long sequences)
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_seg_ref, kv_seg_ref, q_ref, k_ref, v_ref, do_ref,
                   lse_ref, delta_ref, dq_ref, dq_acc,
                   *, scale, causal, offset, bq, bk, num_kv, use_segs):
    kv_idx = pl.program_id(2)
    q_idx = pl.program_id(1)

    @pl.when(kv_idx == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = True
    if causal:
        run = kv_idx * bk <= q_idx * bq + bq - 1 + offset

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, q_idx, kv_idx, bq, bk, offset)
        if use_segs:
            seg_ok = q_seg_ref[0, :, 0][:, None] == kv_seg_ref[0, 0, :][None, :]
            s = jnp.where(seg_ok, s, DEFAULT_MASK_VALUE)
        lse = lse_ref[0, :, 0]
        p = jnp.exp2(s - lse[:, None])
        if use_segs or offset != 0:
            p = jnp.where(jnp.isfinite(lse)[:, None], p, 0.0)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        delta = delta_ref[0, :, 0]
        ds = p * (dp - delta[:, None])
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kv_idx == num_kv - 1)
    def _finalize():
        dq_ref[0] = (dq_acc[:] * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_seg_ref, kv_seg_ref, q_ref, k_ref, v_ref, do_ref,
                    lse_ref, delta_ref, dk_ref, dv_ref, dk_acc, dv_acc,
                    *, scale, causal, offset, bq, bk, num_q, use_segs):
    q_idx = pl.program_id(2)
    kv_idx = pl.program_id(1)

    @pl.when(q_idx == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = True
    if causal:
        # q block strictly before kv block -> fully masked
        run = q_idx * bq + bq - 1 + offset >= kv_idx * bk

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, q_idx, kv_idx, bq, bk, offset)
        if use_segs:
            seg_ok = q_seg_ref[0, :, 0][:, None] == kv_seg_ref[0, 0, :][None, :]
            s = jnp.where(seg_ok, s, DEFAULT_MASK_VALUE)
        lse = lse_ref[0, :, 0]
        p = jnp.exp2(s - lse[:, None])
        if use_segs or offset != 0:
            p = jnp.where(jnp.isfinite(lse)[:, None], p, 0.0)
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        delta = delta_ref[0, :, 0]
        ds = p * (dp - delta[:, None])
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(q_idx == num_q - 1)
    def _finalize():
        dk_ref[0] = (dk_acc[:] * (1.0 / LOG2E)).astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd_split(scale, causal, segment_ids, res, do, causal_offset):
    q, k, v, out, lse = res
    b, sq, h, d = q.shape
    sk = k.shape[1]
    qr = (q * (scale * LOG2E)).astype(q.dtype) \
        .transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kr = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vr = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    dor = do.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    outr = out.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    lser = (lse * LOG2E).reshape(b * h, sq)
    # delta = rowsum(do * o)  [bh, sq] -> narrow-lane [bh, sq, SUBLANES]
    delta = jnp.sum(dor.astype(jnp.float32) * outr.astype(jnp.float32),
                    axis=-1)
    delta = jnp.broadcast_to(delta[:, :, None], (b * h, sq, SUBLANES))
    lser = jnp.broadcast_to(lser[:, :, None], (b * h, sq, SUBLANES))
    bq, _ = _block_sizes(sq, d, q.dtype, role="bwd")
    _, bk = _block_sizes(sk, d, q.dtype, role="bwd")
    num_q, num_kv = sq // bq, sk // bk

    use_segs = segment_ids is not None
    seg_specs, seg_args = _seg_operands(segment_ids, b, h, sq, sk, bq, bk)

    dq_kernel = functools.partial(
        _bwd_dq_kernel, scale=scale, causal=causal, offset=causal_offset,
        bq=bq, bk=bk, num_kv=num_kv, use_segs=use_segs)
    if not use_segs:
        dq_kernel = functools.partial(_nosegs_kernel, dq_kernel)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(b * h, num_q, num_kv),
        in_specs=[
            *seg_specs,
            pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, bq, SUBLANES), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, bq, SUBLANES), lambda bh, i, j: (bh, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=_dim_semantics("parallel", "parallel", "arbitrary"),
        interpret=_interpret(),
    )(*seg_args, qr, kr, vr, dor, lser, delta)

    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, scale=scale, causal=causal, offset=causal_offset,
        bq=bq, bk=bk, num_q=num_q, use_segs=use_segs)
    if not use_segs:
        dkv_kernel = functools.partial(_nosegs_kernel, dkv_kernel)
    dkv_seg_specs = [] if not use_segs else [
        pl.BlockSpec((1, bq, SUBLANES), lambda bh, j, i: (bh, i, 0)),
        pl.BlockSpec((1, SUBLANES, bk), lambda bh, j, i: (bh, 0, j)),
    ]
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(b * h, num_kv, num_q),
        in_specs=[
            *dkv_seg_specs,
            pl.BlockSpec((1, bq, d), lambda bh, j, i: (bh, i, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, j, i: (bh, j, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, j, i: (bh, j, 0)),
            pl.BlockSpec((1, bq, d), lambda bh, j, i: (bh, i, 0)),
            pl.BlockSpec((1, bq, SUBLANES), lambda bh, j, i: (bh, i, 0)),
            pl.BlockSpec((1, bq, SUBLANES), lambda bh, j, i: (bh, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda bh, j, i: (bh, j, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, j, i: (bh, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        compiler_params=_dim_semantics("parallel", "parallel", "arbitrary"),
        interpret=_interpret(),
    )(*seg_args, qr, kr, vr, dor, lser, delta)

    dq = dq.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    dk = dk.reshape(b, h, sk, d).transpose(0, 2, 1, 3)
    dv = dv.reshape(b, h, sk, d).transpose(0, 2, 1, 3)
    return dq, dk, dv


def _flash_bwd(scale, causal, segment_ids, res, g, causal_offset=0):
    do = g[0] if isinstance(g, (tuple, list)) else g
    q, k, v, out, lse = res
    sk, d = k.shape[1], k.shape[3]
    # fused pins two full-sk fp32 scratch planes PLUS the full-sk dk/dv
    # output blocks (constant-index out_specs) in VMEM per bh iteration
    dkv_bytes = 2 * sk * d * (4 + jnp.dtype(k.dtype).itemsize)
    if dkv_bytes <= _FUSED_DKV_VMEM_BYTES:
        return _flash_bwd_fused(scale, causal, segment_ids,
                                (q, k, v, out, lse), do, causal_offset)
    return _flash_bwd_split(scale, causal, segment_ids,
                            (q, k, v, out, lse), do, causal_offset)


# ---------------------------------------------------------------------------
# public entry with custom VJP
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash(q, k, v, segment_ids, scale, causal, use_segs):
    out, _ = _flash_fwd(q, k, v, scale, causal,
                        segment_ids if use_segs else None)
    return out


def _flash_fwd_rule(q, k, v, segment_ids, scale, causal, use_segs):
    segs = segment_ids if use_segs else None
    out, lse = _flash_fwd(q, k, v, scale, causal, segs)
    return out, (q, k, v, segment_ids, out, lse)


def _flash_bwd_rule(scale, causal, use_segs, res, g):
    q, k, v, segment_ids, out, lse = res
    segs = segment_ids if use_segs else None
    dq, dk, dv = _flash_bwd(scale, causal, segs, (q, k, v, out, lse), g)
    dsegs = np.zeros(segment_ids.shape, jax.dtypes.float0)
    return dq, dk, dv, dsegs


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q, k, v, causal: bool = True,
                    softmax_scale: Optional[float] = None,
                    segment_ids: Optional[jax.Array] = None) -> jax.Array:
    """Flash attention on [b, s, h, d]; differentiable (works under jit —
    segment_ids is a real traced argument with zero cotangent)."""
    scale = softmax_scale if softmax_scale is not None \
        else 1.0 / math.sqrt(q.shape[-1])
    use_segs = segment_ids is not None
    if segment_ids is None:
        segment_ids = jnp.zeros((q.shape[0], q.shape[1]), jnp.int32)
    return _flash(q, k, v, segment_ids, scale, causal, use_segs)


def flash_attention_with_lse(q, k, v, causal: bool = True,
                             softmax_scale: Optional[float] = None,
                             segment_ids: Optional[jax.Array] = None):
    """Forward-only variant returning (out, lse) — the ring-attention
    building block."""
    scale = softmax_scale if softmax_scale is not None \
        else 1.0 / math.sqrt(q.shape[-1])
    return _flash_fwd(q, k, v, scale, causal, segment_ids)
