"""Embedding memory-compression methods.

Capability counterpart of the reference's EmbeddingMemoryCompression tool
(``tools/EmbeddingMemoryCompression/methods/layers/`` — the VLDB'24
benchmark of ~19 compression methods).  Every class is a drop-in
``Module``: ``ids -> [..., dim]`` embeddings, so CTR models
(:mod:`hetu_tpu.models.ctr`) accept any of them via their ``embedding=``
argument.  Methods are grouped by family:

hashing     — :class:`HashEmbedding` (hash.py), :class:`CompositionalEmbedding`
              (compo.py, quotient-remainder), :class:`ROBEEmbedding` (robe.py),
              :class:`DHEEmbedding` (dhe.py)
quantization— :class:`DPQEmbedding` (dpq.py), :class:`MGQEEmbedding` (mgqe.py),
              :class:`QuantizedEmbedding` (quantize.py/alpt.py, int8 + learned
              scale via straight-through)
factorization— :class:`TensorTrainEmbedding` (tensortrain.py),
              :class:`LowRankEmbedding` (autosrh-style)
pruning     — :class:`DeepLightEmbedding` (deeplight.py, magnitude mask),
              :class:`PEPEmbedding` (pep.py, learned-threshold soft pruning),
              :class:`OptEmbedEmbedding` (optembed.py, learnable dim mask)
mixed-dim   — :class:`MixedDimensionEmbedding` (mde.py/adapt.py, frequency-
              tiered dims + projection), :class:`AutoDimEmbedding`
              (autodim.py, soft dim selection)

All ops are dense gathers/matmuls (MXU-friendly); masks use
straight-through estimators instead of dynamic sparsity so shapes stay
static under jit.
"""
from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import ops
from ..graph.ctor import (ConstantInitializer, NormalInitializer,
                          parameter)
from ..nn.module import Module

__all__ = [
    "HashEmbedding", "CompositionalEmbedding", "ROBEEmbedding",
    "DHEEmbedding", "DPQEmbedding", "MGQEEmbedding", "QuantizedEmbedding",
    "TensorTrainEmbedding", "LowRankEmbedding", "DeepLightEmbedding",
    "PEPEmbedding", "OptEmbedEmbedding", "MixedDimensionEmbedding",
    "AutoDimEmbedding", "AdaptiveEmbedding", "ALPTEmbedding",
    "AutoSrhEmbedding", "DedupEmbedding", "SparseEmbedding",
]

_P1 = 2654435761  # Knuth multiplicative hashing constants
_P2 = 40503


def _hash(ids, salt: int, mod: int):
    h = (ids.astype(jnp.uint32) * np.uint32(_P1)
         + np.uint32(salt * _P2 + 1))
    return (h % np.uint32(mod)).astype(jnp.int32)


class _CompressedEmbedding(Module):
    """Shared bits: target (num_embeddings, dim) + memory accounting."""

    def __init__(self, num_embeddings: int, embedding_dim: int):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim

    def compression_ratio(self) -> float:
        """full-table params / this method's params."""
        full = self.num_embeddings * self.embedding_dim
        mine = 0
        for _, p in self.named_parameters():
            mine += int(np.prod(p.shape))
        return full / max(1, mine)


class HashEmbedding(_CompressedEmbedding):
    """Hash trick: one shared table of ``table_size`` rows (hash.py)."""

    def __init__(self, num_embeddings, embedding_dim, table_size: int,
                 scale: float = 0.01, name: str = "hash_emb"):
        super().__init__(num_embeddings, embedding_dim)
        self.table_size = table_size
        self.table = parameter(NormalInitializer(0.0, scale),
                               (table_size, embedding_dim),
                               name=f"{name}.table")

    def forward(self, ids):
        mod = self.table_size
        slot = ops.functional._op("hash_ids",
                                  lambda i: _hash(i, 0, mod), [ids])
        return ops.embedding_lookup(self.table, slot)


class CompositionalEmbedding(_CompressedEmbedding):
    """Quotient-remainder compositional embedding (compo.py): two small
    tables combined elementwise (mul or sum)."""

    def __init__(self, num_embeddings, embedding_dim, num_buckets: int,
                 combine: str = "mul", scale: float = 0.01,
                 name: str = "compo_emb"):
        super().__init__(num_embeddings, embedding_dim)
        assert combine in ("mul", "sum")
        self.combine = combine
        self.num_buckets = num_buckets
        q_rows = (num_embeddings + num_buckets - 1) // num_buckets
        self.q_table = parameter(NormalInitializer(0.0, scale),
                                 (q_rows, embedding_dim),
                                 name=f"{name}.q")
        self.r_table = parameter(NormalInitializer(0.0, scale),
                                 (num_buckets, embedding_dim),
                                 name=f"{name}.r")

    def forward(self, ids):
        nb = self.num_buckets
        q = ops.functional._op("quotient", lambda i: i // nb, [ids])
        r = ops.functional._op("remainder", lambda i: i % nb, [ids])
        eq = ops.embedding_lookup(self.q_table, q)
        er = ops.embedding_lookup(self.r_table, r)
        return eq * er if self.combine == "mul" else eq + er


class ROBEEmbedding(_CompressedEmbedding):
    """ROBE-Z (robe.py): rows are chunks read from one shared flat
    parameter array at hashed offsets."""

    def __init__(self, num_embeddings, embedding_dim, robe_size: int,
                 block_size: int = 8, scale: float = 0.01,
                 name: str = "robe_emb"):
        super().__init__(num_embeddings, embedding_dim)
        assert embedding_dim % block_size == 0
        self.block_size = block_size
        self.num_blocks = embedding_dim // block_size
        self.robe_size = robe_size
        self.flat = parameter(NormalInitializer(0.0, scale), (robe_size,),
                              name=f"{name}.flat")
        self._arange = np.arange(block_size)

    def forward(self, ids):
        B, Z, nb = self.block_size, self.robe_size, self.num_blocks
        off = self._arange

        def _impl(flat, i):
            # per-(id, block) hashed start offset into the flat array
            blocks = jnp.arange(nb, dtype=jnp.int32)
            starts = _hash(i[..., None] * nb + blocks, 1, Z - B)  # [..., nb]
            idx = starts[..., None] + off                        # [..., nb, B]
            return flat[idx].reshape(*i.shape, nb * B)

        return ops.functional._op("robe_lookup", _impl, [self.flat, ids])


class DHEEmbedding(_CompressedEmbedding):
    """Deep hash embedding (dhe.py): k hash codes -> MLP decoder."""

    def __init__(self, num_embeddings, embedding_dim, num_hashes: int = 16,
                 hidden: int = 64, num_layers: int = 2,
                 name: str = "dhe_emb"):
        super().__init__(num_embeddings, embedding_dim)
        self.num_hashes = num_hashes
        dims = [num_hashes] + [hidden] * (num_layers - 1) + [embedding_dim]
        self.ws = []
        self.bs = []
        for li in range(len(dims) - 1):
            w = parameter(NormalInitializer(0.0, 1.0 / math.sqrt(dims[li])),
                          (dims[li], dims[li + 1]), name=f"{name}.w{li}")
            b = parameter(ConstantInitializer(0.0), (dims[li + 1],),
                          name=f"{name}.b{li}")
            self.register_parameter(f"w{li}", w)
            self.register_parameter(f"b{li}", b)
            self.ws.append(w)
            self.bs.append(b)

    def forward(self, ids):
        k = self.num_hashes

        def _codes(i):
            salts = jnp.arange(k, dtype=jnp.int32)
            h = _hash(i[..., None] * k + salts, 7, 1 << 20)
            return (h.astype(jnp.float32) / (1 << 19)) - 1.0  # [-1, 1)

        x = ops.functional._op("dhe_codes", _codes, [ids])
        for li, (w, b) in enumerate(zip(self.ws, self.bs)):
            x = ops.matmul(x, w) + b
            if li < len(self.ws) - 1:
                x = ops.gelu(x)
        return x


class DPQEmbedding(_CompressedEmbedding):
    """Differentiable product quantization (dpq.py): per-subspace
    codebooks, hard assignment with a straight-through estimator."""

    def __init__(self, num_embeddings, embedding_dim, num_codebooks: int = 4,
                 codebook_size: int = 64, scale: float = 0.05,
                 name: str = "dpq_emb"):
        super().__init__(num_embeddings, embedding_dim)
        assert embedding_dim % num_codebooks == 0
        self.num_codebooks = num_codebooks
        self.codebook_size = codebook_size
        sub = embedding_dim // num_codebooks
        # query table: what gets compared against codewords
        self.query = parameter(NormalInitializer(0.0, scale),
                               (num_embeddings, num_codebooks, sub),
                               name=f"{name}.query")
        self.codebooks = parameter(NormalInitializer(0.0, scale),
                                   (num_codebooks, codebook_size, sub),
                                   name=f"{name}.codebooks")

    def _mask_distances(self, d, ids):
        """Hook: restrict codeword choices per id (overridden by MGQE)."""
        return d

    def forward(self, ids):
        mask = self._mask_distances

        def _impl(query, books, i):
            q = query[i]                                  # [..., C, sub]
            # distances to codewords: [..., C, K]
            d = jnp.einsum("...cs,cks->...ck", q, books)
            d = mask(d, i)
            # soft-to-hard straight-through (the DPQ paper's tempered
            # softmax): forward = hard codeword, backward flows through
            # the soft mixture so BOTH the query table and the codebooks
            # receive gradient (the deployed artifact is the codebooks)
            soft = jax.nn.softmax(d, axis=-1)             # [..., C, K]
            cw_soft = jnp.einsum("...ck,cks->...cs", soft, books)
            idx = jnp.argmax(d, axis=-1)                  # [..., C]
            cw_hard = jnp.einsum("...ck,cks->...cs",
                                 jax.nn.one_hot(idx, books.shape[1]),
                                 books)
            out = cw_soft + jax.lax.stop_gradient(cw_hard - cw_soft)
            return out.reshape(*i.shape, -1)

        return ops.functional._op(f"{type(self).__name__}_lookup", _impl,
                                  [self.query, self.codebooks, ids])

    def compression_ratio(self) -> float:
        # deployed size = codes (C * log2(K) bits) + codebooks; the query
        # table exists only at training time (dpq.py's inference path)
        full = self.num_embeddings * self.embedding_dim * 32
        codes = self.num_embeddings * self.num_codebooks \
            * math.log2(self.codebook_size)
        books = int(np.prod(self.codebooks.shape)) * 32
        return full / (codes + books)


class MGQEEmbedding(DPQEmbedding):
    """Multi-granular quantized embedding (mgqe.py): frequent ids use
    more codewords than rare ids (per-id codebook-size cap)."""

    def __init__(self, num_embeddings, embedding_dim, num_codebooks: int = 4,
                 codebook_size: int = 64, hot_fraction: float = 0.1,
                 cold_codebook_size: int = 16, name: str = "mgqe_emb",
                 **kw):
        super().__init__(num_embeddings, embedding_dim,
                         num_codebooks=num_codebooks,
                         codebook_size=codebook_size, name=name, **kw)
        # ids < hot_boundary are "hot" (assumed frequency-sorted vocab,
        # the reference's setting on Criteo)
        self.hot_boundary = max(1, int(num_embeddings * hot_fraction))
        self.cold_codebook_size = cold_codebook_size

    def _mask_distances(self, d, ids):
        # cold ids may only use the first `cold_codebook_size` codewords
        K = d.shape[-1]
        cold = (ids >= self.hot_boundary)[..., None, None]
        mask = jnp.arange(K) >= self.cold_codebook_size
        return jnp.where(cold & mask, -jnp.inf, d)


class QuantizedEmbedding(_CompressedEmbedding):
    """Uniform quantization with a learned per-row scale and
    straight-through rounding (quantize.py; ALPT's learned step size,
    alpt.py)."""

    def __init__(self, num_embeddings, embedding_dim, bits: int = 8,
                 scale: float = 0.01, name: str = "quant_emb"):
        super().__init__(num_embeddings, embedding_dim)
        self.bits = bits
        self.table = parameter(NormalInitializer(0.0, scale),
                               (num_embeddings, embedding_dim),
                               name=f"{name}.table")
        self.step = parameter(ConstantInitializer(scale / 8),
                              (num_embeddings, 1), name=f"{name}.step")

    def forward(self, ids):
        qmax = 2 ** (self.bits - 1) - 1

        def _impl(table, step, i):
            w = table[i]
            s = jnp.abs(step[i]) + 1e-8
            wn = w / s
            q = jnp.clip(jnp.round(wn), -qmax - 1, qmax)
            # LSQ-style STE: round passes gradient through to w, and the
            # dequant multiply keeps s differentiable so the learned step
            # actually trains (ALPT)
            q_ste = wn + jax.lax.stop_gradient(q - wn)
            return q_ste * s

        return ops.functional._op("quant_lookup", _impl,
                                  [self.table, self.step, ids])

    def compression_ratio(self) -> float:
        full = self.num_embeddings * self.embedding_dim * 32
        mine = self.num_embeddings * (self.embedding_dim * self.bits + 32)
        return full / mine


class TensorTrainEmbedding(_CompressedEmbedding):
    """TT-Rec (tensortrain.py): the table as a 3-core tensor-train."""

    def __init__(self, num_embeddings, embedding_dim, ranks: int = 16,
                 scale: float = 0.3, name: str = "tt_emb"):
        super().__init__(num_embeddings, embedding_dim)
        # factor shapes: N ~ n1*n2*n3, D = d1*d2*d3
        self.n = _factor3(num_embeddings)
        self.d = _factor3(embedding_dim)
        self.ranks = (1, ranks, ranks, 1)
        r = self.ranks
        self.cores = []
        for k in range(3):
            core = parameter(
                NormalInitializer(0.0, scale),
                (self.n[k], r[k] * self.d[k] * r[k + 1]),
                name=f"{name}.core{k}")
            self.register_parameter(f"core{k}", core)
            self.cores.append(core)

    def forward(self, ids):
        n1, n2, n3 = self.n
        d1, d2, d3 = self.d
        r = self.ranks

        def _impl(c0, c1, c2, i):
            i1 = i // (n2 * n3)
            i2 = (i // n3) % n2
            i3 = i % n3
            g0 = c0[i1].reshape(*i.shape, r[0] * d1, r[1])
            g1 = c1[i2].reshape(*i.shape, r[1], d2 * r[2])
            g2 = c2[i3].reshape(*i.shape, r[2], d3 * r[3])
            x = jnp.einsum("...ar,...rb->...ab", g0, g1)  # [d1, d2*r2]
            x = x.reshape(*i.shape, d1 * d2, r[2])
            x = jnp.einsum("...ar,...rb->...ab", x, g2)   # [d1*d2, d3]
            return x.reshape(*i.shape, d1 * d2 * d3)

        return ops.functional._op("tt_lookup", _impl,
                                  [*self.cores, ids])


class LowRankEmbedding(_CompressedEmbedding):
    """Low-rank factorization E = U V (autosrh-style base)."""

    def __init__(self, num_embeddings, embedding_dim, rank: int,
                 scale: float = 0.05, name: str = "lowrank_emb"):
        super().__init__(num_embeddings, embedding_dim)
        self.u = parameter(NormalInitializer(0.0, scale),
                           (num_embeddings, rank), name=f"{name}.u")
        self.v = parameter(NormalInitializer(0.0, scale),
                           (rank, embedding_dim), name=f"{name}.v")

    def forward(self, ids):
        return ops.matmul(ops.embedding_lookup(self.u, ids), self.v)


class DeepLightEmbedding(_CompressedEmbedding):
    """DeepLight (deeplight.py): magnitude pruning with a target sparsity
    ramp; the mask is applied with a straight-through estimator."""

    def __init__(self, num_embeddings, embedding_dim,
                 target_sparsity: float = 0.9, scale: float = 0.01,
                 name: str = "deeplight_emb"):
        super().__init__(num_embeddings, embedding_dim)
        self.target_sparsity = target_sparsity
        self.table = parameter(NormalInitializer(0.0, scale),
                               (num_embeddings, embedding_dim),
                               name=f"{name}.table")
        # sparsity lives in a (non-trainable) graph variable so ramping
        # it mid-training takes effect inside the compiled step (a plain
        # Python attribute would be snapshotted at trace time)
        self.sparsity = parameter(ConstantInitializer(0.0), (),
                                  name=f"{name}.sparsity", trainable=False)

    def set_sparsity(self, s: float) -> None:
        """Ramp callback (the reference anneals sparsity during
        training)."""
        g = self.sparsity.graph
        g.reset_variable(self.sparsity,
                         np.float32(min(s, self.target_sparsity)))

    def forward(self, ids):
        def _impl(table, s, i):
            w = table[i]
            thresh = jnp.quantile(jnp.abs(w), jnp.clip(s, 0.0, 1.0))
            pruned = jnp.where(jnp.abs(w) >= thresh, w, 0.0)
            ste = w + jax.lax.stop_gradient(pruned - w)
            return jnp.where(s > 0.0, ste, w)

        return ops.functional._op("deeplight_lookup", _impl,
                                  [self.table, self.sparsity, ids])


class PEPEmbedding(_CompressedEmbedding):
    """PEP (pep.py): learnable soft-threshold pruning
    w' = sign(w) * relu(|w| - sigmoid(g))."""

    def __init__(self, num_embeddings, embedding_dim, scale: float = 0.01,
                 init_threshold: float = -8.0, name: str = "pep_emb"):
        super().__init__(num_embeddings, embedding_dim)
        self.table = parameter(NormalInitializer(0.0, scale),
                               (num_embeddings, embedding_dim),
                               name=f"{name}.table")
        self.gate = parameter(ConstantInitializer(init_threshold),
                              (num_embeddings, 1), name=f"{name}.gate")

    def forward(self, ids):
        def _impl(table, gate, i):
            w = table[i]
            g = jax.nn.sigmoid(gate[i])
            return jnp.sign(w) * jax.nn.relu(jnp.abs(w) - g)

        return ops.functional._op("pep_lookup", _impl,
                                  [self.table, self.gate, ids])


class OptEmbedEmbedding(_CompressedEmbedding):
    """OptEmbed (optembed.py): learnable per-dimension mask via a
    temperature sigmoid gate with straight-through binarization."""

    def __init__(self, num_embeddings, embedding_dim, scale: float = 0.01,
                 temperature: float = 2.0, name: str = "optembed_emb"):
        super().__init__(num_embeddings, embedding_dim)
        self.temperature = temperature
        self.table = parameter(NormalInitializer(0.0, scale),
                               (num_embeddings, embedding_dim),
                               name=f"{name}.table")
        self.dim_logits = parameter(ConstantInitializer(1.0),
                                    (embedding_dim,),
                                    name=f"{name}.dim_logits")

    def forward(self, ids):
        tau = self.temperature

        def _impl(table, logits, i):
            w = table[i]
            soft = jax.nn.sigmoid(logits / tau)
            hard = (soft > 0.5).astype(w.dtype)
            mask = soft + jax.lax.stop_gradient(hard - soft)
            return w * mask

        return ops.functional._op("optembed_lookup", _impl,
                                  [self.table, self.dim_logits, ids])


class MixedDimensionEmbedding(_CompressedEmbedding):
    """Mixed dimensions by frequency tier (mde.py / adapt.py): hot ids
    get full-dim rows, cold ids get a narrow table + projection."""

    def __init__(self, num_embeddings, embedding_dim,
                 hot_fraction: float = 0.1, cold_dim: Optional[int] = None,
                 scale: float = 0.01, name: str = "mde_emb"):
        super().__init__(num_embeddings, embedding_dim)
        self.hot_rows = max(1, int(num_embeddings * hot_fraction))
        self.cold_dim = cold_dim or max(1, embedding_dim // 8)
        self.hot = parameter(NormalInitializer(0.0, scale),
                             (self.hot_rows, embedding_dim),
                             name=f"{name}.hot")
        self.cold = parameter(NormalInitializer(0.0, scale),
                              (num_embeddings - self.hot_rows,
                               self.cold_dim), name=f"{name}.cold")
        self.proj = parameter(NormalInitializer(0.0, scale),
                              (self.cold_dim, embedding_dim),
                              name=f"{name}.proj")

    def forward(self, ids):
        hb = self.hot_rows

        def _impl(hot, cold, proj, i):
            is_hot = i < hb
            eh = hot[jnp.clip(i, 0, hot.shape[0] - 1)]
            ec = cold[jnp.clip(i - hb, 0, cold.shape[0] - 1)] @ proj
            return jnp.where(is_hot[..., None], eh, ec)

        return ops.functional._op("mde_lookup", _impl,
                                  [self.hot, self.cold, self.proj, ids])


class AutoDimEmbedding(_CompressedEmbedding):
    """AutoDim (autodim.py): softmax selection over candidate dims, each
    candidate a narrow table + projection; differentiable architecture
    params pick the dimension."""

    def __init__(self, num_embeddings, embedding_dim,
                 candidate_dims: Sequence[int] = (2, 8, 32),
                 scale: float = 0.01, name: str = "autodim_emb"):
        super().__init__(num_embeddings, embedding_dim)
        self.candidate_dims = tuple(candidate_dims)
        self.tables = []
        self.projs = []
        for k, d in enumerate(self.candidate_dims):
            t = parameter(NormalInitializer(0.0, scale),
                          (num_embeddings, d), name=f"{name}.t{k}")
            p = parameter(NormalInitializer(0.0, scale),
                          (d, embedding_dim), name=f"{name}.p{k}")
            self.register_parameter(f"t{k}", t)
            self.register_parameter(f"p{k}", p)
            self.tables.append(t)
            self.projs.append(p)
        self.alpha = parameter(ConstantInitializer(0.0),
                               (len(self.candidate_dims),),
                               name=f"{name}.alpha")

    def forward(self, ids):
        outs = [ops.matmul(ops.embedding_lookup(t, ids), p)
                for t, p in zip(self.tables, self.projs)]
        w = ops.softmax(self.alpha, axis=-1)
        acc = None
        for k, o in enumerate(outs):
            term = o * ops.getitem(w, k)
            acc = term if acc is None else acc + term
        return acc

    def selected_dim(self, graph) -> int:
        a = np.asarray(graph.get_tensor_value(self.alpha))
        return self.candidate_dims[int(np.argmax(a))]


def _factor3(n: int) -> Sequence[int]:
    """n1 <= n2 <= n3 with n1*n2*n3 >= n, as balanced as possible."""
    c = int(round(n ** (1 / 3)))
    for a in range(c, 0, -1):
        if n % a == 0:
            rest = n // a
            b = int(round(rest ** 0.5))
            for bb in range(b, 0, -1):
                if rest % bb == 0:
                    return sorted((a, bb, rest // bb))
    return (1, 1, n)


class AdaptiveEmbedding(_CompressedEmbedding):
    """DeepRec adaptive embedding (adapt.py): frequent ids get private
    rows in a full-dim table, rare ids share a small hashed table; every
    lookup is freq_row(remap) + rare_row(hash) so the two tiers blend."""

    def __init__(self, num_embeddings, embedding_dim, num_freq: int,
                 num_rare: int, remap_indices: Sequence[int],
                 scale: float = 0.01, name: str = "adapt_emb"):
        super().__init__(num_embeddings, embedding_dim)
        assert len(remap_indices) == num_embeddings
        self.num_freq = num_freq
        self.num_rare = num_rare
        self.freq_table = parameter(NormalInitializer(0.0, scale),
                                    (num_freq, embedding_dim),
                                    name=f"{name}.freq")
        self.rare_table = parameter(NormalInitializer(0.0, scale),
                                    (num_rare, embedding_dim),
                                    name=f"{name}.rare")
        self._remap = np.asarray(remap_indices, np.int32)

    def forward(self, ids):
        remap_np = jnp.asarray(self._remap)
        n_rare = self.num_rare

        def _impl(freq, rare, i):
            r = remap_np[i]                      # frequency-ranked id
            is_freq = (r < freq.shape[0])[..., None]
            # rare ids must NOT touch any frequent id's private row
            hi = jnp.where(is_freq,
                           freq[jnp.clip(r, 0, freq.shape[0] - 1)], 0.0)
            lo = rare[r % n_rare]
            return hi + lo

        return ops.functional._op("adapt_lookup", _impl,
                                  [self.freq_table, self.rare_table, ids])


class ALPTEmbedding(QuantizedEmbedding):
    """ALPT (alpt.py): low-precision table with a learned per-row scale
    trained jointly (adaptive step size).  The quantize-dequantize
    round-trip with the LSQ straight-through estimator is shared with
    :class:`QuantizedEmbedding`; ALPT's distinguishing digit widths
    (8/16) are enforced here."""

    def __init__(self, num_embeddings, embedding_dim, digit: int = 8,
                 init_scale: float = 0.01, name: str = "alpt_emb"):
        assert digit in (8, 16), "ALPT supports digit in (8, 16)"
        super().__init__(num_embeddings, embedding_dim, bits=digit,
                         scale=init_scale, name=name)
        self.digit = digit


class AutoSrhEmbedding(_CompressedEmbedding):
    """AutoSrh (autosrh.py): a full table gated by per-frequency-group,
    per-dimension trainable ``alpha``; after the search phase the alphas
    are frozen/thresholded (``retrain=True``) so near-zero entries prune
    (soft row-dimension sparsity)."""

    def __init__(self, num_embeddings, embedding_dim, nsplit: int,
                 group_indices: Sequence[int], scale: float = 0.01,
                 retrain: bool = False, prune_rate: float = 0.0,
                 name: str = "autosrh_emb"):
        super().__init__(num_embeddings, embedding_dim)
        assert len(group_indices) == num_embeddings
        self.nsplit = nsplit
        self.retrain = retrain
        self.prune_rate = prune_rate
        self.table = parameter(NormalInitializer(0.0, scale),
                               (num_embeddings, embedding_dim),
                               name=f"{name}.table")
        self.alpha = parameter(ConstantInitializer(1.0),
                               (nsplit, embedding_dim),
                               name=f"{name}.alpha")
        self._groups = np.asarray(group_indices, np.int32)

    def forward(self, ids):
        groups_np = jnp.asarray(self._groups)
        retrain = self.retrain
        rate = self.prune_rate

        def _impl(table, alpha, i):
            e = table[i]
            a = alpha[groups_np[i]]
            if retrain:
                a = jax.lax.stop_gradient(a)      # frozen after search
                if rate > 0:
                    thresh = jnp.quantile(jnp.abs(alpha), rate)
                    a = jnp.where(jnp.abs(a) >= thresh, a, 0.0)
            return e * a

        return ops.functional._op("autosrh_lookup", _impl,
                                  [self.table, self.alpha, ids])


class DedupEmbedding(_CompressedEmbedding):
    """Deduplication (deduplication.py): rows are grouped into blocks of
    ``nemb_per_block``; duplicate blocks share storage through a
    block-remap, so the stored table has only the unique blocks."""

    def __init__(self, dedup_table: np.ndarray,
                 remap_indices: Sequence[int], nemb_per_block: int,
                 num_embeddings: Optional[int] = None,
                 trainable: bool = True, name: str = "dedup_emb"):
        n_blocks = len(remap_indices)
        num_embeddings = num_embeddings or n_blocks * nemb_per_block
        super().__init__(num_embeddings, dedup_table.shape[1])
        self.nemb_per_block = nemb_per_block
        self.trainable = trainable
        self.table = parameter(np.asarray(dedup_table, np.float32),
                               dedup_table.shape, name=f"{name}.table")
        self._remap = np.asarray(remap_indices, np.int32)

    def forward(self, ids):
        remap_np = jnp.asarray(self._remap)
        npb = self.nemb_per_block
        trainable = self.trainable

        def _impl(table, i):
            block = remap_np[i // npb]            # unique-block index
            row = block * npb + i % npb
            out = table[row]
            return out if trainable else jax.lax.stop_gradient(out)

        return ops.functional._op("dedup_lookup", _impl,
                                  [self.table, ids])


class SparseEmbedding(_CompressedEmbedding):
    """Inference-form sparse table (sparse.py / AutoSrhRetrain's csr
    form): each row stores only its ``nnz_per_row`` largest-magnitude
    values + their column indices (padded CSR — static shapes for
    XLA).  Built FROM a dense (possibly pruned) table."""

    def __init__(self, dense_table: np.ndarray, nnz_per_row: int,
                 name: str = "sparse_emb"):
        n, d = dense_table.shape
        super().__init__(n, d)
        assert 0 < nnz_per_row <= d
        self.nnz = nnz_per_row
        order = np.argsort(-np.abs(dense_table), axis=1)[:, :nnz_per_row]
        cols = np.sort(order, axis=1).astype(np.int32)
        vals = np.take_along_axis(dense_table, cols, axis=1)
        self._cols = cols                        # [n, nnz] static buffers
        self.values = parameter(vals.astype(np.float32), vals.shape,
                                name=f"{name}.values")

    def forward(self, ids):
        cols_np = jnp.asarray(self._cols)
        d = self.embedding_dim

        def _impl(values, i):
            v = values[i]                        # [..., nnz]
            c = cols_np[i]                       # [..., nnz]
            out = jnp.zeros((*v.shape[:-1], d), v.dtype)
            return jnp.put_along_axis(out, c, v, axis=-1,
                                      inplace=False)

        return ops.functional._op("sparse_lookup", _impl,
                                  [self.values, ids])

    def compression_ratio(self) -> float:
        full = self.num_embeddings * self.embedding_dim * 32
        mine = self.num_embeddings * self.nnz * (32 + 32)  # val + col idx
        return full / mine
