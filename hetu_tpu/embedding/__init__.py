"""Embedding subsystem: HET-style cached embeddings + host parameter
server (PS analog) for CTR-scale tables.

Covers the reference's v1 PS/embedding stack: ps-lite
(``hetu/v1/ps-lite/``), HET cache (``hetu/v1/src/hetu_cache/``).
"""
from .cache import CachePolicy
from .cached import CachedEmbedding
from .compression import (AdaptiveEmbedding, ALPTEmbedding,
                          AutoDimEmbedding, AutoSrhEmbedding,
                          CompositionalEmbedding, DedupEmbedding,
                          DeepLightEmbedding, DHEEmbedding, DPQEmbedding,
                          HashEmbedding, LowRankEmbedding, MGQEEmbedding,
                          MixedDimensionEmbedding, OptEmbedEmbedding,
                          PEPEmbedding, QuantizedEmbedding, ROBEEmbedding,
                          SparseEmbedding, TensorTrainEmbedding)
from .host import HostParameterServer

__all__ = [
    "CachePolicy", "CachedEmbedding", "HostParameterServer",
    "AutoDimEmbedding", "CompositionalEmbedding", "DeepLightEmbedding",
    "DHEEmbedding", "DPQEmbedding", "HashEmbedding", "LowRankEmbedding",
    "MGQEEmbedding", "MixedDimensionEmbedding", "OptEmbedEmbedding",
    "PEPEmbedding", "QuantizedEmbedding", "ROBEEmbedding",
    "TensorTrainEmbedding", "AdaptiveEmbedding", "ALPTEmbedding",
    "AutoSrhEmbedding", "DedupEmbedding", "SparseEmbedding",
]
