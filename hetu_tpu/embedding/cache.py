"""Embedding cache policies (host-side bookkeeping).

Python face of the native core (``hetu_tpu/csrc/embed_cache.cc``),
counterpart of the reference's HET caches
(``hetu/v1/src/hetu_cache/include/{lru_cache.h,lfu_cache.h,
lfuopt_cache.h}``).  A pure-Python fallback implements identical
semantics when no compiler is available.
"""
from __future__ import annotations

import ctypes
from typing import List, Tuple

import numpy as np

from ..csrc.build import load_embed_cache_core

POLICIES = {"lru": 0, "lfu": 1, "lfuopt": 2}


class CachePolicy:
    """key -> slot map of bounded size with LRU/LFU/LFUOpt eviction.

    ``lookup(keys)`` returns (slots, is_miss, evicted_keys, evicted_slots):
    evicted rows must be written back to the master table by the caller
    before their slots are overwritten.
    """

    def __init__(self, limit: int, policy: str = "lru",
                 use_native: bool = True):
        assert policy in POLICIES, f"unknown policy {policy!r}"
        self.limit = int(limit)
        self.policy = policy
        self._lib = load_embed_cache_core() if use_native else None
        if self._lib is not None:
            self._handle = self._lib.hetu_cache_create(
                POLICIES[policy], self.limit)
        else:
            self._py = _PyCache(self.limit, policy)

    def __len__(self) -> int:
        if self._lib is not None:
            return int(self._lib.hetu_cache_size(self._handle))
        return len(self._py.map)

    def lookup(self, keys: np.ndarray) -> Tuple[np.ndarray, np.ndarray,
                                                np.ndarray, np.ndarray]:
        keys = np.ascontiguousarray(keys, np.int64).reshape(-1)
        n = len(keys)
        # validate BEFORE mutating: a batch with more unique keys than
        # slots would otherwise partially evict/insert and corrupt the
        # caller's resident bookkeeping
        n_unique = len(np.unique(keys))
        if n_unique > self.limit:
            raise ValueError(
                f"batch has more unique keys ({n_unique}) than the cache "
                f"limit ({self.limit})")
        if self._lib is not None:
            slots = np.empty(n, np.int64)
            miss = np.empty(n, np.uint8)
            ek = np.empty(n, np.int64)
            es = np.empty(n, np.int64)
            ne = self._lib.hetu_cache_lookup(
                self._handle,
                keys.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), n,
                slots.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                miss.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                ek.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                es.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
            if ne < 0:
                raise ValueError(
                    f"batch has more unique keys than the cache limit "
                    f"({self.limit})")
            return slots, miss.astype(bool), ek[:ne].copy(), es[:ne].copy()
        return self._py.lookup(keys)

    def __del__(self):
        if getattr(self, "_lib", None) is not None and \
                getattr(self, "_handle", None) is not None:
            self._lib.hetu_cache_destroy(self._handle)
            self._handle = None


class _PyCache:
    """Fallback with semantics identical to the native core: victim =
    min (priority, tiebreak); LRU -> (0, last access), LFU -> (freq,
    insertion time), LFUOpt -> (freq, last access)."""

    def __init__(self, limit: int, policy: str):
        self.limit = limit
        self.policy = policy
        self.map = {}                   # key -> slot
        self.freq = {}                  # key -> freq
        self.tie = {}                   # key -> tiebreak clock
        self.batch = {}                 # key -> last batch id (pinning)
        self.clock = 0
        self.batch_id = 0
        self.free = list(range(limit - 1, -1, -1))

    def _touch(self, key):
        self.freq[key] += 1
        if self.policy != "lfu":        # LFU keeps insertion time
            self.clock += 1
            self.tie[key] = self.clock
        self.batch[key] = self.batch_id

    def _victim(self):
        cands = [k for k in self.map if self.batch[k] != self.batch_id]
        if not cands:
            raise ValueError(f"batch has more unique keys than the cache "
                             f"limit ({self.limit})")
        if self.policy == "lru":
            return min(cands, key=lambda k: self.tie[k])
        return min(cands, key=lambda k: (self.freq[k], self.tie[k]))

    def lookup(self, keys):
        self.batch_id += 1
        n = len(keys)
        slots = np.empty(n, np.int64)
        miss = np.zeros(n, bool)
        ek, es = [], []
        for i, key in enumerate(keys):
            key = int(key)
            if key in self.map:
                slots[i] = self.map[key]
                self._touch(key)
                continue
            if not self.free:
                v = self._victim()
                ek.append(v)
                es.append(self.map[v])
                self.free.append(self.map.pop(v))
                self.freq.pop(v)
                self.tie.pop(v)
                self.batch.pop(v)
            slot = self.free.pop()
            self.map[key] = slot
            self.freq[key] = 1
            self.clock += 1
            self.tie[key] = self.clock
            self.batch[key] = self.batch_id
            slots[i] = slot
            miss[i] = True
        return slots, miss, np.asarray(ek, np.int64), np.asarray(es, np.int64)
