"""Host-RAM parameter server for embedding tables (PS analog).

Capability counterpart of the reference's ps-lite parameter server
(``hetu/v1/ps-lite/src/{worker.cc,PSFunc.cc,PSFhandle_embedding.cc}`` —
push/pull with server-side sparse optimizers) re-expressed for TPU: the
master tables live in host RAM (numpy), only the rows a batch touches
move to the device.  ``push`` applies the server-side sparse update
(SGD / AdaGrad / Adam, as the reference's embedding PS handlers do).
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np


class HostParameterServer:
    """Named host-side embedding tables with sparse push/pull."""

    def __init__(self, optimizer: str = "sgd", lr: float = 0.05,
                 betas: Tuple[float, float] = (0.9, 0.999),
                 eps: float = 1e-8):
        assert optimizer in ("sgd", "adagrad", "adam")
        self.optimizer = optimizer
        self.lr = lr
        self.betas = betas
        self.eps = eps
        self.tables: Dict[str, np.ndarray] = {}
        self._state: Dict[str, Dict[str, np.ndarray]] = {}
        self._step: Dict[str, int] = {}

    def register(self, name: str, num_embeddings: int, dim: int,
                 init: Optional[np.ndarray] = None, scale: float = 0.01,
                 seed: int = 0) -> None:
        if init is not None:
            table = np.asarray(init, np.float32).copy()
            assert table.shape == (num_embeddings, dim)
        else:
            rng = np.random.RandomState(seed)
            table = (rng.randn(num_embeddings, dim) * scale).astype(
                np.float32)
        self.tables[name] = table
        st: Dict[str, np.ndarray] = {}
        if self.optimizer == "adagrad":
            st["accum"] = np.zeros_like(table)
        elif self.optimizer == "adam":
            st["m"] = np.zeros_like(table)
            st["v"] = np.zeros_like(table)
        self._state[name] = st
        self._step[name] = 0

    def pull(self, name: str, keys: np.ndarray) -> np.ndarray:
        """Fetch rows for (possibly repeated) keys."""
        return self.tables[name][np.asarray(keys, np.int64)]

    def push(self, name: str, keys: np.ndarray, grads: np.ndarray) -> None:
        """Apply sparse gradients: repeated keys are summed first (the
        reference's server-side aggregation), then one optimizer step runs
        on the touched rows only."""
        keys = np.asarray(keys, np.int64).reshape(-1)
        grads = np.asarray(grads, np.float32).reshape(len(keys), -1)
        uniq, inv = np.unique(keys, return_inverse=True)
        g = np.zeros((len(uniq), grads.shape[1]), np.float32)
        np.add.at(g, inv, grads)
        table = self.tables[name]
        st = self._state[name]
        if self.optimizer == "sgd":
            table[uniq] -= self.lr * g
        elif self.optimizer == "adagrad":
            st["accum"][uniq] += g * g
            table[uniq] -= self.lr * g / (np.sqrt(st["accum"][uniq])
                                          + self.eps)
        else:  # adam (per-table step count; sparse variant)
            self._step[name] += 1
            t = self._step[name]
            b1, b2 = self.betas
            st["m"][uniq] = b1 * st["m"][uniq] + (1 - b1) * g
            st["v"][uniq] = b2 * st["v"][uniq] + (1 - b2) * g * g
            mhat = st["m"][uniq] / (1 - b1 ** t)
            vhat = st["v"][uniq] / (1 - b2 ** t)
            table[uniq] -= self.lr * mhat / (np.sqrt(vhat) + self.eps)
