"""Cache-enabled embedding training (HET analog).

Counterpart of the reference's HET system (VLDB'22;
``hetu/v1/src/hetu_cache/include/{cache.h,embedding.h,hetu_client.h}``):
the full table lives in host RAM (master), a bounded device cache of hot
rows lives in HBM as a regular trainable variable ``[cache_size, dim]``,
and a host-side policy (:class:`hetu_tpu.embedding.cache.CachePolicy`,
native C++ core) maps keys to cache slots.

Per step: ``prepare_batch(ids)`` resolves ids -> slots, writes evicted
rows back to the master and stages missed rows into the device cache;
the graph then runs a STATIC-shape gather on the cache variable and the
optimizer dense-updates it on device (TPU-friendly: no dynamic shapes,
no host round-trip inside the compiled step).  ``flush()`` writes every
resident row back.  Unlike HET's bounded-staleness push/pull (pull_bound/
push_bound, cache.h:25-26), synchronization here is exact at eviction
and flush.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ..graph.ctor import NormalInitializer, parameter, placeholder
from ..graph.graph import Graph, get_default_graph
from .. import ops
from ..nn.module import Module
from .cache import CachePolicy


class CachedEmbedding(Module):
    def __init__(self, num_embeddings: int, embedding_dim: int,
                 cache_size: int, policy: str = "lfu",
                 scale: float = 0.01, seed: int = 0,
                 name: str = "cached_embed"):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.cache_size = cache_size
        rng = np.random.RandomState(seed)
        self.master = (rng.randn(num_embeddings, embedding_dim)
                       * scale).astype(np.float32)
        self._policy = CachePolicy(cache_size, policy)
        self._resident: Dict[int, int] = {}    # key -> slot
        self.cache_table = parameter(
            NormalInitializer(0.0, scale), (cache_size, embedding_dim),
            name=f"{name}.cache")
        self._graph: Graph = self.cache_table.graph or get_default_graph()
        self._optimizer = None

    def attach_optimizer(self, optimizer) -> None:
        """Register the optimizer training ``cache_table`` so slot-keyed
        optimizer state (Adam m/v, momentum) is zeroed when a new key is
        staged into a slot — otherwise the newcomer inherits the evicted
        key's accumulated state."""
        self._optimizer = optimizer

    def _zero_slot_opt_state(self, slots: np.ndarray) -> None:
        if self._optimizer is None or not len(slots):
            return
        self._optimizer.reset_state_rows(self.cache_table, slots)

    # -- host-side step preparation ---------------------------------------

    def prepare_batch(self, ids: np.ndarray) -> np.ndarray:
        """Resolve ids -> device-cache slots, syncing rows as needed.
        Returns slots with the same shape as ids (feed them to the slot
        placeholder)."""
        ids_arr = np.asarray(ids, np.int64)
        uniq, inv = np.unique(ids_arr.reshape(-1), return_inverse=True)
        slots_u, miss, ev_keys, ev_slots = self._policy.lookup(uniq)
        g = self._graph
        if len(ev_keys) or miss.any():
            cache = np.asarray(g.get_tensor_value(self.cache_table))
            if len(ev_keys):
                self.master[ev_keys] = cache[ev_slots]
                for k in ev_keys:
                    self._resident.pop(int(k), None)
            if miss.any():
                cache = cache.copy()
                cache[slots_u[miss]] = self.master[uniq[miss]]
                g.reset_variable(self.cache_table, cache)
                self._zero_slot_opt_state(slots_u[miss])
        for k, s in zip(uniq, slots_u):
            self._resident[int(k)] = int(s)
        return slots_u[inv].reshape(ids_arr.shape).astype(np.int32)

    def flush(self) -> None:
        """Write all resident rows back to the master table."""
        if not self._resident:
            return
        cache = np.asarray(self._graph.get_tensor_value(self.cache_table))
        keys = np.fromiter(self._resident.keys(), np.int64)
        slots = np.fromiter(self._resident.values(), np.int64)
        self.master[keys] = cache[slots]

    # -- graph-side -------------------------------------------------------

    def forward(self, slots):
        """slots: int tensor of cache-slot ids -> [..., dim] embeddings
        (a static-shape gather on the cache variable)."""
        return ops.embedding_lookup(self.cache_table, slots)

    @property
    def hit_info(self):
        return {"resident": len(self._resident),
                "cache_size": self.cache_size}
