"""DistributedStates — the sharding spec at the heart of the framework.

TPU-native re-expression of the reference's central abstraction
(``hetu/graph/distributed_states.h/.cc``): a tensor's layout over a device
group is a map ``{dim -> split_count}`` with two special dims,

* ``-1`` — duplicate (replicated copies),
* ``-2`` — partial (pending-reduce partial sums),

plus an ``order`` list giving the significance of each split dim in the
mixed-radix device numbering, and a ``zero`` flag marking optimizer-state
sharding (ZeRO).

Where the reference lowers DS transitions to NCCL collectives at graph
substitution time (``executable_graph.cc:1006`` SubstituteCommOp), we lower
to ``jax.sharding.NamedSharding`` / ``PartitionSpec`` over a
``jax.sharding.Mesh`` and let GSPMD insert the collectives.  GSPMD has no
user-visible *partial* state, so partial(-2) is resolved at our graph level:
the ``check_*`` predicates below (semantics identical to
``distributed_states.h:110-115``) decide which collective converts ds A to
ds B, exactly as the reference's comm-op deduction does.
"""
from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

# Special dims.
DUPLICATE = -1
PARTIAL = -2
NULL_HETERO_DIM = -3  # DistributedStatesUnion sentinel (distributed_states.h:155)


class DistributedStates:
    """Sharding layout over an ordered device group of ``device_num`` devices."""

    __slots__ = ("_device_num", "_states", "_order", "_zero")

    def __init__(self, device_num: int,
                 states: Optional[Dict[int, int]] = None,
                 order: Optional[Sequence[int]] = None,
                 zero: bool = False):
        if device_num < 1:
            raise ValueError("device_num must be >= 1")
        self._device_num = int(device_num)
        self._zero = bool(zero)
        self._set_states(states or {})
        self._set_order(list(order) if order is not None else [])

    # -- construction helpers -------------------------------------------------

    @staticmethod
    def pure_duplicate(device_num: int) -> "DistributedStates":
        return DistributedStates(device_num, {DUPLICATE: device_num})

    @staticmethod
    def split(device_num: int, dim: int) -> "DistributedStates":
        return DistributedStates(device_num, {dim: device_num})

    def _set_states(self, states: Dict[int, int]) -> None:
        res = {k: v for k, v in states.items() if v > 1}
        prod = 1
        for v in res.values():
            prod *= v
        if prod != self._device_num:
            raise ValueError(
                f"states {states} imply {prod} devices, expected {self._device_num}")
        res.setdefault(PARTIAL, 1)
        res.setdefault(DUPLICATE, 1)
        self._states = res

    def _set_order(self, order: List[int]) -> None:
        active = sorted(k for k, v in self._states.items() if v > 1)
        if not order:
            self._order = active
        else:
            missing = [k for k in active if k not in order]
            if missing:
                raise ValueError(f"order {order} missing split dims {missing}")
            self._order = [o for o in order if self._states.get(o, 1) > 1]

    # -- accessors ------------------------------------------------------------

    @property
    def device_num(self) -> int:
        return self._device_num

    @property
    def states(self) -> Dict[int, int]:
        return dict(self._states)

    @property
    def order(self) -> List[int]:
        return list(self._order)

    @property
    def zero(self) -> bool:
        return self._zero

    def with_zero(self, zero: bool) -> "DistributedStates":
        return DistributedStates(self._device_num, self._states, self._order, zero)

    def get_dim(self, dim: int) -> int:
        return self._states.get(dim, 1)

    # -- basic predicates (distributed_states.cc:221-266) ---------------------

    def check_equal(self, other: "DistributedStates") -> bool:
        return (self._device_num == other._device_num
                and self._states == other._states
                and self._order == other._order)

    def check_max_dim(self, max_dim: int) -> bool:
        return all(o < max_dim for o in self._order)

    def check_pure_duplicate(self) -> bool:
        return self._device_num == self.get_dim(DUPLICATE)

    # -- combine/reduce machinery (distributed_states.cc:102-293) -------------

    def _combine_states(self, src: Sequence[int], dst: int) -> Dict[int, int]:
        """Merge split dims ``src`` into ``dst`` (renumbering positives)."""
        states = dict(self._states)
        value = 1
        for s in src:
            if s == dst:
                raise ValueError("cannot combine a dim into itself")
            if s in (PARTIAL, DUPLICATE):
                value *= states.get(s, 1)
                states[s] = 1
            else:
                if s in states:
                    value *= states.pop(s)
                # dims after s shift forward by one
                for key in sorted(k for k in states if k >= 0 and k > s):
                    states[key - 1] = states.pop(key)
        if dst in (PARTIAL, DUPLICATE):
            states[dst] = states.get(dst, 1) * value
        else:
            for s in src:
                if s >= 0 and dst > s:
                    dst -= 1
            states[dst] = states.get(dst, 1) * value
        return states

    def _combine_order(self, src: Sequence[int], dst: int) -> List[int]:
        order = list(self._order)
        inds = sorted(order.index(d) for d in (*src, dst) if d in order)
        if inds:
            if any(inds[i] != inds[0] + i for i in range(len(inds))):
                raise ValueError("cannot combine non-adjacent dims in order")
            order[inds[0]] = dst
            del order[inds[0] + 1:inds[0] + len(inds)]
            for i, o in enumerate(order):
                if o > 0:
                    shift = sum(1 for s in src if 0 <= s < o)
                    order[i] = o - shift
        return order

    @staticmethod
    def _norm(states: Dict[int, int], order: List[int]) -> Tuple[Dict[int, int], List[int]]:
        s = {k: v for k, v in states.items() if v > 1}
        o = [d for d in order if s.get(d, 1) > 1]
        return s, o

    def check_combine(self, dst_ds: "DistributedStates",
                      src: Sequence[int], dst: int) -> bool:
        try:
            states = self._combine_states(src, dst)
            order = self._combine_order(src, dst)
        except ValueError:
            return False
        return (self._norm(states, order)
                == self._norm(dst_ds._states, dst_ds._order))

    def _reduce_states(self, dim: int) -> Dict[int, int]:
        states = dict(self._states)
        if dim in (PARTIAL, DUPLICATE):
            states[dim] = 1
        else:
            states.pop(dim, None)
        return states

    def check_reduce_dim(self, dst_ds: "DistributedStates", dim: int) -> bool:
        states = self._reduce_states(dim)
        order = [o for o in self._order if o != dim]
        return (self._norm(states, order)
                == self._norm(dst_ds._states, dst_ds._order))

    def get_split_dim(self, merged_ds: "DistributedStates") -> int:
        """The (single) positive dim on which self is more split than merged."""
        split_dim = NULL_HETERO_DIM
        merged = merged_ds._states
        for k, v in self._states.items():
            if k >= 0 and v > 1 and merged.get(k, 1) < v:
                if split_dim != NULL_HETERO_DIM:
                    raise ValueError(
                        f"only one gather dim supported: {self._states} vs {merged}")
                split_dim = k
        return split_dim

    # -- collective deduction predicates (distributed_states.h:110-115) -------

    def check_allreduce(self, dst_ds: "DistributedStates") -> bool:
        return self.get_dim(PARTIAL) > 1 and self.check_combine(
            dst_ds, [PARTIAL], DUPLICATE)

    def check_scatter(self, dst_ds: "DistributedStates") -> bool:
        try:
            scatter_dim = dst_ds.get_split_dim(self)
        except ValueError:
            return False
        return self.get_dim(DUPLICATE) > 1 and self.check_combine(
            dst_ds, [DUPLICATE], scatter_dim)

    def check_allgather(self, dst_ds: "DistributedStates") -> bool:
        try:
            gather_dim = self.get_split_dim(dst_ds)
        except ValueError:
            return False
        if gather_dim == NULL_HETERO_DIM:
            return False
        return (self.get_dim(gather_dim) > 1 and dst_ds.get_dim(DUPLICATE) > 1
                and dst_ds.check_combine(self, [DUPLICATE], gather_dim))

    def check_reducescatter(self, dst_ds: "DistributedStates") -> bool:
        try:
            scatter_dim = dst_ds.get_split_dim(self)
        except ValueError:
            return False
        return self.get_dim(PARTIAL) > 1 and self.check_combine(
            dst_ds, [PARTIAL], scatter_dim)

    def check_broadcast(self, dst_ds: "DistributedStates") -> bool:
        return dst_ds.get_dim(DUPLICATE) > 1 and dst_ds.check_reduce_dim(
            self, DUPLICATE)

    def check_reduce(self, dst_ds: "DistributedStates") -> bool:
        return self.get_dim(PARTIAL) > 1 and self.check_reduce_dim(
            dst_ds, PARTIAL)

    # -- device <-> shard mapping (distributed_states.cc:360-420) -------------

    def get_loop_sizes(self) -> List[int]:
        """Stride (in device indices) of each order dim."""
        sizes = [1]
        for o in reversed(self._order):
            sizes.insert(0, sizes[0] * self.get_dim(o))
        return sizes[1:] if len(sizes) > 1 else [1]

    def map_device_to_state_index(self, device_index: int) -> Dict[int, int]:
        """Which slice of each dim device ``device_index`` owns."""
        state_index: Dict[int, int] = {}
        for o in reversed(self._order):
            n = self._states[o]
            state_index[o] = device_index % n
            device_index //= n
        return state_index

    def get_dup_group_index(self, device_index: int) -> int:
        idx = self.map_device_to_state_index(device_index)
        dup_group, interval = 0, 1
        for dim in sorted(self._order, reverse=True):
            if dim < 0:
                break
            dup_group += idx[dim] * interval
            interval *= self.get_dim(dim)
        return dup_group

    def get_group_indices_by_dim(self, dim: int, device_index: int) -> List[int]:
        """Device indices of the collective group along ``dim`` that contains
        ``device_index`` (reference ``get_devices_by_dim``)."""
        pos = self._order.index(dim)
        interval = 1
        for o in self._order[pos + 1:]:
            interval *= self._states[o]
        macro = interval * self.get_dim(dim)
        start = device_index - device_index % macro + device_index % interval
        return list(range(start, start + macro, interval))

    def local_slice(self, global_shape: Sequence[int],
                    device_index: int) -> Tuple[slice, ...]:
        """The slice of the global tensor owned by ``device_index``.

        Host-side data slicing; equivalent of the reference's
        ``parallel_data_provider`` (``parallel_multi_ds.py:16``).
        """
        idx = self.map_device_to_state_index(device_index)
        slices = []
        for d, size in enumerate(global_shape):
            n = self.get_dim(d)
            if size % n != 0:
                raise ValueError(f"dim {d} size {size} not divisible by {n}")
            chunk = size // n
            i = idx.get(d, 0)
            slices.append(slice(i * chunk, (i + 1) * chunk))
        return tuple(slices)

    def local_shape(self, global_shape: Sequence[int]) -> Tuple[int, ...]:
        return tuple(s // self.get_dim(d) for d, s in enumerate(global_shape))

    # -- misc -----------------------------------------------------------------

    def __eq__(self, other) -> bool:
        return isinstance(other, DistributedStates) and self.check_equal(other)

    def __hash__(self) -> int:
        return hash((self._device_num, tuple(sorted(self._states.items())),
                     tuple(self._order)))

    def __repr__(self) -> str:
        states = {k: v for k, v in sorted(self._states.items()) if v > 1}
        z = ", zero" if self._zero else ""
        return f"DS(n={self._device_num}, states={states}, order={self._order}{z})"


def deduce_comm_kind(src: DistributedStates, dst: DistributedStates) -> str:
    """Which collective converts ``src`` into ``dst``.

    Mirrors the decision procedure of the reference's ``SubstituteCommOp``
    (``executable_graph.cc:1006``): try the cheap structured collectives
    first, fall back to a general resharding (batched point-to-point in the
    reference; a generic GSPMD reshard for us).
    """
    if src.check_equal(dst):
        return "identity"
    if src.check_allreduce(dst):
        return "all_reduce"
    if src.check_allgather(dst):
        return "all_gather"
    if src.check_reducescatter(dst):
        return "reduce_scatter"
    if src.check_scatter(dst):
        return "scatter"
    if src.check_broadcast(dst):
        return "broadcast"
    if src.check_reduce(dst):
        return "reduce"
    return "reshard"  # generic (BatchedISendIRecv in the reference)


# -- pspec edges: PartitionSpec -> DS, and per-edge comm deduction ------------
#
# The per-edge attribution pass (hetu_tpu/analysis/edges.py) predicts the
# complete expected collective set of an executable from its
# producer -> consumer pspec transitions.  PartitionSpecs are the lowered
# form of DistributedStates here (GSPMD meshes instead of ordered device
# groups), so an edge between two annotations maps back into DS space and
# the reference's comm-op deduction (`deduce_comm_kind` above) names the
# collective GSPMD will insert for it.


def _ds_from_splits(device_num: int,
                    splits: Dict[int, int]) -> DistributedStates:
    """Assemble a DS from per-dim split counts over ``device_num``
    devices, leftover factor as duplicate(-1), with POSITIVES-FIRST
    order (duplicate least significant): a gathered / scattered dim
    then trades places with the duplicate factor exactly as
    ``check_combine`` expects, so allgather/scatter/reducescatter
    deduction works on pspec-derived states (the canonical sorted order
    would put -1 first and spuriously fail the order check)."""
    states = dict(splits)
    split_total = 1
    for v in states.values():
        split_total *= v
    states[DUPLICATE] = device_num // split_total
    order = sorted(k for k, v in states.items() if k >= 0 and v > 1)
    if states[DUPLICATE] > 1:
        order.append(DUPLICATE)
    return DistributedStates(device_num, states, order)


def pspec_shard_divisor(pspec, mesh_axes: Dict[str, int]) -> int:
    """How many ways a ``PartitionSpec`` shards a value over the mesh:
    the product of the named-axis sizes it mentions (tuple entries
    flattened, unknown axes size 1).  ``None`` pspec = replicated = 1.
    Shared by graph registration (``_arg_memory_facts``) and the static
    memory pass (``analysis.memory.classify_args``) so registered and
    fallback divisors can never disagree on pspec semantics."""
    if pspec is None:
        return 1
    d = 1
    for entry in pspec:
        if entry is None:
            continue
        for a in (entry if isinstance(entry, tuple) else (entry,)):
            d *= int(mesh_axes.get(str(a), 1))
    return d


def pspec_to_ds(pspec, ndim: int, mesh_axes: Dict[str, int]
                ) -> DistributedStates:
    """Lower a ``PartitionSpec`` over a named mesh into a
    :class:`DistributedStates`: each sharded tensor dim becomes a split
    dim with the product of its mesh-axis sizes, the leftover device
    factor becomes duplicate(-1).  ``pspec=None`` means fully replicated
    (GSPMD's default for unannotated values)."""
    device_num = 1
    for s in mesh_axes.values():
        device_num *= int(s)
    splits: Dict[int, int] = {}
    if pspec is not None:
        for d, entry in enumerate(pspec):
            if entry is None:
                continue
            ents = entry if isinstance(entry, tuple) else (entry,)
            split = 1
            for a in ents:
                if a is not None:
                    split *= int(mesh_axes.get(a, 1))
            if split > 1:
                if d >= ndim:
                    raise ValueError(
                        f"pspec {pspec} has more sharded entries than "
                        f"tensor dims ({ndim})")
                splits[d] = splits.get(d, 1) * split
    return _ds_from_splits(device_num, splits)


def _spec_pairs(pspec) -> set:
    """{(dim, axis)} placements of a PartitionSpec (None -> empty)."""
    pairs = set()
    if pspec is None:
        return pairs
    for d, entry in enumerate(pspec):
        if entry is None:
            continue
        for a in (entry if isinstance(entry, tuple) else (entry,)):
            if a is not None:
                pairs.add((d, str(a)))
    return pairs


def deduce_pspec_transition(src_spec, src_shape: Sequence[int],
                            dst_spec, dst_shape: Sequence[int],
                            mesh_axes: Dict[str, int]) -> str:
    """Collective kind implied by a producer -> consumer pspec edge.

    Same-shape edges are pure layout transitions: lower both specs to DS
    and run the reference deduction (:func:`deduce_comm_kind`).  When the
    op between the two annotations changes the shape (a matmul, an
    einsum dispatch, an embedding lookup) there is no dim correspondence,
    so the edge is classified by how the mesh-axis placements moved:

    * axes *lost* entirely (sharded input contracted away) — the result
      is partial over those axes: ``all_reduce``;
    * axes *gained* (a sharded weight splits the output) — a local
      slice: ``scatter`` (no forward comm; its autodiff dual is not);
    * placements *moved* or mixed — a generic ``reshard`` (GSPMD lowers
      these to all-to-all / all-gather / collective-permute chains).
    """
    src_pairs, dst_pairs = _spec_pairs(src_spec), _spec_pairs(dst_spec)
    live = {a for a, s in mesh_axes.items() if int(s) > 1}
    src_pairs = {(d, a) for d, a in src_pairs if a in live}
    dst_pairs = {(d, a) for d, a in dst_pairs if a in live}
    if src_pairs == dst_pairs:
        return "identity"
    if tuple(src_shape) == tuple(dst_shape):
        # project onto the CHANGED mesh axes only: axes that keep their
        # dim placement are spectators (their device subgroups never
        # communicate), and the DS predicates are all-or-nothing over
        # the device group, so the deduction runs on the subgroup the
        # transition actually moves data across.
        moved = {a for _d, a in src_pairs ^ dst_pairs}
        n_sub = 1
        for a in moved:
            n_sub *= int(mesh_axes[a])

        def _sub_ds(pairs):
            splits: Dict[int, int] = {}
            for d, a in pairs:
                if a in moved:
                    splits[d] = splits.get(d, 1) * int(mesh_axes[a])
            return _ds_from_splits(n_sub, splits)

        try:
            return deduce_comm_kind(_sub_ds(src_pairs),
                                    _sub_ds(dst_pairs))
        except ValueError:
            pass
    src_axes = {a for _, a in src_pairs}
    dst_axes = {a for _, a in dst_pairs}
    lost = src_axes - dst_axes
    gained = dst_axes - src_axes
    if lost and not gained:
        return "all_reduce"    # contraction over the sharded dim: partial
    if gained and not lost:
        return "scatter"       # sharded weight slices the output locally
    return "reshard"


# -- coalesced gradient-comm predictions -------------------------------------
#
# The comm-op deduction above predicts WHICH collective converts one DS into
# another; the functions below extend the prediction to the coalesced
# gradient-sync layer (comm.py all_reduce_coalesced): given the gradient
# set and transport they enumerate the exact collective sequence the traced
# program must contain, and `count_hlo_collectives` checks the lowered XLA
# text against it — the analogue of the reference asserting its
# AllReduceCoalesce op list at substitution time.


def predict_grad_comm_collectives(entries, device_num: int,
                                  bucket_mb: float = 4.0,
                                  transport: str = "fp32",
                                  block: Optional[int] = None) -> List[dict]:
    """Predict the collectives one coalesced gradient sync emits.

    ``entries``: [(key, shape, dtype)] of the gradient set, in sync
    order.  Returns one dict per collective: {kind, payload_bytes,
    wire_bytes, dtype} — fp32 emits one all_reduce per bucket; bf16 one
    all_to_all + one all_gather per bucket; int8 adds the fp32 absmax
    sidecar exchange (2 all_to_all + 2 all_gather per bucket).
    """
    from .comm import (INT8_BLOCK, plan_buckets, quantized_chunk,
                       ring_wire_bytes)
    block = block or INT8_BLOCK
    n = device_num
    preds: List[dict] = []

    def _emit(kind, payload, dtype):
        preds.append({"kind": kind, "payload_bytes": int(payload),
                      "wire_bytes": ring_wire_bytes(kind, payload, n),
                      "dtype": dtype})

    for b in plan_buckets(entries, bucket_mb):
        numel = sum(b.numels)
        if transport == "fp32":
            _emit("all_reduce", b.nbytes, b.dtype)
            continue
        chunk = quantized_chunk(numel, n, block)
        if transport == "bf16":
            _emit("all_to_all", n * chunk * 2, "bfloat16")
            _emit("all_gather", n * chunk * 2, "bfloat16")
        elif transport == "int8":
            _emit("all_to_all", n * chunk, "int8")
            _emit("all_to_all", n * (chunk // block) * 4, "float32")
            _emit("all_gather", n * chunk, "int8")
            _emit("all_gather", n * (chunk // block) * 4, "float32")
        else:
            raise ValueError(f"unknown transport {transport!r}")
    return preds


def count_hlo_collectives(hlo_text: str,
                          include_ppermute: bool = False
                          ) -> Dict[str, int]:
    """Count collective ops in lowered StableHLO / HLO text.

    Handles ``stablehlo.all_reduce``, classic ``all-reduce(``, and the
    async pair spelling after XLA's latency-hiding scheduler
    (``all-reduce-start(`` — the matching ``-done`` is not counted, so
    each async collective still counts once).

    ``include_ppermute`` adds ``collective-permute`` to the tally.  It
    is opt-in (the per-edge attribution pass uses it) because the
    legacy exact-count consumers — ``verify_grad_comm_emission`` and
    the declared ``allowed_gspmd`` diff — have no way to predict
    permutes, and a legitimate ppermute chain (ring attention, the
    SPMD pipeline) must not start tripping them.
    """
    import re
    pats = {
        "all_reduce": r"stablehlo\.all_reduce|all-reduce(?:-start)?\(",
        "all_gather": r"stablehlo\.all_gather|all-gather(?:-start)?\(",
        "all_to_all": r"stablehlo\.all_to_all|all-to-all(?:-start)?\(",
        "reduce_scatter":
            r"stablehlo\.reduce_scatter|reduce-scatter(?:-start)?\(",
    }
    if include_ppermute:
        pats["ppermute"] = (r"stablehlo\.collective_permute|"
                            r"collective-permute(?:-start)?\(")
    return {k: len(re.findall(p, hlo_text)) for k, p in pats.items()}


def verify_grad_comm_emission(hlo_text: str, prediction: List[dict],
                              extra: Optional[Dict[str, int]] = None) -> None:
    """Assert the lowered program contains exactly the predicted
    collectives (plus ``extra`` known ones, e.g. the scalar-loss pmean of
    a training step).  Raises AssertionError on mismatch."""
    want: Dict[str, int] = {}
    for p in prediction:
        want[p["kind"]] = want.get(p["kind"], 0) + 1
    for k, v in (extra or {}).items():
        want[k] = want.get(k, 0) + v
    got = count_hlo_collectives(hlo_text)
    bad = {k: (want.get(k, 0), got.get(k, 0))
           for k in set(want) | set(got)
           if want.get(k, 0) != got.get(k, 0)}
    if bad:
        raise AssertionError(
            f"emitted collectives do not match prediction "
            f"(kind: want/got): {bad}")


def predict_flat_update_collectives(entries, device_num: int,
                                    bucket_mb: float = 4.0,
                                    transport: str = "fp32",
                                    block: Optional[int] = None,
                                    zero: int = 2) -> List[dict]:
    """Predict the collectives of one reduce-scatter-only flat sync
    (flat dp-sharded optimizer state, ``Optimizer(flat_state=True)``).

    ``zero <= 2`` (params replicated at rest): per bucket, ONE
    reduce-scatter chain carrying the gradients (fp32: a single
    ``psum_scatter``; bf16/int8: the phase-1 quantized exchange only —
    the phase-2 regather of the all-reduce path is gone) plus ONE
    all-gather of the UPDATED parameters riding the bucket's WEIGHT
    dtype (tag ``param_comm``).  Zero gradient all-gathers, ever —
    exactly half the gradient wire bytes of the all-reduce path at the
    same transport.

    ``zero >= 3`` (params sharded at rest): the per-bucket all-gather
    moves to the FRONT of the step — the just-in-time ``param_gather``
    that materializes the working weights from the flat fp32 master
    before the forward — and the post-update gather disappears (only
    the 1/dp shard stays resident).  Same collective kinds, counts and
    wire bytes as ``zero=2``; only the tag/position differ.
    """
    from .comm import (INT8_BLOCK, plan_buckets, quantized_chunk,
                       ring_wire_bytes)
    block = block or INT8_BLOCK
    n = device_num
    preds: List[dict] = []

    def _emit(kind, payload, dtype, tag=None):
        p = {"kind": kind, "payload_bytes": int(payload),
             "wire_bytes": ring_wire_bytes(kind, payload, n),
             "dtype": dtype}
        if tag is not None:
            p["tag"] = tag
        preds.append(p)

    for b in plan_buckets(entries, bucket_mb):
        numel = sum(b.numels)
        chunk = quantized_chunk(numel, n, block)
        itemsize = np.dtype(b.dtype).itemsize
        if zero >= 3:
            # just-in-time weight gather from the flat master, before
            # any gradient exchange this step
            _emit("all_gather", n * chunk * itemsize, b.dtype,
                  tag="param_gather")
        if transport == "fp32":
            _emit("reduce_scatter", n * chunk * 4, "float32")
        elif transport == "bf16":
            _emit("all_to_all", n * chunk * 2, "bfloat16")
        elif transport == "int8":
            _emit("all_to_all", n * chunk, "int8")
            _emit("all_to_all", n * (chunk // block) * 4, "float32")
        else:
            raise ValueError(f"unknown transport {transport!r}")
        if zero < 3:
            # updated-param gather in the weight dtype (tag param_comm)
            _emit("all_gather", n * chunk * itemsize, b.dtype,
                  tag="param_comm")
    return preds


def predict_update_step_collectives(entries, device_num: int,
                                    transport: str = "fp32",
                                    bucket_mb: float = 4.0,
                                    block: Optional[int] = None,
                                    scalar_fetches: int = 1,
                                    flat: bool = False,
                                    clip: bool = False,
                                    zero: int = 2,
                                    opt_extra: Optional[Dict[str, int]]
                                    = None):
    """Step-level prediction for an explicit-grad-comm training
    executable: the coalesced gradient-sync collectives
    (:func:`predict_grad_comm_collectives`, or
    :func:`predict_flat_update_collectives` when ``flat`` — the
    reduce-scatter-only ZeRO-2/3 path, ``zero`` selecting whether the
    per-bucket weight gather is the post-update ``param_comm`` or the
    just-in-time ``param_gather`` of params-sharded-at-rest) plus one
    all_reduce (the scalar pmean) per scalar fetch, plus the
    global-norm-clip psum when the flat path clips (``clip``; the
    all-reduce path clips on full local grads with no collective).
    Returns ``(prediction, extra)`` in exactly the form
    :func:`verify_grad_comm_emission` consumes, so the general analysis
    pass (``hetu_tpu.analysis``) and direct HLO assertions share one
    predictor."""
    if flat:
        preds = predict_flat_update_collectives(
            entries, device_num, bucket_mb=bucket_mb,
            transport=transport, block=block, zero=zero)
    else:
        preds = predict_grad_comm_collectives(
            entries, device_num, bucket_mb=bucket_mb,
            transport=transport, block=block)
    n_ar = int(scalar_fetches) + (1 if (flat and clip) else 0)
    extra = {"all_reduce": n_ar} if n_ar else {}
    # optimizer-declared in-region collectives beyond the grad/param
    # chains (e.g. Adafactor's factored-stat psums)
    for k, v in (opt_extra or {}).items():
        extra[k] = extra.get(k, 0) + int(v)
    return preds, extra


class SplitPattern:
    """Contiguous vs. non-contiguous split (distributed_states.h:139)."""

    def __init__(self, contiguous: bool = True):
        self._contiguous = bool(contiguous)

    @property
    def is_contiguous(self) -> bool:
        return self._contiguous

    def check_equal(self, other: "SplitPattern") -> bool:
        return self._contiguous == other._contiguous

    def __repr__(self) -> str:
        return f"SplitPattern({'contig' if self._contiguous else 'noncontig'})"


class DistributedStatesUnion:
    """Per-pipeline list of DS for heterogeneous strategies.

    ``hetero_dim`` is the tensor dim along which the union members differ
    (-3/NULL when homogeneous); mirrors ``distributed_states.h:157-240``.
    """

    def __init__(self, ds_list: Sequence[DistributedStates],
                 hetero_dim: int = NULL_HETERO_DIM,
                 split_pattern: Optional[SplitPattern] = None):
        self._ds_list = list(ds_list)
        self._hetero_dim = hetero_dim
        self._split_pattern = split_pattern or SplitPattern(True)

    @property
    def ds_list(self) -> List[DistributedStates]:
        return list(self._ds_list)

    @property
    def hetero_dim(self) -> int:
        return self._hetero_dim

    @property
    def split_pattern(self) -> SplitPattern:
        return self._split_pattern

    def is_hetero(self) -> bool:
        return self._hetero_dim != NULL_HETERO_DIM

    def size(self) -> int:
        return len(self._ds_list)

    def get(self, i: int) -> DistributedStates:
        return self._ds_list[i]

    def get_default_ds(self) -> DistributedStates:
        if not self._ds_list:
            raise ValueError("empty DS union")
        return self._ds_list[0]

    def check_equal(self, other: "DistributedStatesUnion") -> bool:
        return (self._hetero_dim == other._hetero_dim
                and len(self._ds_list) == len(other._ds_list)
                and all(a.check_equal(b)
                        for a, b in zip(self._ds_list, other._ds_list)))

    def __repr__(self) -> str:
        h = f", hetero_dim={self._hetero_dim}" if self.is_hetero() else ""
        return f"DSUnion({self._ds_list!r}{h})"


class DistributedStatesHierarchy:
    """Per-strategy list of DS unions (``tensor.h:255`` ds_hierarchy)."""

    def __init__(self, unions: Sequence[DistributedStatesUnion] = ()):
        self._unions = list(unions)

    def add(self, union: DistributedStatesUnion) -> None:
        self._unions.append(union)

    def get(self, strategy_id: int) -> DistributedStatesUnion:
        return self._unions[strategy_id]

    def size(self) -> int:
        return len(self._unions)

    def __repr__(self) -> str:
        return f"DSHierarchy({self._unions!r})"
