"""Hot switching between parallelism strategies (elastic training).

TPU-native re-expression of the reference's SwitchExecGraph
(``hetu/graph/switch_exec_graph.{h,cc}``): live repartitioning of params /
grads / optimizer states when the execution plan changes (elastic scaling,
Malleus strategy retune).  The reference hand-builds a comm graph of
``BufferBatchedIsendIrecv`` transfers from a ``ParamSlice``/``ParamBlock``
intersection of the source and destination shardings
(``switch_exec_graph.h:459,672``); here the same intersection is computed
from ``jax.sharding`` index maps (:class:`SwitchPlan`, for introspection,
cost accounting and tests) while the data movement itself is a single
``jax.device_put`` per array — XLA emits the minimal
collective-permute/all-gather plan over ICI, and async dispatch overlaps
the transfers the way the reference overlaps its switch stream with
compute (``executable_graph.h:307-315``).

Switch modes mirror ``switch_exec_graph.h:42-48``.
"""
from __future__ import annotations

import enum
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


class SwitchMode(enum.Enum):
    """What to migrate (reference SWITCH_ORIGIN_PARAM / TRANSFER_PARAM /
    ..._AND_OPTIMIZER / CURRENT_GRAD / ACCUMULATE_GRAD)."""
    ORIGIN_PARAM = "origin_param"
    TRANSFER_PARAM = "transfer_param"              # + dtype transfer
    ORIGIN_PARAM_AND_OPTIMIZER = "origin_param_and_optimizer"
    TRANSFER_PARAM_AND_OPTIMIZER = "transfer_param_and_optimizer"
    CURRENT_GRAD = "current_grad"
    ACCUMULATE_GRAD = "accumulate_grad"


def _slices_key(idx) -> Tuple[Tuple[int, Optional[int]], ...]:
    return tuple((s.start or 0, s.stop) for s in idx)


def _overlap(a, b, shape):
    """Intersection of two index tuples; None if empty."""
    out = []
    for sa, sb, dim in zip(a, b, shape):
        lo = max(sa.start or 0, sb.start or 0)
        hi = min(sa.stop if sa.stop is not None else dim,
                 sb.stop if sb.stop is not None else dim)
        if lo >= hi:
            return None
        out.append((lo, hi))
    return tuple(out)


def symbolic_repack_transfers(numel: int, itemsize: int,
                              src_ranges: Dict[int, Tuple[int, int]],
                              dst_ranges: Dict[int, Tuple[int, int]]
                              ) -> List[Tuple[int, int, Tuple[int, int],
                                              int]]:
    """Device-free twin of :class:`SwitchPlan` for the 1-D flat-state
    repack (dp resize of the per-bucket dp-sharded optimizer buffers).

    ``src_ranges`` / ``dst_ranges`` map rank -> half-open ``(lo, hi)``
    interval of the flat buffer owned before / after the resize.
    Returns ``(dst_rank, src_rank, (lo, hi), nbytes)`` transfers sorted
    deterministically — every rank deriving this plan independently
    must produce the same list, which is exactly the invariant the
    schedule verifier's ``switch-repack-divergence`` rule checks.
    """
    transfers: List[Tuple[int, int, Tuple[int, int], int]] = []
    for dst, (dlo, dhi) in sorted(dst_ranges.items()):
        for src, (slo, shi) in sorted(src_ranges.items()):
            lo, hi = max(dlo, slo), min(dhi, shi, numel)
            if lo >= hi:
                continue
            transfers.append((dst, src, (lo, hi), (hi - lo) * itemsize))
    transfers.sort()
    return transfers


class SwitchPlan:
    """ParamSlice/ParamBlock intersection of two shardings of one tensor.

    ``transfers`` lists (dst_device, src_device, global_slice) triples: for
    every slice a destination device needs, the closest source replica is
    picked (reference placement algorithms FCFS/round-robin,
    switch_exec_graph.h:26-32 — we use nearest-by-id which matches
    round-robin on TPU meshes).
    """

    def __init__(self, shape: Tuple[int, ...], itemsize: int,
                 src: NamedSharding, dst: NamedSharding):
        self.shape = tuple(shape)
        self.src, self.dst = src, dst
        src_map = src.devices_indices_map(self.shape)
        dst_map = dst.devices_indices_map(self.shape)
        # group src replicas per distinct slice
        owners: Dict[Tuple, List[Any]] = {}
        for d, idx in src_map.items():
            owners.setdefault(_slices_key(idx), []).append(d)
        self.transfers: List[Tuple[Any, Any, Tuple]] = []
        local_bytes = 0
        moved_bytes = 0
        for dd, didx in dst_map.items():
            for skey, sdevs in owners.items():
                sidx = tuple(slice(lo, hi) for lo, hi in skey)
                ov = _overlap(didx, sidx, self.shape)
                if ov is None:
                    continue
                # prefer a source replica already on the dst device
                src_dev = next((d for d in sdevs if d.id == dd.id),
                               min(sdevs, key=lambda d: abs(d.id - dd.id)))
                n = int(np.prod([hi - lo for lo, hi in ov])) * itemsize
                if src_dev.id == dd.id:
                    local_bytes += n
                else:
                    moved_bytes += n
                self.transfers.append((dd, src_dev, ov))
        self.local_bytes = local_bytes
        self.moved_bytes = moved_bytes


class SwitchProfile:
    """Per-switch accounting (reference SWITCH_PROFILE_LEVEL TIME/MEMORY)."""

    def __init__(self):
        self.num_tensors = 0
        self.total_bytes = 0
        self.moved_bytes = 0
        # bytes routed through a flat-state unpack -> migrate -> repack
        # (dp resize of per-bucket dp-sharded optimizer buffers)
        self.repack_bytes = 0
        self.seconds = 0.0

    def as_dict(self) -> Dict[str, float]:
        return {"num_tensors": self.num_tensors,
                "total_bytes": self.total_bytes,
                "moved_bytes": self.moved_bytes,
                "repack_bytes": self.repack_bytes,
                "seconds": self.seconds}


def switch_state(state: Dict[Any, jax.Array],
                 dst_shardings: Dict[Any, NamedSharding],
                 dtype: Optional[Any] = None,
                 profile: Optional[SwitchProfile] = None
                 ) -> Dict[Any, jax.Array]:
    """Reshard every array in ``state`` to its destination sharding.

    All device_puts are issued before any result is waited on, so
    transfers overlap (the reference's batched-isend-irecv buffers).
    """
    out: Dict[Any, jax.Array] = {}
    t0 = time.perf_counter()
    for key, arr in state.items():
        dst = dst_shardings.get(key)
        cast = dtype is not None and hasattr(arr, "dtype") \
            and jnp.issubdtype(arr.dtype, jnp.floating) \
            and arr.dtype != jnp.dtype(dtype)
        if dst is None or not hasattr(arr, "shape"):
            out[key] = arr.astype(dtype) if cast else arr
            continue
        if profile is not None and isinstance(arr, jax.Array):
            profile.num_tensors += 1
            profile.total_bytes += arr.nbytes
            if isinstance(arr.sharding, NamedSharding):
                plan = SwitchPlan(arr.shape, arr.dtype.itemsize,
                                  arr.sharding, dst)
                profile.moved_bytes += plan.moved_bytes
        if cast:
            # fuse cast + reshard in one compiled program: no host-side
            # intermediate, and a narrowing cast rides the wire narrow
            out[key] = jax.jit(lambda x, d=dtype: x.astype(d),
                               out_shardings=dst)(arr)
        else:
            out[key] = jax.device_put(arr, dst)
    for v in out.values():
        if isinstance(v, jax.Array):
            v.block_until_ready()
    if profile is not None:
        profile.seconds += time.perf_counter() - t0
    return out


class SwitchExecGraph:
    """Migrate a DefineAndRunGraph (+optimizer) to a new mesh / shardings.

    ``pspec_overrides`` maps param Tensor -> new PartitionSpec; params not
    listed keep their current spec (same axis names, new mesh extents —
    the common dp/tp ratio change).  After the switch the graph's plan
    pool entries for the old strategy are left in place (keyed by
    strategy id) and a new strategy id is activated, mirroring the
    reference's ExecGraphPlan pool + SwitchParams flow
    (``define_and_run_graph.cc:1073-1129``).
    """

    def __init__(self, graph, new_mesh: Mesh,
                 pspec_overrides: Optional[Dict[Any, PartitionSpec]] = None,
                 mode: SwitchMode = SwitchMode.ORIGIN_PARAM_AND_OPTIMIZER,
                 dtype: Optional[Any] = None):
        self.graph = graph
        self.new_mesh = new_mesh
        self.pspec_overrides = dict(pspec_overrides or {})
        self.mode = mode
        self.dtype = dtype
        self.profile = SwitchProfile()

    def _dst_sharding(self, t) -> Optional[NamedSharding]:
        spec = self.pspec_overrides.get(t)
        if spec is None:
            spec = getattr(t, "pspec", None)
        if spec is None:
            return None
        # drop axis names the new mesh doesn't have (e.g. pp removed)
        def _fix(entry):
            if entry is None:
                return None
            names = entry if isinstance(entry, tuple) else (entry,)
            kept = tuple(n for n in names if n in self.new_mesh.axis_names)
            if not kept:
                return None
            return kept if len(kept) > 1 else kept[0]
        spec = PartitionSpec(*[_fix(e) for e in spec])
        return NamedSharding(self.new_mesh, spec)

    def switch(self, optimizer=None) -> SwitchProfile:
        g = self.graph
        param_modes = (SwitchMode.ORIGIN_PARAM, SwitchMode.TRANSFER_PARAM,
                       SwitchMode.ORIGIN_PARAM_AND_OPTIMIZER,
                       SwitchMode.TRANSFER_PARAM_AND_OPTIMIZER)
        opt_modes = (SwitchMode.ORIGIN_PARAM_AND_OPTIMIZER,
                     SwitchMode.TRANSFER_PARAM_AND_OPTIMIZER)
        if optimizer is None and self.mode in opt_modes:
            raise ValueError(f"mode {self.mode} migrates optimizer states "
                             "but no optimizer was passed")
        tensors = {tid: t for tid, t in g._var_tensors.items()}
        dsts = {}
        fixed_specs = {}
        for tid, t in tensors.items():
            sh = self._dst_sharding(t)
            if sh is not None:
                dsts[tid] = sh
                fixed_specs[t] = sh.spec
            else:
                # no pspec means replicated — the array must still leave
                # the old device set when the mesh shrinks/moves
                dsts[tid] = NamedSharding(self.new_mesh, PartitionSpec())
        dtype = self.dtype if self.mode in (
            SwitchMode.TRANSFER_PARAM,
            SwitchMode.TRANSFER_PARAM_AND_OPTIMIZER) else None
        if self.mode in param_modes:
            g._var_data = switch_state(g._var_data, dsts, dtype=dtype,
                                       profile=self.profile)
            # persist the (axis-fixed) specs so the next run builds
            # NamedShardings valid on the new mesh
            for t, spec in fixed_specs.items():
                t.pspec = spec
        # optimizer states follow their param's sharding (+ ZeRO re-deduced
        # against the new mesh)
        if optimizer is not None and self.mode in opt_modes \
                and optimizer._state:
            old_mesh = g.mesh
            g.mesh = self.new_mesh
            try:
                if any(k.startswith("flat_") for k in optimizer._state) \
                        and getattr(optimizer, "_flat_layout", None) \
                        is not None:
                    # flat dp-sharded state: repack through the layout
                    # index instead of bailing to per-param state
                    self._switch_flat(optimizer, tensors)
                else:
                    self._switch_per_param(optimizer, tensors)
            finally:
                g.mesh = old_mesh
        # grads: pending accumulations must always follow the params off
        # the old mesh (they share the params' layouts), and the grad-only
        # modes migrate exactly them
        if g._grad_accum:
            g._grad_accum = switch_state(g._grad_accum, dsts,
                                         profile=self.profile)
        g.mesh = self.new_mesh
        return self.profile

    def _switch_per_param(self, optimizer, tensors) -> None:
        """Per-parameter optimizer-state migration (graph mesh already
        set to the new mesh by the caller)."""
        g = self.graph
        new_state: Dict[str, Any] = {}
        optimizer._shardings = {}
        for slot, tree in optimizer._state.items():
            if not isinstance(tree, dict):
                # non-dict slots — scalar step counters AND
                # structured pytrees (Adafactor's optax state) —
                # are committed to the old device set after a
                # run.  Param-shaped leaves keyed by tensor id
                # (e.g. optax momentum) follow their param's
                # sharding; everything else (factored vectors,
                # counters) replicates — so a momentum-bearing
                # Adafactor can't materialize a full replicated
                # state copy per device mid-switch.
                repl = NamedSharding(self.new_mesh, PartitionSpec())

                def _place(path, a):
                    if not isinstance(a, jax.Array):
                        return a
                    sh = repl
                    for k in reversed(path):
                        if isinstance(k, jax.tree_util.DictKey):
                            t = tensors.get(k.key)
                            if t is not None \
                                    and tuple(t.concrete_shape()) \
                                    == tuple(a.shape):
                                cand = optimizer._state_sharding(
                                    t, a, g)
                                if cand is not None:
                                    sh = cand
                            break
                    return jax.device_put(a, sh)
                tree = jax.tree_util.tree_map_with_path(_place, tree)
                new_state[slot] = tree
                continue
            slot_dsts = {}
            for tid, arr in tree.items():
                t = tensors.get(tid)
                if t is None:
                    continue
                sh = optimizer._state_sharding(t, arr, g)
                if sh is None:
                    # fully-replicated on the NEW device set — the
                    # state must still leave the old mesh
                    sh = NamedSharding(self.new_mesh,
                                       PartitionSpec())
                slot_dsts[tid] = sh
                optimizer._shardings[tid] = sh
            new_state[slot] = switch_state(tree, slot_dsts,
                                           profile=self.profile)
        optimizer._state = new_state

    def _switch_flat(self, optimizer, tensors) -> None:
        """Flat dp-sharded optimizer state across a mesh change (graph
        mesh already set to the new mesh by the caller).

        A dp resize changes the bucket chunk quantization, so the flat
        buffers cannot simply be resharded: each per-bucket buffer is
        unpacked through the OLD :class:`FlatStateLayout` index into the
        per-param view, those arrays migrate onto the new device set
        (with the usual :class:`SwitchPlan` wire accounting), and the
        state is repacked under the NEW dp's layout — it never leaves
        the flat regime, so the next train step's reduce-scatter
        geometry is immediately valid with no per-param fallback step.
        The repacked payload is counted in ``profile.repack_bytes``.
        """
        from ..optim.flat_state import FlatStateLayout, sync_order
        g = self.graph
        old_lay = optimizer._flat_layout
        st = optimizer._state
        dp_axis = optimizer.dp_axis
        if dp_axis not in self.new_mesh.axis_names:
            raise ValueError(
                f"flat_state optimizer needs axis {dp_axis!r} on the new "
                f"mesh; got {self.new_mesh.axis_names}")
        dp = self.new_mesh.shape[dp_axis]
        slots = sorted(k[len("flat_"):] for k in st
                       if k.startswith("flat_") and k != "flat_master")
        xs = sync_order([tensors[k] for k in old_lay.index
                         if k in tensors])
        # per-param view through the OLD index (fp32, padding dropped)
        per: Dict[str, Dict[Any, jax.Array]] = {
            "master": old_lay.unpack(st["flat_master"])}
        for s in slots:
            per[s] = old_lay.unpack(st[f"flat_{s}"])
        # each per-param piece follows its param's (ZeRO re-deduced)
        # sharding on the new mesh for the wire trip, replicated when
        # no dp split applies
        slot_dsts = {}
        for t in xs:
            arr = per["master"].get(t.id)
            if arr is None:
                continue
            sh = optimizer._state_sharding(t, arr, g)
            slot_dsts[t.id] = sh if sh is not None else NamedSharding(
                self.new_mesh, PartitionSpec())
        for name in per:
            per[name] = switch_state(per[name], slot_dsts,
                                     profile=self.profile)
            self.profile.repack_bytes += sum(
                a.nbytes for a in per[name].values()
                if isinstance(a, jax.Array))
        # repack under the new dp: same entries, new chunk quantization
        new_lay = FlatStateLayout(old_lay.entries, dp,
                                  bucket_mb=old_lay.bucket_mb,
                                  block=old_lay.block)
        sh_flat = NamedSharding(self.new_mesh, PartitionSpec(dp_axis))
        repl = NamedSharding(self.new_mesh, PartitionSpec())
        new_state: Dict[str, Any] = {}
        for key, val in st.items():
            if key == "flat_master":
                new_state[key] = [jax.device_put(a, sh_flat)
                                  for a in new_lay.pack(per["master"])]
            elif key.startswith("flat_"):
                new_state[key] = [
                    jax.device_put(a, sh_flat)
                    for a in new_lay.pack(per[key[len("flat_"):]])]
            else:
                # step counter + any replicated extra state (e.g.
                # Adafactor's factored stats): optimizers whose extras
                # depend on the bucket geometry re-derive them via the
                # repack hook
                val = optimizer._flat_repack_extra(key, val, old_lay,
                                                   new_lay)
                new_state[key] = jax.tree_util.tree_map(
                    lambda a: jax.device_put(a, repl)
                    if isinstance(a, jax.Array) else a, val)
        # mesh-bound caches from the old topology must not leak through
        optimizer._shardings = {}
        optimizer._param_shardings = {}
        optimizer._param_base_shardings = {}
        optimizer._flat_layout = new_lay
        optimizer._state = new_state
        optimizer._packed_var_writes = getattr(g, "_var_writes", 0)
        if optimizer.zero >= 3:
            # ZeRO-3 at rest: the migrated working copies go back to
            # their dp-sharded resting layout on the new mesh
            for t in xs:
                arr = g._var_data.get(t.id)
                if arr is None or not hasattr(arr, "shape"):
                    continue
                sh = optimizer._state_sharding(t, arr, g)
                if sh is None:
                    continue
                optimizer._param_shardings[t.id] = sh
                g._var_data[t.id] = jax.device_put(arr, sh)
