"""Ring attention — context parallelism over a mesh axis.

TPU-native re-expression of the reference's ``AttnCommRing``
(``hetu/graph/ops/ParallelAttention.h:342``, ``.cc:611,781``): the sequence
is sharded over the ``cp`` mesh axis; KV blocks circulate the ring
(``lax.ppermute`` — the reference's ``BatchedISendIRecv`` ring exchange)
while each rank runs blockwise flash attention on its local Q against the
visiting KV, merging partial results with online log-sum-exp correction
(the reference's ``ExecCorr``).  XLA overlaps the ppermute with the
per-round kernels the way the reference overlaps its comm/attn CUDA
streams via events.

Per-pair mask classes mirror ``AttnMask`` CAUSAL/FULL/EMPTY
(``ParallelAttention.h:25``) for the NORMAL (contiguous) split pattern;
the backward ring piggybacks dKV accumulators around the ring exactly one
full cycle so they land home (reference grad piggyback, ``.cc:781``).

Usage: inside ``shard_map`` with the sequence dim sharded over
``axis_name``; or via :func:`ring_attention_sharded` which wraps the
shard_map for [b, s, h, d] inputs.
"""
from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.pallas.flash_attention import (_flash_bwd, _flash_fwd,
                                          flash_attention_with_lse)


def _merge(acc, o_r, lse_r):
    """Online LSE merge of one round's (normalized out, lse) into the
    accumulator (reference ExecCorr, ParallelAttention.h:361).

    m/denom/lse live in [b, h, s]; the out accumulator in [b, s, h, d].
    """
    m, denom, out = acc
    m_new = jnp.maximum(m, lse_r)
    # where lse_r == -inf (empty round) the contribution vanishes;
    # exp(-inf - -inf) would be nan, so guard the all-empty case
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    c_old = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
    c_new = jnp.where(jnp.isfinite(lse_r), jnp.exp(lse_r - m_safe), 0.0)
    denom_new = denom * c_old + c_new
    to_out = lambda c: c.transpose(0, 2, 1)[..., None]  # [b,h,s]->[b,s,h,1]
    out_new = out * to_out(c_old) + o_r * to_out(c_new)
    return m_new, denom_new, out_new


def _pair_fwd(q, k, v, scale, mask_kind):
    """(out, lse) of one (q-rank, kv-rank) pair; mask_kind 0=causal 1=full
    2=empty."""
    b, s, h, d = q.shape

    def causal_fn(_):
        o, lse = _flash_fwd(q, k, v, scale, True, None)
        return o.astype(jnp.float32), lse  # branch dtypes must match empty_fn

    def full_fn(_):
        o, lse = _flash_fwd(q, k, v, scale, False, None)
        return o.astype(jnp.float32), lse

    def empty_fn(_):
        return (jnp.zeros((b, s, h, d), jnp.float32),
                jnp.full((b, h, s), -jnp.inf, jnp.float32))

    return lax.switch(mask_kind, [causal_fn, full_fn, empty_fn], None)


def _pair_bwd(q, k, v, do, out, lse, scale, mask_kind):
    """dq, dk, dv of one pair given global lse; empty pairs short-circuit."""
    def causal_fn(_):
        return _flash_bwd(scale, True, None, (q, k, v, out, lse), do)

    def full_fn(_):
        return _flash_bwd(scale, False, None, (q, k, v, out, lse), do)

    def empty_fn(_):
        return (jnp.zeros_like(q), jnp.zeros_like(k), jnp.zeros_like(v))

    return lax.switch(mask_kind, [causal_fn, full_fn, empty_fn], None)


def _mask_kind(my_rank, kv_rank, causal: bool):
    """NORMAL split pattern: earlier ranks' KV fully visible, own rank
    causal, later ranks empty (ParallelAttention.h:25 CAUSAL/FULL/EMPTY)."""
    if not causal:
        return jnp.int32(1)
    return jnp.where(kv_rank == my_rank, 0,
                     jnp.where(kv_rank < my_rank, 1, 2)).astype(jnp.int32)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_attn(q, k, v, axis_name, scale, causal):
    out, _ = _ring_fwd_impl(q, k, v, axis_name, scale, causal)
    return out


def _ring_fwd_impl(q, k, v, axis_name, scale, causal):
    cp = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, s, h, d = q.shape
    perm = [(i, (i + 1) % cp) for i in range(cp)]

    def body(r, carry):
        (k_cur, v_cur), acc = carry
        kv_rank = (my - r) % cp
        kind = _mask_kind(my, kv_rank, causal)
        o_r, lse_r = _pair_fwd(q, k_cur, v_cur, scale, kind)
        acc = _merge(acc, o_r, lse_r)
        # rotate KV to the next rank (skippable on last round, but keeping
        # it makes the loop uniform; XLA overlaps it with the next round)
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt), acc

    init_acc = (jnp.full((b, h, s), -jnp.inf, jnp.float32),   # m
                jnp.zeros((b, h, s), jnp.float32),            # denom
                jnp.zeros((b, s, h, d), jnp.float32))         # out (bqhd)
    # note: out accum uses [b, s, h, d] but m/denom use [b, h, s]; transpose
    # lse-space corrections into out-space on the fly inside _merge
    (_, _), (m, denom, out_acc) = lax.fori_loop(
        0, cp, body, ((k, v), init_acc))
    safe = jnp.where(denom == 0.0, 1.0, denom)
    # denom is [b, h, s]; out_acc is [b, s, h, d]
    out = out_acc / safe.transpose(0, 2, 1)[..., None]
    lse = jnp.where(denom == 0.0, -jnp.inf, m + jnp.log(safe))
    return out.astype(q.dtype), lse


def _ring_fwd_rule(q, k, v, axis_name, scale, causal):
    out, lse = _ring_fwd_impl(q, k, v, axis_name, scale, causal)
    return out, (q, k, v, out, lse)


def _ring_bwd_rule(axis_name, scale, causal, res, do):
    q, k, v, out, lse = res
    cp = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % cp) for i in range(cp)]

    def body(r, carry):
        (k_cur, v_cur), (dk_cur, dv_cur), dq_acc = carry
        kv_rank = (my - r) % cp
        kind = _mask_kind(my, kv_rank, causal)
        dq_c, dk_c, dv_c = _pair_bwd(q, k_cur, v_cur, do, out, lse,
                                     scale, kind)
        dq_acc = dq_acc + dq_c.astype(jnp.float32)
        dk_cur = dk_cur + dk_c.astype(jnp.float32)
        dv_cur = dv_cur + dv_c.astype(jnp.float32)
        # rotate KV and its grad accumulators together (grad piggyback):
        # after cp shifts they arrive back at the owning rank
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        dk_nxt = lax.ppermute(dk_cur, axis_name, perm)
        dv_nxt = lax.ppermute(dv_cur, axis_name, perm)
        return (k_nxt, v_nxt), (dk_nxt, dv_nxt), dq_acc

    init = ((k, v), (jnp.zeros(k.shape, jnp.float32),
                     jnp.zeros(v.shape, jnp.float32)),
            jnp.zeros(q.shape, jnp.float32))
    (_, (dk, dv), dq) = lax.fori_loop(0, cp, body, init)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_attn.defvjp(_ring_fwd_rule, _ring_bwd_rule)


def ring_attention(q, k, v, axis_name: str = "cp", causal: bool = True,
                   softmax_scale: Optional[float] = None) -> jax.Array:
    """Ring attention on sequence-sharded [b, s_local, h, d] inputs.

    Must be called inside shard_map/pjit with ``axis_name`` in scope.
    """
    scale = softmax_scale if softmax_scale is not None \
        else 1.0 / math.sqrt(q.shape[-1])
    return _ring_attn(q, k, v, axis_name, scale, causal)


def ring_attention_sharded(q, k, v, mesh, axis_name: str = "cp",
                           causal: bool = True,
                           softmax_scale: Optional[float] = None,
                           batch_axis: Optional[str] = "dp",
                           head_axis: Optional[str] = "tp") -> jax.Array:
    """Convenience wrapper: shard_map ring attention over a mesh for global
    [b, s, h, d] arrays (seq sharded over ``axis_name``; batch over
    ``batch_axis``; heads over ``head_axis`` — the reference's TP head
    split + CP combination)."""
    from jax.sharding import PartitionSpec as P
    from .comm import shard_map

    def axis_or_none(name):
        return name if (name and name in mesh.axis_names) else None

    spec = P(axis_or_none(batch_axis), axis_name, axis_or_none(head_axis),
             None)

    fn = shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis_name, causal,
                                       softmax_scale),
        mesh, (spec, spec, spec), spec)
    return fn(q, k, v)
