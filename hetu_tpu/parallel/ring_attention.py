"""Ring attention — context parallelism over a mesh axis.

TPU-native re-expression of the reference's ``AttnCommRing``
(``hetu/graph/ops/ParallelAttention.h:342``, ``.cc:611,781``): the sequence
is sharded over the ``cp`` mesh axis; KV blocks circulate the ring
(``lax.ppermute`` — the reference's ``BatchedISendIRecv`` ring exchange)
while each rank runs blockwise flash attention on its local Q against the
visiting KV, merging partial results with online log-sum-exp correction
(the reference's ``ExecCorr``).  XLA overlaps the ppermute with the
per-round kernels the way the reference overlaps its comm/attn CUDA
streams via events.

Split patterns (reference ``SplitPattern`` NORMAL/SYM,
``ParallelAttention.h:19``, env ``HETU_PARALLEL_ATTN_SPLIT_PATTERN``):

- ``normal`` — contiguous split.  Under a causal mask the per-pair
  classes are CAUSAL/FULL/EMPTY and the *last* rank does ~cp× the work
  of rank 0 (the imbalance SYM exists to kill).
- ``sym`` — symmetric (head+tail) split: the global sequence is cut into
  ``2·cp`` chunks and rank i holds chunks ``(i, 2cp-1-i)``.  Per-pair
  masks then fall into the reference's five classes
  (``AttnMask`` CAUSAL/ROW/COL/EMPTY/FULL, ``.cc:140-200``): the pair
  with itself is the composite causal (head-causal / tail-sees-head /
  tail-causal), earlier ranks' KV is visible only in its head half
  (COL), later ranks only to the tail Q half (ROW) — every (rank, round)
  does exactly ``s_local²/2`` score work, i.e. perfectly balanced.

Variable per-rank sequence lengths (reference ``_seq_len_list``) and
packed/varlen sequences ride the same mechanism: local segment ids
(global doc ids, ``-1`` = padding) travel the ring *with* their KV block
and mask score entries whose q/kv ids differ.  This works under BOTH
split patterns: the segment mask is an id-equality test — independent of
position order — so it composes multiplicatively with the SYM structural
masks (CAUSAL_SYM/COL/ROW), each branch slicing the travelling id pair to
its q/kv halves (reference supports ``_seq_len_list`` under SYM,
``ParallelAttention.h:342``, ``.cc:140-200``).

Usage: inside ``shard_map`` with the sequence dim sharded over
``axis_name``; or via :func:`ring_attention_sharded` which wraps the
shard_map for [b, s, h, d] inputs.
"""
from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .comm import axis_size

from ..ops.pallas.flash_attention import _flash_bwd, _flash_fwd

# pair-mask classes (reference AttnMask, ParallelAttention.h:25);
# at runtime they are compressed into per-pattern 0..2 branch indices
# (see _mask_kind) so only reachable branches compile
CAUSAL, FULL, EMPTY, CAUSAL_SYM, COL, ROW = range(6)


def _seg_slice(segs, qs, ks):
    """Slice a (q_ids, kv_ids) tuple to the given q/kv ranges; None
    ranges keep the full side, segs=None stays None (shared by the SYM
    fwd/bwd branches so their masks cannot diverge)."""
    if segs is None:
        return None
    q_ids, kv_ids = segs
    return (q_ids if qs is None else q_ids[:, qs],
            kv_ids if ks is None else kv_ids[:, ks])


def _merge(acc, o_r, lse_r):
    """Online LSE merge of one round's (normalized out, lse) into the
    accumulator (reference ExecCorr, ParallelAttention.h:361).

    m/denom/lse live in [b, h, s]; the out accumulator in [b, s, h, d].
    """
    m, denom, out = acc
    m_new = jnp.maximum(m, lse_r)
    # where lse_r == -inf (empty round) the contribution vanishes;
    # exp(-inf - -inf) would be nan, so guard the all-empty case
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    c_old = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
    c_new = jnp.where(jnp.isfinite(lse_r), jnp.exp(lse_r - m_safe), 0.0)
    denom_new = denom * c_old + c_new
    to_out = lambda c: c.transpose(0, 2, 1)[..., None]  # [b,h,s]->[b,s,h,1]
    out_new = out * to_out(c_old) + o_r * to_out(c_new)
    return m_new, denom_new, out_new


def _pair_fwd(q, k, v, scale, mask_kind, segs, pattern, causal):
    """(out, lse) of one (q-rank, kv-rank) pair.

    ``mask_kind`` is a 0..2 class index whose meaning depends on the
    static ``pattern`` (normal: CAUSAL/FULL/EMPTY; sym:
    CAUSAL_SYM/COL/ROW) so only the three reachable branches compile;
    ``segs`` is None or a ``(q_ids [b,s], kv_ids [b,s])`` tuple — under
    SYM each branch slices the pair to its q/kv halves.
    """
    b, s, h, d = q.shape
    sh = s // 2

    def causal_fn(_):
        o, lse = _flash_fwd(q, k, v, scale, True, segs)
        return o.astype(jnp.float32), lse  # branch dtypes must match empty_fn

    def full_fn(_):
        o, lse = _flash_fwd(q, k, v, scale, False, segs)
        return o.astype(jnp.float32), lse

    def empty_fn(_):
        return (jnp.zeros((b, s, h, d), jnp.float32),
                jnp.full((b, h, s), -jnp.inf, jnp.float32))

    def causal_sym_fn(_):
        # [[causal, empty], [full, causal]] on (head, tail) halves:
        # qh vs kh causal; qt vs full kv causal shifted by sh
        o1, l1 = _flash_fwd(q[:, :sh], k[:, :sh], v[:, :sh], scale, True,
                            _seg_slice(segs, slice(None, sh), slice(None, sh)))
        o2, l2 = _flash_fwd(q[:, sh:], k, v, scale, True,
                            _seg_slice(segs, slice(sh, None), None),
                            causal_offset=sh)
        return (jnp.concatenate([o1, o2], axis=1).astype(jnp.float32),
                jnp.concatenate([l1, l2], axis=2))

    def col_fn(_):
        # all q rows see only the kv head half (earlier chunk)
        o, lse = _flash_fwd(q, k[:, :sh], v[:, :sh], scale, False,
                            _seg_slice(segs, None, slice(None, sh)))
        return o.astype(jnp.float32), lse

    def row_fn(_):
        # only the q tail half sees this (later) rank's kv
        o2, l2 = _flash_fwd(q[:, sh:], k, v, scale, False,
                            _seg_slice(segs, slice(sh, None), None))
        o = jnp.concatenate(
            [jnp.zeros((b, sh, h, d), jnp.float32), o2.astype(jnp.float32)],
            axis=1)
        lse = jnp.concatenate(
            [jnp.full((b, h, sh), -jnp.inf, jnp.float32), l2], axis=2)
        return o, lse

    if not causal:
        return full_fn(None)
    branches = [causal_sym_fn, col_fn, row_fn] if pattern == "sym" \
        else [causal_fn, full_fn, empty_fn]
    return lax.switch(mask_kind, branches, None)


def _pair_bwd(q, k, v, do, out, lse, scale, mask_kind, segs, pattern,
              causal):
    """dq, dk, dv of one pair given global lse; empty pairs short-circuit.
    Branch selection mirrors :func:`_pair_fwd`."""
    b, s, h, d = q.shape
    sh = s // 2

    def causal_fn(_):
        return _flash_bwd(scale, True, segs, (q, k, v, out, lse), do)

    def full_fn(_):
        return _flash_bwd(scale, False, segs, (q, k, v, out, lse), do)

    def empty_fn(_):
        return (jnp.zeros_like(q), jnp.zeros_like(k), jnp.zeros_like(v))

    def causal_sym_fn(_):
        dq1, dk1, dv1 = _flash_bwd(
            scale, True, _seg_slice(segs, slice(None, sh), slice(None, sh)),
            (q[:, :sh], k[:, :sh], v[:, :sh], out[:, :sh], lse[:, :, :sh]),
            do[:, :sh])
        dq2, dk2, dv2 = _flash_bwd(
            scale, True, _seg_slice(segs, slice(sh, None), None),
            (q[:, sh:], k, v, out[:, sh:], lse[:, :, sh:]),
            do[:, sh:], causal_offset=sh)
        dq = jnp.concatenate([dq1, dq2], axis=1)
        pad = jnp.zeros((b, sh, h, d), dk1.dtype)
        dk = jnp.concatenate([dk1, pad], axis=1) + dk2
        dv = jnp.concatenate([dv1, pad], axis=1) + dv2
        return dq, dk, dv

    def col_fn(_):
        dq, dkh, dvh = _flash_bwd(
            scale, False, _seg_slice(segs, None, slice(None, sh)),
            (q, k[:, :sh], v[:, :sh], out, lse), do)
        pad = jnp.zeros((b, s - sh, h, d), dkh.dtype)
        return (dq, jnp.concatenate([dkh, pad], axis=1),
                jnp.concatenate([dvh, pad], axis=1))

    def row_fn(_):
        dq2, dk, dv = _flash_bwd(
            scale, False, _seg_slice(segs, slice(sh, None), None),
            (q[:, sh:], k, v, out[:, sh:], lse[:, :, sh:]), do[:, sh:])
        dq = jnp.concatenate(
            [jnp.zeros((b, sh, h, d), dq2.dtype), dq2], axis=1)
        return dq, dk, dv

    if not causal:
        return full_fn(None)
    branches = [causal_sym_fn, col_fn, row_fn] if pattern == "sym" \
        else [causal_fn, full_fn, empty_fn]
    return lax.switch(mask_kind, branches, None)


def _mask_kind(my_rank, kv_rank, causal: bool, pattern: str):
    """Classify the (q-rank, kv-rank) pair into a 0..2 branch index
    (reference GenerateAttnInfo, ParallelAttention.cc:140-200): under
    "normal" 0/1/2 = CAUSAL/FULL/EMPTY, under "sym" = CAUSAL_SYM/COL/ROW
    — in both patterns self-pair / earlier-rank / later-rank."""
    if not causal:
        return jnp.int32(0)  # unused: _pair_* short-circuit to full
    return jnp.where(kv_rank == my_rank, 0,
                     jnp.where(kv_rank < my_rank, 1, 2)).astype(jnp.int32)


def _ring_segs(q_ids, kv_ids, use_segs):
    return (q_ids, kv_ids) if use_segs else None


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _ring_attn(q, k, v, seg_ids, axis_name, scale, causal, pattern,
               use_segs):
    out, _ = _ring_fwd_impl(q, k, v, seg_ids, axis_name, scale, causal,
                            pattern, use_segs)
    return out


def _ring_fwd_impl(q, k, v, seg_ids, axis_name, scale, causal, pattern,
                   use_segs):
    cp = axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, s, h, d = q.shape
    perm = [(i, (i + 1) % cp) for i in range(cp)]
    # kv-side ids: padding (-1) maps to -2 so q-pad never matches kv-pad
    kv_ids0 = jnp.where(seg_ids < 0, -2, seg_ids)

    def body(r, carry):
        (k_cur, v_cur, kvseg_cur), acc = carry
        kv_rank = (my - r) % cp
        kind = _mask_kind(my, kv_rank, causal, pattern)
        o_r, lse_r = _pair_fwd(q, k_cur, v_cur, scale, kind,
                               _ring_segs(seg_ids, kvseg_cur, use_segs),
                               pattern, causal)
        acc = _merge(acc, o_r, lse_r)
        # rotate KV (and its segment ids) to the next rank (reference
        # BatchedISendIRecv ring); XLA overlaps with the next round
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        s_nxt = lax.ppermute(kvseg_cur, axis_name, perm)
        return (k_nxt, v_nxt, s_nxt), acc

    init_acc = (jnp.full((b, h, s), -jnp.inf, jnp.float32),   # m
                jnp.zeros((b, h, s), jnp.float32),            # denom
                jnp.zeros((b, s, h, d), jnp.float32))         # out (bqhd)
    (_, _, _), (m, denom, out_acc) = lax.fori_loop(
        0, cp, body, ((k, v, kv_ids0), init_acc))
    safe = jnp.where(denom == 0.0, 1.0, denom)
    # denom is [b, h, s]; out_acc is [b, s, h, d]
    out = out_acc / safe.transpose(0, 2, 1)[..., None]
    lse = jnp.where(denom == 0.0, -jnp.inf, m + jnp.log(safe))
    return out.astype(q.dtype), lse


def _ring_fwd_rule(q, k, v, seg_ids, axis_name, scale, causal, pattern,
                   use_segs):
    out, lse = _ring_fwd_impl(q, k, v, seg_ids, axis_name, scale, causal,
                              pattern, use_segs)
    return out, (q, k, v, seg_ids, out, lse)


def _ring_bwd_rule(axis_name, scale, causal, pattern, use_segs, res, do):
    q, k, v, seg_ids, out, lse = res
    cp = axis_size(axis_name)
    my = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % cp) for i in range(cp)]
    kv_ids0 = jnp.where(seg_ids < 0, -2, seg_ids)

    def body(r, carry):
        (k_cur, v_cur, kvseg_cur), (dk_cur, dv_cur), dq_acc = carry
        kv_rank = (my - r) % cp
        kind = _mask_kind(my, kv_rank, causal, pattern)
        dq_c, dk_c, dv_c = _pair_bwd(
            q, k_cur, v_cur, do, out, lse, scale, kind,
            _ring_segs(seg_ids, kvseg_cur, use_segs), pattern, causal)
        dq_acc = dq_acc + dq_c.astype(jnp.float32)
        dk_cur = dk_cur + dk_c.astype(jnp.float32)
        dv_cur = dv_cur + dv_c.astype(jnp.float32)
        # rotate KV and its grad accumulators together (grad piggyback):
        # after cp shifts they arrive back at the owning rank
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        s_nxt = lax.ppermute(kvseg_cur, axis_name, perm)
        dk_nxt = lax.ppermute(dk_cur, axis_name, perm)
        dv_nxt = lax.ppermute(dv_cur, axis_name, perm)
        return (k_nxt, v_nxt, s_nxt), (dk_nxt, dv_nxt), dq_acc

    init = ((k, v, kv_ids0), (jnp.zeros(k.shape, jnp.float32),
                              jnp.zeros(v.shape, jnp.float32)),
            jnp.zeros(q.shape, jnp.float32))
    (_, (dk, dv), dq) = lax.fori_loop(0, cp, body, init)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            np.zeros(seg_ids.shape, jax.dtypes.float0))


_ring_attn.defvjp(_ring_fwd_rule, _ring_bwd_rule)


# ---------------------------------------------------------------------------
# SYM layout helpers


def sym_indices(s_global: int, cp: int) -> np.ndarray:
    """Permutation putting the global sequence into SYM ring layout:
    2·cp chunks, rank i's shard = [chunk i, chunk 2cp-1-i]."""
    assert s_global % (2 * cp) == 0, \
        f"seq {s_global} not divisible by 2*cp={2 * cp}"
    ch = s_global // (2 * cp)
    idx = []
    for i in range(cp):
        idx.extend(range(i * ch, (i + 1) * ch))
        idx.extend(range((2 * cp - 1 - i) * ch, (2 * cp - i) * ch))
    return np.asarray(idx, dtype=np.int64)


def sym_inverse_indices(s_global: int, cp: int) -> np.ndarray:
    fwd = sym_indices(s_global, cp)
    inv = np.empty_like(fwd)
    inv[fwd] = np.arange(s_global)
    return inv


def sym_shard(x, cp: int, axis: int = 1):
    """Reorder a GLOBAL array so contiguous cp-sharding yields the SYM
    layout (apply before feeding a seq-sharded pjit/shard_map)."""
    return jnp.take(x, jnp.asarray(sym_indices(x.shape[axis], cp)),
                    axis=axis)


def sym_unshard(x, cp: int, axis: int = 1):
    return jnp.take(x, jnp.asarray(sym_inverse_indices(x.shape[axis], cp)),
                    axis=axis)


def pair_score_area(cp: int, pattern: str, causal: bool = True
                    ) -> np.ndarray:
    """Relative attention-score work per (rank, round), in units of
    (s_local)² — the balance diagnostic the tests assert on.  Under
    NORMAL+causal the last rank does ~cp× rank 0's work; under SYM every
    entry is 0.5."""
    area = np.zeros((cp, cp))
    for i in range(cp):
        for r in range(cp):
            j = (i - r) % cp
            if not causal:
                area[i, r] = 1.0
            elif pattern == "sym":
                area[i, r] = 0.5  # CAUSAL_SYM, COL and ROW all cover half
            else:
                area[i, r] = 0.5 if j == i else (1.0 if j < i else 0.0)
    return area


# ---------------------------------------------------------------------------
# public API


def ring_attention(q, k, v, axis_name: str = "cp", causal: bool = True,
                   softmax_scale: Optional[float] = None,
                   split_pattern: str = "normal",
                   segment_ids: Optional[jax.Array] = None,
                   seq_len: Optional[jax.Array] = None) -> jax.Array:
    """Ring attention on sequence-sharded [b, s_local, h, d] inputs.

    Must be called inside shard_map/pjit with ``axis_name`` in scope.

    ``split_pattern``: "normal" (contiguous) or "sym" (symmetric causal
    load balancing; shard with :func:`sym_shard`).
    ``segment_ids``: local [b, s_local] global doc ids for packed
    sequences; ``-1`` marks padding.  Under SYM the ids are in the
    rank's local (head+tail chunk) layout and ride the ring with the KV.
    ``seq_len``: this rank's valid length (scalar; positions >= seq_len
    are padding) — the reference's per-rank ``_seq_len_list``.  May be
    combined with ``segment_ids``.
    """
    scale = softmax_scale if softmax_scale is not None \
        else 1.0 / math.sqrt(q.shape[-1])
    b, s = q.shape[0], q.shape[1]
    use_segs = segment_ids is not None or seq_len is not None
    if split_pattern == "sym" and s % 2 != 0:
        raise ValueError(f"sym split needs an even local seq, got {s}")
    if segment_ids is None:
        seg_ids = jnp.zeros((b, s), jnp.int32)
    else:
        seg_ids = segment_ids.astype(jnp.int32)
    if seq_len is not None:
        pos = jnp.arange(s, dtype=jnp.int32)[None, :]
        seg_ids = jnp.where(pos < seq_len, seg_ids, -1)
    return _ring_attn(q, k, v, seg_ids, axis_name, scale, causal,
                      split_pattern, use_segs)


def ring_attention_sharded(q, k, v, mesh, axis_name: str = "cp",
                           causal: bool = True,
                           softmax_scale: Optional[float] = None,
                           batch_axis: Optional[str] = "dp",
                           head_axis: Optional[str] = "tp",
                           split_pattern: str = "normal",
                           segment_ids: Optional[jax.Array] = None,
                           seq_lens: Optional[jax.Array] = None
                           ) -> jax.Array:
    """Convenience wrapper: shard_map ring attention over a mesh for global
    [b, s, h, d] arrays (seq sharded over ``axis_name``; batch over
    ``batch_axis``; heads over ``head_axis`` — the reference's TP head
    split + CP combination).

    With ``split_pattern="sym"`` the caller's GLOBAL arrays are reordered
    into the SYM layout on the way in and back on the way out.
    ``seq_lens``: [cp] per-rank valid lengths (``_seq_len_list``).
    ``segment_ids``: global [b, s] packed doc ids (-1 pad).
    """
    from jax.sharding import PartitionSpec as P
    from .comm import shard_map

    cp = mesh.shape[axis_name]
    _maybe_profile_ring(q, k, v, mesh, axis_name, causal, split_pattern,
                        softmax_scale)

    def axis_or_none(name):
        return name if (name and name in mesh.axis_names) else None

    spec = P(axis_or_none(batch_axis), axis_name, axis_or_none(head_axis),
             None)
    if split_pattern == "sym":
        q, k, v = (sym_shard(x, cp, axis=1) for x in (q, k, v))

    if segment_ids is not None or seq_lens is not None:
        b, s = q.shape[0], q.shape[1]
        segs = jnp.zeros((b, s), jnp.int32) if segment_ids is None \
            else segment_ids.astype(jnp.int32)
        if split_pattern == "sym":
            # ids follow their tokens into the SYM layout; seq_lens below
            # then mask per-rank LOCAL tail positions (the reference's
            # _seq_len_list semantics), i.e. in the reordered frame.
            segs = sym_shard(segs, cp, axis=1)
        if seq_lens is not None:
            s_local = s // cp
            pos = jnp.arange(s, dtype=jnp.int32)[None, :]
            local_pos = pos % s_local
            rank = pos // s_local
            lens = jnp.asarray(seq_lens, jnp.int32)[rank]
            segs = jnp.where(local_pos < lens, segs, -1)

        fn = shard_map(
            lambda q, k, v, sg: ring_attention(
                q, k, v, axis_name, causal, softmax_scale, split_pattern,
                segment_ids=sg),
            mesh, (spec, spec, spec, P(axis_or_none(batch_axis),
                                       axis_name)), spec)
        out = fn(q, k, v, segs)
        if split_pattern == "sym":
            out = sym_unshard(out, cp, axis=1)
        return out

    fn = shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis_name, causal,
                                       softmax_scale, split_pattern),
        mesh, (spec, spec, spec), spec)
    out = fn(q, k, v)
    if split_pattern == "sym":
        out = sym_unshard(out, cp, axis=1)
    return out


def profile_ring_rounds(q, k, v, mesh, axis_name: str = "cp",
                        causal: bool = True,
                        split_pattern: str = "normal",
                        softmax_scale: Optional[float] = None,
                        reps: int = 3):
    """Measured per-round wall times of the KV ring (the reference's
    optional AttnCommRing per-round profiling, ParallelAttention.h:411-413).

    Each round r is executed as its own jitted program (KV pre-shifted by
    r hops, one _pair_fwd per rank), so the per-(rank, round) cost —
    which pair_score_area predicts analytically — can be measured.
    Returns a list of ``cp`` median times in seconds.

    For the comm/attn/corr/grad decomposition use
    :func:`profile_ring_breakdown`.
    """
    rows = profile_ring_breakdown(q, k, v, mesh, axis_name, causal,
                                  split_pattern, softmax_scale, reps,
                                  include_bwd=False)
    return [r["attn_s"] for r in rows]


def profile_ring_breakdown(q, k, v, mesh, axis_name: str = "cp",
                           causal: bool = True,
                           split_pattern: str = "normal",
                           softmax_scale: Optional[float] = None,
                           reps: int = 3, include_bwd: bool = True,
                           metrics=None):
    """Per-round comm / attn / correction / grad timings of the KV ring —
    the TPU-native analogue of the reference's event-based per-round
    instrumentation (``ParallelAttention.h:411-413`` attn/corr events on
    the comm/attn streams, env-gated).

    XLA fuses the real ring into one program, so intra-program events
    don't exist; instead each phase of each round is jitted standalone:

    - ``comm_s``  — one KV+ids ring hop (``lax.ppermute`` pair)
    - ``attn_s``  — ``_pair_fwd`` for that round's mask class
    - ``corr_s``  — the online-LSE ``_merge`` of the round's partials
    - ``grad_s``  — ``_pair_bwd`` (when ``include_bwd``)

    Returns a list of ``cp`` dicts (one per round).  Pass a
    ``utils.metrics.Metrics`` as ``metrics`` to record each round's times
    as ``ring_{comm,attn,corr,grad}_s`` series (step = round index) — the
    CP bench table.  Also triggered per-shape inside
    :func:`ring_attention_sharded` by ``HETU_TPU_RING_PROFILE=1``.
    """
    import time as _time
    from jax.sharding import PartitionSpec as P
    from .comm import shard_map

    cp = mesh.shape[axis_name]
    scale = softmax_scale if softmax_scale is not None \
        else 1.0 / math.sqrt(q.shape[-1])
    if split_pattern == "sym":
        q, k, v = (sym_shard(x, cp, axis=1) for x in (q, k, v))
    b, s = q.shape[0], q.shape[1] // cp
    spec = P(None, axis_name, None, None)
    sspec = P(None, axis_name)
    seg0 = jnp.zeros((b, s * cp), jnp.int32)
    perm1 = [(i, (i + 1) % cp) for i in range(cp)]

    def fetch(out):
        # block_until_ready can be a no-op under remote-relay PJRT
        # backends (bench.py:47): force a real host fetch of one element
        # (plain first-element slice — ravel would gather the whole
        # sharded array and pollute the timing)
        jax.block_until_ready(out)
        leaf = jax.tree_util.tree_leaves(out)[0]
        np.asarray(leaf[(0,) * leaf.ndim])

    def timed(fn, args):
        fetch(fn(*args))                     # compile + warm
        ts = []
        for _ in range(reps):
            t0 = _time.perf_counter()
            fetch(fn(*args))
            ts.append(_time.perf_counter() - t0)
        return float(np.median(ts))

    def comm_fn(k, v, sg):
        return (lax.ppermute(k, axis_name, perm1),
                lax.ppermute(v, axis_name, perm1),
                lax.ppermute(sg, axis_name, perm1))

    # one ring hop: both the timed comm phase AND the between-round KV
    # rotation, so attn_s times _pair_fwd alone on pre-rotated inputs
    comm_jit = jax.jit(shard_map(comm_fn, mesh, (spec, spec, sspec),
                                 (spec, spec, sspec)))

    def attn_fn(r):
        def f(q, k_r, v_r):
            my = lax.axis_index(axis_name)
            kind = _mask_kind(my, (my - r) % cp, causal, split_pattern)
            o, lse = _pair_fwd(q, k_r, v_r, scale, kind, None,
                               split_pattern, causal)
            return o, lse                    # lse: [b, h, s_local]
        return jax.jit(shard_map(f, mesh, (spec, spec, spec),
                                 (spec, P(None, None, axis_name))))

    def _corr_impl(o_r, lse_r):
        bq, sl, h, d = o_r.shape
        acc = (jnp.full((bq, h, sl), -jnp.inf, jnp.float32),
               jnp.zeros((bq, h, sl), jnp.float32),
               jnp.zeros((bq, sl, h, d), jnp.float32))
        m, denom, out = _merge(acc, o_r.astype(jnp.float32), lse_r)
        return out

    corr_jit = jax.jit(shard_map(
        _corr_impl, mesh, (spec, P(None, None, axis_name)), spec))

    def bwd_fn(r):
        def f(q, k_r, v_r, do, out, lse):
            my = lax.axis_index(axis_name)
            kind = _mask_kind(my, (my - r) % cp, causal, split_pattern)
            return _pair_bwd(q, k_r, v_r, do, out, lse,
                             scale, kind, None, split_pattern, causal)
        lspec = P(None, None, axis_name)
        return jax.jit(shard_map(
            f, mesh, (spec, spec, spec, spec, spec, lspec),
            (spec, spec, spec)))

    rows = []
    k_r, v_r, sg_r = k, v, seg0
    for r in range(cp):
        afn = attn_fn(r)
        o_r, lse_r = afn(q, k_r, v_r)
        jax.block_until_ready(o_r)
        row = {
            "round": r,
            "comm_s": timed(comm_jit, (k_r, v_r, sg_r)),
            "attn_s": timed(lambda *a: afn(*a)[0], (q, k_r, v_r)),
            "corr_s": timed(corr_jit, (o_r, lse_r)),
        }
        if include_bwd:
            bfn = bwd_fn(r)
            row["grad_s"] = timed(
                lambda q, kk, vv: bfn(q, kk, vv, o_r, o_r, lse_r)[0],
                (q, k_r, v_r))
        rows.append(row)
        if metrics is not None:
            metrics.log(r, **{f"ring_{kk[:-2]}_s": vv
                              for kk, vv in row.items() if kk != "round"})
        # rotate KV to the next round's position (same hop the ring takes)
        k_r, v_r, sg_r = comm_jit(k_r, v_r, sg_r)
        jax.block_until_ready(k_r)
    return rows


def _maybe_profile_ring(q, k, v, mesh, axis_name, causal, split_pattern,
                        softmax_scale):
    """HETU_TPU_RING_PROFILE=1: once per (shape, pattern), run the
    per-round breakdown and log the CP table (reference env
    HETU_PARALLEL_ATTN_PROFILE gating its ring events)."""
    import os
    if os.environ.get("HETU_TPU_RING_PROFILE") != "1":
        return
    if any(isinstance(x, jax.core.Tracer) for x in (q, k, v)):
        # called during tracing (ring inside a jitted step): timings
        # would be trace-time garbage; profile only eager concrete calls
        return
    key = (q.shape, k.shape, causal, split_pattern, mesh.shape[axis_name])
    if key in _RING_PROFILED:
        return
    _RING_PROFILED.add(key)
    from ..utils.logging_utils import get_logger
    from ..utils.metrics import Metrics
    log = get_logger("ring_attention")
    path = os.environ.get("HETU_TPU_RING_PROFILE_FILE")
    rec = Metrics(log_file=path) if path else Metrics()
    try:
        rows = profile_ring_breakdown(
            q, k, v, mesh, axis_name, causal, split_pattern, softmax_scale,
            include_bwd=os.environ.get("HETU_TPU_RING_PROFILE_BWD",
                                       "1") == "1",
            metrics=rec)
    finally:
        rec.close()
    hdr = "round   comm_ms   attn_ms   corr_ms" + \
        ("   grad_ms" if "grad_s" in rows[0] else "")
    lines = [hdr]
    for row in rows:
        cells = [f"{row['round']:5d}"] + [
            f"{row[c] * 1e3:9.3f}" for c in
            ("comm_s", "attn_s", "corr_s", "grad_s") if c in row]
        lines.append(" ".join(cells))
    log.info("ring attention per-round profile (%s, cp=%d, s_local=%d):\n%s",
             split_pattern, mesh.shape[axis_name],
             q.shape[1] // mesh.shape[axis_name], "\n".join(lines))
    return rows


_RING_PROFILED: set = set()
