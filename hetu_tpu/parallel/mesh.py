"""Device mesh model and DS -> jax.sharding lowering.

The reference binds a ``DistributedStates`` to an ordered ``DeviceGroup``
and derives NCCL groups from the DS order (``distributed_states.cc:399``
``get_devices_by_dim``).  On TPU the analogue is a ``jax.sharding.Mesh``:
we build a mesh whose *flat device order matches the DS placement order* and
whose axes are the DS order dims, then lower the DS to a
``NamedSharding(mesh, PartitionSpec(...))``.  XLA/GSPMD then derives the
collective groups the same way ``get_devices_by_dim`` does — by striding the
flat device list along each axis.

Two usage styles:

* **Standard 3D/4D training** — build one global mesh with named axes
  (``dp``/``cp``/``tp``/``pp``...) via :func:`create_mesh` and annotate with
  `PartitionSpec` by axis name (the idiomatic jax path, used by the nn
  parallel layers).
* **DS-driven** — arbitrary ``DistributedStates`` lowered by
  :func:`ds_to_named_sharding` (used by resharding, checkpoint, hot switch).
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec
P = PartitionSpec

from .dstates import DUPLICATE, PARTIAL, DistributedStates

# Canonical axis names for the standard training mesh.
AXIS_DP = "dp"      # data parallel
AXIS_CP = "cp"      # context (sequence) parallel — ring attention
AXIS_TP = "tp"      # tensor/model parallel
AXIS_PP = "pp"      # pipeline parallel (stage axis, used by shard_map PP)
AXIS_EP = "ep"      # expert parallel


def create_mesh(shape: Dict[str, int],
                devices: Optional[Sequence[jax.Device]] = None,
                allow_split_physical_axes: bool = True) -> Mesh:
    """Create a Mesh with named axes from a ``{axis: size}`` dict.

    Axis order in ``shape`` is significant: later axes are
    innermost/fastest-varying (ride ICI first), mirroring the DS ``order``
    semantics.  Standard layout: ``{"pp": ..., "dp": ..., "cp": ...,
    "tp": ...}`` keeps TP on the innermost (highest-bandwidth) axis.
    """
    names = tuple(shape.keys())
    sizes = tuple(int(shape[n]) for n in names)
    n = int(np.prod(sizes)) if sizes else 1
    if devices is None:
        try:
            # Topology-aware assignment on real TPU slices.
            from jax.experimental import mesh_utils
            dev_array = mesh_utils.create_device_mesh(
                sizes, allow_split_physical_axes=allow_split_physical_axes)
            return Mesh(dev_array, names)
        except Exception:
            devices = jax.devices()[:n]
    if len(devices) < n:
        raise ValueError(f"need {n} devices for mesh {shape}, got {len(devices)}")
    dev_array = np.asarray(devices[:n]).reshape(sizes)
    return Mesh(dev_array, names)


def single_device_mesh(device: Optional[jax.Device] = None) -> Mesh:
    dev = device or jax.devices()[0]
    return Mesh(np.asarray([dev]).reshape((1,)), (AXIS_DP,))


def mesh_axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape.get(axis, 1) if axis in mesh.axis_names else 1


# ---------------------------------------------------------------------------
# DS -> NamedSharding lowering
# ---------------------------------------------------------------------------

def _axis_name_for(dim: int) -> str:
    if dim == DUPLICATE:
        return "_dup"
    if dim == PARTIAL:
        return "_partial"
    return f"_s{dim}"


def ds_to_mesh_and_spec(ds: DistributedStates,
                        devices: Sequence[jax.Device],
                        ) -> Tuple[Mesh, PartitionSpec]:
    """Lower a DS (+ its ordered placement devices) to (Mesh, PartitionSpec).

    The mesh axes are the DS ``order`` dims, outermost first, so that the
    flat device order of the mesh equals the DS device numbering — the exact
    invariant ``map_device_to_state_index`` (``distributed_states.cc:371``)
    encodes.  Duplicate/partial dims become unassigned mesh axes
    (replication); a *partial* tensor is represented as replicated storage
    whose values are partial sums — reduction placement is decided at graph
    level via ``deduce_comm_kind``.
    """
    if len(devices) != ds.device_num:
        raise ValueError(
            f"DS over {ds.device_num} devices, got {len(devices)}")
    order = ds.order
    if not order:
        mesh = Mesh(np.asarray(devices).reshape((1,)), ("_dup",))
        return mesh, P()
    sizes = tuple(ds.get_dim(o) for o in order)
    names = tuple(_axis_name_for(o) for o in order)
    dev_array = np.asarray(devices).reshape(sizes)
    mesh = Mesh(dev_array, names)
    ndim = max((o for o in order if o >= 0), default=-1) + 1
    spec = [None] * ndim
    for o in order:
        if o >= 0:
            spec[o] = _axis_name_for(o)
    return mesh, P(*spec)


def ds_to_named_sharding(ds: DistributedStates,
                         devices: Sequence[jax.Device]) -> NamedSharding:
    mesh, spec = ds_to_mesh_and_spec(ds, devices)
    return NamedSharding(mesh, spec)


def ds_from_partition_spec(mesh: Mesh, spec: PartitionSpec,
                           partial_axes: Sequence[str] = (),
                           zero: bool = False) -> DistributedStates:
    """Inverse lowering: a (mesh, pspec) pair back to a DistributedStates.

    Used to reason about GSPMD-produced shardings in DS terms (tests,
    checkpoint resharding).  ``partial_axes`` marks mesh axes over which the
    array holds partial sums (unreduced), which GSPMD cannot express but DS
    can (dim -2).
    """
    device_num = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
    states: Dict[int, int] = {}
    dim_of_axis: Dict[str, int] = {}
    spec_tuple = tuple(spec) if spec is not None else ()
    for d, entry in enumerate(spec_tuple):
        if entry is None:
            continue
        axes = entry if isinstance(entry, (tuple, list)) else (entry,)
        n = 1
        for a in axes:
            n *= mesh.shape[a]
            dim_of_axis[a] = d
        if n > 1:
            states[d] = states.get(d, 1) * n
    partial = 1
    for a in partial_axes:
        partial *= mesh.shape[a]
        dim_of_axis[a] = PARTIAL
    if partial > 1:
        states[PARTIAL] = partial
    dup = device_num // int(np.prod(list(states.values()))) if states else device_num
    if dup > 1:
        states[DUPLICATE] = dup
    # Order: mesh axis order, outermost first; replicated axes -> DUPLICATE.
    order: List[int] = []
    for a in mesh.axis_names:
        d = dim_of_axis.get(a, DUPLICATE)
        if d not in order:
            order.append(d)
    order = [o for o in order if states.get(o, 1) > 1]
    return DistributedStates(device_num, states, order, zero=zero)


# ---------------------------------------------------------------------------
# Test/simulation support
# ---------------------------------------------------------------------------

def force_virtual_cpu_devices(n: int = 8) -> None:
    """Request ``n`` virtual CPU devices (must run before jax backend init).

    This is the multi-device simulation story the reference lacks
    (SURVEY.md §4 takeaway): DP/TP/PP/CP tests run on
    ``--xla_force_host_platform_device_count`` fake devices.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
