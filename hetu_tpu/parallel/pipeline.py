"""Pipeline parallelism — SPMD collective-permute pipelining.

TPU-native re-expression of the reference's pipeline engine
(``hetu/graph/executable_graph.cc:1343`` GPipe / ``:1376`` PipeDream-Flush
schedules, stage-boundary P2P ops on ``kP2PStream``): under XLA's SPMD
model every pp rank runs the same program, so stages are expressed as
*stacked* layer parameters sharded over the ``pp`` mesh axis, and the
schedule is a ``lax.scan`` over ticks in which activations hop stages via
``lax.ppermute`` (the P2P send/recv).  Micro-batches stream through the
ring; the pipeline fills/drains over ``M + S - 1`` ticks (GPipe bubble).

The backward pass is jax.grad through the scan: XLA transposes the
ppermute into the reverse hop and reverses the schedule; with
``jax.checkpoint`` on the stage body the activation-memory profile matches
PipeDream-Flush (the reference hand-writes these schedules; the compiler
derives them here).

Composes with dp/tp/cp: only ``pp`` is manual (partial-manual shard_map);
inner ops keep their GSPMD shardings on the other axes.
"""
from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .comm import comm_tag


def pipeline_spmd(stage_fn: Callable[[Any, jax.Array], Any],
                  stage_params: Any,
                  x: jax.Array,
                  num_micro_batches: int,
                  mesh: Mesh,
                  pp_axis: str = "pp",
                  remat: bool = True,
                  with_aux: bool = False):
    """Run ``x`` through S pipeline stages (S = mesh pp size).

    stage_params: pytree whose leaves are stacked [S, ...] and sharded over
    ``pp_axis`` on dim 0; ``stage_fn(local_params, x_mb)`` applies ONE
    stage (leaves passed with the leading stage dim stripped) and must
    preserve the activation shape (homogeneous stages — transformer
    blocks).  x: [batch, ...], micro-batched internally along dim 0.
    Returns [batch, ...] last-stage outputs, replicated over pp.

    ``with_aux=True``: stage_fn returns ``(y, aux_scalar)`` (e.g. the MoE
    balance loss); the pipeline returns ``(out, aux)`` where aux is the
    micro-batch MEAN of the per-stage aux sums — warmup/drain ticks (which
    compute on garbage activations) are masked out, matching the pp=1
    per-micro-batch accumulation exactly.
    """
    S = mesh.shape[pp_axis]
    M = num_micro_batches
    assert x.shape[0] % M == 0, \
        f"batch {x.shape[0]} not divisible by {M} micro-batches"
    if S == 1:
        params0 = jax.tree_util.tree_map(lambda p: p[0], stage_params)
        outs = [stage_fn(params0, mb) for mb in jnp.split(x, M, axis=0)]
        if with_aux:
            aux = sum(o[1] for o in outs) / M
            return jnp.concatenate([o[0] for o in outs], axis=0), aux
        return jnp.concatenate(outs, axis=0)

    mb_size = x.shape[0] // M
    x_mb = x.reshape(M, mb_size, *x.shape[1:])
    uniform_fn = stage_fn if with_aux \
        else (lambda p, v: (stage_fn(p, v), jnp.zeros((), jnp.float32)))
    body = jax.checkpoint(uniform_fn) if remat else uniform_fn
    fwd_perm = [(i, (i + 1) % S) for i in range(S)]

    def pp_fn(params_local, x_mb_local):
        params = jax.tree_util.tree_map(lambda p: p[0], params_local)
        stage = lax.axis_index(pp_axis)
        T = M + S - 1

        def tick(carry, t):
            recv, out_buf, aux_sum = carry
            # stage 0 consumes micro-batch t (clamped during drain)
            inp_idx = jnp.clip(t, 0, M - 1)
            first_in = lax.dynamic_index_in_dim(x_mb_local, inp_idx, 0,
                                                keepdims=False)
            x_in = jnp.where(stage == 0, first_in, recv)
            y, aux = body(params, x_in)
            # this stage holds micro-batch t-stage at this tick; outside
            # [0, M) it's warmup/drain garbage — mask its aux out
            mb_idx = t - stage
            live = jnp.logical_and(mb_idx >= 0, mb_idx < M)
            aux_sum = aux_sum + jnp.where(live, aux.astype(jnp.float32), 0.0)
            # the last stage finishes micro-batch t-(S-1) at this tick
            out_idx = t - (S - 1)
            valid = jnp.logical_and(stage == S - 1,
                                    jnp.logical_and(out_idx >= 0,
                                                    out_idx < M))
            safe_idx = jnp.clip(out_idx, 0, M - 1)
            cur = lax.dynamic_index_in_dim(out_buf, safe_idx, 0,
                                           keepdims=False)
            out_buf = lax.dynamic_update_index_in_dim(
                out_buf, jnp.where(valid, y, cur), safe_idx, 0)
            # hop to the next stage (reference P2P send/recv at stage
            # boundaries); XLA overlaps this with the next tick's compute.
            # comm_tag so the analyzer attributes the scan-body ppermute
            # chain to the pipeline (M + S - 1 hops x activation bytes)
            with comm_tag("pipeline/hop"):
                send = lax.ppermute(y, pp_axis, fwd_perm)
            return (send, out_buf, aux_sum), None

        init_recv = jnp.zeros((mb_size, *x_mb_local.shape[2:]),
                              x_mb_local.dtype)
        out_sds, _ = jax.eval_shape(
            lambda p, v: uniform_fn(p, v), params,
            jax.ShapeDtypeStruct(init_recv.shape, init_recv.dtype))
        out_buf0 = jnp.zeros((M, *out_sds.shape), out_sds.dtype)
        (_, out_buf, aux_sum), _ = lax.scan(
            tick, (init_recv, out_buf0, jnp.zeros((), jnp.float32)),
            jnp.arange(T))
        # out_buf is only valid on the last stage; broadcast it so the
        # (replicated) out_specs is truthful
        mask = (stage == S - 1).astype(out_buf.dtype)
        with comm_tag("pipeline/collect"):
            return lax.psum(out_buf * mask, pp_axis), \
                lax.psum(aux_sum, pp_axis) / M

    from .comm import shard_map
    fn = shard_map(
        pp_fn, mesh,
        in_specs=(P(pp_axis), P()),
        out_specs=(P(), P()),
        axis_names={pp_axis}, check_rep=False)
    out_mb, aux = fn(stage_params, x_mb)
    out = out_mb.reshape(M * mb_size, *out_mb.shape[2:])
    return (out, aux) if with_aux else out


def spmd_hop_schedule(num_micro_batches: int, num_stages: int):
    """The symbolic collective sequence one SPMD pipeline step issues
    per rank: ``M + S - 1`` tick-loop ``ppermute`` hops (the scanned
    ``pipeline/hop`` site above) followed by the two ``pipeline/collect``
    psums that broadcast the last stage's outputs and the aux scalar.

    Every pp rank runs the same scanned program, so the sequence is
    rank-uniform by construction — the schedule verifier
    (:mod:`hetu_tpu.analysis.schedule`) consumes this to model the SPMD
    pipeline's collective stream without tracing it.
    """
    T = num_micro_batches + num_stages - 1
    return [("ppermute", "pipeline/hop")] * T \
        + [("all_reduce", "pipeline/collect")] * 2


def stack_stage_params(per_layer_params: list, num_stages: int):
    """Stack L homogeneous per-layer param pytrees into [S, L/S, ...] leaves
    (dim 0 to be sharded over pp); the reference's layer-range-to-stage
    assignment (DeviceGroupUnion placement) specialized to equal ranges."""
    L = len(per_layer_params)
    assert L % num_stages == 0, \
        f"{L} layers not divisible into {num_stages} stages"
    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs, axis=0), *per_layer_params)
    return jax.tree_util.tree_map(
        lambda p: p.reshape(num_stages, L // num_stages, *p.shape[1:]),
        stacked)
