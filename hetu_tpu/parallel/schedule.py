"""Pipeline schedules — GPipe and PipeDream-Flush (1F1B).

TPU-native counterpart of the reference's schedule generators
(``hetu/graph/executable_graph.cc:1343`` ``GenerateGpipeSchedule`` and
``:1376`` ``GeneratePipedreamFlushSchedule``): emit, per pipeline stage,
the ordered list of forward/backward micro-batch tasks the executor runs.
The MPMD runtime (:mod:`hetu_tpu.parallel.pipeline_mpmd`) consumes these
task lists; unlike the reference's per-rank CUDA task loop, here a single
controller enqueues tasks onto per-stage device submeshes and XLA's async
dispatch provides the overlap.

The property that makes 1F1B 1F1B: the number of *in-flight* micro-batches
(forward done, backward not yet) at stage ``s`` never exceeds ``S - s``
(pipeline depth bound), while GPipe's grows to ``M``.  ``max_in_flight``
computes that bound for any schedule so tests (and the runtime's memory
accounting) can assert it.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Literal, Sequence

TaskKind = Literal["F", "B"]


@dataclass(frozen=True)
class Task:
    kind: str           # "F" | "B"
    micro_batch: int

    def __repr__(self) -> str:  # compact: F0, B3
        return f"{self.kind}{self.micro_batch}"


def generate_gpipe_schedule(num_stages: int, num_micro_batches: int,
                            inference: bool = False) -> List[List[Task]]:
    """All forwards, then all backwards (fill/drain).

    Reference ``GenerateGpipeSchedule`` (executable_graph.cc:1343).
    """
    out: List[List[Task]] = []
    for _ in range(num_stages):
        tasks = [Task("F", m) for m in range(num_micro_batches)]
        if not inference:
            tasks += [Task("B", m) for m in range(num_micro_batches)]
        out.append(tasks)
    return out


def generate_pipedream_flush_schedule(num_stages: int,
                                      num_micro_batches: int,
                                      inference: bool = False
                                      ) -> List[List[Task]]:
    """1F1B (PipeDream-Flush): warmup forwards, steady-state alternating
    one-forward-one-backward, cooldown backwards, synchronous flush at the
    end of the step.

    Reference ``GeneratePipedreamFlushSchedule``
    (executable_graph.cc:1376).  Stage ``s`` (0-indexed) runs
    ``min(M, S-1-s)`` warmup forwards, so at most ``S - s`` micro-batches
    are ever in flight.
    """
    S, M = num_stages, num_micro_batches
    if inference:
        return generate_gpipe_schedule(S, M, inference=True)
    out: List[List[Task]] = []
    for s in range(S):
        warmup = min(M, S - 1 - s)
        tasks: List[Task] = [Task("F", m) for m in range(warmup)]
        f, b = warmup, 0
        # steady state: 1F1B
        while f < M:
            tasks.append(Task("F", f))
            f += 1
            tasks.append(Task("B", b))
            b += 1
        # cooldown: drain remaining backwards
        while b < M:
            tasks.append(Task("B", b))
            b += 1
        out.append(tasks)
    return out


def generate_interleaved_1f1b_schedule(num_stages: int,
                                       num_micro_batches: int,
                                       num_chunks: int
                                       ) -> List[List[Task]]:
    """Interleaved 1F1B with virtual pipeline stages (Megatron-LM's
    interleaved schedule; beyond the reference, which has GPipe + plain
    1F1B only).

    Each physical stage ``s`` hosts ``num_chunks`` model chunks; virtual
    stage ``v = chunk * S + s`` forms a depth ``V = S * C`` pipeline
    whose per-physical-stage bubble shrinks ~C-fold: ranks start work on
    chunk 0 of later micro-batches while chunk 1 of earlier ones is
    still in flight.  Returns per-VIRTUAL-stage task lists (length
    ``S * C``) directly consumable by the MPMD runtime with meshes
    repeating with period ``S``.

    The Megatron ordering needs ``M % S == 0``; other M fall back to
    plain 1F1B over the virtual chain (correct, larger warmup).
    """
    S, C, M = num_stages, num_chunks, num_micro_batches
    if C == 1:
        return generate_pipedream_flush_schedule(S, M)
    V = S * C
    if M % S != 0:
        return generate_pipedream_flush_schedule(V, M)

    def f_task(k):  # k-th forward in a rank's interleaved order
        group, within = divmod(k, S * C)
        chunk, m = divmod(within, S)
        return chunk, group * S + m

    def b_task(k):  # chunks drained in reverse order
        group, within = divmod(k, S * C)
        chunk, m = divmod(within, S)
        return C - 1 - chunk, group * S + m

    out: List[List[Task]] = [[] for _ in range(V)]
    total_f = M * C
    for s in range(S):
        warmup = min(total_f, (S - s - 1) * 2 + (C - 1) * S)
        rank_tasks: List[tuple] = []
        f = b = 0
        for _ in range(warmup):
            rank_tasks.append(("F", *f_task(f)))
            f += 1
        while f < total_f:
            rank_tasks.append(("F", *f_task(f)))
            f += 1
            rank_tasks.append(("B", *b_task(b)))
            b += 1
        while b < total_f:
            rank_tasks.append(("B", *b_task(b)))
            b += 1
        # project the physical rank's order onto its virtual stages
        # (per-device execution order is preserved by async dispatch;
        # cross-stage causality is the runtime's readiness gating)
        for kind, chunk, m in rank_tasks:
            out[chunk * S + s].append(Task(kind, m))
    return out


def p2p_events(schedule: Sequence[Sequence[Task]]
               ) -> List[List[tuple]]:
    """Project a per-stage task schedule onto the stage-boundary P2P
    events each stage issues, in program order.

    Returns, per stage, ``("send"|"recv", "F"|"B", micro_batch,
    peer_stage)`` tuples: a forward task at stage ``s`` first receives
    the activation from ``s-1`` (s > 0), computes, then sends to
    ``s+1`` (s < S-1); a backward task receives the output grad from
    ``s+1`` and sends the input grad to ``s-1``.  This is the symbolic
    order the MPMD runtime's ``p2p_log`` tap records at execution time
    and the schedule verifier (:mod:`hetu_tpu.analysis.schedule`)
    checks for cross-rank pairing — one projection, three consumers.
    """
    S = len(schedule)
    out: List[List[tuple]] = []
    for s, tasks in enumerate(schedule):
        ev: List[tuple] = []
        for t in tasks:
            m = t.micro_batch
            if t.kind == "F":
                if s > 0:
                    ev.append(("recv", "F", m, s - 1))
                if s < S - 1:
                    ev.append(("send", "F", m, s + 1))
            else:
                if s < S - 1:
                    ev.append(("recv", "B", m, s + 1))
                if s > 0:
                    ev.append(("send", "B", m, s - 1))
        out.append(ev)
    return out


def max_in_flight(stage_tasks: Sequence[Task]) -> int:
    """Peak number of micro-batches with forward done but backward not —
    the stage's activation-stash high-water mark."""
    live = 0
    peak = 0
    for t in stage_tasks:
        if t.kind == "F":
            live += 1
            peak = max(peak, live)
        else:
            live -= 1
    return peak


def validate_schedule(schedule: Sequence[Sequence[Task]],
                      num_micro_batches: int) -> None:
    """Sanity checks: every stage runs F and B exactly once per
    micro-batch; per-stage B(m) comes after F(m)."""
    for s, tasks in enumerate(schedule):
        seen_f = [False] * num_micro_batches
        seen_b = [False] * num_micro_batches
        for t in tasks:
            if t.kind == "F":
                assert not seen_f[t.micro_batch], (s, t)
                seen_f[t.micro_batch] = True
            else:
                assert seen_f[t.micro_batch], (s, t)
                assert not seen_b[t.micro_batch], (s, t)
                seen_b[t.micro_batch] = True
        assert all(seen_f) and all(seen_b), f"stage {s} incomplete"
