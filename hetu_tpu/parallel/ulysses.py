"""Ulysses sequence parallelism — all-to-all head-scatter attention.

DeepSpeed-Ulysses-style context parallelism (Jacobs et al., 2023),
provided as a second CP implementation NEXT TO ring attention.  The
reference has no Ulysses path (SURVEY.md §2.3: ring CP only) — this is a
TPU-native extension: ``lax.all_to_all`` maps directly onto ICI and, for
a single all-to-all pair per layer, moves less data than a
ring of ppermutes whenever the per-chip sequence fits.

Mechanics (inside shard_map over the ``cp`` axis):

1. inputs arrive sequence-sharded ``[b, s_local, h, d]``;
2. ``all_to_all`` scatters heads / gathers sequence ->
   ``[b, s_global, h/cp, d]`` — every rank now holds the FULL sequence
   for a head slice, so plain (flash) attention applies with no online
   cross-rank LSE correction and no SYM causal rebalancing: Ulysses is
   load-balanced by construction (each rank computes the same causal
   triangle over fewer heads);
3. attention (Pallas flash kernel);
4. reverse ``all_to_all`` restores ``[b, s_local, h, d]``.

Packed/varlen sequences: the [b, s_local] segment ids are all-gathered
(tiny int32 traffic) so the full-sequence attention sees global doc
boundaries — equivalent to the ring path's ids-ride-the-ring.
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .comm import axis_size

from ..ops.pallas.flash_attention import flash_attention


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis_name: str = "cp", causal: bool = True,
                      softmax_scale: Optional[float] = None,
                      segment_ids: Optional[jax.Array] = None) -> jax.Array:
    """All-to-all sequence-parallel attention on sequence-sharded
    ``[b, s_local, h, d]`` inputs.  Must run inside shard_map/pjit with
    ``axis_name`` in scope; ``h`` must be divisible by the axis size.

    ``segment_ids``: local ``[b, s_local]`` global doc ids (-1 pad) for
    packed sequences.
    """
    cp = axis_size(axis_name)
    h = q.shape[2]
    if h % cp != 0:
        raise ValueError(
            f"ulysses needs heads ({h}) divisible by the {axis_name!r} "
            f"axis size ({cp}); use ring_attention for h < cp")
    for name, x in (("k", k), ("v", v)):
        if x.shape[2] != h:
            raise ValueError(
                f"ulysses needs {name} heads ({x.shape[2]}) equal to q "
                f"heads ({h}) — the flash kernel takes one head count; "
                f"repeat GQA kv heads to match q first (the model path "
                f"does this)")
    scale = softmax_scale if softmax_scale is not None \
        else 1.0 / math.sqrt(q.shape[-1])
    if cp == 1:
        return flash_attention(q, k, v, causal=causal, softmax_scale=scale,
                               segment_ids=segment_ids)

    def seq_gather_head_scatter(x):
        # [b, s_local, h, d] -> [b, s_global, h/cp, d]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qg = seq_gather_head_scatter(q)
    kg = seq_gather_head_scatter(k)
    vg = seq_gather_head_scatter(v)
    segs = None
    if segment_ids is not None:
        # global ids on every rank (the full sequence is local now)
        segs = lax.all_gather(segment_ids.astype(jnp.int32), axis_name,
                              axis=1, tiled=True)          # [b, s_global]
    out = flash_attention(qg, kg, vg, causal=causal, softmax_scale=scale,
                          segment_ids=segs)
    # [b, s_global, h/cp, d] -> [b, s_local, h, d] (heads reassembled in
    # rank order = original order)
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def ulysses_attention_sharded(q, k, v, mesh, axis_name: str = "cp",
                              causal: bool = True,
                              softmax_scale: Optional[float] = None,
                              batch_axis: Optional[str] = "dp",
                              head_axis: Optional[str] = "tp",
                              segment_ids: Optional[jax.Array] = None
                              ) -> jax.Array:
    """Convenience wrapper for GLOBAL [b, s, h, d] arrays: shard the
    sequence over ``axis_name`` (batch over ``batch_axis``, heads over
    ``head_axis`` — TP + CP compose; the head constraint applies to the
    per-TP-rank head count) and run :func:`ulysses_attention`.

    Head counts that don't divide cp (x tp) are zero-PADDED up to the
    next multiple and the pad heads sliced off the output — attention is
    per-head, so pad heads never touch real ones (the ROADMAP GQA
    head-divisibility relaxation; compute waste is pad/h)."""
    from jax.sharding import PartitionSpec as P
    from .comm import shard_map

    def axis_or_none(name):
        return name if (name and name in mesh.axis_names) else None

    h = q.shape[2]
    for name, x in (("k", k), ("v", v)):
        if x.shape[2] != h:
            raise ValueError(
                f"ulysses needs {name} heads ({x.shape[2]}) equal to q "
                f"heads ({h}) — repeat GQA kv heads to match q first "
                f"(the model path does this); padding cannot substitute "
                f"for repetition")
    bspec = axis_or_none(batch_axis)
    hspec = axis_or_none(head_axis)
    unit = mesh.shape[axis_name] * (mesh.shape[hspec] if hspec else 1)
    pad = (-h) % unit
    if pad:
        def zpad(x):
            z = jnp.zeros((*x.shape[:2], pad, x.shape[3]), x.dtype)
            return jnp.concatenate([x, z], axis=2)
        q, k, v = zpad(q), zpad(k), zpad(v)

    spec = P(bspec, axis_name, hspec, None)
    if segment_ids is None:
        f = shard_map(
            lambda q, k, v: ulysses_attention(
                q, k, v, axis_name, causal, softmax_scale),
            mesh, (spec, spec, spec), spec)
        out = f(q, k, v)
    else:
        sspec = P(bspec, axis_name)
        f = shard_map(
            lambda q, k, v, s: ulysses_attention(
                q, k, v, axis_name, causal, softmax_scale, segment_ids=s),
            mesh, (spec, spec, spec, sspec), spec)
        out = f(q, k, v, segment_ids.astype(jnp.int32))
    return out[:, :, :h] if pad else out
