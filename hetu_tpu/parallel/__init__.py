from . import dstates
from .dstates import (DUPLICATE, PARTIAL, NULL_HETERO_DIM,
                      DistributedStates, DistributedStatesUnion,
                      DistributedStatesHierarchy, SplitPattern,
                      deduce_comm_kind, predict_grad_comm_collectives,
                      predict_update_step_collectives,
                      count_hlo_collectives, verify_grad_comm_emission)
from .mesh import (AXIS_DP, AXIS_CP, AXIS_TP, AXIS_PP, AXIS_EP,
                   create_mesh, single_device_mesh, mesh_axis_size,
                   ds_to_mesh_and_spec, ds_to_named_sharding,
                   ds_from_partition_spec, force_virtual_cpu_devices)
from .pipeline import pipeline_spmd, stack_stage_params
from .ring_attention import ring_attention, ring_attention_sharded
from .switch import (SwitchMode, SwitchPlan, SwitchProfile, SwitchExecGraph,
                     switch_state)
from . import comm

__all__ = [
    "DUPLICATE", "PARTIAL", "NULL_HETERO_DIM",
    "DistributedStates", "DistributedStatesUnion", "DistributedStatesHierarchy",
    "SplitPattern", "deduce_comm_kind", "dstates",
    "predict_grad_comm_collectives", "predict_update_step_collectives",
    "count_hlo_collectives", "verify_grad_comm_emission",
    "AXIS_DP", "AXIS_CP", "AXIS_TP", "AXIS_PP", "AXIS_EP",
    "create_mesh", "single_device_mesh", "mesh_axis_size",
    "ds_to_mesh_and_spec", "ds_to_named_sharding", "ds_from_partition_spec",
    "force_virtual_cpu_devices", "comm",
    "pipeline_spmd", "stack_stage_params",
    "ring_attention", "ring_attention_sharded",
    "SwitchMode", "SwitchPlan", "SwitchProfile", "SwitchExecGraph",
    "switch_state",
]
