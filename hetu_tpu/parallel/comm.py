"""Collective communication primitives.

TPU-native equivalent of the reference's communication backend
(``hetu/impl/communication/comm_group.h:27-144`` virtual collective set and
the graph-level comm ops in ``hetu/graph/ops/Communication.h``).  Instead of
NCCL groups on dedicated CUDA streams, collectives here are XLA ops emitted
inside ``shard_map``/pjit over a named mesh axis; XLA schedules them onto
ICI/DCN and overlaps with compute (async collectives).

Mapping table (reference -> ours):

==============================  =====================================
``AllReduce``                   :func:`all_reduce` (``lax.psum``)
``AllGather(gather_dim)``       :func:`all_gather`
``ReduceScatter(scatter_dim)``  :func:`reduce_scatter` (``lax.psum_scatter``)
``AlltoAll``                    :func:`all_to_all`
``Broadcast/Reduce``            :func:`broadcast` / :func:`reduce`
``Send/Recv/BatchedISendIRecv`` :func:`ppermute` rings/sets
``AllReduceCoalesce``           :func:`all_reduce_coalesced` (fused
                                size-capped buckets, optional EQuARX
                                bf16/int8 quantized transport)
``Barrier``                     :func:`barrier`
==============================  =====================================

All functions must be called *inside* a ``shard_map``-ed function with the
named axis in scope (the usual jax idiom); the graph layer and the parallel
nn layers arrange that.
"""
from __future__ import annotations

import contextlib
from typing import (Dict, List, Mapping, NamedTuple, Optional, Sequence,
                    Tuple)

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def shard_map(f, mesh, in_specs, out_specs, check_rep: bool = False,
              axis_names=None):
    """Version-stable shard_map wrapper.

    jax>=0.8 exposes ``jax.shard_map`` (check_rep renamed to check_vma,
    partial-manual via ``axis_names``); older jax has
    ``jax.experimental.shard_map.shard_map`` (check_rep, partial-manual
    via the complementary ``auto`` set).  ``axis_names``, when given,
    restricts manual mode to those mesh axes.
    """
    if hasattr(jax, "shard_map"):
        kw = {}
        if axis_names is not None:
            kw["axis_names"] = set(axis_names)
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_rep, **kw)
    from jax.experimental.shard_map import shard_map as _shard_map
    if axis_names is not None and \
            frozenset(axis_names) != frozenset(mesh.axis_names):
        # old-jax auto= lowering is broken: even trivial partial-manual
        # programs die in XLA with `Check failed: IsManualSubgroup()`
        # (spmd_partitioner.cc:512 on jaxlib 0.4.36).  Raise cleanly
        # instead of letting the compile abort the process.
        raise NotImplementedError(
            "partial-manual shard_map (axis_names a proper subset of the "
            "mesh axes) requires jax>=0.8; this jax's auto= lowering "
            "hits an XLA IsManualSubgroup check failure")
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_rep)


def _operand_bytes(x) -> int:
    return int(np.prod(np.shape(x))) * np.dtype(jnp.result_type(x)).itemsize


def all_reduce(x: jax.Array, axis: str, op: str = "sum") -> jax.Array:
    if _STATS_STACK:
        _record("all_reduce", _operand_bytes(x), jnp.result_type(x),
                axis_size(axis), axis)
    if op == "sum":
        return lax.psum(x, axis)
    if op == "mean":
        return lax.pmean(x, axis)
    if op == "max":
        return lax.pmax(x, axis)
    if op == "min":
        return lax.pmin(x, axis)
    raise ValueError(f"unsupported reduce op {op!r}")


def all_gather(x: jax.Array, axis: str, gather_dim: int = 0,
               tiled: bool = True) -> jax.Array:
    """Gather shards along ``gather_dim`` (reference AllGather, comm_group.h:95)."""
    if _STATS_STACK:
        n = axis_size(axis)
        _record("all_gather", _operand_bytes(x) * n, jnp.result_type(x),
                n, axis)
    return lax.all_gather(x, axis, axis=gather_dim, tiled=tiled)


def reduce_scatter(x: jax.Array, axis: str, scatter_dim: int = 0) -> jax.Array:
    """Sum-reduce then scatter along ``scatter_dim`` (comm_group.h:101)."""
    if _STATS_STACK:
        _record("reduce_scatter", _operand_bytes(x), jnp.result_type(x),
                axis_size(axis), axis)
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_dim, tiled=True)


def all_to_all(x: jax.Array, axis: str, split_dim: int,
               concat_dim: int, tiled: bool = True) -> jax.Array:
    """AlltoAll (comm_group.h:77) — the EP/MoE dispatch primitive."""
    if _STATS_STACK:
        _record("all_to_all", _operand_bytes(x), jnp.result_type(x),
                axis_size(axis), axis)
    return lax.all_to_all(x, axis, split_axis=split_dim,
                          concat_axis=concat_dim, tiled=tiled)


def broadcast(x: jax.Array, axis: str, root: int = 0) -> jax.Array:
    """Broadcast from ``root`` along ``axis`` (comm_group.h:63)."""
    idx = lax.axis_index(axis)
    n = axis_size(axis)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(masked, axis)


def reduce(x: jax.Array, axis: str, root: int = 0) -> jax.Array:
    """Reduce to ``root`` (others receive zeros) (comm_group.h:85)."""
    s = lax.psum(x, axis)
    idx = lax.axis_index(axis)
    return jnp.where(idx == root, s, jnp.zeros_like(s))


def ppermute(x: jax.Array, axis: str,
             perm: Sequence[Tuple[int, int]]) -> jax.Array:
    """Point-to-point permutation — the reference's ``BatchedISendIRecv``
    (comm_group.h:120): an arbitrary set of (src, dst) pairs exchanged as one
    grouped transfer."""
    return lax.ppermute(x, axis, perm)


def ring_shift(x: jax.Array, axis: str, shift: int = 1) -> jax.Array:
    """Shift shards around the ring formed by ``axis`` — the KV-ring exchange
    of ring attention (``ops/ParallelAttention.cc:611``)."""
    n = axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def axis_index(axis: str) -> jax.Array:
    return lax.axis_index(axis)


def axis_size(axis: str) -> int:
    """Static size of a named axis (jax<0.6 lacks lax.axis_size; the
    psum-of-1 constant folds to the axis size at trace time)."""
    if hasattr(lax, "axis_size"):
        return int(lax.axis_size(axis))
    return int(lax.psum(1, axis))


def barrier(coordinator=None, name: str = "default",
            world_size: Optional[int] = None,
            timeout: float = 60.0) -> None:
    """Host-level barrier (reference gRPC Barrier, heturpc.proto:44).

    Within a single jit program XLA collectives are self-synchronizing;
    this is only for host-side coordination between programs.

    Single-host: a tiny device all-reduce (drains in-flight programs on
    all local devices).  Multi-host: pass the process's
    ``rpc.CoordinatorClient`` as ``coordinator`` — the barrier then goes
    through its cross-host rendezvous (``CoordinatorClient.barrier``),
    the way the reference routes Barrier through heturpc.  When a client
    has been registered via :func:`set_coordinator` it is used
    automatically.
    """
    coord = coordinator if coordinator is not None else _COORDINATOR[0]
    if coord is not None:
        # an unresolvable world size would make the server release the
        # barrier immediately (n=0) — a silent no-op; fail loudly instead
        ws = world_size if world_size is not None \
            else getattr(coord, "world_size", None)
        if not ws:
            raise ValueError(
                "coordinator barrier needs a world_size (pass it here or "
                "start the CoordinatorServer with world_size=N)")
        coord.barrier(name=name, world_size=ws, timeout=timeout)
        return
    # Tiny all-reduce over all devices, blocking until complete.
    n = jax.device_count()
    if n > 1:
        x = jnp.ones((n,))
        jax.block_until_ready(
            jax.pmap(lambda v: lax.psum(v, "i"), axis_name="i")(x))


def partial_reduce(x: jax.Array, axis: str, participating,
                   op: str = "mean") -> jax.Array:
    """Partial (asynchronous-DP) reduce — v1's ``PartialReduce``
    (``v1/python/hetu/preduce.py:8``): only the *ready* subset of ranks
    contributes; everyone receives the subset's mean (or sum).

    ``participating`` is a per-rank scalar (bool/0-1, may be traced):
    unlike the reference, which forms an ad-hoc NCCL group from the ranks
    that arrived within a time window, XLA groups are static — so the
    subset is expressed as a mask and lowered to one full-axis ``psum``
    of masked contributions plus a participant count.  Ranks outside the
    subset still receive the reduced value (the v1 semantics: stale
    workers adopt the fresh average on their next partial round).
    """
    p = jnp.asarray(participating, x.dtype)
    total = lax.psum(x * p, axis)
    if op == "sum":
        return total
    if op == "mean":
        count = lax.psum(p, axis)
        return total / jnp.maximum(count, 1)
    raise ValueError(f"unsupported partial_reduce op {op!r}")


_COORDINATOR: list = [None]


def set_coordinator(client) -> None:
    """Register the process's CoordinatorClient so :func:`barrier` (and
    other host-level sync points) route through the cross-host
    coordinator instead of the local-device fallback."""
    _COORDINATOR[0] = client


# -- split collectives (hetero ZeRO, ops/Communication.h:655-845) -----------
#
# The reference defines SplitAllGather/SplitAllReduce/SplitReduceScatter that
# run a collective independently over *sub-groups* of unequal sizes (needed
# when hetero pipelines give parameter shards different replication factors).
# ``groups`` is a static partition of the axis indices, e.g. [[0,1,2],
# [3,4,5,6,7]] — subgroup sizes may differ.  Without ``groups`` the whole
# axis is one group (the homogeneous case).
#
# XLA's AllReduce takes unequal replica groups natively (axis_index_groups);
# AllGather/ReduceScatter are shape-uniform in SPMD, so the unequal cases
# pad to the largest subgroup: split_all_gather returns
# max_group_size*shard rows per rank (rows beyond the own group's
# contribution are zero), split_reduce_scatter returns L//min(group sizes)
# rows (rows beyond the own rank's L//group_size chunk are zero).  The
# per-rank valid extents are static, derivable from ``groups`` — the same
# contract as the reference's per-group tensor lists.


def _norm_groups(groups, n: int):
    """Validate + normalize a static group partition of range(n)."""
    gs = [list(map(int, g)) for g in groups]
    flat = sorted(i for g in gs for i in g)
    if flat != list(range(n)):
        raise ValueError(
            f"groups {gs} must partition the {n} axis indices exactly")
    return gs


def _group_tables(groups, n: int):
    """(group_id [n], members [n_groups, max_g] padded with -1,
    rank_in_group [n], group_size [n]) as numpy arrays."""
    import numpy as np
    gid = np.zeros(n, np.int32)
    rin = np.zeros(n, np.int32)
    gsz = np.zeros(n, np.int32)
    max_g = max(len(g) for g in groups)
    members = np.full((len(groups), max_g), -1, np.int32)
    for g_i, g in enumerate(groups):
        for r, dev in enumerate(g):
            gid[dev] = g_i
            rin[dev] = r
            gsz[dev] = len(g)
            members[g_i, r] = dev
    return gid, members, rin, gsz


def split_all_reduce(x: jax.Array, subgroup_axis: str,
                     groups: Optional[Sequence[Sequence[int]]] = None
                     ) -> jax.Array:
    """AllReduce within each (possibly unequal) subgroup
    (SplitAllReduceOp, ops/Communication.h:718)."""
    if groups is None:
        return lax.psum(x, subgroup_axis)
    n = axis_size(subgroup_axis)
    gs = _norm_groups(groups, n)
    return lax.psum(x, subgroup_axis,
                    axis_index_groups=[tuple(g) for g in gs])


def split_all_gather(x: jax.Array, subgroup_axis: str,
                     gather_dim: int = 0,
                     groups: Optional[Sequence[Sequence[int]]] = None
                     ) -> jax.Array:
    """AllGather within each subgroup (SplitAllGatherOp,
    ops/Communication.h:655).  With unequal ``groups`` the result is
    padded to max group size: shape[gather_dim] ==
    max_g * x.shape[gather_dim]; each rank's first
    own_group_size * shard rows are its group's concatenated shards, the
    rest zeros."""
    if groups is None:
        return lax.all_gather(x, subgroup_axis, axis=gather_dim, tiled=True)
    gather_dim = gather_dim % x.ndim
    n = axis_size(subgroup_axis)
    gs = _norm_groups(groups, n)
    sizes = {len(g) for g in gs}
    if len(sizes) == 1:
        return lax.all_gather(x, subgroup_axis, axis=gather_dim, tiled=True,
                              axis_index_groups=[tuple(g) for g in gs])
    gid_t, members_t, _, _ = _group_tables(gs, n)
    my = lax.axis_index(subgroup_axis)
    # full-axis gather, then select own group's members (padded to max_g)
    allx = lax.all_gather(x, subgroup_axis, axis=0, tiled=False)  # [n, ...]
    members = jnp.asarray(members_t)[jnp.asarray(gid_t)[my]]      # [max_g]
    picked = jnp.take(allx, jnp.maximum(members, 0), axis=0)
    mask_shape = [members.shape[0]] + [1] * (picked.ndim - 1)
    picked = jnp.where((members >= 0).reshape(mask_shape), picked, 0)
    # tile into gather_dim:  [max_g, ..., s, ...] -> [..., max_g*s, ...]
    picked = jnp.moveaxis(picked, 0, gather_dim)
    shape = list(x.shape)
    shape[gather_dim] = members.shape[0] * x.shape[gather_dim]
    return picked.reshape(shape)


# -- coalesced + quantized gradient collectives ------------------------------
#
# Reference AllReduceCoalesce (comm_group.h:27-144): per-tensor gradient
# allreduce leaves link bandwidth on the table, so same-dtype gradients are
# flattened into size-capped fused buckets and synced with ONE collective
# per bucket.  On top of the bucketing sits a quantized transport (EQuARX,
# PAPERS.md): the payload crosses the wire as bf16 or blockwise-absmax int8
# while the *reduction* accumulates in fp32, via the two-phase
#
#   quantize -> all_to_all (reduce-scatter exchange) -> dequantize ->
#   accumulate fp32 -> [mean] -> quantize -> all_gather -> dequantize
#
# so each element is quantized exactly twice regardless of group size and
# the reduction error stays bounded per absmax block.  fp32 transport uses
# a single psum per bucket, which is bit-identical to per-tensor psum
# (elementwise reduction over the same rank order).

GRAD_COMM_TRANSPORTS = ("fp32", "bf16", "int8")

#: default blockwise-absmax block for the int8 transport (elements/block;
#: scale sidecar overhead = 4 bytes per block)
INT8_BLOCK = 256


class Bucket(NamedTuple):
    """One fused bucket: same-dtype tensors flattened back to back."""
    keys: Tuple             # caller keys, flatten order
    shapes: Tuple           # original shapes, same order
    numels: Tuple[int, ...]
    dtype: str              # canonical numpy dtype name
    nbytes: int             # payload bytes (sum of tensor bytes)


class CommRecord(NamedTuple):
    kind: str               # all_reduce | reduce_scatter | all_gather | all_to_all
    payload_bytes: int      # logical payload size (global, pre-sharding)
    wire_bytes: float       # per-rank bytes on the wire (ring algorithm)
    dtype: str
    axis: str
    tag: str = ""           # attribution tag (ambient comm_tag scope)


class CommStats:
    """Trace-time collective accounting (bytes-on-wire bookkeeping).

    Collectives recorded while a :func:`comm_stats` scope is active
    correspond 1:1 to collective ops in the traced XLA program — tracing
    a jitted function (or ``.lower()``-ing it) under the scope counts
    exactly what the program will launch per step.
    """

    def __init__(self):
        self.records: List[CommRecord] = []

    @property
    def num_collectives(self) -> int:
        return len(self.records)

    @property
    def total_wire_bytes(self) -> float:
        return sum(r.wire_bytes for r in self.records)

    @property
    def total_payload_bytes(self) -> int:
        return sum(r.payload_bytes for r in self.records)

    def by_kind(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for r in self.records:
            out[r.kind] = out.get(r.kind, 0) + 1
        return out

    def summary(self) -> dict:
        return {"num_collectives": self.num_collectives,
                "wire_bytes_per_rank": round(self.total_wire_bytes, 1),
                "payload_bytes": self.total_payload_bytes,
                "by_kind": self.by_kind()}


_STATS_STACK: List[CommStats] = []
_TAG_STACK: List[str] = []


@contextlib.contextmanager
def comm_stats():
    """``with comm_stats() as s:`` — record collectives traced inside."""
    s = CommStats()
    _STATS_STACK.append(s)
    try:
        yield s
    finally:
        _STATS_STACK.remove(s)


@contextlib.contextmanager
def comm_tag(tag: str):
    """Attribute collectives emitted inside to ``tag``.

    Dual-plane tagging: the tag is (1) pushed onto the ambient stack so
    trace-time :class:`CommRecord` s carry it, and (2) entered as a jax
    ``named_scope`` so it lands on the eqn name-stack in the traced
    jaxpr — the static analyzer (``hetu_tpu/analysis``) reads it back
    from the program itself, with no side channel.
    """
    _TAG_STACK.append(tag)
    try:
        with jax.named_scope(tag):
            yield
    finally:
        _TAG_STACK.pop()


def current_comm_tag() -> str:
    return "/".join(_TAG_STACK)


def ring_wire_bytes(kind: str, payload_bytes: float, n: int) -> float:
    """Per-rank bytes sent over the wire by the ring algorithm for a
    collective moving ``payload_bytes`` across ``n`` ranks (the standard
    bandwidth-optimal accounting; ICI all-reduce = RS + AG)."""
    if n <= 1:
        return 0.0
    frac = (n - 1) / n
    if kind == "all_reduce":
        return 2.0 * payload_bytes * frac
    if kind in ("reduce_scatter", "all_gather", "all_to_all"):
        return payload_bytes * frac
    if kind == "ppermute":
        # one hop: every rank sends its full local payload once; a
        # K-hop chain (pipeline ticks, ring attention) is K records (or
        # one record with count=K), so totals come out as hops x payload
        return float(payload_bytes)
    raise ValueError(f"unknown collective kind {kind!r}")


def _record(kind: str, payload_bytes: int, dtype, n: int, axis: str) -> None:
    if not _STATS_STACK:
        return
    rec = CommRecord(kind, int(payload_bytes),
                     ring_wire_bytes(kind, payload_bytes, n),
                     np.dtype(dtype).name, axis, current_comm_tag())
    for s in _STATS_STACK:
        s.records.append(rec)


def plan_buckets(entries: Sequence[Tuple],
                 bucket_mb: float = 4.0) -> List[Bucket]:
    """Greedy size-capped bucketing of ``(key, shape, dtype)`` entries.

    Order-preserving within each dtype (gradients arrive roughly in
    reverse-layer order, so adjacent buckets stay adjacent in the
    backward schedule — the overlap-friendly property of the reference's
    AllReduceCoalesce grouping).  A tensor larger than the cap gets its
    own bucket.
    """
    cap = max(1, int(float(bucket_mb) * (1 << 20)))
    buckets: List[Bucket] = []
    open_idx: Dict[str, int] = {}   # dtype -> index into buckets
    for key, shape, dtype in entries:
        dt = np.dtype(dtype)
        numel = int(np.prod(shape)) if len(tuple(shape)) else 1
        nbytes = numel * dt.itemsize
        i = open_idx.get(dt.name)
        if i is not None and buckets[i].nbytes + nbytes <= cap:
            b = buckets[i]
            buckets[i] = Bucket(b.keys + (key,), b.shapes + (tuple(shape),),
                                b.numels + (numel,), b.dtype,
                                b.nbytes + nbytes)
        else:
            buckets.append(Bucket((key,), (tuple(shape),), (numel,),
                                  dt.name, nbytes))
            open_idx[dt.name] = len(buckets) - 1
    return buckets


def _normalize_tree(xs):
    """(items [(key, arr)], rebuild) for dict / list / tuple inputs."""
    if isinstance(xs, Mapping):
        items = list(xs.items())
        return items, (lambda vals: dict(zip([k for k, _ in items], vals)))
    items = list(enumerate(xs))
    return items, (lambda vals: list(vals))


def _flatten_bucket(bucket: Bucket, lookup) -> jax.Array:
    return jnp.concatenate([jnp.ravel(lookup[k]) for k in bucket.keys])


def _unflatten_bucket(flat: jax.Array, bucket: Bucket) -> List[jax.Array]:
    out, off = [], 0
    for shape, numel in zip(bucket.shapes, bucket.numels):
        out.append(lax.dynamic_slice_in_dim(flat, off, numel).reshape(shape))
        off += numel
    return out


def quantized_chunk(numel: int, n: int, block: int = INT8_BLOCK) -> int:
    """Per-rank chunk length for the two-phase quantized path: the padded
    flat buffer is ``n * chunk`` with ``chunk`` a block multiple, so int8
    absmax blocks never straddle rank boundaries."""
    per = -(-numel // n)             # ceil
    return -(-per // block) * block


def _quantize_rows(rows: jax.Array, block: int):
    """Blockwise int8 absmax quantize of ``[r, chunk]`` rows
    (chunk % block == 0, so blocks stay within rows).  Reuses the
    checkpoint-path quantizer (ops/quantization.py)."""
    from ..ops.quantization import quantize_int8   # lazy: avoid pkg cycle
    r, chunk = rows.shape
    q, scales = quantize_int8(rows, blocksize=block)
    return q.reshape(r, chunk), scales.reshape(r, chunk // block)


def _dequantize_rows(codes: jax.Array, scales: jax.Array,
                     block: int) -> jax.Array:
    from ..ops.quantization import dequantize_int8   # lazy: avoid pkg cycle
    return dequantize_int8(codes.reshape(-1), scales.reshape(-1),
                           codes.shape, blocksize=block)


def _axis_groups(groups, n):
    if groups is None:
        return None, n
    gs = _norm_groups(groups, n)
    sizes = {len(g) for g in gs}
    if len(sizes) != 1:
        raise ValueError(
            "quantized transports need equal-size subgroups (XLA "
            f"all_to_all/all_gather are shape-uniform); got {gs}. "
            "Use transport='fp32' for unequal groups.")
    return [tuple(g) for g in gs], sizes.pop()


def _qreduce_scatter_flat(flat: jax.Array, axis: str, op: str,
                          transport: str, block: int,
                          groups=None) -> jax.Array:
    """Phase 1 of the EQuARX two-phase reduction on a flat fp32 buffer:
    each rank ends up owning the fully-reduced (fp32-accumulated) chunk
    at its own rank offset.  Returns the ``[chunk]`` fp32 shard."""
    n_axis = axis_size(axis)
    idx_groups, n = _axis_groups(groups, n_axis)
    N = flat.shape[0]
    chunk = quantized_chunk(N, n, block)
    flat = jnp.pad(flat.astype(jnp.float32), (0, n * chunk - N))
    rows = flat.reshape(n, chunk)
    if transport == "bf16":
        payload = rows.astype(jnp.bfloat16)
        ex = lax.all_to_all(payload, axis, split_axis=0, concat_axis=0,
                            tiled=False, axis_index_groups=idx_groups)
        _record("all_to_all", n * chunk * 2, jnp.bfloat16, n, axis)
        acc = jnp.sum(ex.astype(jnp.float32), axis=0)
    elif transport == "int8":
        codes, scales = _quantize_rows(rows, block)
        exc = lax.all_to_all(codes, axis, split_axis=0, concat_axis=0,
                             tiled=False, axis_index_groups=idx_groups)
        _record("all_to_all", n * chunk, jnp.int8, n, axis)
        with comm_tag("scales"):
            exs = lax.all_to_all(scales, axis, split_axis=0, concat_axis=0,
                                 tiled=False, axis_index_groups=idx_groups)
            _record("all_to_all", n * (chunk // block) * 4, jnp.float32, n,
                    axis)
        acc = jnp.sum(_dequantize_rows(exc, exs, block), axis=0)
    else:
        raise ValueError(f"unknown quantized transport {transport!r}")
    if op == "mean":
        acc = acc / n
    elif op != "sum":
        raise ValueError(f"unsupported op {op!r} for quantized transport")
    return acc


def _qall_gather_flat(chunk_arr: jax.Array, axis: str, transport: str,
                      block: int, numel: int, groups=None) -> jax.Array:
    """Phase 2: broadcast each rank's reduced chunk through the quantized
    transport; returns the full flat fp32 buffer (length ``numel``)."""
    n_axis = axis_size(axis)
    idx_groups, n = _axis_groups(groups, n_axis)
    chunk = chunk_arr.shape[0]
    if transport == "bf16":
        g = lax.all_gather(chunk_arr.astype(jnp.bfloat16), axis,
                           tiled=False, axis_index_groups=idx_groups)
        _record("all_gather", n * chunk * 2, jnp.bfloat16, n, axis)
        full = g.astype(jnp.float32)
    elif transport == "int8":
        codes, scales = _quantize_rows(chunk_arr.reshape(1, chunk), block)
        gc = lax.all_gather(codes[0], axis, tiled=False,
                            axis_index_groups=idx_groups)
        _record("all_gather", n * chunk, jnp.int8, n, axis)
        with comm_tag("scales"):
            gs = lax.all_gather(scales[0], axis, tiled=False,
                                axis_index_groups=idx_groups)
            _record("all_gather", n * (chunk // block) * 4, jnp.float32, n,
                    axis)
        full = _dequantize_rows(gc, gs, block)
    else:
        raise ValueError(f"unknown quantized transport {transport!r}")
    return full.reshape(-1)[:numel]


def _reduce_flat(flat: jax.Array, axis: str, op: str, transport: str,
                 block: int, groups) -> jax.Array:
    """All-reduce one flat bucket through the selected transport."""
    n = axis_size(axis)
    # wire accounting: grouped collectives move data within each
    # subgroup only — record with the largest group's ring factor, not
    # the full axis's
    n_rec = n if groups is None else max(len(g) for g in groups)
    if transport == "fp32":
        _record("all_reduce", flat.shape[0] * np.dtype(flat.dtype).itemsize,
                flat.dtype, n_rec, axis)
        if groups is not None:
            red = split_all_reduce(flat, axis, groups)
            if op == "mean":
                red = red / _own_group_size(axis, groups, n)
            elif op != "sum":
                raise ValueError(f"unsupported coalesced op {op!r}")
            return red
        if op == "sum":
            return lax.psum(flat, axis)
        if op == "mean":
            return lax.pmean(flat, axis)
        raise ValueError(f"unsupported coalesced op {op!r}")
    orig_dtype = flat.dtype
    shard = _qreduce_scatter_flat(flat, axis, op, transport, block, groups)
    full = _qall_gather_flat(shard, axis, transport, block, flat.shape[0],
                             groups)
    return full.astype(orig_dtype)


def _own_group_size(axis: str, groups, n: int):
    gs = _norm_groups(groups, n)
    _gid, _members, _rin, gsz = _group_tables(gs, n)
    return jnp.asarray(gsz, jnp.float32)[lax.axis_index(axis)]


def all_reduce_coalesced(xs, axis: str, op: str = "sum",
                         bucket_mb: float = 4.0,
                         transport: str = "fp32",
                         block: int = INT8_BLOCK,
                         groups: Optional[Sequence[Sequence[int]]] = None):
    """Bucketed (optionally quantized) all-reduce of a gradient pytree.

    ``xs``: dict or list of arrays; returns the same structure.  Arrays
    are flattened into same-dtype buckets capped at ``bucket_mb`` MiB and
    reduced with ONE collective chain per bucket (reference
    AllReduceCoalesce, comm_group.h:27; EQuARX quantized transport).

    transport:
      - ``"fp32"`` — one ``psum`` per bucket; bit-identical to per-tensor
        ``psum`` (elementwise reduction, same rank order).
      - ``"bf16"`` — payload cast to bf16, fp32 accumulation (two-phase).
      - ``"int8"`` — blockwise-absmax int8 payload + fp32 scale sidecar,
        fp32 accumulation; each element quantized exactly twice.

    ``groups``: optional static subgroup partition (SplitAllReduce
    semantics).  fp32 supports unequal groups; quantized transports need
    equal-size groups.  Must be called inside shard_map with ``axis``.
    """
    if transport not in GRAD_COMM_TRANSPORTS:
        raise ValueError(f"transport must be one of {GRAD_COMM_TRANSPORTS}, "
                         f"got {transport!r}")
    items, rebuild = _normalize_tree(xs)
    lookup = dict(items)
    buckets = plan_buckets(
        [(k, np.shape(v), jnp.result_type(v)) for k, v in items], bucket_mb)
    out: Dict = {}
    for bi, b in enumerate(buckets):
        with comm_tag(f"grad_comm/bucket{bi}"):
            flat = _flatten_bucket(b, lookup)
            red = _reduce_flat(flat, axis, op, transport, block, groups)
        for k, arr in zip(b.keys, _unflatten_bucket(red, b)):
            out[k] = arr.astype(lookup[k].dtype)
    return rebuild([out[k] for k, _ in items])


class CoalescedLayout(NamedTuple):
    """Static layout of a reduce-scattered coalesced gradient set: one
    entry per bucket, enough to all-gather + unflatten later (the
    per-group tensor-list contract of the reference's coalesce ops)."""
    buckets: Tuple[Bucket, ...]
    chunks: Tuple[int, ...]      # per-bucket per-rank chunk length
    list_input: bool = False     # rebuild a list (not a dict) on gather
    groups: Optional[Tuple[Tuple[int, ...], ...]] = None  # split variant


def reduce_scatter_coalesced(xs, axis: str, op: str = "sum",
                             bucket_mb: float = 4.0,
                             transport: str = "fp32",
                             block: int = INT8_BLOCK):
    """Bucketed reduce-scatter: each rank ends up owning the reduced
    chunk of every bucket at its own rank offset (ZeRO grad sync,
    reference SplitReduceScatter under zero, Communication.h:583).

    Returns ``(chunks, layout)``: ``chunks[i]`` is this rank's fp32
    shard of bucket i; complete with :func:`all_gather_coalesced`.
    """
    if transport not in GRAD_COMM_TRANSPORTS:
        raise ValueError(f"transport must be one of {GRAD_COMM_TRANSPORTS}, "
                         f"got {transport!r}")
    items, _rebuild = _normalize_tree(xs)
    lookup = dict(items)
    buckets = plan_buckets(
        [(k, np.shape(v), jnp.result_type(v)) for k, v in items], bucket_mb)
    n = axis_size(axis)
    chunks, chunk_lens = [], []
    for bi, b in enumerate(buckets):
        with comm_tag(f"grad_comm/bucket{bi}"):
            flat = _flatten_bucket(b, lookup)
            chunk = quantized_chunk(flat.shape[0], n, block)
            if transport == "fp32":
                padded = jnp.pad(flat.astype(jnp.float32),
                                 (0, n * chunk - flat.shape[0]))
                _record("reduce_scatter",
                        padded.shape[0] * np.dtype(padded.dtype).itemsize,
                        padded.dtype, n, axis)
                shard = lax.psum_scatter(padded, axis, scatter_dimension=0,
                                         tiled=True)
                if op == "mean":
                    shard = shard / n
                elif op != "sum":
                    raise ValueError(f"unsupported coalesced op {op!r}")
            else:
                shard = _qreduce_scatter_flat(flat, axis, op, transport,
                                              block)
        chunks.append(shard)
        chunk_lens.append(chunk)
    return chunks, CoalescedLayout(tuple(buckets), tuple(chunk_lens),
                                   not isinstance(xs, Mapping))


def all_gather_coalesced(chunks, layout: CoalescedLayout, axis: str,
                         transport: str = "fp32",
                         block: int = INT8_BLOCK,
                         tag: str = "grad_comm"):
    """Inverse of :func:`reduce_scatter_coalesced`: gather every rank's
    chunks and unflatten back to the original container (dict keyed like
    the input mapping, or a list when the input was a sequence).

    The plain (non-quantized) path gathers in the BUCKET dtype, not the
    chunk dtype: casting the fp32 chunk before the collective is
    elementwise-identical to casting after, so a bf16 parameter set
    crosses the wire as bf16 — the ZeRO-2 updated-param all-gather rides
    the weight dtype instead of fp32 (half the gather bytes).  ``tag``
    names the attribution scope: the flat-optimizer path tags its param
    gather ``param_comm`` so byte accounting (and the
    grad-allgather-under-zero2 lint) can tell parameter traffic from
    gradient traffic."""
    if layout.groups is not None:
        # grouped shards are padded per-rank to the largest chunk; a
        # full-axis gather would interleave groups and padding into
        # garbage — fail loudly (per-rank valid extents are derivable
        # from layout.groups, the split_reduce_scatter contract)
        raise NotImplementedError(
            "all_gather_coalesced does not support grouped layouts "
            "(from split_reduce_scatter_coalesced); consume the shards "
            "with the per-group valid extents from layout.groups")
    n = axis_size(axis)
    out: Dict = {}
    for bi, (shard, b, chunk) in enumerate(zip(chunks, layout.buckets,
                                               layout.chunks)):
        numel = sum(b.numels)
        with comm_tag(f"{tag}/bucket{bi}"):
            if transport == "fp32":
                wire_dt = np.dtype(b.dtype)
                _record("all_gather", n * chunk * wire_dt.itemsize,
                        wire_dt, n, axis)
                full = lax.all_gather(shard.astype(wire_dt), axis,
                                      tiled=True)[:numel]
            else:
                full = _qall_gather_flat(shard, axis, transport, block,
                                         numel)
        for k, arr in zip(b.keys, _unflatten_bucket(full, b)):
            out[k] = arr.astype(np.dtype(b.dtype))
    if layout.list_input:
        return [out[i] for i in range(len(out))]
    return out


def split_all_reduce_coalesced(xs, subgroup_axis: str,
                               groups: Optional[Sequence[Sequence[int]]] = None,
                               op: str = "sum", bucket_mb: float = 4.0,
                               transport: str = "fp32",
                               block: int = INT8_BLOCK):
    """Coalesced SplitAllReduce: one fused collective per bucket, run
    independently over (possibly unequal) subgroups.  fp32 handles
    unequal groups natively (psum axis_index_groups); quantized
    transports require equal-size groups."""
    return all_reduce_coalesced(xs, subgroup_axis, op=op,
                                bucket_mb=bucket_mb, transport=transport,
                                block=block, groups=groups)


def split_reduce_scatter_coalesced(xs, subgroup_axis: str,
                                   groups: Optional[Sequence[Sequence[int]]]
                                   = None,
                                   bucket_mb: float = 4.0):
    """Coalesced SplitReduceScatter over (possibly unequal) subgroups:
    flattens each bucket, pads to a common multiple of every subgroup
    size, and runs one :func:`split_reduce_scatter` per bucket.  Returns
    ``(flat_shards, layout)`` with the padded-to-largest-chunk contract
    of :func:`split_reduce_scatter`."""
    items, _rebuild = _normalize_tree(xs)
    lookup = dict(items)
    buckets = plan_buckets(
        [(k, np.shape(v), jnp.result_type(v)) for k, v in items], bucket_mb)
    n = axis_size(subgroup_axis)
    sizes = [len(g) for g in groups] if groups is not None else [n]
    lcm = int(np.lcm.reduce(np.asarray(sizes, np.int64)))
    shards, chunk_lens = [], []
    for b in buckets:
        flat = _flatten_bucket(b, lookup)
        pad = (-flat.shape[0]) % lcm
        padded = jnp.pad(flat, (0, pad))
        _record("reduce_scatter",
                padded.shape[0] * np.dtype(padded.dtype).itemsize,
                padded.dtype, max(sizes), subgroup_axis)
        shards.append(split_reduce_scatter(padded, subgroup_axis, 0, groups))
        chunk_lens.append(padded.shape[0] // min(sizes))
    gtuple = tuple(tuple(int(i) for i in g) for g in groups) \
        if groups is not None else None
    return shards, CoalescedLayout(tuple(buckets), tuple(chunk_lens),
                                   not isinstance(xs, Mapping), gtuple)


def split_reduce_scatter(x: jax.Array, subgroup_axis: str,
                         scatter_dim: int = 0,
                         groups: Optional[Sequence[Sequence[int]]] = None
                         ) -> jax.Array:
    """ReduceScatter within each subgroup (SplitReduceScatterOp,
    ops/Communication.h:782).  With unequal ``groups`` the result is
    padded to the largest chunk (L // min group size); each rank's first
    L // own_group_size rows are its chunk of the group-reduced tensor,
    the rest zeros."""
    if groups is None:
        return lax.psum_scatter(x, subgroup_axis,
                                scatter_dimension=scatter_dim, tiled=True)
    scatter_dim = scatter_dim % x.ndim
    n = axis_size(subgroup_axis)
    gs = _norm_groups(groups, n)
    sizes = {len(g) for g in gs}
    if len(sizes) == 1:
        return lax.psum_scatter(x, subgroup_axis,
                                scatter_dimension=scatter_dim, tiled=True,
                                axis_index_groups=[tuple(g) for g in gs])
    L = x.shape[scatter_dim]
    for g in gs:
        if L % len(g) != 0:
            raise ValueError(
                f"scatter dim {L} not divisible by subgroup size {len(g)}")
    max_chunk = L // min(sizes)
    gid_t, _, rin_t, gsz_t = _group_tables(gs, n)
    my = lax.axis_index(subgroup_axis)
    reduced = lax.psum(x, subgroup_axis,
                       axis_index_groups=[tuple(g) for g in gs])
    chunk = L // jnp.asarray(gsz_t)[my]                # traced per-rank
    offset = jnp.asarray(rin_t)[my] * chunk
    # static-size slice of max_chunk starting at offset (pad tail so the
    # slice never clamps into another rank's chunk), then mask the excess
    pad = [(0, 0)] * x.ndim
    pad[scatter_dim] = (0, max_chunk)
    padded = jnp.pad(reduced, pad)
    starts = [jnp.int32(0)] * x.ndim
    starts[scatter_dim] = offset
    sizes_out = list(x.shape)
    sizes_out[scatter_dim] = max_chunk
    out = lax.dynamic_slice(padded, starts, sizes_out)
    pos_shape = [1] * x.ndim
    pos_shape[scatter_dim] = max_chunk
    pos = jnp.arange(max_chunk).reshape(pos_shape)
    return jnp.where(pos < chunk, out, 0)
