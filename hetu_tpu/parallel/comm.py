"""Collective communication primitives.

TPU-native equivalent of the reference's communication backend
(``hetu/impl/communication/comm_group.h:27-144`` virtual collective set and
the graph-level comm ops in ``hetu/graph/ops/Communication.h``).  Instead of
NCCL groups on dedicated CUDA streams, collectives here are XLA ops emitted
inside ``shard_map``/pjit over a named mesh axis; XLA schedules them onto
ICI/DCN and overlaps with compute (async collectives).

Mapping table (reference -> ours):

==============================  =====================================
``AllReduce``                   :func:`all_reduce` (``lax.psum``)
``AllGather(gather_dim)``       :func:`all_gather`
``ReduceScatter(scatter_dim)``  :func:`reduce_scatter` (``lax.psum_scatter``)
``AlltoAll``                    :func:`all_to_all`
``Broadcast/Reduce``            :func:`broadcast` / :func:`reduce`
``Send/Recv/BatchedISendIRecv`` :func:`ppermute` rings/sets
``AllReduceCoalesce``           XLA all-reduce combining (automatic)
``Barrier``                     :func:`barrier`
==============================  =====================================

All functions must be called *inside* a ``shard_map``-ed function with the
named axis in scope (the usual jax idiom); the graph layer and the parallel
nn layers arrange that.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def shard_map(f, mesh, in_specs, out_specs, check_rep: bool = False):
    """Version-stable shard_map wrapper (jax>=0.8 renamed check_rep)."""
    return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=check_rep)


def all_reduce(x: jax.Array, axis: str, op: str = "sum") -> jax.Array:
    if op == "sum":
        return lax.psum(x, axis)
    if op == "mean":
        return lax.pmean(x, axis)
    if op == "max":
        return lax.pmax(x, axis)
    if op == "min":
        return lax.pmin(x, axis)
    raise ValueError(f"unsupported reduce op {op!r}")


def all_gather(x: jax.Array, axis: str, gather_dim: int = 0,
               tiled: bool = True) -> jax.Array:
    """Gather shards along ``gather_dim`` (reference AllGather, comm_group.h:95)."""
    return lax.all_gather(x, axis, axis=gather_dim, tiled=tiled)


def reduce_scatter(x: jax.Array, axis: str, scatter_dim: int = 0) -> jax.Array:
    """Sum-reduce then scatter along ``scatter_dim`` (comm_group.h:101)."""
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_dim, tiled=True)


def all_to_all(x: jax.Array, axis: str, split_dim: int,
               concat_dim: int, tiled: bool = True) -> jax.Array:
    """AlltoAll (comm_group.h:77) — the EP/MoE dispatch primitive."""
    return lax.all_to_all(x, axis, split_axis=split_dim,
                          concat_axis=concat_dim, tiled=tiled)


def broadcast(x: jax.Array, axis: str, root: int = 0) -> jax.Array:
    """Broadcast from ``root`` along ``axis`` (comm_group.h:63)."""
    idx = lax.axis_index(axis)
    n = lax.axis_size(axis)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(masked, axis)


def reduce(x: jax.Array, axis: str, root: int = 0) -> jax.Array:
    """Reduce to ``root`` (others receive zeros) (comm_group.h:85)."""
    s = lax.psum(x, axis)
    idx = lax.axis_index(axis)
    return jnp.where(idx == root, s, jnp.zeros_like(s))


def ppermute(x: jax.Array, axis: str,
             perm: Sequence[Tuple[int, int]]) -> jax.Array:
    """Point-to-point permutation — the reference's ``BatchedISendIRecv``
    (comm_group.h:120): an arbitrary set of (src, dst) pairs exchanged as one
    grouped transfer."""
    return lax.ppermute(x, axis, perm)


def ring_shift(x: jax.Array, axis: str, shift: int = 1) -> jax.Array:
    """Shift shards around the ring formed by ``axis`` — the KV-ring exchange
    of ring attention (``ops/ParallelAttention.cc:611``)."""
    n = lax.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def axis_index(axis: str) -> jax.Array:
    return lax.axis_index(axis)


def axis_size(axis: str) -> int:
    return lax.axis_size(axis)


def barrier() -> None:
    """Host-level barrier (reference gRPC Barrier, heturpc.proto:44).

    Within a single jit program XLA collectives are self-synchronizing; this
    is only for host-side coordination between programs.
    """
    # Tiny all-reduce over all devices, blocking until complete.
    n = jax.device_count()
    if n > 1:
        x = jnp.ones((n,))
        jax.block_until_ready(
            jax.pmap(lambda v: lax.psum(v, "i"), axis_name="i")(x))


# -- split collectives (hetero ZeRO, ops/Communication.h:655-845) -----------
#
# The reference defines SplitAllGather/SplitAllReduce/SplitReduceScatter that
# run a collective independently over *sub-groups* of unequal sizes (needed
# when hetero pipelines give parameter shards different replication factors).
# On TPU, unequal sub-groups of one logical axis are expressed by reshaping
# the mesh axis into (outer, inner) axes; the inner axis is the sub-group.
# These wrappers document the mapping and implement the equal-subgroup case.

def split_all_reduce(x: jax.Array, subgroup_axis: str) -> jax.Array:
    return lax.psum(x, subgroup_axis)


def split_all_gather(x: jax.Array, subgroup_axis: str,
                     gather_dim: int = 0) -> jax.Array:
    return lax.all_gather(x, subgroup_axis, axis=gather_dim, tiled=True)


def split_reduce_scatter(x: jax.Array, subgroup_axis: str,
                         scatter_dim: int = 0) -> jax.Array:
    return lax.psum_scatter(x, subgroup_axis, scatter_dimension=scatter_dim,
                            tiled=True)
