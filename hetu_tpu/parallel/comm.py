"""Collective communication primitives.

TPU-native equivalent of the reference's communication backend
(``hetu/impl/communication/comm_group.h:27-144`` virtual collective set and
the graph-level comm ops in ``hetu/graph/ops/Communication.h``).  Instead of
NCCL groups on dedicated CUDA streams, collectives here are XLA ops emitted
inside ``shard_map``/pjit over a named mesh axis; XLA schedules them onto
ICI/DCN and overlaps with compute (async collectives).

Mapping table (reference -> ours):

==============================  =====================================
``AllReduce``                   :func:`all_reduce` (``lax.psum``)
``AllGather(gather_dim)``       :func:`all_gather`
``ReduceScatter(scatter_dim)``  :func:`reduce_scatter` (``lax.psum_scatter``)
``AlltoAll``                    :func:`all_to_all`
``Broadcast/Reduce``            :func:`broadcast` / :func:`reduce`
``Send/Recv/BatchedISendIRecv`` :func:`ppermute` rings/sets
``AllReduceCoalesce``           XLA all-reduce combining (automatic)
``Barrier``                     :func:`barrier`
==============================  =====================================

All functions must be called *inside* a ``shard_map``-ed function with the
named axis in scope (the usual jax idiom); the graph layer and the parallel
nn layers arrange that.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def shard_map(f, mesh, in_specs, out_specs, check_rep: bool = False):
    """Version-stable shard_map wrapper (jax>=0.8 renamed check_rep)."""
    return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=check_rep)


def all_reduce(x: jax.Array, axis: str, op: str = "sum") -> jax.Array:
    if op == "sum":
        return lax.psum(x, axis)
    if op == "mean":
        return lax.pmean(x, axis)
    if op == "max":
        return lax.pmax(x, axis)
    if op == "min":
        return lax.pmin(x, axis)
    raise ValueError(f"unsupported reduce op {op!r}")


def all_gather(x: jax.Array, axis: str, gather_dim: int = 0,
               tiled: bool = True) -> jax.Array:
    """Gather shards along ``gather_dim`` (reference AllGather, comm_group.h:95)."""
    return lax.all_gather(x, axis, axis=gather_dim, tiled=tiled)


def reduce_scatter(x: jax.Array, axis: str, scatter_dim: int = 0) -> jax.Array:
    """Sum-reduce then scatter along ``scatter_dim`` (comm_group.h:101)."""
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_dim, tiled=True)


def all_to_all(x: jax.Array, axis: str, split_dim: int,
               concat_dim: int, tiled: bool = True) -> jax.Array:
    """AlltoAll (comm_group.h:77) — the EP/MoE dispatch primitive."""
    return lax.all_to_all(x, axis, split_axis=split_dim,
                          concat_axis=concat_dim, tiled=tiled)


def broadcast(x: jax.Array, axis: str, root: int = 0) -> jax.Array:
    """Broadcast from ``root`` along ``axis`` (comm_group.h:63)."""
    idx = lax.axis_index(axis)
    n = lax.axis_size(axis)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(masked, axis)


def reduce(x: jax.Array, axis: str, root: int = 0) -> jax.Array:
    """Reduce to ``root`` (others receive zeros) (comm_group.h:85)."""
    s = lax.psum(x, axis)
    idx = lax.axis_index(axis)
    return jnp.where(idx == root, s, jnp.zeros_like(s))


def ppermute(x: jax.Array, axis: str,
             perm: Sequence[Tuple[int, int]]) -> jax.Array:
    """Point-to-point permutation — the reference's ``BatchedISendIRecv``
    (comm_group.h:120): an arbitrary set of (src, dst) pairs exchanged as one
    grouped transfer."""
    return lax.ppermute(x, axis, perm)


def ring_shift(x: jax.Array, axis: str, shift: int = 1) -> jax.Array:
    """Shift shards around the ring formed by ``axis`` — the KV-ring exchange
    of ring attention (``ops/ParallelAttention.cc:611``)."""
    n = lax.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def axis_index(axis: str) -> jax.Array:
    return lax.axis_index(axis)


def axis_size(axis: str) -> int:
    return lax.axis_size(axis)


def barrier(coordinator=None, name: str = "default",
            world_size: Optional[int] = None,
            timeout: float = 60.0) -> None:
    """Host-level barrier (reference gRPC Barrier, heturpc.proto:44).

    Within a single jit program XLA collectives are self-synchronizing;
    this is only for host-side coordination between programs.

    Single-host: a tiny device all-reduce (drains in-flight programs on
    all local devices).  Multi-host: pass the process's
    ``rpc.CoordinatorClient`` as ``coordinator`` — the barrier then goes
    through its cross-host rendezvous (``CoordinatorClient.barrier``),
    the way the reference routes Barrier through heturpc.  When a client
    has been registered via :func:`set_coordinator` it is used
    automatically.
    """
    coord = coordinator if coordinator is not None else _COORDINATOR[0]
    if coord is not None:
        # an unresolvable world size would make the server release the
        # barrier immediately (n=0) — a silent no-op; fail loudly instead
        ws = world_size if world_size is not None \
            else getattr(coord, "world_size", None)
        if not ws:
            raise ValueError(
                "coordinator barrier needs a world_size (pass it here or "
                "start the CoordinatorServer with world_size=N)")
        coord.barrier(name=name, world_size=ws, timeout=timeout)
        return
    # Tiny all-reduce over all devices, blocking until complete.
    n = jax.device_count()
    if n > 1:
        x = jnp.ones((n,))
        jax.block_until_ready(
            jax.pmap(lambda v: lax.psum(v, "i"), axis_name="i")(x))


def partial_reduce(x: jax.Array, axis: str, participating,
                   op: str = "mean") -> jax.Array:
    """Partial (asynchronous-DP) reduce — v1's ``PartialReduce``
    (``v1/python/hetu/preduce.py:8``): only the *ready* subset of ranks
    contributes; everyone receives the subset's mean (or sum).

    ``participating`` is a per-rank scalar (bool/0-1, may be traced):
    unlike the reference, which forms an ad-hoc NCCL group from the ranks
    that arrived within a time window, XLA groups are static — so the
    subset is expressed as a mask and lowered to one full-axis ``psum``
    of masked contributions plus a participant count.  Ranks outside the
    subset still receive the reduced value (the v1 semantics: stale
    workers adopt the fresh average on their next partial round).
    """
    p = jnp.asarray(participating, x.dtype)
    total = lax.psum(x * p, axis)
    if op == "sum":
        return total
    if op == "mean":
        count = lax.psum(p, axis)
        return total / jnp.maximum(count, 1)
    raise ValueError(f"unsupported partial_reduce op {op!r}")


_COORDINATOR: list = [None]


def set_coordinator(client) -> None:
    """Register the process's CoordinatorClient so :func:`barrier` (and
    other host-level sync points) route through the cross-host
    coordinator instead of the local-device fallback."""
    _COORDINATOR[0] = client


# -- split collectives (hetero ZeRO, ops/Communication.h:655-845) -----------
#
# The reference defines SplitAllGather/SplitAllReduce/SplitReduceScatter that
# run a collective independently over *sub-groups* of unequal sizes (needed
# when hetero pipelines give parameter shards different replication factors).
# ``groups`` is a static partition of the axis indices, e.g. [[0,1,2],
# [3,4,5,6,7]] — subgroup sizes may differ.  Without ``groups`` the whole
# axis is one group (the homogeneous case).
#
# XLA's AllReduce takes unequal replica groups natively (axis_index_groups);
# AllGather/ReduceScatter are shape-uniform in SPMD, so the unequal cases
# pad to the largest subgroup: split_all_gather returns
# max_group_size*shard rows per rank (rows beyond the own group's
# contribution are zero), split_reduce_scatter returns L//min(group sizes)
# rows (rows beyond the own rank's L//group_size chunk are zero).  The
# per-rank valid extents are static, derivable from ``groups`` — the same
# contract as the reference's per-group tensor lists.


def _norm_groups(groups, n: int):
    """Validate + normalize a static group partition of range(n)."""
    gs = [list(map(int, g)) for g in groups]
    flat = sorted(i for g in gs for i in g)
    if flat != list(range(n)):
        raise ValueError(
            f"groups {gs} must partition the {n} axis indices exactly")
    return gs


def _group_tables(groups, n: int):
    """(group_id [n], members [n_groups, max_g] padded with -1,
    rank_in_group [n], group_size [n]) as numpy arrays."""
    import numpy as np
    gid = np.zeros(n, np.int32)
    rin = np.zeros(n, np.int32)
    gsz = np.zeros(n, np.int32)
    max_g = max(len(g) for g in groups)
    members = np.full((len(groups), max_g), -1, np.int32)
    for g_i, g in enumerate(groups):
        for r, dev in enumerate(g):
            gid[dev] = g_i
            rin[dev] = r
            gsz[dev] = len(g)
            members[g_i, r] = dev
    return gid, members, rin, gsz


def split_all_reduce(x: jax.Array, subgroup_axis: str,
                     groups: Optional[Sequence[Sequence[int]]] = None
                     ) -> jax.Array:
    """AllReduce within each (possibly unequal) subgroup
    (SplitAllReduceOp, ops/Communication.h:718)."""
    if groups is None:
        return lax.psum(x, subgroup_axis)
    n = lax.axis_size(subgroup_axis)
    gs = _norm_groups(groups, n)
    return lax.psum(x, subgroup_axis,
                    axis_index_groups=[tuple(g) for g in gs])


def split_all_gather(x: jax.Array, subgroup_axis: str,
                     gather_dim: int = 0,
                     groups: Optional[Sequence[Sequence[int]]] = None
                     ) -> jax.Array:
    """AllGather within each subgroup (SplitAllGatherOp,
    ops/Communication.h:655).  With unequal ``groups`` the result is
    padded to max group size: shape[gather_dim] ==
    max_g * x.shape[gather_dim]; each rank's first
    own_group_size * shard rows are its group's concatenated shards, the
    rest zeros."""
    if groups is None:
        return lax.all_gather(x, subgroup_axis, axis=gather_dim, tiled=True)
    gather_dim = gather_dim % x.ndim
    n = lax.axis_size(subgroup_axis)
    gs = _norm_groups(groups, n)
    sizes = {len(g) for g in gs}
    if len(sizes) == 1:
        return lax.all_gather(x, subgroup_axis, axis=gather_dim, tiled=True,
                              axis_index_groups=[tuple(g) for g in gs])
    gid_t, members_t, _, _ = _group_tables(gs, n)
    my = lax.axis_index(subgroup_axis)
    # full-axis gather, then select own group's members (padded to max_g)
    allx = lax.all_gather(x, subgroup_axis, axis=0, tiled=False)  # [n, ...]
    members = jnp.asarray(members_t)[jnp.asarray(gid_t)[my]]      # [max_g]
    picked = jnp.take(allx, jnp.maximum(members, 0), axis=0)
    mask_shape = [members.shape[0]] + [1] * (picked.ndim - 1)
    picked = jnp.where((members >= 0).reshape(mask_shape), picked, 0)
    # tile into gather_dim:  [max_g, ..., s, ...] -> [..., max_g*s, ...]
    picked = jnp.moveaxis(picked, 0, gather_dim)
    shape = list(x.shape)
    shape[gather_dim] = members.shape[0] * x.shape[gather_dim]
    return picked.reshape(shape)


def split_reduce_scatter(x: jax.Array, subgroup_axis: str,
                         scatter_dim: int = 0,
                         groups: Optional[Sequence[Sequence[int]]] = None
                         ) -> jax.Array:
    """ReduceScatter within each subgroup (SplitReduceScatterOp,
    ops/Communication.h:782).  With unequal ``groups`` the result is
    padded to the largest chunk (L // min group size); each rank's first
    L // own_group_size rows are its chunk of the group-reduced tensor,
    the rest zeros."""
    if groups is None:
        return lax.psum_scatter(x, subgroup_axis,
                                scatter_dimension=scatter_dim, tiled=True)
    scatter_dim = scatter_dim % x.ndim
    n = lax.axis_size(subgroup_axis)
    gs = _norm_groups(groups, n)
    sizes = {len(g) for g in gs}
    if len(sizes) == 1:
        return lax.psum_scatter(x, subgroup_axis,
                                scatter_dimension=scatter_dim, tiled=True,
                                axis_index_groups=[tuple(g) for g in gs])
    L = x.shape[scatter_dim]
    for g in gs:
        if L % len(g) != 0:
            raise ValueError(
                f"scatter dim {L} not divisible by subgroup size {len(g)}")
    max_chunk = L // min(sizes)
    gid_t, _, rin_t, gsz_t = _group_tables(gs, n)
    my = lax.axis_index(subgroup_axis)
    reduced = lax.psum(x, subgroup_axis,
                       axis_index_groups=[tuple(g) for g in gs])
    chunk = L // jnp.asarray(gsz_t)[my]                # traced per-rank
    offset = jnp.asarray(rin_t)[my] * chunk
    # static-size slice of max_chunk starting at offset (pad tail so the
    # slice never clamps into another rank's chunk), then mask the excess
    pad = [(0, 0)] * x.ndim
    pad[scatter_dim] = (0, max_chunk)
    padded = jnp.pad(reduced, pad)
    starts = [jnp.int32(0)] * x.ndim
    starts[scatter_dim] = offset
    sizes_out = list(x.shape)
    sizes_out[scatter_dim] = max_chunk
    out = lax.dynamic_slice(padded, starts, sizes_out)
    pos_shape = [1] * x.ndim
    pos_shape[scatter_dim] = max_chunk
    pos = jnp.arange(max_chunk).reshape(pos_shape)
    return jnp.where(pos < chunk, out, 0)
