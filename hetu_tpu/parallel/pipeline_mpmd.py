"""MPMD pipeline runtime — per-stage programs on device submeshes.

The reference executes pipelines as per-rank task loops over a generated
schedule (``ExecutableGraph::CrucialRun``, ``executable_graph.cc:1788``:
``GeneratePipedreamFlushSchedule`` + per-micro-batch ``ComputeFunc`` with
P2P at stage boundaries).  Under XLA's SPMD model a single program cannot
give different stages genuinely different amounts of work — masking makes
a slow device burn the same wall clock — so heterogeneous pipelines
(Malleus: unequal layers per stage, unequal micro-batches per pipeline)
are expressed here the multi-program way:

- every stage is its own jitted program compiled for its own
  ``jax.sharding.Mesh`` submesh (dp/tp inside the stage via GSPMD);
- a controller walks the 1F1B (or GPipe) schedule from
  :mod:`hetu_tpu.parallel.schedule`, enqueueing stage computations; JAX's
  async dispatch overlaps stages that live on disjoint devices (the
  analogue of the reference's per-rank CUDA streams);
- stage-boundary activations/grads move with ``jax.device_put`` between
  submeshes (ICI transfers; the reference's ``kP2PStream`` send/recv);
- backward stashes only the stage *input* and recomputes the forward
  inside the vjp (activation recompute by default, like running the
  reference with recompute on), so the live-memory profile is the
  schedule's in-flight bound: ``S - s`` for 1F1B vs ``M`` for GPipe.

Per-step memory/teardown accounting is kept in :class:`StepStats` so
tests can assert the 1F1B < GPipe activation high-water directly.
"""
from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..obs.tracer import get_tracer
from .schedule import (Task, generate_gpipe_schedule,
                       generate_interleaved_1f1b_schedule,
                       generate_pipedream_flush_schedule, max_in_flight,
                       validate_schedule)


def _put(tree, mesh: Optional[Mesh], spec: P):
    """Transfer a pytree onto ``mesh`` with ``spec`` (stage-boundary P2P)."""
    if mesh is None:
        return tree
    sh = NamedSharding(mesh, spec)
    return jax.tree_util.tree_map(lambda a: jax.device_put(a, sh), tree)


def _tree_bytes(tree) -> int:
    return sum(a.size * a.dtype.itemsize
               for a in jax.tree_util.tree_leaves(tree)
               if hasattr(a, "dtype"))


# grad scale+accumulate as ONE jitted call per B task (donated
# accumulator) — per-leaf eager dispatch here was the dominant controller
# cost per task (the reference keeps its hot loop free of per-tensor host
# work, executable_graph.cc:1424).  Module-level so every stage shares
# one jit cache; `w.astype(a.dtype)` keeps grad dtypes (bf16 stages must
# not be promoted to f32 by a strongly-typed scalar).
_scale_grads = jax.jit(
    lambda dp, w: jax.tree_util.tree_map(
        lambda a: a * w.astype(a.dtype), dp))
_accum_grads = jax.jit(
    lambda acc, dp, w: jax.tree_util.tree_map(
        lambda a, b: a + b * w.astype(b.dtype), acc, dp),
    donate_argnums=0)


class Stage:
    """One pipeline stage: a forward program (+ derived backward) on a
    device submesh.

    ``fwd(params, x, rng) -> y`` for non-last stages;
    ``loss_fwd(params, x, target, rng) -> scalar mean loss`` on the last
    stage (the loss lives with the last stage, as in the reference).
    ``act_spec`` is the PartitionSpec of the activation on this stage's
    submesh (usually ``P("dp", None, ...)``).
    """

    def __init__(self, fwd: Callable, params: Any,
                 mesh: Optional[Mesh] = None,
                 act_spec: P = P(),
                 is_last: bool = False):
        self.params = params
        self.mesh = mesh
        self.act_spec = act_spec
        self.is_last = is_last
        self._fwd = fwd
        if is_last:
            # fused F+B on the last stage: B(m) directly follows F(m) in
            # every schedule.  vjp rather than value_and_grad so an
            # integer x (S == 1: the stage input is the token ids) yields
            # a float0 cotangent instead of an error.
            def _loss_grads(params, x, target, rng):
                loss, vjp = jax.vjp(
                    lambda p, xx: fwd(p, xx, target, rng), params, x)
                dp, dx = vjp(jnp.ones_like(loss))
                return loss, dp, dx
            self.step_last = jax.jit(_loss_grads)
            self.fwd_only = jax.jit(lambda p, x, t, r: fwd(p, x, t, r))
        else:
            self.fwd_jit = jax.jit(fwd)

            def _bwd(params, x, rng, dy):
                _, vjp = jax.vjp(lambda p, xx: fwd(p, xx, rng), params, x)
                dp, dx = vjp(dy)
                return dp, dx
            self.bwd_jit = jax.jit(_bwd)


@dataclass
class StepStats:
    """Per-step accounting the tests assert on."""
    loss: float = 0.0
    stash_peak: List[int] = field(default_factory=list)      # per (pipe,stage)
    stash_peak_bytes: List[int] = field(default_factory=list)
    schedule: str = ""
    # controller dispatch accounting: wall time of the host task loop
    # (device work is dispatched async inside it) and the final
    # loss-fetch sync, so dispatch overhead is measurable (the per-stage
    # jit-call MPMD design trades this for flexibility)
    controller_seconds: float = 0.0
    sync_seconds: float = 0.0
    num_tasks: int = 0

    @property
    def max_stash(self) -> int:
        return max(self.stash_peak) if self.stash_peak else 0


class MPMDPipelineRuntime:
    """Drive P pipelines of S stages through a pipeline schedule.

    ``pipes[p]`` is the list of :class:`Stage` for pipeline ``p``
    (pipelines may have *different* per-stage layer counts — their
    programs are independent).  ``train_step`` takes per-pipeline lists of
    ``(x_mb, target_mb)`` micro-batches (lengths may differ per pipeline:
    Malleus micro-batch apportionment) and returns the sample-weighted
    mean loss plus per-stage parameter grads, already summed across
    pipelines per :meth:`reduce` keys.
    """

    def __init__(self, pipes: Sequence[Sequence[Stage]],
                 schedule: str = "1f1b", num_chunks: int = 1):
        assert pipes and all(len(p) == len(pipes[0]) for p in pipes), \
            "all pipelines must have the same number of stages"
        self.pipes = [list(p) for p in pipes]
        self.num_stages = len(self.pipes[0])
        if schedule not in ("1f1b", "gpipe", "interleaved"):
            raise ValueError(
                f"unknown schedule {schedule!r}; pick 1f1b | gpipe | "
                f"interleaved")
        self.schedule_name = schedule
        # interleaved virtual stages: pipes carry S*C entries whose meshes
        # repeat with period S (chunk c of physical stage s at c*S + s)
        self.num_chunks = int(num_chunks)
        if schedule == "interleaved":
            assert self.num_chunks > 1, \
                "schedule='interleaved' needs num_chunks > 1"
            assert self.num_stages % self.num_chunks == 0, \
                (self.num_stages, self.num_chunks)
        for p in self.pipes:
            assert p[-1].is_last and not any(st.is_last for st in p[:-1])
        # per-(pipe, stage, micro-batch) memory snapshots when enabled via
        # HETU_MEMORY_PROFILE=MICRO_BATCH (reference
        # executable_graph.cc:1738-1761 _all_micro_batches_memory_info)
        from ..utils.profiler import MemoryProfiler
        self.memory_profiler = MemoryProfiler()
        # per-(P, counts) jitted rng-table builders: fold_in costs ~5ms
        # of host dispatch per eager call, so the whole table is built in
        # ONE jit call per step instead of 2 fold_ins per task
        self._fold_cache: Dict[Tuple, Any] = {}
        # executed-order p2p tap: one ("send"|"recv", "F"|"B", pipe,
        # stage, micro_batch, peer_stage) entry per stage-boundary
        # transfer the controller actually performed, in execution
        # order.  Reset each train_step.  The schedule verifier's
        # symbolic projection (``schedule.p2p_events``) must match this
        # log exactly — the tap is what makes that claim testable.
        self.p2p_log: List[Tuple[str, str, int, int, int, int]] = []

    def _schedule(self, M: int) -> List[List[Task]]:
        if self.schedule_name == "interleaved":
            sched = generate_interleaved_1f1b_schedule(
                self.num_stages // self.num_chunks, M, self.num_chunks)
        else:
            gen = (generate_pipedream_flush_schedule if self.schedule_name
                   == "1f1b" else generate_gpipe_schedule)
            sched = gen(self.num_stages, M)
        validate_schedule(sched, M)
        return sched

    def train_step(self, data: Sequence[Sequence[Tuple[Any, Any]]],
                   rng: Optional[jax.Array] = None
                   ) -> Tuple[Any, List[List[Any]], StepStats]:
        """Run one step.  Returns (mean_loss, grads[p][s], stats).

        grads[p][s] matches pipes[p][s].params; each micro-batch's loss is
        a mean over its own samples, so grads are rescaled by
        ``m_p / M_total`` to make the step equivalent to one global-batch
        mean regardless of the per-pipeline micro-batch apportionment.
        """
        P_n = len(self.pipes)
        counts = [len(d) for d in data]
        assert len(data) == P_n and all(counts)
        M_total = sum(counts)
        stats = StepStats(schedule=self.schedule_name)

        # per-pipe schedules (each pipe has its own micro-batch count)
        scheds = [self._schedule(m) for m in counts]
        ptr = [[0] * self.num_stages for _ in range(P_n)]
        # in-flight state, keyed (pipe, stage, mb)
        acts: Dict[Tuple[int, int, int], Any] = {}
        stash: Dict[Tuple[int, int, int], Any] = {}
        gin: Dict[Tuple[int, int, int], Any] = {}
        stash_live = [[0] * self.num_stages for _ in range(P_n)]
        stash_peak = [[0] * self.num_stages for _ in range(P_n)]
        stash_bytes = [[0] * self.num_stages for _ in range(P_n)]
        grads: List[List[Any]] = [[None] * self.num_stages
                                  for _ in range(P_n)]
        losses: List[List[Any]] = [[] for _ in range(P_n)]
        if rng is None:
            rng = jax.random.PRNGKey(0)

        self.p2p_log = []
        # seed stage-0 inputs
        for p in range(P_n):
            for m, (x_mb, _) in enumerate(data[p]):
                acts[(p, 0, m)] = x_mb

        fold_key = (P_n, tuple(counts))
        fold_fn = self._fold_cache.get(fold_key)
        if fold_fn is None:
            def _rng_table(r, _counts=tuple(counts), _P=P_n):
                return [[jax.random.fold_in(jax.random.fold_in(r, p), m)
                         for m in range(_counts[p])] for p in range(_P)]
            fold_fn = jax.jit(_rng_table)
            self._fold_cache[fold_key] = fold_fn
        # host numpy keys: uncommitted inputs keep every stage's jit call
        # on the C++ fast path (a device-committed key from the default
        # device forces a slow-path reshard per call on the submeshes)
        rngs = jax.device_get(fold_fn(rng))

        def mb_rng(p, m):
            return rngs[p][m]

        def ready(p, s, t: Task) -> bool:
            if t.kind == "F":
                return (p, s, t.micro_batch) in acts
            if s == self.num_stages - 1:
                return (p, s, t.micro_batch) in acts
            return (p, s, t.micro_batch) in gin

        w_arr = jnp.float32(1.0 / M_total)   # hoisted: one host->dev put

        def run_task(p, s, t: Task) -> None:
            stage = self.pipes[p][s]
            m = t.micro_batch
            if t.kind == "F":
                if s > 0:
                    # the popped activation arrived from stage s-1's
                    # _put — the forward recv side of the boundary
                    self.p2p_log.append(("recv", "F", p, s, m, s - 1))
                x = acts.pop((p, s, m))
                if stage.is_last:
                    # loss+grads fused into the B task; keep the input
                    acts[(p, s, m)] = x
                    return
                y = stage.fwd_jit(stage.params, x, mb_rng(p, m))
                stash[(p, s, m)] = x
                stash_live[p][s] += 1
                stash_peak[p][s] = max(stash_peak[p][s], stash_live[p][s])
                stash_bytes[p][s] = max(stash_bytes[p][s],
                                        stash_live[p][s] * _tree_bytes(x))
                nxt = self.pipes[p][s + 1]
                acts[(p, s + 1, m)] = _put(y, nxt.mesh, nxt.act_spec)
                self.p2p_log.append(("send", "F", p, s, m, s + 1))
                return
            # backward
            if stage.is_last:
                x = acts.pop((p, s, m))
                tgt = data[p][m][1]
                loss, dp, dx = stage.step_last(stage.params, x, tgt,
                                               mb_rng(p, m))
                losses[p].append(loss)
            else:
                x = stash.pop((p, s, m))
                stash_live[p][s] -= 1
                self.p2p_log.append(("recv", "B", p, s, m, s + 1))
                dy = gin.pop((p, s, m))
                dp, dx = stage.bwd_jit(stage.params, x, mb_rng(p, m), dy)
            grads[p][s] = _scale_grads(dp, w_arr) \
                if grads[p][s] is None \
                else _accum_grads(grads[p][s], dp, w_arr)
            if s > 0:
                # dx has the shape/spec of THIS stage's input activation;
                # it lands on the previous stage's submesh
                prev = self.pipes[p][s - 1]
                gin[(p, s - 1, m)] = _put(dx, prev.mesh, stage.act_spec)
                self.p2p_log.append(("send", "B", p, s, m, s - 1))

        # controller loop: round-robin over (pipe, stage), executing the
        # next schedule task whenever its input is available (the
        # reference's CrucialRun task loop, one controller instead of one
        # process per rank)
        remaining = sum(len(s) for sch in scheds for s in sch)
        stats.num_tasks = remaining
        tracer = get_tracer()
        t_ctrl = time.perf_counter()
        while remaining:
            progress = False
            for p in range(P_n):
                for s in range(self.num_stages):
                    i = ptr[p][s]
                    if i >= len(scheds[p][s]):
                        continue
                    t = scheds[p][s][i]
                    if ready(p, s, t):
                        if tracer.enabled:
                            # per-stage-task span (trace plane): dispatch
                            # wall time per pipe/stage row — async XLA
                            # execution overlaps under it, so this shows
                            # the SCHEDULE shape, not device occupancy
                            _ts = tracer.now()
                            run_task(p, s, t)
                            tracer.complete(
                                f"{t.kind} mb{t.micro_batch}", _ts,
                                tracer.now() - _ts,
                                track=f"pipe{p}/stage{s}", pipe=p,
                                stage=s, micro_batch=t.micro_batch,
                                kind=t.kind)
                        else:
                            run_task(p, s, t)
                        if self.memory_profiler.enabled:
                            self.memory_profiler.snapshot(
                                f"pipe{p}.stage{s}.{t.kind}",
                                micro_batch_id=t.micro_batch)
                        ptr[p][s] = i + 1
                        remaining -= 1
                        progress = True
            assert progress, "pipeline schedule deadlocked"
        stats.controller_seconds = time.perf_counter() - t_ctrl

        # weighted mean loss (micro-batch losses are per-mb means); pipes
        # live on disjoint submeshes, so the cross-pipe sum happens on
        # host at the step boundary — ONE stacked fetch per pipe, not a
        # device->host sync per micro-batch
        t_sync = time.perf_counter()
        loss = sum(float(np.asarray(jnp.stack(l)).sum())
                   for l in losses if l) / M_total
        stats.sync_seconds = time.perf_counter() - t_sync
        for p in range(P_n):
            stats.stash_peak.extend(stash_peak[p])
            stats.stash_peak_bytes.extend(stash_bytes[p])
        stats.loss = float(loss)
        return loss, grads, stats


# ---------------------------------------------------------------------------
# static-analysis registration


def register_stage_executables(runtime: "MPMDPipelineRuntime", name: str,
                               stage_args, stage_meta=None) -> List[str]:
    """Register every stage program of an MPMD pipeline with the static
    analyzer (``hetu_tpu.analysis``): last stages register their fused
    loss+grads program (``step_last``, a train executable), the others
    their forward.

    ``stage_args(p, s, stage) -> tuple`` returns the abstract argument
    specs (ShapeDtypeStructs) the stage's jit is traced with;
    ``stage_meta(p, s, stage) -> dict`` optionally supplies extra
    registration meta (declared DS-transition edges, pipeline hop info,
    param pspecs) merged over the defaults.  Returns the registered
    names (``{name}/pipe{p}-stage{s}``).
    """
    from ..graph.graph import clear_executables, register_executable
    clear_executables(name)
    names: List[str] = []
    S = runtime.num_stages
    for p, pipe in enumerate(runtime.pipes):
        for s, stage in enumerate(pipe):
            mesh_axes = {str(a): int(sz)
                         for a, sz in stage.mesh.shape.items()} \
                if stage.mesh is not None else {}
            meta: Dict[str, Any] = {
                "kind": "pipeline_stage",
                "train": bool(stage.is_last),
                "mesh_axes": mesh_axes,
                "params": [],
                "scalar_fetches": 1 if stage.is_last else 0,
                # stage boundaries move via jax.device_put between
                # submeshes (the reference's kP2PStream), not via
                # in-program collectives — hops live in the controller
                "pipeline": {"num_stages": S, "stage": s, "hops": 0},
            }
            if stage_meta is not None:
                extra = stage_meta(p, s, stage) or {}
                pl = {**meta["pipeline"], **(extra.pop("pipeline", {}))}
                meta.update(extra)
                meta["pipeline"] = pl
            fn = stage.step_last if stage.is_last else stage.fwd_jit
            ex_name = f"{name}/pipe{p}-stage{s}"
            register_executable(ex_name, fn, stage_args(p, s, stage),
                                meta)
            names.append(ex_name)
    return names


# ---------------------------------------------------------------------------
# cross-pipeline (hetero-DP) grad reduction


def reduce_layer_grads(runtime: MPMDPipelineRuntime,
                       grads: List[List[Any]],
                       layer_keys: List[List[Sequence[Any]]]
                       ) -> List[List[Any]]:
    """Sum grads across pipelines for params shared by key.

    ``layer_keys[p][s]`` is a pytree-of-keys matching ``grads[p][s]``'s
    top-level dict entries: entries with equal keys across pipelines are
    the same logical parameter (e.g. global layer index, "wte") and their
    grads are summed (the hetero-DP grad exchange; reference hetero-ZeRO
    SplitAllReduce, ``ops/Communication.h:655``).  Entries keyed ``None``
    are pipeline-private.  Reduction happens on the owning stage's mesh of
    pipeline 0 and results are broadcast back to every pipeline's copy.
    """
    P_n = len(runtime.pipes)
    # collect: key -> list of (p, s, entry_name); note a key can repeat
    # across *stages* of one pipeline too (tied wte on first/last stage)

    locations: Dict[Any, List[Tuple[int, int, Any]]] = {}
    for p in range(P_n):
        for s, keys in enumerate(layer_keys[p]):
            for name, key in keys.items():
                if key is None:
                    continue
                locations.setdefault(key, []).append((p, s, name))
    for key, locs in locations.items():
        if len(locs) < 2:
            continue
        p0, s0, n0 = locs[0]
        home = runtime.pipes[p0][s0]
        total = grads[p0][s0][n0]
        for (p, s, n) in locs[1:]:
            g = _put(grads[p][s][n], home.mesh, P())
            total = jax.tree_util.tree_map(jnp.add, total, g)
        for (p, s, n) in locs:
            st = runtime.pipes[p][s]
            grads[p][s][n] = _put(total, st.mesh, P()) \
                if (p, s) != (p0, s0) else total
    return grads


# ---------------------------------------------------------------------------
# per-stage optimizer


class MPMDAdam:
    """Adam over MPMD stage params: one jitted update per stage program,
    states living on the stage's submesh with the params.

    After :func:`reduce_layer_grads`, replicated copies (DP replicas,
    tied weights) receive identical grads, so identical updates keep the
    copies consistent without any extra broadcast (the reference instead
    re-broadcasts after ZeRO updates; with full states per stage none is
    needed).
    """

    def __init__(self, runtime: MPMDPipelineRuntime, lr: float = 1e-3,
                 beta1: float = 0.9, beta2: float = 0.999,
                 eps: float = 1e-8, weight_decay: float = 0.0):
        self.runtime = runtime
        self.hp = (lr, beta1, beta2, eps, weight_decay)
        self.t = 0
        zeros = lambda tree: jax.tree_util.tree_map(jnp.zeros_like, tree)
        self.m = [[zeros(st.params) for st in pipe]
                  for pipe in runtime.pipes]
        self.v = [[zeros(st.params) for st in pipe]
                  for pipe in runtime.pipes]

        lr_, b1, b2, eps_, wd = self.hp

        def upd(params, g, m, v, t):
            m = jax.tree_util.tree_map(
                lambda mm, gg: b1 * mm + (1 - b1) * gg, m, g)
            v = jax.tree_util.tree_map(
                lambda vv, gg: b2 * vv + (1 - b2) * gg * gg, v, g)
            bc1 = 1 - b1 ** t
            bc2 = 1 - b2 ** t

            def one(p, mm, vv):
                step = lr_ * (mm / bc1) / (jnp.sqrt(vv / bc2) + eps_)
                if wd:
                    step = step + lr_ * wd * p
                return p - step
            params = jax.tree_util.tree_map(one, params, m, v)
            return params, m, v
        self._upd = jax.jit(upd)

    def apply(self, grads: List[List[Any]]) -> None:
        self.t += 1
        t = float(self.t)
        for p, pipe in enumerate(self.runtime.pipes):
            for s, stage in enumerate(pipe):
                if grads[p][s] is None:
                    continue
                stage.params, self.m[p][s], self.v[p][s] = self._upd(
                    stage.params, grads[p][s], self.m[p][s],
                    self.v[p][s], t)
