"""Elastic training engine (Malleus).

TPU-native re-expression of the reference's ``python/elastic/engine``:
straggler profiling, heterogeneity-aware strategy solving, a Trainer
that live-switches the graph between parallel layouts, and — the fault
plane (DESIGN.md §18) — a :class:`FaultTolerantTrainer` that survives
an actual worker death: periodic flat-state snapshots through
``safetensors_io``, coordinator-backed death detection
(:class:`WorkerMonitor`), re-plan on the survivors, restore, and the
loss curve continues exactly.
"""
from .ft import FaultTolerantTrainer, TrainBuild, WorkerMonitor
from .straggler import Straggler, StragglerWorkload
from .strategy import Strategy, StrategyModel
from .trainer import Trainer

__all__ = ["FaultTolerantTrainer", "Straggler", "StragglerWorkload",
           "Strategy", "StrategyModel", "TrainBuild", "Trainer",
           "WorkerMonitor"]
