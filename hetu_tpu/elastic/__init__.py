"""Elastic training engine (Malleus).

TPU-native re-expression of the reference's ``python/elastic/engine``:
straggler profiling, heterogeneity-aware strategy solving, and a Trainer
that live-switches the graph between parallel layouts.
"""
from .straggler import Straggler, StragglerWorkload
from .strategy import Strategy, StrategyModel
from .trainer import Trainer

__all__ = ["Straggler", "StragglerWorkload", "Strategy", "StrategyModel",
           "Trainer"]
