"""Straggler profiling + synthetic straggler workloads.

TPU-native re-expression of the reference's straggler detector
(``python/elastic/engine/straggler.py:20``: per-GPU op timings written to
``HETU_STRAGGLER_LOG_FILE`` by the C++ executor and read back as relative
slowdown ratios) and its fault-injection workloads
(``workloads/cuda/workload_heavy_compute.cu`` — spin kernels launched
beside training; ``examples/malleus/test_straggler_workload.py``).

On TPU a single XLA program is SPMD across the slice, so per-device timing
comes from per-*host* step timing (each host drives its local devices;
slow hosts gate their devices) merged through the coordinator KV store.
For single-process simulation and tests, ratios can be injected via
``HETU_TPU_STRAGGLER_RATIOS`` (comma list) or a registered
:class:`StragglerWorkload` — the analogue of the reference's spin-kernel
injection.
"""
from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

ENV_RATIOS = "HETU_TPU_STRAGGLER_RATIOS"
ENV_LOG_FILE = "HETU_TPU_STRAGGLER_LOG_FILE"


class StragglerWorkload:
    """Synthetic per-device slowdown injection (fault injection for tests;
    reference workload_{heavy_compute,heavy_communicate,stall_communicate}).

    ``ratios[i]`` is the slowdown multiplier of device i (1.0 = healthy).
    When registered on a :class:`Straggler`, profiling reports these ratios
    as if they had been measured.
    """

    def __init__(self, ratios: Sequence[float]):
        self.ratios = [float(r) for r in ratios]

    def perturb(self, base_seconds: float) -> List[float]:
        return [base_seconds * r for r in self.ratios]


class Straggler:
    """Measure relative per-device slowdown ratios.

    Usage (mirrors the reference Straggler)::

        prof = Straggler(num_devices)
        prof.begin_profile()
        for _ in range(k): graph.run(...)   # timed steps
        prof.end_profile(steps=k)
        ratios = prof.read_profile()        # [1.0, 1.0, 1.7, ...]
    """

    def __init__(self, num_devices: int, kv_store=None, host_id: int = 0,
                 devices_per_host: Optional[int] = None):
        self.num_devices = num_devices
        self.kv = kv_store           # coordinator KV (multi-host merge)
        self.host_id = host_id
        self.devices_per_host = devices_per_host or num_devices
        self._t0: Optional[float] = None
        self._seconds_per_step: Optional[float] = None
        self._workload: Optional[StragglerWorkload] = None

    # -- fault injection -----------------------------------------------------

    def inject(self, workload: Optional[StragglerWorkload]) -> None:
        self._workload = workload

    # -- profiling -----------------------------------------------------------

    def begin_profile(self) -> None:
        self._t0 = time.perf_counter()

    def end_profile(self, steps: int = 1) -> None:
        assert self._t0 is not None, "begin_profile not called"
        self._seconds_per_step = (time.perf_counter() - self._t0) / max(1, steps)
        self._t0 = None
        if self.kv is not None:
            self.kv.put(f"straggler/{self.host_id}",
                        json.dumps(self._seconds_per_step))
        log = os.environ.get(ENV_LOG_FILE)
        if log:
            with open(log, "a") as f:
                f.write(json.dumps({"host": self.host_id,
                                    "sec_per_step": self._seconds_per_step})
                        + "\n")

    def read_profile(self) -> List[float]:
        """Relative slowdown ratio per device (min over devices == 1.0)."""
        env = os.environ.get(ENV_RATIOS)
        if env:
            vals = [float(x) for x in env.split(",")]
            assert len(vals) == self.num_devices, \
                f"{ENV_RATIOS} has {len(vals)} entries, " \
                f"need {self.num_devices}"
            return self._normalize(vals)
        if self._workload is not None:
            base = self._seconds_per_step or 1.0
            return self._normalize(self._workload.perturb(base))
        if self.kv is not None:
            # merge per-host step times: a host's devices all inherit its time
            n_hosts = (self.num_devices + self.devices_per_host - 1) \
                // self.devices_per_host
            per_host: List[Optional[float]] = []
            for h in range(n_hosts):
                v = self.kv.get(f"straggler/{h}", timeout=5.0)
                per_host.append(float(json.loads(v)) if v is not None
                                else None)
            observed = [v for v in per_host if v is not None] \
                or [self._seconds_per_step or 1.0]
            # a host that never reported is the straggler scenario itself:
            # treat it as far slower than anything observed, never as healthy
            missing = [h for h, v in enumerate(per_host) if v is None]
            if missing:
                import warnings
                warnings.warn(f"straggler profile missing for hosts "
                              f"{missing}; treating them as 10x slowest")
                worst = max(observed) * 10.0
                per_host = [worst if v is None else v for v in per_host]
            vals = []
            for i in range(self.num_devices):
                vals.append(per_host[i // self.devices_per_host])
            return self._normalize(vals)
        # single-host SPMD: XLA gives no per-device skew; everything healthy
        return [1.0] * self.num_devices

    @staticmethod
    def _normalize(vals: Sequence[float]) -> List[float]:
        lo = min(vals)
        if lo <= 0:
            raise ValueError(f"non-positive straggler timing {vals}")
        return [v / lo for v in vals]
