"""Fault-tolerant elastic training: survive worker deaths AND silent
numeric/durability failures.

The elastic loop so far could re-plan around *stragglers*; a dead
worker was fatal — its parameter and optimizer shards live in its HBM
and are simply gone.  This module closes that gap the Malleus way
(SURVEY.md §3.5), and — ISSUE 14 — extends the same recovery loop to
the failures that never raise anything:

* **Durable snapshots** — every ``checkpoint_every`` steps the trainer
  saves model params + FLAT optimizer state as a checksummed
  checkpoint *generation* (``resilience/generations.py``: fresh
  ``gen-<step>/`` dir, blake2b manifest committed atomically, last-N
  retention).  ``safetensors_io`` decomposes the flat buffers
  per-parameter, so the snapshot restores into ANY dp size.
* **Death detection** — a :class:`WorkerMonitor`: N process-local
  training workers registered on the ``rpc`` coordinator exactly like
  serving replicas, each owning an equal slice of the device list; a
  rank that stops heartbeating past the TTL maps to lost devices.
* **Re-plan + verified restore** — on a death verdict the trainer asks
  :class:`~hetu_tpu.elastic.strategy.StrategyModel` for the best layout
  over the survivors, rebuilds the graph there (``build_fn``), restores
  the newest generation that VERIFIES (falling back past corrupted or
  half-written ones — ``restore_fallbacks``), rewinds to its step, and
  keeps training.  The loss curve *continues exactly*: flat-state math
  is bit-identical across dp sizes, and re-run steps replay the SAME
  data cursors.
* **Numeric sentry ladder** — when the optimizer carries a
  :class:`~hetu_tpu.resilience.sentry.NumericSentry`, every step's
  on-device verdict is read alongside the loss: an anomalous step
  (NaN/Inf loss or grads, grad-norm spike, relative loss spike) was
  already SKIPPED on-device with bitwise-zero residue; the trainer
  burns that data cursor and retries the step on fresh data.  ``k``
  consecutive anomalies — or a loss spike, which means the optimizer
  state itself is suspect — rewind to the last good generation and
  resume with the jumped cursor.

``step_fn(cursor)`` receives a **data cursor**, not the step index:
committed steps pin their cursor (a rewind replays the same batches —
that is what makes re-run losses bit-identical), a skipped step burns
its cursor and draws a fresh one.  FaultPlan seams injected here:
``worker_death``, ``grad_nan`` / ``grad_spike`` / ``loss_spike``
(through :meth:`DefineAndRunGraph.inject_numeric_fault` — a fed code,
never a retrace), ``shard_corrupt`` (byte flips in the newest
generation) and ``kill_mid_write`` (the checkpoint writer dies between
shards).  MTTR (detect → first committed post-recovery step) is
recorded per recovery in :attr:`FaultTolerantTrainer.recoveries`;
counters land in :meth:`FaultTolerantTrainer.metrics_summary` and the
Prometheus text of :meth:`metrics_text` (DESIGN.md §19).
"""
from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..obs.tracer import get_tracer
from ..rpc.coordinator import CoordinatorClient, CoordinatorServer
from ..utils.metrics import make_instrument, render_prometheus
from .strategy import StrategyModel

#: the failure counters the trainer exposes (metrics_summary +
#: Prometheus), next to PR 12's cluster failure counters
TRAINER_COUNTERS = (
    "sentry_anomalies", "steps_skipped", "rewinds", "restore_fallbacks",
    "emergency_flushes", "checkpoints_written",
    "checkpoint_write_failures", "worker_recoveries",
)


class WorkerMonitor:
    """Process-local training workers on the rpc liveness plane.

    Each rank owns ``len(devices) // num_workers`` devices; killing a
    rank (chaos ``worker_death``) stops its heartbeat thread, the
    coordinator's TTL declares it dead, and
    :meth:`surviving_devices` shrinks accordingly.  The same
    coordinator machinery the serving cluster and the multi-host
    bootstrap use — one liveness plane for the whole system."""

    def __init__(self, num_workers: int, devices: Sequence[Any],
                 ttl: float = 0.5, heartbeat_interval: float = 0.1,
                 server: Optional[CoordinatorServer] = None):
        if num_workers < 1 or len(devices) % num_workers:
            raise ValueError(
                f"{len(devices)} devices do not split over "
                f"{num_workers} workers")
        self.devices = list(devices)
        self.num_workers = int(num_workers)
        self.per_worker = len(devices) // num_workers
        self._own_server = server is None
        self.server = server if server is not None else \
            CoordinatorServer(world_size=num_workers, ttl=ttl).start()
        self.clients: List[CoordinatorClient] = []
        self._hb_stops = []
        for i in range(num_workers):
            c = CoordinatorClient(self.server.address,
                                  uid=f"trainer-w{i}", ttl=ttl)
            c.connect()
            self.clients.append(c)
            self._hb_stops.append(
                c.start_heartbeat_thread(interval=heartbeat_interval))

    def kill_worker(self, rank: int) -> None:
        """The injected death: heartbeats stop NOW, the verdict lands
        once the TTL lapses — the same two-step reality a crashed
        remote host has."""
        self._hb_stops[rank].set()

    def dead_workers(self) -> List[int]:
        return self.server.dead_ranks()

    def wait_for_verdict(self, rank: int, timeout: float = 10.0) -> bool:
        """Block until ``rank`` is declared dead (test/bench helper —
        a real loop just polls :meth:`dead_workers` between steps)."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            if rank in self.dead_workers():
                return True
            time.sleep(0.02)
        return False

    def surviving_devices(self, dead: Sequence[int]) -> List[Any]:
        dead = set(dead)
        out: List[Any] = []
        for r in range(self.num_workers):
            if r not in dead:
                out.extend(self.devices[r * self.per_worker:
                                        (r + 1) * self.per_worker])
        return out

    def close(self) -> None:
        for s in self._hb_stops:
            s.set()
        for c in self.clients:
            try:
                c.close()
            except Exception:
                pass
        if self._own_server:
            self.server.stop()


@dataclass
class TrainBuild:
    """What ``build_fn(dp, devices)`` returns: a freshly-built graph on
    the given layout.  ``step_fn(cursor) -> float`` runs one optimizer
    step on the batch the data cursor selects and returns the loss;
    ``model``/``optimizer`` feed the checkpoint plane."""
    graph: Any
    model: Any
    optimizer: Any
    step_fn: Callable[[int], float]
    close: Optional[Callable[[], None]] = None


class FaultTolerantTrainer:
    """Checkpoint → detect → re-plan → verified restore → continue,
    plus the numeric-sentry skip/rewind ladder.

    ``build_fn(dp: int, devices) -> TrainBuild`` must rebuild the SAME
    model deterministically (same init seed) for any dp — recovery
    calls it on the survivor layout and immediately overwrites params +
    optimizer state from the snapshot, so only the architecture needs
    to be reproducible, not the init values.

    ``rewind_after``: k consecutive sentry anomalies before the policy
    ladder rewinds to the last good generation (single anomalies are
    skipped on-device and the step retried on fresh data).
    ``rewind_on_loss_spike``: a loss-spike verdict rewinds immediately
    — a spike with finite gradients means the optimizer state already
    absorbed something poisonous.  ``emergency_flush``: on a death
    verdict, flush the current (survivor-visible) state as an
    ``emergency`` generation before re-planning — best-effort and
    verified on read like every generation, off by default because a
    death mid-step can leave untrustworthy state.
    """

    def __init__(self, build_fn: Callable[..., TrainBuild],
                 devices: Sequence[Any],
                 monitor: Optional[WorkerMonitor] = None,
                 checkpoint_dir: str = "/tmp/hetu_ft_ck",
                 checkpoint_every: int = 4,
                 solver_factory: Optional[
                     Callable[[int], StrategyModel]] = None,
                 keep_checkpoints: int = 2,
                 rewind_after: int = 3,
                 rewind_on_loss_spike: bool = True,
                 max_rewinds: int = 8,
                 emergency_flush: bool = False):
        self.build_fn = build_fn
        self.devices = list(devices)
        self.monitor = monitor
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every = int(checkpoint_every)
        self.keep_checkpoints = int(keep_checkpoints)
        self.rewind_after = int(rewind_after)
        self.rewind_on_loss_spike = bool(rewind_on_loss_spike)
        # termination bound for the ladder: a DETERMINISTIC pathology
        # (every fresh batch anomalous) would otherwise skip->rewind->
        # replay forever; past this many rewinds the trainer surrenders
        # the anomaly loudly instead of churning disk
        self.max_rewinds = int(max_rewinds)
        self.emergency_flush = bool(emergency_flush)
        # default layout policy: pure dp over every available device
        # (the homogeneous solver's own preference); a solver_factory
        # lets hetero-aware callers re-plan tp/pp too
        self.solver_factory = solver_factory
        self.recoveries: List[Dict[str, Any]] = []
        self.step = 0
        self.attempts = 0
        self._handled: set = set()
        self._injected: set = set()            # fault-event identity guard
        self._ck_steps: List[int] = []
        self._killed_at: Optional[float] = None
        # data-cursor plane: committed steps PIN their cursor (rewind
        # replays the same batches), a sentry skip burns its cursor and
        # the retry draws a fresh one
        self._cursor_of_step: Dict[int, int] = {}
        self._next_cursor = 0
        self.burned_cursors: List[int] = []
        self.counters = {name: make_instrument("counter", name)
                         for name in TRAINER_COUNTERS}
        os.makedirs(checkpoint_dir, exist_ok=True)
        self.dp = self._choose_dp(len(self.devices))
        self.build = build_fn(self.dp, self.devices)
        # the step-0 snapshot: a death before the first periodic
        # checkpoint must still have something to restore
        self._checkpoint()

    # -- layout choice -------------------------------------------------------

    def _choose_dp(self, n: int) -> int:
        if self.solver_factory is not None:
            plan = self.solver_factory(n).make_plans([1.0] * n,
                                                     top_k=1)[0]
            return int(plan.dp)
        # default policy: the largest power of two <= n — global batch
        # sizes are overwhelmingly power-of-two, and a dp that does not
        # divide the batch cannot build (a 4-worker fleet losing one
        # worker of 8 devices recovers on dp=4 of the 6 survivors)
        dp = 1
        while dp * 2 <= n:
            dp *= 2
        return dp

    # -- data-cursor plane ---------------------------------------------------

    def _cursor_for(self, step: int) -> int:
        cur = self._cursor_of_step.get(step)
        if cur is None:
            cur = self._next_cursor
            self._next_cursor += 1
            self._cursor_of_step[step] = cur
        return cur

    def _burn_cursor(self, step: int) -> None:
        cur = self._cursor_of_step.pop(step, None)
        if cur is not None:
            self.burned_cursors.append(cur)

    def committed_cursors(self) -> List[int]:
        """The cursor each committed step actually trained on — the
        clean-batch sequence a fault-free reference run must consume to
        reproduce this run's losses bit-for-bit."""
        return [self._cursor_of_step[s] for s in range(self.step)
                if s in self._cursor_of_step]

    # -- checkpoint plane (checksummed generations) --------------------------

    def _checkpoint(self, emergency: bool = False) -> bool:
        from ..resilience.generations import save_generation
        from ..utils.checkpoint import WriterDeathError
        tr = get_tracer()
        try:
            save_generation(self.build.model, self.build.optimizer,
                            self.checkpoint_dir, step=self.step,
                            keep=self.keep_checkpoints,
                            emergency=emergency)
        except WriterDeathError as e:
            # the kill_mid_write chaos verdict: the writer died between
            # shards — the partial generation never committed a
            # manifest, previous generations stay restorable
            self.counters["checkpoint_write_failures"].inc()
            if tr.enabled:
                tr.instant("checkpoint_write_died", track="chaos",
                           ts=tr.now(), step=self.step, error=str(e))
            self._sync_ck_steps()
            return False
        self.counters["checkpoints_written"].inc()
        if tr.enabled:
            tr.instant("checkpoint", track="trainer", ts=tr.now(),
                       step=self.step, emergency=bool(emergency))
        self._sync_ck_steps(self.step)
        return True

    def _sync_ck_steps(self, new_step: Optional[int] = None) -> None:
        """This run's committed generations, post-retention — never a
        stale directory another process left under the same root."""
        from ..resilience.generations import MANIFEST, generation_dir
        steps = set(self._ck_steps)
        if new_step is not None:
            steps.add(int(new_step))
        self._ck_steps = [
            s for s in sorted(steps)
            if os.path.isfile(os.path.join(
                generation_dir(self.checkpoint_dir, s), MANIFEST))]

    def latest_checkpoint(self) -> int:
        return self._ck_steps[-1]

    def _restore_latest(self) -> Dict[str, Any]:
        """Verified restore: newest generation whose digests check,
        falling back past corrupted/partial ones (each fallback is a
        counter bump + a chaos-track instant)."""
        from ..resilience.generations import load_latest_generation
        info = load_latest_generation(self.build.model,
                                      self.build.optimizer,
                                      self.checkpoint_dir,
                                      steps=self._ck_steps)
        if info["fallbacks"]:
            self.counters["restore_fallbacks"].inc(
                len(info["fallbacks"]))
            tr = get_tracer()
            if tr.enabled:
                for fb in info["fallbacks"]:
                    tr.instant("restore_fallback", track="chaos",
                               ts=tr.now(),
                               generation=fb["generation"],
                               problem=fb["problems"][0]
                               if fb["problems"] else "?")
        return info

    # -- recovery: worker death ----------------------------------------------

    def _recover(self, dead: Sequence[int], losses: Dict[int, float],
                 killed_at: Optional[float]) -> None:
        t0 = time.perf_counter()
        survivors = self.monitor.surviving_devices(self._handled)
        if not survivors:
            raise RuntimeError("every worker died; nothing to recover on")
        tr = get_tracer()
        if tr.enabled:
            tr.instant("worker_dead", track="trainer", ts=tr.now(),
                       dead=sorted(dead), survivors=len(survivors),
                       step=self.step)
        detect_step = self.step
        if self.emergency_flush and self.step not in self._ck_steps:
            # best-effort flush of the current state before teardown
            # (skipped when this step already has a committed
            # generation — the flush would re-save identical state and
            # needlessly churn the newest restore point).  Bit-level
            # integrity is digest-verified on read; a flush that dies
            # mid-write never commits and save_generation restores any
            # generation it displaced.
            try:
                if self._checkpoint(emergency=True):
                    self.counters["emergency_flushes"].inc()
                    if tr.enabled:
                        tr.instant("emergency_flush", track="chaos",
                                   ts=tr.now(), step=self.step)
            except Exception as e:
                # a failed flush must not block the recovery, but it
                # must be VISIBLE (counter + chaos instant), not
                # silently discarded
                self.counters["checkpoint_write_failures"].inc()
                if tr.enabled:
                    tr.instant("emergency_flush_failed", track="chaos",
                               ts=tr.now(), step=self.step,
                               error=str(e)[:120])
        new_dp = self._choose_dp(len(survivors))
        # the dead workers' HBM shards are GONE: rebuild on the
        # survivor layout and restore the last durable snapshot —
        # never read the old graph's device state
        if self.build.close is not None:
            self.build.close()
        self.build = self.build_fn(new_dp, survivors)
        info = self._restore_latest()
        ck_step = info["generation"]
        rewound = self.step - ck_step
        for s in range(ck_step, self.step):
            losses.pop(s, None)
        self.step = ck_step
        self.dp = new_dp
        self.counters["worker_recoveries"].inc()
        self._reset_sentry()
        rec = {"kind": "worker_death", "dead": sorted(dead),
               "detected_at_step": detect_step,
               "resumed_from_step": ck_step, "rewound_steps": rewound,
               "restore_fallbacks": len(info["fallbacks"]),
               "dp": new_dp, "devices": len(survivors),
               "rebuild_s": time.perf_counter() - t0,
               # MTTR anchor: the kill instant when this death was
               # injected, else the detection time — per-record, so a
               # later detection can never inherit a stale kill time
               "_t0": killed_at if killed_at is not None else t0,
               "killed_at": killed_at}
        self.recoveries.append(rec)
        if tr.enabled:
            tr.instant("recovered", track="trainer", ts=tr.now(),
                       **{k: v for k, v in rec.items()
                          if k not in ("killed_at",)})

    # -- recovery: numeric rewind --------------------------------------------

    def _reset_sentry(self) -> None:
        sentry = getattr(self.build.optimizer, "sentry", None)
        if sentry is not None:
            # the restored state predates the anomaly streak: forget
            # the EMA/consecutive history with it
            sentry.reset()

    def _sentry_verdict(self) -> Optional[Dict[str, Any]]:
        sentry = getattr(self.build.optimizer, "sentry", None)
        if sentry is None:
            return None
        return sentry.last_verdict()

    def _numeric_rewind(self, losses: Dict[int, float],
                        reason: str) -> None:
        t0 = time.perf_counter()
        tr = get_tracer()
        if not self._ck_steps:
            # nothing committed to rewind to (the step-0 snapshot
            # itself failed to write): stay in skip-only mode rather
            # than abort the run the ladder exists to save — but
            # BOUNDED, or a deterministic pathology loops forever here
            # just like the rewind path max_rewinds ends
            self._rewinds_unavailable = \
                getattr(self, "_rewinds_unavailable", 0) + 1
            if tr.enabled:
                tr.instant("sentry_rewind_unavailable", track="chaos",
                           ts=tr.now(), step=self.step, reason=reason)
            if self._rewinds_unavailable > self.max_rewinds:
                raise RuntimeError(
                    f"numeric anomaly persists with no committed "
                    f"checkpoint generation to rewind to "
                    f"({self._rewinds_unavailable} attempts, last "
                    f"reason: {reason}) — skip-only mode cannot make "
                    f"progress")
            return
        if int(self.counters["rewinds"].value) >= self.max_rewinds:
            raise RuntimeError(
                f"numeric anomaly persists after {self.max_rewinds} "
                f"rewinds (last reason: {reason}) — this is not a "
                f"transient fault; inspect the data/lr/model instead "
                f"of rewinding forever")
        if tr.enabled:
            tr.instant("sentry_rewind", track="chaos", ts=tr.now(),
                       step=self.step, reason=reason)
        info = self._restore_latest()
        ck_step = info["generation"]
        rewound = self.step - ck_step
        for s in range(ck_step, self.step + 1):
            losses.pop(s, None)
        self.step = ck_step
        self._reset_sentry()
        self.counters["rewinds"].inc()
        rec = {"kind": "numeric_rewind", "reason": reason,
               "resumed_from_step": ck_step, "rewound_steps": rewound,
               "restore_fallbacks": len(info["fallbacks"]),
               "rebuild_s": time.perf_counter() - t0,
               "_t0": t0, "mttr_pending": True}
        self.recoveries.append(rec)
        if tr.enabled:
            tr.instant("recovered", track="trainer", ts=tr.now(),
                       kind="numeric_rewind", reason=reason,
                       resumed_from_step=ck_step,
                       rewound_steps=rewound)

    # -- chaos injection seams -----------------------------------------------

    def _apply_fault_events(self, fault_plan) -> None:
        from ..fault.plan import NUMERIC_KINDS
        tr = get_tracer()
        numeric_armed = False
        for ev in fault_plan.due(self.step):
            key = (ev.step, ev.kind, ev.target)
            if key in self._injected:
                continue
            if ev.kind == "worker_death":
                if self.monitor is None or ev.target in self._handled:
                    continue
                self._injected.add(key)
                self.monitor.kill_worker(ev.target)
                self._killed_at = time.perf_counter()
                if tr.enabled:
                    tr.instant("fault", track="chaos", ts=tr.now(),
                               kind="worker_death", target=ev.target,
                               step=self.step)
                # the verdict needs the TTL to lapse; a real fleet
                # just keeps stepping until it lands
                self.monitor.wait_for_verdict(ev.target)
            elif ev.kind in NUMERIC_KINDS:
                # one numeric poison per attempt: a second event due at
                # the same step injects on the retry
                if numeric_armed:
                    continue
                if not hasattr(self.build.graph, "inject_numeric_fault"):
                    continue
                if self.monitor is not None and \
                        set(self.monitor.dead_workers()) - self._handled:
                    # a death verdict is pending: the recovery rebuild
                    # would replace the graph and lose the armed code —
                    # defer (un-marked) to the post-recovery retry
                    continue
                self._injected.add(key)
                numeric_armed = True
                self.build.graph.inject_numeric_fault(ev.kind)
                if tr.enabled:
                    tr.instant("fault", track="chaos", ts=tr.now(),
                               kind=ev.kind, step=self.step)
            elif ev.kind == "shard_corrupt":
                from ..resilience.generations import corrupt_generation
                try:
                    path = corrupt_generation(self.checkpoint_dir,
                                              seed=ev.step)
                except RuntimeError:
                    continue   # nothing committed yet: retry when the
                    # step is revisited, never mark it injected
                self._injected.add(key)
                if tr.enabled:
                    tr.instant("fault", track="chaos", ts=tr.now(),
                               kind="shard_corrupt", step=self.step,
                               path=os.path.basename(path))
            elif ev.kind == "kill_mid_write":
                from ..utils.checkpoint import arm_kill_mid_write
                self._injected.add(key)
                self._armed_kill = True
                arm_kill_mid_write(after_files=1)
                if tr.enabled:
                    tr.instant("fault", track="chaos", ts=tr.now(),
                               kind="kill_mid_write", step=self.step)
            # serving-plane kinds in a training plan: ignore

    # -- the loop ------------------------------------------------------------

    def train(self, total_steps: int, fault_plan=None) -> List[float]:
        """Train ``total_steps`` with death detection between steps and
        the sentry skip/rewind ladder on every step's verdict.
        ``fault_plan`` events are injected at their step (the chaos
        seams); recovery rewinds to the newest VERIFYING snapshot and
        replays the same data cursors, so per-step losses are keyed and
        re-computed steps overwrite with identical values."""
        losses: Dict[int, float] = {}
        tr = get_tracer()
        try:
            return self._train_loop(losses, total_steps, fault_plan, tr)
        finally:
            # an armed-but-unfired kill_mid_write (no checkpoint write
            # followed the injection) must not outlive this trainer and
            # kill an unrelated save in the same process
            if getattr(self, "_armed_kill", False):
                from ..utils.checkpoint import disarm_kill_mid_write
                disarm_kill_mid_write()
                self._armed_kill = False

    def _train_loop(self, losses: Dict[int, float], total_steps: int,
                    fault_plan, tr) -> List[float]:
        while self.step < total_steps:
            if fault_plan is not None:
                self._apply_fault_events(fault_plan)
            if self.monitor is not None:
                dead = set(self.monitor.dead_workers()) - self._handled
                if dead:
                    self._handled |= dead
                    self._recover(dead, losses, self._killed_at)
                    self._killed_at = None
                    if self.recoveries:
                        self.recoveries[-1]["mttr_pending"] = True
            cursor = self._cursor_for(self.step)
            loss_val = float(self.build.step_fn(cursor))
            self.attempts += 1
            verdict = self._sentry_verdict()
            if verdict is not None and verdict["anomaly"]:
                # the update was already skipped ON-DEVICE (bitwise-zero
                # residue); burn the poisoned batch and retry the step
                self.counters["sentry_anomalies"].inc()
                self.counters["steps_skipped"].inc()
                if tr.enabled:
                    tr.instant("sentry_skip", track="chaos",
                               ts=tr.now(), step=self.step,
                               cursor=cursor,
                               **{k: verdict[k] for k in
                                  ("loss_nonfinite", "grad_nonfinite",
                                   "grad_spike", "loss_spike",
                                   "consecutive")})
                self._burn_cursor(self.step)
                if self.rewind_on_loss_spike and verdict["loss_spike"]:
                    self._numeric_rewind(losses, reason="loss_spike")
                elif verdict["consecutive"] >= self.rewind_after:
                    self._numeric_rewind(
                        losses,
                        reason=f"{verdict['consecutive']} consecutive "
                               f"anomalies")
                continue
            losses[self.step] = loss_val
            # finalize MTTR for EVERY recovery awaiting its first
            # committed step (a rewind can pile onto a death recovery
            # before anything commits — both must resolve)
            for rec in self.recoveries:
                if rec.pop("mttr_pending", False):
                    t0 = rec.pop("_t0", None)
                    if t0 is not None:
                        rec["mttr_s"] = time.perf_counter() - t0
            self.step += 1
            self._attach_restore_meta()
            if self.step % self.checkpoint_every == 0 \
                    and self.step < total_steps:
                self._checkpoint()
        return [losses[s] for s in range(total_steps)]

    # -- observability -------------------------------------------------------

    def _attach_restore_meta(self) -> None:
        """Expose this trainer's restore audit records on its registered
        executable(s) so the ``unverified-restore`` rule can gate them
        (analysis/rules.py)."""
        g = getattr(self.build, "graph", None)
        if g is None or not hasattr(g, "analysis_handles"):
            return
        from ..utils.checkpoint import restore_records
        ckdir = self.checkpoint_dir

        def hook():
            return restore_records(ckdir)

        for h in g.analysis_handles():
            h.meta.setdefault("restores", hook)

    def metrics_summary(self) -> Dict[str, Any]:
        out = {name: int(c.value) for name, c in self.counters.items()}
        out["attempts"] = int(self.attempts)
        out["step"] = int(self.step)
        out["recoveries"] = len(self.recoveries)
        return out

    def metrics_text(self) -> str:
        """Prometheus exposition of the trainer failure counters."""
        return render_prometheus(
            {f"trainer_{k}": v for k, v in self.counters.items()})

    def close(self) -> None:
        if self.build.close is not None:
            self.build.close()


def write_recovery_report(trainer: FaultTolerantTrainer,
                          path: str) -> Dict[str, Any]:
    """Freeze the recovery record (bench/CI artifact)."""
    out = {"recoveries": trainer.recoveries,
           "checkpoints": list(trainer._ck_steps),
           "metrics": trainer.metrics_summary(),
           "final_dp": trainer.dp}
    with open(path, "w") as fh:
        json.dump(out, fh, indent=1)
    return out
