"""Fault-tolerant elastic training: survive an actual worker death.

The elastic loop so far could re-plan around *stragglers*; a dead
worker was fatal — its parameter and optimizer shards live in its HBM
and are simply gone.  This module closes that gap the Malleus way
(SURVEY.md §3.5) with three pieces the repo already has, driven end to
end:

* **Durable snapshots** — every ``checkpoint_every`` steps the trainer
  saves model params + FLAT optimizer state through
  ``utils.checkpoint.save_checkpoint`` (``safetensors_io`` decomposes
  the flat buffers per-parameter, so the snapshot restores into ANY dp
  size — the dp8→dp4 round-trip the IO layer already asserts).
* **Death detection** — a :class:`WorkerMonitor`: N process-local
  training workers registered on the ``rpc`` coordinator exactly like
  serving replicas, each owning an equal slice of the device list; a
  rank that stops heartbeating past the TTL maps to lost devices.
* **Re-plan + restore** — on a death verdict the trainer asks
  :class:`~hetu_tpu.elastic.strategy.StrategyModel` for the best layout
  over the survivors, rebuilds the graph there (``build_fn``), restores
  the latest snapshot, rewinds to its step, and keeps training.  The
  loss curve *continues exactly*: flat-state math is bit-identical
  across dp sizes, so the recovered run's per-step losses equal a
  fault-free run's (asserted in tests/test_fault.py and gated by
  ``bench.py chaos_bench``'s ``loss_curve_continues``).

MTTR (kill → first completed post-recovery step) is recorded per
recovery in :attr:`FaultTolerantTrainer.recoveries`.
"""
from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..obs.tracer import get_tracer
from ..rpc.coordinator import CoordinatorClient, CoordinatorServer
from .strategy import StrategyModel


class WorkerMonitor:
    """Process-local training workers on the rpc liveness plane.

    Each rank owns ``len(devices) // num_workers`` devices; killing a
    rank (chaos ``worker_death``) stops its heartbeat thread, the
    coordinator's TTL declares it dead, and
    :meth:`surviving_devices` shrinks accordingly.  The same
    coordinator machinery the serving cluster and the multi-host
    bootstrap use — one liveness plane for the whole system."""

    def __init__(self, num_workers: int, devices: Sequence[Any],
                 ttl: float = 0.5, heartbeat_interval: float = 0.1,
                 server: Optional[CoordinatorServer] = None):
        if num_workers < 1 or len(devices) % num_workers:
            raise ValueError(
                f"{len(devices)} devices do not split over "
                f"{num_workers} workers")
        self.devices = list(devices)
        self.num_workers = int(num_workers)
        self.per_worker = len(devices) // num_workers
        self._own_server = server is None
        self.server = server if server is not None else \
            CoordinatorServer(world_size=num_workers, ttl=ttl).start()
        self.clients: List[CoordinatorClient] = []
        self._hb_stops = []
        for i in range(num_workers):
            c = CoordinatorClient(self.server.address,
                                  uid=f"trainer-w{i}", ttl=ttl)
            c.connect()
            self.clients.append(c)
            self._hb_stops.append(
                c.start_heartbeat_thread(interval=heartbeat_interval))

    def kill_worker(self, rank: int) -> None:
        """The injected death: heartbeats stop NOW, the verdict lands
        once the TTL lapses — the same two-step reality a crashed
        remote host has."""
        self._hb_stops[rank].set()

    def dead_workers(self) -> List[int]:
        return self.server.dead_ranks()

    def wait_for_verdict(self, rank: int, timeout: float = 10.0) -> bool:
        """Block until ``rank`` is declared dead (test/bench helper —
        a real loop just polls :meth:`dead_workers` between steps)."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            if rank in self.dead_workers():
                return True
            time.sleep(0.02)
        return False

    def surviving_devices(self, dead: Sequence[int]) -> List[Any]:
        dead = set(dead)
        out: List[Any] = []
        for r in range(self.num_workers):
            if r not in dead:
                out.extend(self.devices[r * self.per_worker:
                                        (r + 1) * self.per_worker])
        return out

    def close(self) -> None:
        for s in self._hb_stops:
            s.set()
        for c in self.clients:
            try:
                c.close()
            except Exception:
                pass
        if self._own_server:
            self.server.stop()


@dataclass
class TrainBuild:
    """What ``build_fn(dp, devices)`` returns: a freshly-built graph on
    the given layout.  ``step_fn(step) -> float`` runs one optimizer
    step and returns the loss; ``model``/``optimizer`` feed the
    checkpoint plane."""
    graph: Any
    model: Any
    optimizer: Any
    step_fn: Callable[[int], float]
    close: Optional[Callable[[], None]] = None


class FaultTolerantTrainer:
    """Checkpoint → detect → re-plan → restore → continue.

    ``build_fn(dp: int, devices) -> TrainBuild`` must rebuild the SAME
    model deterministically (same init seed) for any dp — recovery
    calls it on the survivor layout and immediately overwrites params +
    optimizer state from the snapshot, so only the architecture needs
    to be reproducible, not the init values.
    """

    def __init__(self, build_fn: Callable[..., TrainBuild],
                 devices: Sequence[Any],
                 monitor: Optional[WorkerMonitor] = None,
                 checkpoint_dir: str = "/tmp/hetu_ft_ck",
                 checkpoint_every: int = 4,
                 solver_factory: Optional[
                     Callable[[int], StrategyModel]] = None,
                 keep_checkpoints: int = 2):
        self.build_fn = build_fn
        self.devices = list(devices)
        self.monitor = monitor
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every = int(checkpoint_every)
        self.keep_checkpoints = int(keep_checkpoints)
        # default layout policy: pure dp over every available device
        # (the homogeneous solver's own preference); a solver_factory
        # lets hetero-aware callers re-plan tp/pp too
        self.solver_factory = solver_factory
        self.recoveries: List[Dict[str, Any]] = []
        self.step = 0
        self._handled: set = set()
        self._ck_steps: List[int] = []
        os.makedirs(checkpoint_dir, exist_ok=True)
        self.dp = self._choose_dp(len(self.devices))
        self.build = build_fn(self.dp, self.devices)
        # the step-0 snapshot: a death before the first periodic
        # checkpoint must still have something to restore
        self._checkpoint()

    # -- layout choice -------------------------------------------------------

    def _choose_dp(self, n: int) -> int:
        if self.solver_factory is not None:
            plan = self.solver_factory(n).make_plans([1.0] * n,
                                                     top_k=1)[0]
            return int(plan.dp)
        # default policy: the largest power of two <= n — global batch
        # sizes are overwhelmingly power-of-two, and a dp that does not
        # divide the batch cannot build (a 4-worker fleet losing one
        # worker of 8 devices recovers on dp=4 of the 6 survivors)
        dp = 1
        while dp * 2 <= n:
            dp *= 2
        return dp

    # -- checkpoint plane ----------------------------------------------------

    def _ck_path(self, step: int) -> str:
        return os.path.join(self.checkpoint_dir, f"step{step}")

    def _checkpoint(self) -> None:
        from ..utils.checkpoint import save_checkpoint
        save_checkpoint(self.build.model, self.build.optimizer,
                        self._ck_path(self.step), step=self.step)
        self._ck_steps.append(self.step)
        tr = get_tracer()
        if tr.enabled:
            tr.instant("checkpoint", track="trainer", ts=tr.now(),
                       step=self.step)
        while len(self._ck_steps) > self.keep_checkpoints:
            old = self._ck_steps.pop(0)
            path = self._ck_path(old)
            try:
                for f in os.listdir(path):
                    os.remove(os.path.join(path, f))
                os.rmdir(path)
            except OSError:
                pass

    def latest_checkpoint(self) -> int:
        return self._ck_steps[-1]

    # -- recovery ------------------------------------------------------------

    def _recover(self, dead: Sequence[int], losses: Dict[int, float],
                 killed_at: Optional[float]) -> None:
        from ..utils.checkpoint import load_checkpoint
        t0 = time.perf_counter()
        survivors = self.monitor.surviving_devices(self._handled)
        if not survivors:
            raise RuntimeError("every worker died; nothing to recover on")
        tr = get_tracer()
        if tr.enabled:
            tr.instant("worker_dead", track="trainer", ts=tr.now(),
                       dead=sorted(dead), survivors=len(survivors),
                       step=self.step)
        detect_step = self.step
        new_dp = self._choose_dp(len(survivors))
        # the dead workers' HBM shards are GONE: rebuild on the
        # survivor layout and restore the last durable snapshot —
        # never read the old graph's device state
        if self.build.close is not None:
            self.build.close()
        self.build = self.build_fn(new_dp, survivors)
        ck_step = self.latest_checkpoint()
        load_checkpoint(self.build.model, self.build.optimizer,
                        self._ck_path(ck_step))
        rewound = self.step - ck_step
        for s in range(ck_step, self.step):
            losses.pop(s, None)
        self.step = ck_step
        self.dp = new_dp
        rec = {"dead": sorted(dead), "detected_at_step": detect_step,
               "resumed_from_step": ck_step, "rewound_steps": rewound,
               "dp": new_dp, "devices": len(survivors),
               "rebuild_s": time.perf_counter() - t0,
               "killed_at": killed_at}
        self.recoveries.append(rec)
        if tr.enabled:
            tr.instant("recovered", track="trainer", ts=tr.now(),
                       **{k: v for k, v in rec.items()
                          if k not in ("killed_at",)})

    # -- the loop ------------------------------------------------------------

    def train(self, total_steps: int, fault_plan=None) -> List[float]:
        """Train ``total_steps`` with death detection between steps.
        ``fault_plan`` events of kind ``worker_death`` are injected at
        their step (the chaos seam); recovery rewinds to the last
        snapshot, so per-step losses are keyed and re-computed steps
        overwrite with — by the flat-state contract — identical
        values."""
        losses: Dict[int, float] = {}
        killed_at: Optional[float] = None
        while self.step < total_steps:
            if fault_plan is not None and self.monitor is not None:
                for ev in fault_plan.due(self.step):
                    if ev.kind != "worker_death":
                        continue
                    if ev.target in self._handled:
                        continue
                    self.monitor.kill_worker(ev.target)
                    killed_at = time.perf_counter()
                    tr = get_tracer()
                    if tr.enabled:
                        tr.instant("fault", track="chaos", ts=tr.now(),
                                   kind="worker_death",
                                   target=ev.target, step=self.step)
                    # the verdict needs the TTL to lapse; a real fleet
                    # just keeps stepping until it lands
                    self.monitor.wait_for_verdict(ev.target)
            if self.monitor is not None:
                dead = set(self.monitor.dead_workers()) - self._handled
                if dead:
                    self._handled |= dead
                    self._recover(dead, losses, killed_at)
                    if killed_at is not None and self.recoveries:
                        self.recoveries[-1]["mttr_pending"] = True
            losses[self.step] = float(self.build.step_fn(self.step))
            if self.recoveries and \
                    self.recoveries[-1].pop("mttr_pending", False):
                self.recoveries[-1]["mttr_s"] = \
                    time.perf_counter() - (killed_at or time.perf_counter())
            self.step += 1
            if self.step % self.checkpoint_every == 0 \
                    and self.step < total_steps:
                self._checkpoint()
        return [losses[s] for s in range(total_steps)]

    def close(self) -> None:
        if self.build.close is not None:
            self.build.close()


def write_recovery_report(trainer: FaultTolerantTrainer,
                          path: str) -> Dict[str, Any]:
    """Freeze the recovery record (bench/CI artifact)."""
    out = {"recoveries": trainer.recoveries,
           "checkpoints": list(trainer._ck_steps),
           "final_dp": trainer.dp}
    with open(path, "w") as fh:
        json.dump(out, fh, indent=1)
    return out
