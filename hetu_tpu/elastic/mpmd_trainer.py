"""Elastic trainer over the MPMD hetero pipeline (Malleus end-to-end).

Closes the reference loop of SURVEY.md §3.5 with *hetero execution*: the
:class:`~hetu_tpu.elastic.strategy.StrategyModel` solves unequal
per-stage layer ranges and per-pipeline micro-batch counts from
straggler ratios, and — unlike a rectangular SPMD projection — the MPMD
runtime actually executes them: each stage is its own program on its own
submesh, so a slow device really does get fewer layers and a slow
pipeline fewer micro-batches (reference ``DeducePipeline``,
``define_and_run_graph.cc:139``, and the per-dp micro-batch counts of
``examples/gpt/train_hetu.py:256-335``).

On a layout change the trainer gathers params + Adam moments keyed by
canonical parameter name, rebuilds the stage programs for the new
layout, and reloads state (the SwitchExecGraph migration, here via
``device_put`` resharding).
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from ..models.gpt import GPTConfig
from ..models.gpt_mpmd import MPMDGPT
from ..parallel.pipeline_mpmd import MPMDAdam
from .strategy import Strategy, StrategyModel


def strategy_meshes(strat: Strategy, devices: Sequence[Any]
                    ) -> List[List[Mesh]]:
    """Build per-(pipeline, stage) submeshes from a solved Strategy's
    device permutation (stage-major, pipeline, tp-minor ordering — see
    StrategyModel._solve_one)."""
    tp, pp, dp = strat.tp, strat.pp, strat.dp
    out: List[List[Mesh]] = []
    for p in range(dp):
        stages = []
        for s in range(pp):
            ids = strat.device_order[(s * dp + p) * tp:
                                     (s * dp + p + 1) * tp]
            devs = np.array([devices[i] for i in ids]).reshape(1, tp)
            stages.append(Mesh(devs, ("dp", "tp")))
        out.append(stages)
    return out


class ElasticMPMDTrainer:
    """Profile → re-solve → rebuild+migrate loop over MPMDGPT."""

    def __init__(self, cfg: GPTConfig, solver: StrategyModel,
                 data_provider: Callable[[int], Tuple[np.ndarray,
                                                      np.ndarray]],
                 devices: Optional[Sequence[Any]] = None,
                 lr: float = 1e-3,
                 schedule: str = "1f1b",
                 switch_threshold: float = 0.05,
                 seed: int = 0):
        self.cfg = cfg
        self.solver = solver
        self.data_provider = data_provider
        self.devices = list(devices) if devices is not None \
            else jax.devices()[:solver.n]
        assert len(self.devices) == solver.n
        self.lr = lr
        self.schedule = schedule
        self.switch_threshold = switch_threshold
        self.seed = seed
        self.step_idx = 0
        self.history: List[Dict[str, Any]] = []
        strat = solver.make_plans([1.0] * solver.n, top_k=1)[0]
        self.current_strategy: Strategy = strat
        self.model: MPMDGPT = None  # set by _build
        self.opt: MPMDAdam = None
        self._build(strat, state=None, opt_state=None)

    # -- layout (re)build ----------------------------------------------------

    def _build(self, strat: Strategy,
               state: Optional[Dict[str, Any]],
               opt_state: Optional[Tuple[Dict, Dict, int]]) -> None:
        meshes = strategy_meshes(strat, self.devices)
        self.model = MPMDGPT(self.cfg, stage_layers=strat.stage_layers,
                             meshes=meshes, schedule=self.schedule,
                             seed=self.seed)
        self.opt = MPMDAdam(self.model.runtime, lr=self.lr)
        if state is not None:
            self.model.load_state(state)
        if opt_state is not None:
            m_state, v_state, t = opt_state
            self.model.load_state(m_state, extra=self.opt.m)
            self.model.load_state(v_state, extra=self.opt.v)
            self.opt.t = t
        self.current_strategy = strat

    def _gather_all(self):
        state = self.model.gather_state()
        m = self.model.gather_state(extra=self.opt.m)
        v = self.model.gather_state(extra=self.opt.v)
        return state, (m, v, self.opt.t)

    # -- training ------------------------------------------------------------

    def train_steps(self, steps: int) -> List[float]:
        losses = []
        strat = self.current_strategy
        for _ in range(steps):
            ids, labels = self.data_provider(self.step_idx)
            data = self.model.split_micro_batches(ids, labels,
                                                  strat.micro_batches)
            loss, grads, _ = self.model.train_step(
                data, rng=jax.random.PRNGKey(self.step_idx))
            self.opt.apply(grads)
            losses.append(float(loss))
            self.step_idx += 1
        return losses

    # -- retune --------------------------------------------------------------

    def retune(self, ratios: Sequence[float]) -> bool:
        """Re-solve for straggler ratios; rebuild + migrate when the new
        plan is sufficiently better.  Returns True on a switch."""
        plans = self.solver.make_plans(ratios, top_k=1)
        if not plans:
            return False
        best = plans[0]
        cur = self.solver.estimate(self.current_strategy, ratios)
        if best.est_step_time >= cur * (1 - self.switch_threshold):
            return False
        t0 = time.perf_counter()
        state, opt_state = self._gather_all()
        self._build(best, state=state, opt_state=opt_state)
        self.history.append({
            "step": self.step_idx,
            "strategy": best.describe(),
            "switch_seconds": time.perf_counter() - t0,
        })
        from ..obs.tracer import get_tracer
        tr = get_tracer()
        if tr.enabled:
            # the recovery half of the chaos pair: fault (straggler
            # injected) -> recover (layout switched around it)
            tr.instant("strategy_switch", track="trainer", ts=tr.now(),
                       step=self.step_idx, strategy=best.describe(),
                       switch_seconds=self.history[-1]["switch_seconds"])
        return True

    def run(self, total_steps: int, retune_every: int = 0,
            ratio_provider: Optional[Callable[[int], Sequence[float]]]
            = None, fault_plan=None) -> List[float]:
        """Train ``total_steps``; when ``retune_every`` > 0, retune
        every that many steps.  ``fault_plan`` (hetu_tpu.fault) is the
        chaos seam: ``straggler`` events due at a step slow their
        device by ``ratio`` (duration in steps, 0 = permanent) and the
        next retune re-plans around them — each injection and each
        switch is a tracer instant, so the Perfetto timeline shows
        fault → re-plan like the serving plane does."""
        from ..obs.tracer import get_tracer
        losses: List[float] = []
        ratios = [1.0] * self.solver.n
        heal_at: Dict[int, int] = {}       # device -> step to heal at
        while len(losses) < total_steps:
            if fault_plan is not None:
                for ev in fault_plan.due(self.step_idx):
                    if ev.kind != "straggler" or ev.target < 0 \
                            or ev.target >= self.solver.n:
                        continue
                    ratios[ev.target] = float(ev.ratio)
                    if ev.duration:
                        heal_at[ev.target] = \
                            self.step_idx + int(ev.duration)
                    tr = get_tracer()
                    if tr.enabled:
                        tr.instant("fault", track="chaos", ts=tr.now(),
                                   kind="straggler", target=ev.target,
                                   ratio=float(ev.ratio),
                                   step=self.step_idx)
            for dev, at in list(heal_at.items()):
                if self.step_idx >= at:
                    ratios[dev] = 1.0
                    del heal_at[dev]
            chunk = min(retune_every or total_steps,
                        total_steps - len(losses))
            if fault_plan is not None:
                # a chunk is atomic: stop at the next scheduled event
                # (or heal) so no mid-chunk step is silently skipped —
                # due() matches by exact equality
                upcoming = [e.step for e in fault_plan.events
                            if e.step > self.step_idx] + \
                    [at for at in heal_at.values()
                     if at > self.step_idx]
                if upcoming:
                    chunk = min(chunk,
                                min(upcoming) - self.step_idx)
            losses += self.train_steps(chunk)
            if retune_every and len(losses) < total_steps:
                cur = ratio_provider(self.step_idx) if ratio_provider \
                    else list(ratios)
                self.retune(cur)
        return losses
