"""Elastic Trainer: profile -> re-solve -> hot-switch loop (Malleus).

TPU-native re-expression of the reference's ``Trainer``
(``python/elastic/engine/trainer.py:30``) and the retune call stack
(SURVEY.md §3.5): train under the current strategy, profile stragglers,
solve a new hetero layout with :class:`~hetu_tpu.elastic.StrategyModel`,
and when the plan changes migrate params/optimizer states live via
``DefineAndRunGraph.switch_strategy`` (the SwitchExecGraph analogue).
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import numpy as np

from ..parallel.mesh import create_mesh
from .straggler import Straggler, StragglerWorkload
from .strategy import Strategy, StrategyModel


class Trainer:
    """Drive elastic training over a DefineAndRunGraph.

    Parameters
    ----------
    graph : DefineAndRunGraph with a built model + ``train_op``
    loss, train_op : tensors from the user's model/optimizer build
    optimizer : the optimizer whose states must migrate on switch
    data_provider : callable(step) -> feed_dict
    solver : StrategyModel over the graph's devices
    num_micro_batches : global micro-batch count per step
    """

    def __init__(self, graph, loss, train_op, optimizer,
                 data_provider: Callable[[int], Dict[Any, Any]],
                 solver: StrategyModel,
                 num_micro_batches: int = 1,
                 straggler: Optional[Straggler] = None,
                 switch_threshold: float = 0.05,
                 hetero: str = "project"):
        if hetero not in ("project", "error"):
            raise ValueError(f"hetero must be 'project' or 'error', "
                             f"got {hetero!r}")
        self.graph = graph
        self.loss = loss
        self.train_op = train_op
        self.optimizer = optimizer
        self.data_provider = data_provider
        self.solver = solver
        self.num_micro_batches = num_micro_batches
        self.devices = list(graph.mesh.devices.flat) if graph.mesh is not None \
            else [jax.devices()[0]]
        self.straggler = straggler or Straggler(len(self.devices))
        self.switch_threshold = switch_threshold
        # SPMD meshes are rectangular: a hetero plan (unequal per-pipeline
        # micro-batches / layer splits) is executed here as its homogeneous
        # projection ("project"); pass hetero="error" to fail instead and
        # route to ElasticMPMDTrainer, which executes hetero plans exactly.
        self.hetero = hetero
        self.current_strategy: Optional[Strategy] = None
        self.history: List[Dict[str, Any]] = []
        self.step_idx = 0

    # -- training ------------------------------------------------------------

    def train_steps(self, steps: int) -> List[float]:
        losses = []
        for _ in range(steps):
            feeds = self.data_provider(self.step_idx)
            out = self.graph.run(self.loss, [self.loss, self.train_op],
                                 feeds,
                                 num_micro_batches=self.num_micro_batches)
            losses.append(float(np.asarray(out[0])))
            self.step_idx += 1
        return losses

    # -- profile + retune (reference Trainer.run inner loop) -----------------

    def profile(self, steps: int = 2) -> List[float]:
        self.straggler.begin_profile()
        self.train_steps(steps)
        self.straggler.end_profile(steps=steps)
        return self.straggler.read_profile()

    def retune(self, ratios: Optional[Sequence[float]] = None) -> bool:
        """Re-solve for ``ratios`` and hot-switch if the new plan is
        sufficiently better.  Returns True when a switch happened."""
        if ratios is None:
            ratios = self.straggler.read_profile()
        plans = self.solver.make_plans(ratios, top_k=1)
        if not plans:
            return False
        best = plans[0]
        if self.current_strategy is not None:
            # keep the CURRENT layout (fixed device order / layer split /
            # micro-batch counts) unless the re-solved plan beats it
            cur = self.solver.estimate(self.current_strategy, ratios)
            if best.est_step_time >= cur * (1 - self.switch_threshold):
                return False
        self._apply_strategy(best)
        return True

    def _apply_strategy(self, strat: Strategy) -> None:
        if strat.is_hetero and self.hetero == "error":
            raise RuntimeError(
                f"solved plan is heterogeneous ({strat.describe()}); the "
                "SPMD Trainer would only execute its homogeneous "
                "projection — use hetu_tpu.elastic.ElasticMPMDTrainer for "
                "exact hetero execution, or hetero='project' to accept "
                "the projection")
        devices = [self.devices[i] for i in strat.device_order]
        new_mesh = create_mesh(strat.mesh_shape, devices)
        cur = self.graph.mesh
        if cur is not None \
                and tuple(cur.axis_names) == tuple(new_mesh.axis_names) \
                and dict(cur.shape) == dict(new_mesh.shape) \
                and list(cur.devices.flat) == list(new_mesh.devices.flat):
            # identity layout (e.g. first retune confirms the built mesh):
            # adopt the plan without paying a param/optimizer migration
            self.current_strategy = strat
            return
        t0 = time.perf_counter()
        prof = self.graph.switch_strategy(new_mesh, optimizer=self.optimizer) \
            if self.graph.mesh is not None else None
        self.history.append({
            "step": self.step_idx,
            "strategy": strat.describe(),
            "hetero_projected": strat.is_hetero,
            "switch_seconds": time.perf_counter() - t0,
            "switch_profile": prof.as_dict() if prof is not None else None,
        })
        self.current_strategy = strat

    def run(self, total_steps: int, profile_interval: int = 0,
            profile_steps: int = 2) -> List[float]:
        """Train ``total_steps``; when ``profile_interval`` > 0, profile and
        retune every that many steps (the reference's elastic loop)."""
        losses: List[float] = []
        while len(losses) < total_steps:
            if profile_interval:
                chunk = min(profile_interval, total_steps - len(losses))
                if chunk >= profile_steps:
                    self.straggler.begin_profile()
                    losses += self.train_steps(chunk)
                    self.straggler.end_profile(steps=chunk)
                    self.retune()
                else:
                    losses += self.train_steps(chunk)
            else:
                losses += self.train_steps(total_steps - len(losses))
        return losses
