"""Heterogeneity-aware parallel-strategy solver (Malleus).

TPU-native re-expression of the reference's ``StrategyModel``
(``python/elastic/engine/strategy.py:98``): given per-device straggler
ratios, solve a hetero TP/PP/DP placement — TP groups that quarantine slow
devices together (``solve_tp_arrangments_new``, ``strategy.py:281``),
pipeline patterns (``enumerate_pp_pattern``, ``:562``), per-stage layer
ranges and per-pipeline micro-batch counts (``solve_pp_arrangement``,
``:868``) — minimizing estimated step time.

The output :class:`Strategy` carries a *device permutation* for the new
``jax.sharding.Mesh`` (slow devices grouped so they gate as few peers as
possible) plus the hetero layer/micro-batch splits.  A rectangular SPMD
mesh executes the homogeneous projection; the hetero fields drive
per-pipeline layer ranges and micro-batch apportionment when stages are
laid out explicitly (gpt_pipeline) and are preserved for parity with the
reference's hetero execution.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


@dataclass
class Strategy:
    """One solved parallel layout."""
    tp: int
    pp: int
    dp: int
    device_order: List[int]              # permutation of device indices
    stage_layers: List[List[int]]        # per pipeline: layers per stage
    micro_batches: List[int]             # per pipeline (sums to global M)
    est_step_time: float
    tp_group_times: List[float] = field(default_factory=list)

    @property
    def is_hetero(self) -> bool:
        """True when the plan needs MPMD execution: unequal micro-batch
        apportionment or per-pipeline layer splits that differ — work a
        single rectangular SPMD program cannot make unequal (masking
        would burn the same wall clock on every device; reference
        ``DeducePipeline``, ``define_and_run_graph.cc:139``)."""
        return (len(set(self.micro_batches)) > 1
                or len({tuple(s) for s in self.stage_layers}) > 1)

    @property
    def mesh_shape(self) -> Dict[str, int]:
        # always emit all three axes (size-1 axes are legal meshes): dropping
        # e.g. 'tp' would strip it from param PartitionSpecs on a hot switch
        # and a later switch back to tp>1 would leave weights replicated
        return {"pp": self.pp, "dp": self.dp, "tp": self.tp}

    def describe(self) -> str:
        return (f"tp={self.tp} pp={self.pp} dp={self.dp} "
                f"stages={self.stage_layers} mb={self.micro_batches} "
                f"t~{self.est_step_time:.3f}")


def _partition_layers(num_layers: int, stage_times: Sequence[float]
                      ) -> Tuple[List[int], float]:
    """Split ``num_layers`` over stages with per-layer cost ``stage_times[s]``
    minimizing the max per-stage time (reference solve_pp_arrangement's
    layer-range solve).  Exact DP over (layer, stage) — L and S are small."""
    S = len(stage_times)
    if S == 1:
        return [num_layers], num_layers * stage_times[0]
    # dp[s][l] = min over first s stages handling l layers of max stage time
    INF = float("inf")
    dp = [[INF] * (num_layers + 1) for _ in range(S + 1)]
    choice = [[0] * (num_layers + 1) for _ in range(S + 1)]
    dp[0][0] = 0.0
    for s in range(1, S + 1):
        for l in range(num_layers + 1):
            # stage s takes k layers, the s-1 earlier stages take l-k
            # (each >=1 layer) -> 1 <= k <= l-(s-1)
            for k in range(1, l - (s - 1) + 1) if l else []:
                prev = dp[s - 1][l - k]
                if prev == INF:
                    continue
                t = max(prev, k * stage_times[s - 1])
                if t < dp[s][l]:
                    dp[s][l] = t
                    choice[s][l] = k
    out = []
    l = num_layers
    for s in range(S, 0, -1):
        k = choice[s][l]
        out.append(k)
        l -= k
    out.reverse()
    return out, dp[S][num_layers]


def _apportion(total: int, weights: Sequence[float]) -> List[int]:
    """Integer apportionment of ``total`` by ``weights`` (largest remainder);
    every entry gets at least 1 when total >= len(weights)."""
    w = np.asarray(weights, dtype=np.float64)
    w = w / w.sum()
    raw = w * total
    base = np.floor(raw).astype(int)
    if total >= len(w):
        base = np.maximum(base, 1)
    while base.sum() > total:
        base[int(np.argmax(base))] -= 1
    rem = raw - base
    while base.sum() < total:
        i = int(np.argmax(rem))
        base[i] += 1
        rem[i] = -1
    return base.tolist()


class StrategyModel:
    """Solve hetero TP/PP/DP layouts given straggler ratios.

    ``layer_comm_cost`` models per-layer TP-collective overhead relative to
    per-layer compute at tp=1 (ICI allreduce cost grows with tp);
    ``pipeline_p2p_cost`` models a stage-boundary transfer in layer units.
    """

    def __init__(self, num_devices: int, num_layers: int,
                 num_micro_batches: int = 1,
                 max_tp: Optional[int] = None,
                 tp_candidates: Optional[Sequence[int]] = None,
                 pp_candidates: Optional[Sequence[int]] = None,
                 layer_comm_cost: float = 0.1,
                 pipeline_p2p_cost: float = 0.05):
        self.n = num_devices
        self.num_layers = num_layers
        self.M = num_micro_batches
        self.max_tp = max_tp or num_devices
        self.tp_candidates = list(tp_candidates) if tp_candidates else None
        self.pp_candidates = list(pp_candidates) if pp_candidates else None
        self.layer_comm_cost = layer_comm_cost
        self.pipeline_p2p_cost = pipeline_p2p_cost
        # per-(stage times) layer-partition memo; reset per _solve_one
        self._pipe_cache: Dict[Tuple, Tuple] = {}

    @classmethod
    def from_calibration(cls, calibration, num_devices: int,
                         num_layers: int, batch: int, seq: int,
                         hidden: int, ffn: int, **kw) -> "StrategyModel":
        """Build with MEASURED comm/compute ratios instead of the default
        constants (planner.profile_hardware.Calibration; reference
        profile_hardware.py feeding the Galvatron cost model)."""
        consts = calibration.elastic_constants(batch, seq, hidden, ffn)
        kw.setdefault("layer_comm_cost", consts["layer_comm_cost"])
        kw.setdefault("pipeline_p2p_cost", consts["pipeline_p2p_cost"])
        return cls(num_devices, num_layers, **kw)

    # -- TP grouping (reference solve_tp_arrangments_new) --------------------

    def solve_tp_arrangements(self, ratios: Sequence[float], tp: int
                              ) -> Tuple[List[List[int]], List[float]]:
        """Group devices into TP groups of size ``tp``.  A TP group runs in
        lockstep, so its time is its *slowest* member: sorting by speed and
        chunking quarantines stragglers together (provably optimal for
        minimizing the sum — and the sorted prefix structure Malleus
        exploits)."""
        assert self.n % tp == 0
        order = sorted(range(self.n), key=lambda i: ratios[i])
        groups = [order[i * tp:(i + 1) * tp] for i in range(self.n // tp)]
        times = [max(ratios[i] for i in g) for g in groups]
        return groups, times

    # -- full plan solve -----------------------------------------------------

    def make_plans(self, ratios: Sequence[float],
                   top_k: int = 1) -> List[Strategy]:
        """Enumerate (tp, pp, dp) layouts, solve the hetero layer and
        micro-batch splits for each, rank by estimated step time."""
        assert len(ratios) == self.n
        plans: List[Strategy] = []
        tps = self.tp_candidates or \
            [t for t in (1, 2, 4, 8, 16) if t <= self.max_tp]
        for tp in tps:
            if self.n % tp:
                continue
            n_groups = self.n // tp
            groups, gtimes = self.solve_tp_arrangements(ratios, tp)
            pps = self.pp_candidates or \
                [p for p in (1, 2, 4, 8) if p <= n_groups]
            for pp in pps:
                if n_groups % pp:
                    continue
                dp = n_groups // pp
                plan = self._solve_one(tp, pp, dp, groups, gtimes)
                if plan is not None:
                    plans.append(plan)
        plans.sort(key=lambda p: p.est_step_time)
        return plans[:top_k] if top_k else plans

    def _step_time(self, mb: Sequence[int], pipe_tmax: Sequence[float],
                   pp: int, total_mb: int) -> float:
        return max((m + pp - 1) * t for m, t in zip(mb, pipe_tmax)) \
            / total_mb + (pp - 1) * self.pipeline_p2p_cost

    def _per_layer_cost(self, tp: int) -> float:
        return 1.0 / tp + self.layer_comm_cost * np.log2(max(tp, 1)) / 8

    def estimate(self, strat: Strategy, ratios: Sequence[float]) -> float:
        """Step time of an EXISTING layout (fixed device permutation, layer
        split and micro-batch counts) under new straggler ratios — what the
        current plan would actually cost if kept (reference Trainer compares
        this against the re-solved plan before hot-switching)."""
        tp, pp, dp = strat.tp, strat.pp, strat.dp
        per_layer = self._per_layer_cost(tp)
        pipe_tmax = []
        for p in range(dp):
            tmax = 0.0
            for s in range(pp):
                devs = strat.device_order[(s * dp + p) * tp:
                                          (s * dp + p + 1) * tp]
                gtime = max(ratios[d] for d in devs)
                tmax = max(tmax, strat.stage_layers[p][s] * per_layer * gtime)
            pipe_tmax.append(tmax)
        return self._step_time(strat.micro_batches, pipe_tmax, pp,
                               sum(strat.micro_batches))

    def _solve_pipe(self, pipe: Sequence[int], gtimes: List[float],
                    tp: int, pp: int) -> Tuple[List[int], float]:
        """Layer partition + bottleneck time of ONE pipeline, memoized by
        the STAGE-ORDERED group-times tuple (order matters: the returned
        stage_layers align with stages) — swaps re-solve only the two
        touched pipelines."""
        per_layer = self._per_layer_cost(tp)
        stimes = tuple(gtimes[g] * per_layer for g in pipe[:pp])
        hit = self._pipe_cache.get(stimes)
        if hit is None:
            hit = _partition_layers(self.num_layers, list(stimes))
            self._pipe_cache[stimes] = hit
        return hit

    def _finish_eval(self, stage_layers, pipe_tmax, pp: int, dp: int):
        total_mb = self.M * dp
        mb = _apportion(total_mb, [1.0 / t for t in pipe_tmax]) \
            if dp > 1 else [total_mb]
        step = self._step_time(mb, pipe_tmax, pp, total_mb)
        return stage_layers, pipe_tmax, mb, float(step)

    def _eval_assignment(self, pipelines: List[List[int]],
                         gtimes: List[float], tp: int, pp: int, dp: int):
        """(stage_layers, pipe_tmax, mb, step) of one group->pipeline
        assignment: per-pipeline layer partition (slower stages get fewer
        layers) + Malleus micro-batch apportionment."""
        solved = [self._solve_pipe(p, gtimes, tp, pp) for p in pipelines]
        return self._finish_eval([s[0] for s in solved],
                                 [s[1] for s in solved], pp, dp)

    def _solve_one(self, tp: int, pp: int, dp: int,
                   groups: List[List[int]], gtimes: List[float]
                   ) -> Optional[Strategy]:
        if pp > self.num_layers:
            return None
        # Assign TP groups to pipelines: the reference ENUMERATES pp
        # patterns and solves arrangements (enumerate_pp_pattern,
        # strategy.py:562).  Equivalent search here: three seed patterns
        # over the speed-sorted groups —
        #   round-robin: every pipeline gets a speed mix,
        #   blocked:     stragglers quarantined into one slow pipeline
        #                (which then receives few micro-batches),
        #   snake:       boustrophedon balance of group sums —
        # each refined by pairwise-swap local search under the TRUE step
        # objective (layer partition + apportionment re-solved per move).
        order = sorted(range(len(groups)), key=lambda g: gtimes[g])

        def rr():
            ps = [[] for _ in range(dp)]
            for i, g in enumerate(order):
                ps[i % dp].append(g)
            return ps

        def blocked():
            return [order[p * pp:(p + 1) * pp] for p in range(dp)]

        def snake():
            ps = [[] for _ in range(dp)]
            for i, g in enumerate(order):
                row, col = divmod(i, dp)
                ps[col if row % 2 == 0 else dp - 1 - col].append(g)
            return ps

        self._pipe_cache: Dict[Tuple, Tuple] = {}
        best = None
        # evaluation budget: the swap search is a refinement, not an
        # exhaustive enumeration — on big pods the seeds alone already
        # capture the quarantine-vs-mix tradeoff
        budget = 500
        for seed in (rr, blocked, snake):
            pipelines = seed()
            sl, tmax, mb, step = self._eval_assignment(
                pipelines, gtimes, tp, pp, dp)
            improved, rounds = True, 0
            while improved and rounds < 20 and budget > 0:
                improved = False
                rounds += 1

                def scan_swaps():
                    # returns False as soon as the budget runs dry so the
                    # whole (p1,p2,i1,i2) scan exits, not just the
                    # innermost loop
                    nonlocal sl, tmax, mb, step, improved, budget
                    for p1 in range(dp):
                        for p2 in range(p1 + 1, dp):
                            for i1 in range(pp):
                                for i2 in range(pp):
                                    a, b = (pipelines[p1][i1],
                                            pipelines[p2][i2])
                                    if gtimes[a] == gtimes[b]:
                                        continue  # no-op move
                                    if budget <= 0:
                                        return False
                                    budget -= 1
                                    pipelines[p1][i1], \
                                        pipelines[p2][i2] = b, a
                                    # only the two touched pipelines
                                    # re-solve
                                    r1 = self._solve_pipe(pipelines[p1],
                                                          gtimes, tp, pp)
                                    r2 = self._solve_pipe(pipelines[p2],
                                                          gtimes, tp, pp)
                                    sl2 = list(sl)
                                    tm2 = list(tmax)
                                    sl2[p1], tm2[p1] = r1
                                    sl2[p2], tm2[p2] = r2
                                    s2 = self._finish_eval(sl2, tm2, pp, dp)
                                    if s2[3] < step - 1e-12:
                                        sl, tmax, mb, step = s2
                                        improved = True
                                    else:
                                        pipelines[p1][i1], \
                                            pipelines[p2][i2] = a, b
                    return True

                if not scan_swaps():
                    break
            if best is None or step < best[4]:
                best = ([list(p) for p in pipelines], sl, tmax, mb, step)
        pipelines, stage_layers, pipe_tmax, mb, step = best
        # device order: pipeline-major, stage-major, tp-minor — mesh axes
        # (pp, dp, tp) expect stage-outermost ordering
        device_order: List[int] = []
        for s in range(pp):
            for p in range(dp):
                g = pipelines[p][s]
                device_order.extend(groups[g])
        return Strategy(tp=tp, pp=pp, dp=dp, device_order=device_order,
                        stage_layers=stage_layers, micro_batches=mb,
                        est_step_time=float(step),
                        tp_group_times=[gtimes[g] for p in pipelines
                                        for g in p])
