"""Benchmark: GPT-2 training throughput on the available accelerator.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Primary metric is GPT-2 (124M-class) training tokens/sec/chip
(BASELINE.json north star).  vs_baseline reports measured MFU relative to
the 40%-MFU target (1.0 == 40% MFU), since the reference repo publishes
no raw numbers (BASELINE.md).  MFU counts matmul FLOPs only (embedding
gathers excluded) with a causal attention term — see mfu_formula in the
output.

The BASELINE.json metric list also names BERT-base samples/sec and
multi-chip scaling efficiency; both are measured here and reported in
"extra": BERT on the same chip, scaling on a virtual 8-device CPU mesh
(an upper bound on dispatch/collective overhead — real multi-chip
hardware is not available to this harness; the dp-8 mesh path itself is
validated by dryrun_multichip).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np


def peak_flops_per_chip() -> float:
    """bf16 peak FLOP/s for the local accelerator generation."""
    import jax
    d = jax.devices()[0]
    kind = getattr(d, "device_kind", "").lower()
    if "v5 lite" in kind or "v5e" in kind:
        return 197e12
    if "v5p" in kind or "v5" in kind:
        return 459e12
    if "v4" in kind:
        return 275e12
    if "v6" in kind or "trillium" in kind:
        return 918e12
    return 197e12  # conservative default (also used for CPU smoke runs)


def _sync_vars(g):
    # block_until_ready can be a no-op under remote-relay PJRT backends;
    # force a real host fetch of one element of the first/last updated
    # tensors (waits for the optimizer update)
    arrs = list(g._var_data.values())
    for arr in (arrs[0], arrs[-1]):
        np.asarray(arr.ravel()[0])


def _auto_plan(cfg, batch, seq, on_tpu: bool):
    """Close the planner loop (VERDICT r4 #2 / BASELINE north star): let
    the Galvatron-style search pick the plan the bench runs under —
    calibrated by profile_hardware on the live chip — instead of a
    hand-picked config.  Returns (plan_summary_dict, num_micro_batches,
    recompute_policy_or_None); None summary when planning is disabled
    (HETU_TPU_BENCH_PLAN=0) or fails."""
    if os.environ.get("HETU_TPU_BENCH_PLAN", "1") != "1":
        return None, 1, None
    try:
        from hetu_tpu.planner import (plan_for_gpt, plan_summary,
                                      profile_and_calibrate)
        cal = profile_and_calibrate(reps=3) if on_tpu else None
        # this bench measures PER-CHIP throughput on an unmeshed graph, so
        # the planner's grid is one chip: its free choices are the
        # micro-batch size, recompute, and (at dp>1 configs it would
        # reject) zero — the plan the run actually executes under
        plan = plan_for_gpt(cfg, global_batch=batch, seq=seq, n_chips=1,
                            calibration=cal)
        summ = plan_summary(plan)
        if cal is not None:
            summ["calibration"] = {
                "best_matmul_tflops": round(cal.best_matmul_flops / 1e12, 1),
                "hbm_gbps": round(cal.hbm_bw / 1e9, 1),
                "device_kind": cal.device_kind,
            }
        nmb = max(1, int(plan.num_microbatches))
        # recompute only when the planner chose it for a majority of layers
        remat = "nothing_saveable" if (
            summ["recompute_layers"] * 2 > summ["num_layers"]) else None
        return summ, nmb, remat
    except Exception as e:   # planning must never sink the bench
        return {"error": f"{type(e).__name__}: {e}"}, 1, None


def bench_gpt2(on_tpu: bool):
    import jax
    import hetu_tpu as ht
    from hetu_tpu import optim
    from hetu_tpu.models import GPTConfig, GPTLMHeadModel

    if on_tpu:
        # fused_lm_ce: the [B*S, V] logits tensor (~3.3GB bf16) is never
        # stored as a backward residual — chunked recompute instead
        # (ops/fused_ce.py); disable via HETU_TPU_BENCH_FUSED_CE=0
        fused = os.environ.get("HETU_TPU_BENCH_FUSED_CE", "1") == "1"
        # HETU_TPU_BENCH_MODEL: gpt2 (124M, default) | gpt2-medium (350M,
        # the BASELINE.json north-star model)
        size = os.environ.get("HETU_TPU_BENCH_MODEL", "gpt2")
        if size not in ("gpt2", "gpt2-medium"):
            raise ValueError(f"HETU_TPU_BENCH_MODEL must be gpt2 or "
                             f"gpt2-medium, got {size!r}")
        h, L, nh = (1024, 24, 16) if size == "gpt2-medium" else (768, 12, 12)
        cfg = GPTConfig(vocab_size=50304, hidden_size=h, num_layers=L,
                        num_heads=nh, max_seq_len=1024, sp=False,
                        dtype="bfloat16", position="learned",
                        activation="gelu", norm="layernorm",
                        fused_lm_ce=fused)
        batch = int(os.environ.get(
            "HETU_TPU_BENCH_BATCH", "32" if size == "gpt2" else "16"))
        seq, steps, warmup = 1024, 10, 3
    else:
        cfg = GPTConfig(vocab_size=1024, hidden_size=256, num_layers=4,
                        num_heads=8, max_seq_len=256, sp=False,
                        dtype="float32")
        batch, seq, steps, warmup = 4, 256, 5, 2

    plan, nmb, remat_policy = _auto_plan(cfg, batch, seq, on_tpu)
    if plan is not None and "error" not in plan and batch % max(nmb, 1):
        nmb = 1          # schedule must divide the batch

    import contextlib
    with ht.graph("define_and_run", create_new=True) as g:
        # the recompute policy is read at step-BUILD time (inside the
        # first g.run), so the context must stay open across the runs
        remat_ctx = ht.recompute(remat_policy) if remat_policy \
            else contextlib.nullcontext()
        with remat_ctx:
            ids = ht.placeholder("int32", (batch, seq), name="input_ids")
            labels = ht.placeholder("int32", (batch, seq), name="labels")
            model = GPTLMHeadModel(cfg)
            loss = model(ids, labels, seq_len=seq)
            train_op = optim.AdamOptimizer(lr=1e-4,
                                           weight_decay=0.01).minimize(loss)

            rng = np.random.RandomState(0)
            IDS = rng.randint(0, cfg.vocab_size,
                              (batch, seq)).astype(np.int32)
            L = np.roll(IDS, -1, axis=1)

            for _ in range(warmup):
                g.run(loss, [loss, train_op], {ids: IDS, labels: L},
                      num_micro_batches=nmb)
                _sync_vars(g)
            t0 = time.perf_counter()
            for _ in range(steps):
                g.run(loss, [loss, train_op], {ids: IDS, labels: L},
                      num_micro_batches=nmb)
            _sync_vars(g)
            dt = (time.perf_counter() - t0) / steps

        n_params = sum(
            int(np.prod(t.concrete_shape())) for t in g._var_tensors.values())
        # Honest matmul-FLOP accounting: embedding tables are gathers, not
        # matmuls — exclude wte/wpe from the 6N term.  (lm_head is untied
        # here and IS a matmul, so it stays in n_matmul.)  Attention
        # scores/values add 12*L*S*H per token full, 6*L*S*H causal
        # (fwd=2*S*H per layer causal, bwd=2x fwd).
        n_matmul = sum(
            int(np.prod(t.concrete_shape())) for t in g._var_tensors.values()
            if not (t.name and ("wte" in t.name or "wpe" in t.name)))

    tokens_per_sec = batch * seq / dt
    attn_flops_per_token = 6.0 * cfg.num_layers * seq * cfg.hidden_size
    flops_per_token = 6.0 * n_matmul + attn_flops_per_token
    mfu = flops_per_token * tokens_per_sec / peak_flops_per_chip()
    return {
        "tokens_per_sec": tokens_per_sec,
        "step_time_s": dt,
        "mfu": mfu,
        "params": n_params,
        "params_matmul": n_matmul,
        "batch": batch, "seq": seq,
        "planner_plan": plan,
        "num_micro_batches": nmb,
        "remat": remat_policy or "none",
    }


def bench_bert(on_tpu: bool):
    """BERT-base pretraining samples/sec (BASELINE.json metric 2;
    reference tests/hetu_bert.py setup: MLM + NSP)."""
    import hetu_tpu as ht
    from hetu_tpu import optim
    from hetu_tpu.models.bert import BertConfig, BertForPreTraining

    if on_tpu:
        cfg = BertConfig(vocab_size=30528, hidden_size=768, num_layers=12,
                         num_heads=12, max_seq_len=512, dtype="bfloat16")
        batch, seq, steps, warmup = 32, 128, 10, 3
    else:
        cfg = BertConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                         num_heads=4, max_seq_len=128, dtype="float32")
        batch, seq, steps, warmup = 4, 64, 3, 1

    with ht.graph("define_and_run", create_new=True) as g:
        ids = ht.placeholder("int32", (batch, seq), name="input_ids")
        mlm = ht.placeholder("int32", (batch, seq), name="mlm_labels")
        nsp = ht.placeholder("int32", (batch,), name="nsp_labels")
        model = BertForPreTraining(cfg)
        loss = model(ids, mlm_labels=mlm, nsp_labels=nsp)
        train_op = optim.AdamOptimizer(lr=1e-4).minimize(loss)

        rng = np.random.RandomState(0)
        IDS = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
        MLM = np.where(rng.rand(batch, seq) < 0.15, IDS, -100).astype(np.int32)
        NSP = rng.randint(0, 2, (batch,)).astype(np.int32)
        feed = {ids: IDS, mlm: MLM, nsp: NSP}

        for _ in range(warmup):
            g.run(loss, [loss, train_op], feed)
            _sync_vars(g)
        t0 = time.perf_counter()
        for _ in range(steps):
            g.run(loss, [loss, train_op], feed)
        _sync_vars(g)
        dt = (time.perf_counter() - t0) / steps
    return {"samples_per_sec": batch / dt, "step_time_s": dt,
            "batch": batch, "seq": seq}


def bench_scaling_virtual(n_devices: int = 8) -> dict:
    """dp-scaling efficiency on a virtual CPU mesh (dispatch/collective
    overhead bound; BASELINE.json metric 3 proxy — no multi-chip hardware
    in this harness).  Runs in a JAX_PLATFORMS=cpu subprocess so the
    default backend is never touched (round-3 postmortem)."""
    code = (
        "import os, sys, json, time\n"
        f"sys.path.insert(0, {os.path.dirname(os.path.abspath(__file__))!r})\n"
        "import numpy as np\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import hetu_tpu as ht\n"
        "from jax.sharding import PartitionSpec as P\n"
        "from hetu_tpu import optim\n"
        "from hetu_tpu.models import GPTConfig, GPTLMHeadModel\n"
        "def tput(dp):\n"
        "    cfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=2,\n"
        "                    num_heads=4, max_seq_len=128, sp=False)\n"
        "    mesh = ht.create_mesh({'dp': dp}, jax.devices()[:dp]) \\\n"
        "        if dp > 1 else None\n"
        "    batch = 4 * dp\n"
        "    with ht.graph('define_and_run', create_new=True, mesh=mesh) as g:\n"
        "        ids = ht.parallel_placeholder('int32', (batch, 128),\n"
        "            pspec=P('dp', None) if mesh else None, name='ids')\n"
        "        lbl = ht.parallel_placeholder('int32', (batch, 128),\n"
        "            pspec=P('dp', None) if mesh else None, name='lbl')\n"
        "        model = GPTLMHeadModel(cfg)\n"
        "        loss = model(ids, lbl)\n"
        "        op = optim.AdamOptimizer(lr=1e-4).minimize(loss)\n"
        "        I = np.random.RandomState(0).randint(0, 512, (batch, 128))\n"
        "        I = I.astype(np.int32)\n"
        "        feed = {ids: I, lbl: np.roll(I, -1, 1)}\n"
        "        def sync():\n"
        "            arrs = list(g._var_data.values())\n"
        "            np.asarray(arrs[0].ravel()[0])\n"
        "            np.asarray(arrs[-1].ravel()[0])\n"
        "        for _ in range(2):\n"
        "            g.run(loss, [loss, op], feed)\n"
        "        sync()\n"
        "        t0 = time.perf_counter()\n"
        "        for _ in range(5):\n"
        "            g.run(loss, [loss, op], feed)\n"
        "        sync()\n"
        "        dt = (time.perf_counter() - t0) / 5\n"
        "    return batch * 128 / dt\n"
        f"t1 = tput(1)\n"
        f"tn = tput({n_devices})\n"
        # n virtual devices SHARE one host's cores, so tn/(n*t1) is a
        # lower bound that conflates dispatch overhead with core
        # contention; the speedup vs one virtual device is the
        # meaningful dispatch-overhead signal here
        f"print(json.dumps({{'t1': t1, 'tn': tn,"
        f" 'speedup_vs_1dev': tn / t1,"
        f" 'host_bound_efficiency_lower_bound': tn / ({n_devices} * t1),"
        f" 'note': 'virtual devices share host cores; real scaling "
        f"needs hardware'}}))\n"
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    import re
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n_devices}"
    ).strip()
    try:
        proc = subprocess.run([sys.executable, "-c", code], env=env,
                              capture_output=True, text=True, timeout=1200)
        lines = proc.stdout.strip().splitlines()
        if not lines:
            return {"error": f"rc={proc.returncode}: "
                             f"{proc.stderr.strip()[-400:]}"}
        return json.loads(lines[-1])
    except Exception as e:  # never fail the headline bench on this
        return {"error": f"{type(e).__name__}: {e}"}


def bench_mpmd_dispatch_overhead() -> dict:
    """Controller/dispatch overhead of the MPMD pipeline runtime
    (round-3 review: 'no dispatch-overhead measurement exists').  Runs a
    pp2 GPT on the virtual CPU mesh and reports the host task-loop and
    loss-fetch time as fractions of the step (device work overlaps the
    loop via async dispatch, so the loop time is an upper bound on what
    the controller can add to a step).  JAX_PLATFORMS=cpu subprocess —
    never touches the default backend."""
    code = (
        "import os, sys, json, time\n"
        f"sys.path.insert(0, {os.path.dirname(os.path.abspath(__file__))!r})\n"
        "import numpy as np\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from jax.sharding import Mesh\n"
        "from hetu_tpu.models.gpt import GPTConfig\n"
        "from hetu_tpu.models.gpt_mpmd import MPMDGPT\n"
        "from hetu_tpu.parallel.pipeline_mpmd import MPMDAdam\n"
        "cfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=4,\n"
        "                num_heads=4, max_seq_len=128, sp=False,\n"
        "                dropout=0.0, dtype='float32')\n"
        "devs = jax.devices()[:4]\n"
        "meshes = [[Mesh(np.array(devs[2*s:2*s+2]).reshape(1, 2),\n"
        "               ('dp', 'tp')) for s in range(2)]]\n"
        "m = MPMDGPT(cfg, stage_layers=[[2, 2]], meshes=meshes, seed=0)\n"
        "opt = MPMDAdam(m.runtime, lr=1e-3)\n"
        "rng = np.random.RandomState(0)\n"
        "I = rng.randint(0, 512, (8, 128)).astype(np.int32)\n"
        "L = np.roll(I, -1, 1)\n"
        "for _ in range(2):\n"
        "    d = m.split_micro_batches(I, L, [4])\n"
        "    loss, grads, st = m.train_step(d)\n"
        "    opt.apply(grads)\n"
        "t0 = time.perf_counter()\n"
        "ctrl = sync = 0.0\n"
        "N = 5\n"
        "for _ in range(N):\n"
        "    d = m.split_micro_batches(I, L, [4])\n"
        "    loss, grads, st = m.train_step(d)\n"
        "    opt.apply(grads)\n"
        "    ctrl += st.controller_seconds\n"
        "    sync += st.sync_seconds\n"
        "step = (time.perf_counter() - t0) / N\n"
        # tiny-shape rerun: compute ~0, so per-task time ~= pure host
        # dispatch cost (the component that stays on TPU where device
        # work is async)
        "cfg2 = GPTConfig(vocab_size=64, hidden_size=16, num_layers=4,\n"
        "                 num_heads=2, max_seq_len=8, sp=False,\n"
        "                 dropout=0.0, dtype='float32')\n"
        "m2 = MPMDGPT(cfg2, stage_layers=[[2, 2]], meshes=meshes, seed=0)\n"
        "opt2 = MPMDAdam(m2.runtime, lr=1e-3)\n"
        "I2 = rng.randint(0, 64, (8, 8)).astype(np.int32)\n"
        "L2 = np.roll(I2, -1, 1)\n"
        "for _ in range(2):\n"
        "    d2 = m2.split_micro_batches(I2, L2, [4])\n"
        "    _, g2, _ = m2.train_step(d2)\n"
        "    opt2.apply(g2)\n"
        "ctrl2 = 0.0\n"
        "for _ in range(N):\n"
        "    d2 = m2.split_micro_batches(I2, L2, [4])\n"
        "    _, g2, st2 = m2.train_step(d2)\n"
        "    opt2.apply(g2)\n"
        "    ctrl2 += st2.controller_seconds\n"
        "print(json.dumps({'step_s': step,\n"
        "                  'controller_s': ctrl / N,\n"
        "                  'loss_fetch_s': sync / N,\n"
        "                  'tasks_per_step': st.num_tasks,\n"
        "                  'dispatch_per_task_ms':\n"
        "                      1e3 * ctrl / N / st.num_tasks,\n"
        "                  'host_dispatch_per_task_ms':\n"
        "                      1e3 * ctrl2 / N / st2.num_tasks,\n"
        "                  'note': 'CPU executes jit calls synchronously, "
        "so both columns still include compute. Instrumented breakdown "
        "at tiny shapes: ~1.3ms stage-jit call + ~0.7ms grad accum + "
        "~0.17ms boundary put per task; with async TPU dispatch the "
        "enqueue-only costs microbench at ~0.2ms each, bounding the "
        "controller at ~0.6ms/task pending hardware measurement'}))\n"
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    import re
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
    try:
        proc = subprocess.run([sys.executable, "-c", code], env=env,
                              capture_output=True, text=True, timeout=1200)
        lines = proc.stdout.strip().splitlines()
        if not lines:
            return {"error": f"rc={proc.returncode}: "
                             f"{proc.stderr.strip()[-400:]}"}
        return json.loads(lines[-1])
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}


def bench_comm_microbench() -> dict:
    """Gradient-sync comm microbench (ISSUE: coalesced + quantized
    collectives): collective-call count, analytic bytes-on-wire, and
    step wall time for fp32/bf16/int8 x per-tensor/bucketed on the
    virtual 8-device mesh.

    Calls/bytes come from trace-time accounting (``comm.comm_stats`` —
    1:1 with the collectives in the traced program), so they are valid
    off-hardware; wall time on the shared-core CPU mesh is only a
    dispatch-cost sanity signal.  On TPU the same schema is recaptured
    on hardware and lands in the BENCH_CACHE.json evidence trail
    (cached-TPU slot).  JAX_PLATFORMS=cpu subprocess — never touches
    the default backend."""
    code = (
        "import os, sys, json, time\n"
        f"sys.path.insert(0, {os.path.dirname(os.path.abspath(__file__))!r})\n"
        "import numpy as np\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from jax.sharding import PartitionSpec as P\n"
        "from hetu_tpu.parallel import comm, create_mesh\n"
        "mesh = create_mesh({'dp': 8}, jax.devices()[:8])\n"
        # GPT-2-small-shaped gradient set scaled to d=128: 12 layers x\n
        # (qkv, proj, fc1, fc2 + 4 vecs) + tied head = 98 tensors, ~10MB
        "d = 128\n"
        "shapes = []\n"
        "for _ in range(12):\n"
        "    shapes += [(d, 3 * d), (d, d), (d, 4 * d), (4 * d, d),\n"
        "               (3 * d,), (d,), (4 * d,), (d,)]\n"
        "shapes += [(1024, d), (256, d)]\n"
        "rng = np.random.RandomState(0)\n"
        "grads = [rng.randn(*s).astype(np.float32) for s in shapes]\n"
        "reps = tuple(P() for _ in grads)\n"
        "def per_tensor(*vals):\n"
        "    return tuple(comm.all_reduce(v, 'dp') for v in vals)\n"
        "def bucketed(transport):\n"
        "    def f(*vals):\n"
        "        out = comm.all_reduce_coalesced(\n"
        "            {i: v for i, v in enumerate(vals)}, 'dp',\n"
        "            bucket_mb=4.0, transport=transport)\n"
        "        return tuple(out[i] for i in range(len(vals)))\n"
        "    return f\n"
        # zero2_flat: the reduce-scatter-only ZeRO-2 sync (flat
        # dp-sharded optimizer state): RS -> local elementwise update
        # stand-in -> updated-param all-gather riding the weight dtype
        # (tagged param_comm, so gradient wire bytes stay separable)
        "def zero2_flat(transport):\n"
        "    def f(*vals):\n"
        "        g = {i: v for i, v in enumerate(vals)}\n"
        "        chunks, layout = comm.reduce_scatter_coalesced(\n"
        "            g, 'dp', op='mean', bucket_mb=4.0,\n"
        "            transport=transport)\n"
        "        chunks = [c * 0.999 for c in chunks]\n"
        "        out = comm.all_gather_coalesced(chunks, layout, 'dp',\n"
        "                                        tag='param_comm')\n"
        "        return tuple(out[i] for i in range(len(vals)))\n"
        "    return f\n"
        # zero3_flat: params sharded AT REST (ZeRO-3) — the step opens
        # with the just-in-time param all-gather (tagged param_gather),
        # then RS -> chunk-local update, and ENDS on the 1/dp chunk:
        # no post-update regather, the next step's gather replaces it
        "def zero3_flat(transport):\n"
        "    def f(*vals):\n"
        "        g = {i: v for i, v in enumerate(vals)}\n"
        "        chunks, layout = comm.reduce_scatter_coalesced(\n"
        "            g, 'dp', op='mean', bucket_mb=4.0,\n"
        "            transport=transport)\n"
        "        chunks = [c * 0.999 for c in chunks]\n"
        "        full = comm.all_gather_coalesced(chunks, layout, 'dp',\n"
        "                                         tag='param_gather')\n"
        "        return tuple(full[i] for i in range(len(vals)))\n"
        "    return f\n"
        "def measure(fn):\n"
        "    jf = jax.jit(comm.shard_map(fn, mesh, reps, reps))\n"
        "    with comm.comm_stats() as s:\n"
        "        jf.lower(*grads)\n"
        "    out = jf(*grads)\n"
        "    jax.block_until_ready(out)\n"
        "    t0 = time.perf_counter()\n"
        "    for _ in range(5):\n"
        "        out = jf(*grads)\n"
        "    jax.block_until_ready(out)\n"
        "    dt = (time.perf_counter() - t0) / 5\n"
        "    grad_wire = sum(r.wire_bytes for r in s.records\n"
        "                    if not r.tag.startswith(('param_comm',\n"
        "                                             'param_gather')))\n"
        "    pg_wire = sum(r.wire_bytes for r in s.records\n"
        "                  if r.tag.startswith('param_gather'))\n"
        "    out = {'collective_calls': s.num_collectives,\n"
        "           'wire_mb_per_rank': round(s.total_wire_bytes / 2**20,\n"
        "                                     3),\n"
        "           'grad_wire_mb_per_rank': round(grad_wire / 2**20, 3),\n"
        "           'step_time_ms': round(dt * 1e3, 2)}\n"
        "    if pg_wire:\n"
        "        out['param_gather_wire_mb_per_rank'] = round(\n"
        "            pg_wire / 2**20, 3)\n"
        "    return out\n"
        "res = {'grad_tensors': len(shapes),\n"
        "       'grad_mb': round(sum(g.nbytes for g in grads) / 2**20, 2),\n"
        "       'per_tensor_fp32': measure(per_tensor)}\n"
        "for tr in ('fp32', 'bf16', 'int8'):\n"
        "    res['bucketed_' + tr] = measure(bucketed(tr))\n"
        "    res['zero2_flat_' + tr] = measure(zero2_flat(tr))\n"
        "    res['grad_wire_ratio_allreduce_vs_zero2flat_' + tr] = round(\n"
        "        res['bucketed_' + tr]['grad_wire_mb_per_rank'] /\n"
        "        res['zero2_flat_' + tr]['grad_wire_mb_per_rank'], 2)\n"
        "    res['zero3_flat_' + tr] = measure(zero3_flat(tr))\n"
        # ZeRO-3 at-rest accounting: zero2 keeps every param replicated
        # per rank PLUS its 1/dp fp32 master chunk; zero3 keeps ONLY
        # the chunk (the just-in-time gather is transient)
        "P = sum(g.nbytes for g in grads)\n"
        "res['at_rest_param_mb_per_rank_zero2'] = round(\n"
        "    P * (1 + 1 / 8) / 2**20, 3)\n"
        "res['at_rest_param_mb_per_rank_zero3'] = round(\n"
        "    P / 8 / 2**20, 3)\n"
        "res['at_rest_saving_zero3_vs_zero2'] = round(\n"
        "    res['at_rest_param_mb_per_rank_zero2'] /\n"
        "    res['at_rest_param_mb_per_rank_zero3'], 2)\n"
        "pt = res['per_tensor_fp32']\n"
        "q = res['bucketed_int8']\n"
        "res['calls_ratio_per_tensor_vs_int8'] = round(\n"
        "    pt['collective_calls'] / q['collective_calls'], 2)\n"
        "res['wire_ratio_per_tensor_vs_int8'] = round(\n"
        "    pt['wire_mb_per_rank'] / q['wire_mb_per_rank'], 2)\n"
        "print(json.dumps(res))\n"
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    import re
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
    try:
        proc = subprocess.run([sys.executable, "-c", code], env=env,
                              capture_output=True, text=True, timeout=1200)
        lines = proc.stdout.strip().splitlines()
        if not lines:
            return {"error": f"rc={proc.returncode}: "
                             f"{proc.stderr.strip()[-400:]}"}
        result = json.loads(lines[-1])
    except Exception as e:  # never fail the headline bench on this
        return {"error": f"{type(e).__name__}: {e}"}
    # round-6 evidence: the zero2_flat rows (reduce-scatter-only sync)
    # land in BENCH_r06.json next to this file
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_r06.json")
    try:
        with open(out_path, "w") as fh:
            json.dump(result, fh, indent=1)
    except Exception:
        pass
    return result


def bench_lint_graph() -> dict:
    """The static-analysis gate as a bench target (ISSUE 3: lint-graph;
    ISSUE 5: per-edge attribution): runs ``python -m hetu_tpu.analysis
    --check --format json`` in a pinned-CPU subprocess and reports
    pass/fail, the analyzer's per-executable collective summary, and the
    per-edge coverage (explained collectives / total) per gated family.
    CI tier-1 runs the same gate through the ``lint_graph`` pytest
    marker (tests/test_analysis.py)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)       # the CLI forces its own device count
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "hetu_tpu.analysis", "--check",
             "--format", "json"],
            cwd=here, env=env, capture_output=True, text=True,
            timeout=1200)
        lines = [l for l in proc.stdout.splitlines() if l.strip()]
        payload = {}
        try:
            start = proc.stdout.index("{")
            payload, _ = json.JSONDecoder().raw_decode(proc.stdout[start:])
        except Exception:
            pass
        summary = {}
        for name, ex in payload.get("executables", {}).items():
            cov = ex.get("edge_coverage") or {}
            total = int(cov.get("total", 0))
            pct = (100.0 * cov.get("explained", 0) / total) \
                if total else 100.0
            summary[name] = {
                "collectives": ex.get("collectives", {}),
                "gspmd_collectives": ex.get("gspmd_collectives", {}),
                "findings": ex.get("findings", []),
                "edge_coverage_pct": round(pct, 1),
                "edge_coverage": cov,
            }
        return {"gate_passed": proc.returncode == 0,
                "exit_code": proc.returncode,
                "executables": summary,
                "tail": "" if proc.returncode == 0 else
                        "\n".join(lines[-8:])}
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}


def bench_mem_lint() -> dict:
    """The static peak-HBM model as a bench target (ISSUE 8): runs the
    analysis gate in a pinned-CPU subprocess and reports, per gated
    executable, the predicted peak bytes, the per-kind breakdown, and
    the delta against XLA's own ``compiled.memory_analysis()`` totals —
    the evidence trail that the planner's memory numbers track what the
    compiler actually allocates.  Writes BENCH_MEM.json next to this
    file."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)       # the CLI forces its own device count
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "hetu_tpu.analysis", "--check",
             "--format", "json"],
            cwd=here, env=env, capture_output=True, text=True,
            timeout=1200)
        payload = {}
        try:
            start = proc.stdout.index("{")
            payload, _ = json.JSONDecoder().raw_decode(proc.stdout[start:])
        except Exception:
            pass
        rows = {}
        deltas = []
        for name, ex in payload.get("executables", {}).items():
            mem = ex.get("memory")
            if not mem:
                rows[name] = {"error": "no memory accounting"}
                continue
            row = {
                "predicted_peak_bytes": int(mem["peak_bytes"]),
                "by_kind": mem.get("by_kind", {}),
                "xla_total_bytes": mem.get("xla_total_bytes"),
                "xla_delta_pct": mem.get("xla_delta_pct"),
            }
            if mem.get("xla_delta_pct") is not None:
                deltas.append(abs(float(mem["xla_delta_pct"])))
            rows[name] = row
        result = {
            "gate_passed": proc.returncode == 0,
            "exit_code": proc.returncode,
            "executables": rows,
            # headline: the worst absolute cross-check delta over all
            # gate families (the gate bounds it at 10% / 64KB floor)
            "max_abs_xla_delta_pct": max(deltas) if deltas else None,
        }
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}
    out_path = os.path.join(here, "BENCH_MEM.json")
    try:
        with open(out_path, "w") as fh:
            json.dump(result, fh, indent=1)
    except Exception:
        pass
    return result


def bench_cost_lint() -> dict:
    """The static step-time model as a bench target (ISSUE 10): runs
    the analysis gate in a pinned-CPU subprocess and reports, per gated
    executable, the predicted FLOPs / HBM bytes / step time and the
    deltas against XLA's own ``compiled.cost_analysis()`` totals — plus
    the planner loop closed: the calibrated DP search
    (``planner.search.plan_for_gpt``) must beat every hand-written
    gate-family layout on predicted step time.  Writes BENCH_COST.json
    next to this file."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)       # the CLI forces its own device count
    here = os.path.dirname(os.path.abspath(__file__))
    result: dict = {}
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "hetu_tpu.analysis", "--check",
             "--format", "json"],
            cwd=here, env=env, capture_output=True, text=True,
            timeout=1200)
        payload = {}
        try:
            start = proc.stdout.index("{")
            payload, _ = json.JSONDecoder().raw_decode(proc.stdout[start:])
        except Exception:
            pass
        rows = {}
        fdeltas, bdeltas = [], []
        for name, ex in payload.get("executables", {}).items():
            cost = ex.get("cost")
            if not cost:
                rows[name] = {"error": "no cost accounting"}
                continue
            row = {
                "predicted_flops": int(cost["flops"]),
                "predicted_hbm_bytes": int(cost["hbm_bytes"]),
                "predicted_step_time_us": cost["step_time_us"],
                "comm_time_us": cost.get("comm_time_us"),
                "bound": cost.get("bound"),
                "xla_flops": cost.get("xla_flops"),
                "xla_bytes_accessed": cost.get("xla_bytes_accessed"),
                "xla_flops_delta_pct": cost.get("xla_flops_delta_pct"),
                "xla_bytes_delta_pct": cost.get("xla_bytes_delta_pct"),
            }
            if cost.get("xla_flops_delta_pct") is not None:
                fdeltas.append(abs(float(cost["xla_flops_delta_pct"])))
            if cost.get("xla_bytes_delta_pct") is not None:
                bdeltas.append(abs(float(cost["xla_bytes_delta_pct"])))
            rows[name] = row
        result = {
            "gate_passed": proc.returncode == 0,
            "exit_code": proc.returncode,
            "executables": rows,
            # headline: worst absolute cross-check deltas over all gate
            # families (the gate bounds them at 10% / absolute floors)
            "max_abs_xla_flops_delta_pct": max(fdeltas) if fdeltas
            else None,
            "max_abs_xla_bytes_delta_pct": max(bdeltas) if bdeltas
            else None,
        }
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}
    # planner loop: calibrated search vs hand-written gate-family plans
    # (in-process; the search is pure python over the cost model)
    code = r"""
import json, sys
from hetu_tpu.models.gpt import GPTConfig
from hetu_tpu.planner.cost_model import calibrate_layer_time
from hetu_tpu.planner.search import plan_for_gpt, hand_plan_times
cfg = GPTConfig(vocab_size=50257, hidden_size=768, num_layers=12,
                num_heads=12, max_seq_len=1024, dtype="bfloat16")
cal = calibrate_layer_time(dtype="bfloat16")  # probe lowered ONCE
plan = plan_for_gpt(cfg, global_batch=64, seq=1024, n_chips=8,
                    time_calibration=cal)
hand = hand_plan_times(cfg, global_batch=64, seq=1024, n_chips=8,
                       time_calibration=cal)
print(json.dumps({
    "planner_step_time_ms": round(plan.time * 1e3, 3),
    "planner_layout": {"pp": plan.pp,
                       "dp": plan.layer_strategies[0].dp,
                       "tp": plan.layer_strategies[0].tp,
                       "micro_batch": plan.micro_batch},
    "hand_plans_ms": {k: round(v * 1e3, 3) for k, v in hand.items()},
    "planner_beats_all_hand_plans":
        all(plan.time <= v * (1 + 1e-9) for v in hand.values()),
}))
"""
    try:
        proc = subprocess.run([sys.executable, "-c", code], env=env,
                              cwd=here, capture_output=True, text=True,
                              timeout=1200)
        lines = [l for l in proc.stdout.strip().splitlines() if l]
        result["planner"] = json.loads(lines[-1]) if lines else \
            {"error": proc.stderr.strip()[-400:]}
    except Exception as e:
        result["planner"] = {"error": f"{type(e).__name__}: {e}"}
    out_path = os.path.join(here, "BENCH_COST.json")
    try:
        with open(out_path, "w") as fh:
            json.dump(result, fh, indent=1)
    except Exception:
        pass
    return result


def bench_protocol_lint() -> dict:
    """The serving-protocol verifier as a bench target (DESIGN.md §23):
    exhaustively model-checks the bounded 2-replica serving protocol —
    EVERY interleaving of scheduler/router/chaos/autoscaler choices
    within the default ``ExploreConfig`` caps, counted by memoized DAG
    path counting — replays seeded ~300-event chaos fuzz traces
    through the lifecycle state machines with strict terminal
    conservation, and proves each seeded interaction-bug class is
    caught by the right rule.  Pure Python over the protocol model (no
    jax, no devices).  Writes BENCH_PROTOCOL.json next to this file."""
    from hetu_tpu.analysis.protocol import explore, fuzz_trace, replay
    result: dict = {}
    try:
        t0 = time.perf_counter()
        res = explore()          # default bounded config, exhaustive
        explore_s = time.perf_counter() - t0
        t1 = time.perf_counter()
        fuzz_events = fuzz_violations = 0
        fuzz_seeds = 3
        for seed in range(fuzz_seeds):
            ev = fuzz_trace(seed=seed, n_events=300)
            fuzz_events += len(ev)
            # complete trace: terminal page conservation IS enforced
            fuzz_violations += len(replay(ev))
        fuzz_s = time.perf_counter() - t1
        t2 = time.perf_counter()
        bugs = {}
        for flag, rule in (
                ("drain_inflight", "fence-regression"),
                ("double_adopt", "request-lifecycle-violation"),
                ("stale_accept", "fence-regression"),
                ("free_shared", "page-lifecycle-violation")):
            r = explore(bug=flag)
            bugs[flag] = {
                "found": len(r.violations) > 0,
                "expected_rule": rule,
                "rule_ok": bool(r.violations) and
                all(v.rule == rule for v in r.violations),
                "states_to_find": r.states,
            }
        bugs_s = time.perf_counter() - t2
        result = {
            "explore": {
                "interleavings": res.interleavings,
                "states": res.states,
                "max_depth": res.max_depth,
                "events_checked": res.events_checked,
                "violations": len(res.violations),
                "clean": res.ok,
                "wall_s": round(explore_s, 3),
            },
            "fuzz": {
                "seeds": fuzz_seeds,
                "events": fuzz_events,
                "violations": fuzz_violations,
                "clean": fuzz_violations == 0,
                "wall_s": round(fuzz_s, 3),
            },
            "seeded_bugs": bugs,
            "all_bugs_caught": all(b["found"] and b["rule_ok"]
                                   for b in bugs.values()),
            "bugs_wall_s": round(bugs_s, 3),
        }
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_PROTOCOL.json")
    try:
        with open(out_path, "w") as fh:
            json.dump(result, fh, indent=1)
    except Exception:
        pass
    return result


def bench_schedule_lint() -> dict:
    """The cross-rank collective-schedule verifier as a bench target
    (DESIGN.md §25): extracts and verifies per-rank symbolic schedules
    over the full strategy grid — dp x tp x pp x cp layouts, zero in
    {0, 2, 3}, SPMD-1F1B vs MPMD pipelines (with Malleus uneven
    per-pipe micro-batches), with and without a mid-run dp-resize
    switch — expecting ZERO violations on every clean plan, then
    proves each seeded cross-rank divergence (collective order / group
    / payload skew, dropped recv, recv inversion deadlock, repack
    skew) is caught by EXACTLY its rule with a per-rank subtrace.
    Pure Python over the symbolic schedules (no jax, no devices).
    Writes BENCH_SCHEDULE.json next to this file."""
    from hetu_tpu.analysis.schedule import (extract_schedules,
                                            seeded_bug_corpus,
                                            strategy_grid,
                                            verify_schedules)
    result: dict = {}
    try:
        t0 = time.perf_counter()
        grid_points = 0
        grid_ranks = grid_ops = 0
        dirty = []
        for label, spec in strategy_grid():
            sched = extract_schedules(spec)
            violations = verify_schedules(sched)
            grid_points += 1
            grid_ranks += len(sched)
            grid_ops += sum(len(ops) for ops in sched.values())
            if violations:
                dirty.append({"plan": label,
                              "rules": sorted({v.rule
                                               for v in violations})})
        grid_s = time.perf_counter() - t0
        t1 = time.perf_counter()
        bugs = {}
        for entry in seeded_bug_corpus():
            violations = verify_schedules(entry["schedules"])
            rules = sorted({v.rule for v in violations})
            bugs[entry["name"]] = {
                "found": len(violations) > 0,
                "expected_rule": entry["rule"],
                "rule_ok": rules == [entry["rule"]],
                "has_subtrace": all(v.format_subtrace()
                                    for v in violations),
            }
        bugs_s = time.perf_counter() - t1
        result = {
            "grid": {
                "plans": grid_points,
                "ranks_extracted": grid_ranks,
                "ops_extracted": grid_ops,
                "dirty_plans": dirty,
                "clean": not dirty,
                "wall_s": round(grid_s, 3),
            },
            "seeded_bugs": bugs,
            "all_bugs_caught": all(b["found"] and b["rule_ok"]
                                   and b["has_subtrace"]
                                   for b in bugs.values()),
            "bugs_wall_s": round(bugs_s, 3),
        }
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_SCHEDULE.json")
    try:
        with open(out_path, "w") as fh:
            json.dump(result, fh, indent=1)
    except Exception:
        pass
    return result


def bench_serving_microbench() -> dict:
    """Serving microbench v2 (ISSUE 6): dense-cache ``generate()`` vs
    the UNIFIED ragged prefill+decode engine on a GPT-2-small-
    proportioned model with mixed-length prompts (64/512/1024 + short
    traffic).

    v2 reports, per path, BOTH a cold trace (includes XLA compile — what
    the v1 numbers measured) and a steady-state trace (compile
    amortized — what a long-running service sees), plus the unified
    engine's executable-call count, compile count (must be <= 2: the
    unified step + optional warmup — the old bucket grid compiled
    O(prefill buckets x batch buckets)), per-request KV HBM bytes held,
    and the per-stage TTFT/TBT latency histograms
    (``utils/metrics.py`` Prometheus buckets).

    ISSUE 7 adds a **shared-system-prompt trace** (N users behind one
    512-token header) comparing copy-on-write prefix caching against
    the cache-off engine on equally warm executables: cache hit rate,
    prefill tokens saved, and TTFT p50/p90 cached-vs-cold land under a
    ``prefix_cache`` key.  The KV accounting is
    analytic from shapes — valid off-hardware; wall times on CPU are a
    relative signal only.  Layer count/width are scaled down
    (HETU_TPU_SERVE_BENCH_{HIDDEN,LAYERS} to override) so the CPU run
    finishes in seconds.

    ISSUE 15 adds a **spec_decode section**: draft-model speculative
    decoding (1-layer truncated self-draft, k greedy proposals verified
    in one dedicated ragged verify row) against the same engine with
    spec off, on a single-stream decode trace — the per-token-latency
    regime the feature attacks.  Records tok/s, TTFT/TBT p50/p90,
    accepted-token rate, and the acceptance booleans
    ``spec_temp0_bitwise`` (outputs bit-for-bit the non-speculative
    run's) and ``spec_beats_nonspec_tok_s``.

    ISSUE 16 adds an **mla section**: the same geometry with a
    low-rank kv projection converted to weight-absorbed latent KV
    (``models.gpt.mla_state_from``), served from compressed latent
    pages — full-head vs latent vs latent+int8 page quantization on
    the same mixed trace.  Records KV bytes/token and bytes/req, max
    concurrent 544-token requests at a fixed HBM budget, tok/s, TTFT
    p50/p90, the logit max-abs-delta vs full-head, and the acceptance
    booleans ``mla_kv_bytes_reduced`` / ``mla_more_concurrent_requests``
    / ``mla_accuracy_within_tolerance`` /
    ``mla_temp0_bitwise_vs_solo``.

    ISSUE 9 adds the **trace plane microbench**: tracer overhead on
    warm short replays (no tracer vs disabled SpanTracer vs tracing
    on, paired back-to-back rounds, median per-round delta; the
    disabled-vs-none delta is asserted < 2% AFTER the headline JSON is
    emitted — the no-op path must be free), the Perfetto trace artifact
    (``scratch/serving_trace.json``), and the predicted-vs-observed
    reconciliation table over BOTH executable families (serving unified
    + a tiny traced train step) — all landing in ``BENCH_OBS.json``.

    Writes BENCH_SERVING.json next to this file (keeping the previous
    bucketed-engine numbers under a ``v1`` key for the trajectory) and
    returns the dict.
    """
    code = (
        "import os, sys, json, time\n"
        f"sys.path.insert(0, {os.path.dirname(os.path.abspath(__file__))!r})\n"
        "import numpy as np\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from hetu_tpu.models import GPTConfig\n"
        "from hetu_tpu.models.generate import generate\n"
        "from hetu_tpu.serving import Engine\n"
        "H = int(os.environ.get('HETU_TPU_SERVE_BENCH_HIDDEN', '256'))\n"
        "L = int(os.environ.get('HETU_TPU_SERVE_BENCH_LAYERS', '2'))\n"
        "V, NH, NKV = 1024, 8, 4\n"
        "cfg = GPTConfig(vocab_size=V, hidden_size=H, num_layers=L,\n"
        "                num_heads=NH, num_kv_heads=NKV, max_seq_len=2048,\n"
        "                sp=False, dropout=0.0, position='rotary',\n"
        "                norm='rmsnorm', activation='silu',\n"
        "                tie_embeddings=True)\n"
        "hd, f = cfg.head_dim, cfg.ffn_size\n"
        "rng = np.random.RandomState(0)\n"
        "def w(*s):\n"
        "    return (rng.randn(*s) * 0.02).astype(np.float32)\n"
        "state = {'wte.weight': w(V, H), 'ln_f.weight': np.ones(H, np.float32)}\n"
        "for i in range(L):\n"
        "    state[f'h{i}.ln_1.weight'] = np.ones(H, np.float32)\n"
        "    state[f'h{i}.ln_2.weight'] = np.ones(H, np.float32)\n"
        "    state[f'h{i}.attn.qkv.weight'] = w((NH + 2 * NKV) * hd, H)\n"
        "    state[f'h{i}.attn.out.weight'] = w(H, NH * hd)\n"
        "    state[f'h{i}.mlp.up.weight'] = w(f, H)\n"
        "    state[f'h{i}.mlp.down.weight'] = w(H, f)\n"
        "lens = [64, 64, 512, 64, 1024, 64]\n"
        "new = 32\n"
        "n_tok = len(lens) * new\n"
        "prompts = [rng.randint(1, V, size=n).tolist() for n in lens]\n"
        "kv_itemsize = 4\n"
        "\n"
        "# -- dense baseline: one static batch padded to the longest --\n"
        "smax = max(lens)\n"
        "batch = np.zeros((len(lens), smax), np.int32)\n"
        "for i, p in enumerate(prompts):\n"
        "    batch[i, :len(p)] = p\n"
        "t0 = time.perf_counter()\n"
        "np.asarray(generate(state, cfg, batch, new))\n"
        "dense_cold = time.perf_counter() - t0\n"
        "# steady state = best of 3 (kills 2-core scheduler noise; same\n"
        "# treatment for both paths)\n"
        "dense_warm = float('inf')\n"
        "for _ in range(3):\n"
        "    t0 = time.perf_counter()\n"
        "    np.asarray(generate(state, cfg, batch, new))\n"
        "    dense_warm = min(dense_warm, time.perf_counter() - t0)\n"
        "dense_bytes_per_req = 2 * L * (smax + new) * NKV * hd * kv_itemsize\n"
        "\n"
        "# -- unified engine: ONE ragged prefill+decode executable --\n"
        "eng = Engine(state, cfg, num_pages=24, page_size=128,\n"
        "             max_batch=8, max_model_len=smax + new,\n"
        "             chunk_size=128, prefill_rows=2)\n"
        "t0 = time.perf_counter()\n"
        "reqs = [eng.add_request(p, new, arrival_time=0.0)\n"
        "        for p in prompts]\n"
        "eng.run()\n"
        "cold_wall = time.perf_counter() - t0\n"
        "paged_bytes = [r.peak_pages * eng.pool.page_bytes for r in reqs]\n"
        "mc = eng.metrics_summary()        # COLD-trace metrics (incl.\n"
        "                                  # compile -- what v1 measured)\n"
        "# steady state: same trace on the warm executable, fresh\n"
        "# metrics, best of 3 (same treatment as dense)\n"
        "warm_wall = float('inf')\n"
        "for _ in range(3):\n"
        "    eng.reset_metrics()\n"
        "    t0 = time.perf_counter()\n"
        "    reqs = [eng.add_request(p, new, arrival_time=0.0)\n"
        "            for p in prompts]\n"
        "    eng.run()\n"
        "    warm_wall = min(warm_wall, time.perf_counter() - t0)\n"
        "m = eng.metrics_summary()         # STEADY metrics (last replay)\n"
        "\n"
        "# -- shared-system-prompt trace (ISSUE 7): N users behind one\n"
        "# 512-token header -- copy-on-write prefix caching vs the same\n"
        "# engine with the cache off, both on WARM executables, so the\n"
        "# delta is pure prefill reuse\n"
        "N_USERS, HDR, TAIL, PNEW = 6, 512, 32, 16\n"
        "header = rng.randint(1, V, size=HDR).tolist()\n"
        "users = [header + rng.randint(1, V, size=TAIL).tolist()\n"
        "         for _ in range(N_USERS)]\n"
        "def shared_trace(cache_on):\n"
        "    e = Engine(state, cfg, num_pages=48, page_size=128,\n"
        "               max_batch=8, max_model_len=1024, chunk_size=128,\n"
        "               prefill_rows=2, prefix_cache=cache_on)\n"
        "    rs = [e.add_request(u, PNEW, arrival_time=0.0)\n"
        "          for u in users]\n"
        "    e.run()                       # warm: compile (+ populates\n"
        "    e.reset_metrics()             # the cache when enabled)\n"
        "    t0 = time.perf_counter()\n"
        "    rs = [e.add_request(u, PNEW, arrival_time=0.0)\n"
        "          for u in users]\n"
        "    e.run()\n"
        "    wall = time.perf_counter() - t0\n"
        "    mm = e.metrics_summary()\n"
        "    return e, mm, wall\n"
        "\n"
        "# -- trace plane (ISSUE 9): tracer overhead + the Perfetto\n"
        "# artifact + predicted-vs-observed reconciliation, packaged as\n"
        "# a function so it can run AFTER every headline measurement\n"
        "# (and degrade to an error stub) -- the obs section may never\n"
        "# cost the serving numbers\n"
        "def obs_section():\n"
        "    from hetu_tpu import obs\n"
        "    import statistics\n"
        "    oh_prompts = [p for p, n in zip(prompts, lens) if n == 64]\n"
        "    oh_new = 8\n"
        "    def replay(engine, ps, n_new):\n"
        "        engine.reset_metrics()\n"
        "        t0 = time.perf_counter()\n"
        "        for p in ps:\n"
        "            engine.add_request(p, n_new, arrival_time=0.0)\n"
        "        engine.run()\n"
        "        return time.perf_counter() - t0\n"
        "    # overhead: (a) no tracer (shared no-op), (b) a real\n"
        "    # SpanTracer switched off in place (the guard path a\n"
        "    # service with tracing compiled in but disabled pays),\n"
        "    # (c) tracing on -- short decode-dominated replays in\n"
        "    # back-to-back PAIRED rounds, gated on the median of\n"
        "    # per-round differences: pairing cancels the slow\n"
        "    # scheduler/thermal drift that makes any unpaired wall\n"
        "    # comparison (even min-of-N) swing several percent on a\n"
        "    # busy 2-core host\n"
        "    tr_off = obs.SpanTracer(capacity=1 << 16)\n"
        "    tr_off.enabled = False\n"
        "    tr_on = obs.SpanTracer(capacity=1 << 16)\n"
        "    nulls, d_off, d_on = [], [], []\n"
        "    for _ in range(40):\n"
        "        eng.set_tracer(None)\n"
        "        a = replay(eng, oh_prompts, oh_new)\n"
        "        eng.set_tracer(tr_off)\n"
        "        b = replay(eng, oh_prompts, oh_new)\n"
        "        eng.set_tracer(tr_on)\n"
        "        c = replay(eng, oh_prompts, oh_new)\n"
        "        nulls.append(a)\n"
        "        d_off.append(b - a)\n"
        "        d_on.append(c - a)\n"
        "    eng.set_tracer(None)\n"
        "    null_wall = statistics.median(nulls)\n"
        "    disabled_wall = null_wall + statistics.median(d_off)\n"
        "    traced_wall = null_wall + statistics.median(d_on)\n"
        "    disabled_delta_pct = abs(statistics.median(d_off)) \\\n"
        "        / null_wall * 100.0\n"
        "    traced_overhead_pct = statistics.median(d_on) \\\n"
        "        / null_wall * 100.0\n"
        "    # a tiny traced train step joins the reconciliation table\n"
        "    # as a second executable family (serving is the first)\n"
        "    import hetu_tpu as ht\n"
        "    from hetu_tpu import optim\n"
        "    from hetu_tpu.models import GPTLMHeadModel\n"
        "    tcfg = GPTConfig(vocab_size=V, hidden_size=64,\n"
        "                     num_layers=2, num_heads=4, max_seq_len=64,\n"
        "                     sp=False, dropout=0.0)\n"
        "    ht.set_seed(0)\n"
        "    with obs.trace() as ttr:\n"
        "        with ht.graph('define_and_run', create_new=True,\n"
        "                      prefix='obs_bench') as g:\n"
        "            ids = ht.placeholder('int32', (2, 16), name='ids')\n"
        "            lbl = ht.placeholder('int32', (2, 16), name='lbl')\n"
        "            tloss = GPTLMHeadModel(tcfg)(ids, lbl)\n"
        "            top_ = optim.AdamOptimizer(lr=1e-3).minimize(tloss)\n"
        "            tdata = rng.randint(0, V,\n"
        "                                size=(2, 16)).astype('int32')\n"
        "            for _ in range(3):\n"
        "                g.run(tloss, [tloss, top_],\n"
        "                      {ids: tdata, lbl: tdata})\n"
        "        train_events = ttr.events()\n"
        "    # the frozen artifact: ONE clean traced replay of the full\n"
        "    # mixed trace (not the 40 overhead mini-replays)\n"
        "    tr_art = obs.SpanTracer(capacity=1 << 16)\n"
        "    eng.set_tracer(tr_art)\n"
        "    replay(eng, prompts, new)\n"
        "    eng.set_tracer(None)\n"
        "    all_events = tr_art.events() + train_events\n"
        f"    art_dir = os.path.join({os.path.dirname(os.path.abspath(__file__))!r}, 'scratch')\n"
        "    os.makedirs(art_dir, exist_ok=True)\n"
        "    art_path = os.path.join(art_dir, 'serving_trace.json')\n"
        "    obs.write_chrome_trace(all_events, art_path)\n"
        "    rec = obs.reconcile(all_events)\n"
        "    n_tok_obs = len(oh_prompts) * oh_new\n"
        "    return {\n"
        "      'tracer_overhead': {\n"
        "        'protocol': '40 back-to-back paired rounds x 3 '\n"
        "                    'configs; gate = |median per-round delta| '\n"
        "                    '/ median null wall, short decode trace '\n"
        "                    'on the warm executable',\n"
        "        'untraced_wall_s': round(null_wall, 3),\n"
        "        'disabled_wall_s': round(disabled_wall, 3),\n"
        "        'traced_wall_s': round(traced_wall, 3),\n"
        "        'untraced_tokens_per_sec':\n"
        "            round(n_tok_obs / null_wall, 1),\n"
        "        'disabled_tokens_per_sec':\n"
        "            round(n_tok_obs / disabled_wall, 1),\n"
        "        'traced_tokens_per_sec':\n"
        "            round(n_tok_obs / traced_wall, 1),\n"
        "        'disabled_delta_pct': round(disabled_delta_pct, 2),\n"
        "        'traced_overhead_pct': round(traced_overhead_pct, 2),\n"
        "        'disabled_lt_2pct': bool(disabled_delta_pct < 2.0),\n"
        "      },\n"
        "      'trace_artifact': art_path,\n"
        "      'trace_events': len(all_events),\n"
        "      'trace_dropped': int(tr_art.dropped),\n"
        "      'reconcile': rec.to_dict(),\n"
        "    }, disabled_delta_pct\n"
        "\n"
        "# -- speculative decoding (ISSUE 15): a 1-layer truncated\n"
        "# self-draft proposes k tokens per step, the unified step\n"
        "# verifies them in one dedicated ragged verify row.  Measured\n"
        "# in the regime the feature attacks — single-stream decode,\n"
        "# where every token otherwise costs one full target step\n"
        "# (the standing mixed trace above stays the continuous-\n"
        "# batching throughput headline: at 6-way batching the unified\n"
        "# step already amortizes the weights across rows, and on CPU\n"
        "# the draft overhead outweighs the saved steps there).  Spec\n"
        "# and non-spec run the SAME trace on identically-shaped\n"
        "# engines; temp-0 outputs must be BIT-FOR-BIT equal.\n"
        "from hetu_tpu.models import draft_state_from\n"
        "from hetu_tpu.serving import SpecConfig\n"
        "dstate, dcfg = draft_state_from(state, cfg, max(1, L // 2))\n"
        "sp_prompt = rng.randint(1, V, size=512).tolist()\n"
        "SP_NEW, SP_K = 96, 4\n"
        "def spec_trace(spec_on):\n"
        "    e = Engine(state, cfg, num_pages=24, page_size=128,\n"
        "               max_batch=1, max_model_len=640, chunk_size=128,\n"
        "               prefill_rows=1,\n"
        "               spec=SpecConfig(dstate, dcfg, k=SP_K)\n"
        "               if spec_on else None)\n"
        "    r = e.add_request(sp_prompt, SP_NEW, arrival_time=0.0)\n"
        "    e.run()                      # warm (compile)\n"
        "    wall = float('inf')\n"
        "    for _ in range(3):\n"
        "        e.reset_metrics()\n"
        "        t0 = time.perf_counter()\n"
        "        r = e.add_request(sp_prompt, SP_NEW, arrival_time=0.0)\n"
        "        e.run()\n"
        "        wall = min(wall, time.perf_counter() - t0)\n"
        "    return e, list(r.out_tokens), wall, e.metrics_summary()\n"
        "_, sp_base_out, sp_base_wall, sp_base_m = spec_trace(False)\n"
        "sp_eng, sp_out, sp_wall, sp_m = spec_trace(True)\n"
        "spec_decode = {\n"
        "  'trace': {'prompt_tokens': 512, 'max_new_tokens': SP_NEW,\n"
        "            'concurrency': 1, 'k': SP_K,\n"
        "            'draft_layers': max(1, L // 2),\n"
        "            'regime': 'single-stream decode (per-token '\n"
        "                      'latency, the bottleneck spec attacks; '\n"
        "                      'mixed-trace throughput stays under '\n"
        "                      'unified)'},\n"
        "  'nonspec': {\n"
        "    'tokens_per_sec': round(SP_NEW / sp_base_wall, 1),\n"
        "    'wall_s': round(sp_base_wall, 3),\n"
        "    'ttft_p50_ms': round(sp_base_m['ttft']['p50'] * 1e3, 1),\n"
        "    'ttft_p90_ms': round(sp_base_m['ttft']['p90'] * 1e3, 1),\n"
        "    'tbt_p50_ms': round(sp_base_m['tbt']['p50'] * 1e3, 2),\n"
        "    'tbt_p90_ms': round(sp_base_m['tbt']['p90'] * 1e3, 2),\n"
        "    'executable_calls': int(sp_base_m['executable_calls'])},\n"
        "  'spec': {\n"
        "    'tokens_per_sec': round(SP_NEW / sp_wall, 1),\n"
        "    'wall_s': round(sp_wall, 3),\n"
        "    'ttft_p50_ms': round(sp_m['ttft']['p50'] * 1e3, 1),\n"
        "    'ttft_p90_ms': round(sp_m['ttft']['p90'] * 1e3, 1),\n"
        "    'tbt_p50_ms': round(sp_m['tbt']['p50'] * 1e3, 2),\n"
        "    'tbt_p90_ms': round(sp_m['tbt']['p90'] * 1e3, 2),\n"
        "    'executable_calls': int(sp_m['executable_calls']),\n"
        "    'proposed': int(sp_m['spec_proposed']),\n"
        "    'accepted': int(sp_m['spec_accepted']),\n"
        "    'bonus_tokens': int(sp_m['spec_bonus_tokens']),\n"
        "    'accept_rate': round(sp_m['spec_accept_rate'], 3),\n"
        "    'accepted_per_step': round(sp_m['accepted_per_step'], 2),\n"
        "    'compile_count': int(sp_m['compile_count']),\n"
        "    'host_logit_fetches': int(sp_m['host_logit_fetches'])},\n"
        "  'speedup_vs_nonspec': round(sp_base_wall / sp_wall, 2),\n"
        "  # the ISSUE 15 acceptance gates, recorded as booleans\n"
        "  'spec_temp0_bitwise': sp_out == sp_base_out,\n"
        "  'spec_beats_nonspec_tok_s': sp_wall < sp_base_wall,\n"
        "  'spec_compile_count_ok': int(sp_m['compile_count']) == 4,\n"
        "  'spec_host_logit_fetches_ok':\n"
        "      int(sp_m['host_logit_fetches']) == 0,\n"
        "}\n"
        "\n"
        "# -- MLA compressed latent KV (ISSUE 16): the same geometry\n"
        "# with a LOW-RANK kv projection (joint rank <= LAT), so the\n"
        "# SVD re-factoring in mla_state_from is EXACT and the logit\n"
        "# delta vs full-head is pure fp accumulation noise -- that is\n"
        "# the documented tolerance below, not a model-quality claim.\n"
        "# Learned positions so the int8 page-quant leg applies too.\n"
        "# All three engines run the SAME mixed trace; temp-0 latent\n"
        "# serving must be bitwise vs the latent solo generate().\n"
        "from hetu_tpu.models.gpt import mla_state_from\n"
        "from hetu_tpu.models.generate import (decode_step, _Params,\n"
        "                                      _lm_head)\n"
        "import jax.numpy as jnp\n"
        "LAT, MLA_TOL = 64, 2e-4\n"
        "cfg_fh = GPTConfig(vocab_size=V, hidden_size=H, num_layers=L,\n"
        "                   num_heads=NH, num_kv_heads=NKV,\n"
        "                   max_seq_len=2048, sp=False, dropout=0.0,\n"
        "                   position='learned', norm='rmsnorm',\n"
        "                   activation='silu', tie_embeddings=True)\n"
        "state_fh = dict(state)\n"
        "state_fh['wpe'] = w(2048, H)\n"
        "qs = NH * hd\n"
        "for i in range(L):\n"
        "    u = (rng.randn(2 * NKV * hd, LAT) * 0.1).astype(np.float32)\n"
        "    a = (rng.randn(LAT, H) * 0.2).astype(np.float32)\n"
        "    qkv = state_fh[f'h{i}.attn.qkv.weight'].copy()\n"
        "    qkv[qs:] = u @ a\n"
        "    state_fh[f'h{i}.attn.qkv.weight'] = qkv\n"
        "mstate, mcfg = mla_state_from(state_fh, cfg_fh,\n"
        "                              kv_latent_dim=LAT)\n"
        "# logit fidelity on a fixed probe batch, full-head vs absorbed\n"
        "probe = jnp.asarray(rng.randint(1, V, size=(2, 128)), jnp.int32)\n"
        "pf = _Params(state_fh, cfg_fh)\n"
        "cch = [(jnp.zeros((2, 128, NKV, hd), jnp.float32),\n"
        "        jnp.zeros((2, 128, NKV, hd), jnp.float32))\n"
        "       for _ in range(L)]\n"
        "_, _, hid_f = decode_step(cfg_fh, pf, probe, cch, 0, None,\n"
        "                          None, return_hidden=True)\n"
        "pm = _Params(mstate, mcfg)\n"
        "mch = [(jnp.zeros((2, 128, 1, LAT), jnp.float32),\n"
        "        jnp.zeros((2, 128, 1, 0), jnp.float32))\n"
        "       for _ in range(L)]\n"
        "_, _, hid_m = decode_step(mcfg, pm, probe, mch, 0, None, None,\n"
        "                          return_hidden=True)\n"
        "mla_delta = float(jnp.max(jnp.abs(\n"
        "    _lm_head(pf, hid_f) - _lm_head(pm, hid_m))))\n"
        "def mla_trace(st, cf, quant=None):\n"
        "    e = Engine(st, cf, num_pages=24, page_size=128,\n"
        "               max_batch=8, max_model_len=smax + new,\n"
        "               chunk_size=128, prefill_rows=2,\n"
        "               page_quant=quant)\n"
        "    rs = [e.add_request(p, new, arrival_time=0.0)\n"
        "          for p in prompts]\n"
        "    e.run()                      # warm (compile)\n"
        "    first = [list(r.out_tokens) for r in rs]\n"
        "    wall = float('inf')\n"
        "    for _ in range(3):\n"
        "        e.reset_metrics()\n"
        "        t0 = time.perf_counter()\n"
        "        rs = [e.add_request(p, new, arrival_time=0.0)\n"
        "              for p in prompts]\n"
        "        e.run()\n"
        "        wall = min(wall, time.perf_counter() - t0)\n"
        "    outs = [list(r.out_tokens) for r in rs]\n"
        "    assert outs == first         # replay (cache-warm) == cold\n"
        "    pb = [r.peak_pages * e.pool.page_bytes for r in rs]\n"
        "    return e, outs, wall, e.metrics_summary(), pb\n"
        "fh_e, fh_out, fh_wall, fh_m, fh_b = mla_trace(state_fh, cfg_fh)\n"
        "lt_e, lt_out, lt_wall, lt_m, lt_b = mla_trace(mstate, mcfg)\n"
        "q8_e, q8_out, q8_wall, q8_m, q8_b = mla_trace(mstate, mcfg,\n"
        "                                              quant='int8')\n"
        "lt_solo = [np.asarray(generate(mstate, mcfg,\n"
        "                               np.asarray([p], np.int32),\n"
        "                               new))[0, len(p):].tolist()\n"
        "           for p in prompts]\n"
        "# concurrency at a FIXED HBM budget (the full-head pool's 24\n"
        "# pages), analytic from shapes like every KV accounting here:\n"
        "# smaller pages => more pages in budget => more 544-token\n"
        "# (512 prompt + 32 new) requests resident at once\n"
        "mla_budget = 24 * fh_e.pool.page_bytes\n"
        "def mla_conc(e):\n"
        "    pages = mla_budget // e.pool.page_bytes\n"
        "    per = -(-(512 + new) // e.pool.page_size)\n"
        "    return int(max(pages - 1, 0) // per)   # -1: trash page\n"
        "def mla_leg(e, wall, m, pb):\n"
        "    return {\n"
        "      'kv_bytes_per_token': int(e.pool.kv_bytes_per_token),\n"
        "      'page_bytes': int(e.pool.page_bytes),\n"
        "      'kv_bytes_per_req_mean': int(np.mean(pb)),\n"
        "      'max_concurrent_at_fixed_hbm': mla_conc(e),\n"
        "      'tokens_per_sec': round(n_tok / wall, 1),\n"
        "      'wall_s': round(wall, 2),\n"
        "      'ttft_p50_ms': round(m['ttft']['p50'] * 1e3, 1),\n"
        "      'ttft_p90_ms': round(m['ttft']['p90'] * 1e3, 1),\n"
        "      'compile_count': int(m['compile_count']),\n"
        "      'executable_calls': int(m['executable_calls']),\n"
        "      'host_logit_fetches': int(m['host_logit_fetches'])}\n"
        "mla = {\n"
        "  'trace': {'prompt_lens': lens, 'max_new_tokens': new,\n"
        "            'kv_latent_dim': LAT, 'rope_dim': 0,\n"
        "            'witness': 'low-rank kv (joint rank <= latent '\n"
        "                       'dim), so conversion is exact and the '\n"
        "                       'logit delta is fp noise'},\n"
        "  'full_head': mla_leg(fh_e, fh_wall, fh_m, fh_b),\n"
        "  'latent': mla_leg(lt_e, lt_wall, lt_m, lt_b),\n"
        "  'latent_int8': mla_leg(q8_e, q8_wall, q8_m, q8_b),\n"
        "  'logit_max_abs_delta_vs_full_head': mla_delta,\n"
        "  'logit_tolerance': MLA_TOL,\n"
        "  # the ISSUE 16 acceptance gates, recorded as booleans\n"
        "  'mla_kv_bytes_reduced':\n"
        "      2 * lt_e.pool.kv_bytes_per_token\n"
        "      <= fh_e.pool.kv_bytes_per_token,\n"
        "  'mla_more_concurrent_requests':\n"
        "      mla_conc(lt_e) >= 2 * mla_conc(fh_e),\n"
        "  'mla_accuracy_within_tolerance': mla_delta <= MLA_TOL,\n"
        "  'mla_temp0_bitwise_vs_solo': lt_out == lt_solo,\n"
        "  'mla_matches_full_head_tokens': lt_out == fh_out,\n"
        "}\n"
        "\n"
        "e_cold, m_cold, wall_cold = shared_trace(False)\n"
        "e_hit, m_hit, wall_hit = shared_trace(True)\n"
        "# headline + prefix-cache numbers are all in the can: the obs\n"
        "# section runs last and degrades to an error stub\n"
        "try:\n"
        "    obs_res, obs_delta = obs_section()\n"
        "except Exception as e:\n"
        "    obs_res = {'error': f'{type(e).__name__}: {e}'}\n"
        "    obs_delta = None\n"
        "prompt_toks = sum(len(u) for u in users)\n"
        "saved = int(m_hit['prefix_cache_tokens_saved'])\n"
        "shared = {\n"
        "  'trace': {'n_users': N_USERS, 'header_tokens': HDR,\n"
        "            'tail_tokens': TAIL, 'max_new_tokens': PNEW},\n"
        "  'hit_rate': float(m_hit['prefix_cache_hit_rate']),\n"
        "  'prefill_tokens_saved': saved,\n"
        "  'prefill_tokens_total': prompt_toks,\n"
        "  'prefill_savings_pct': round(100.0 * saved / prompt_toks, 1),\n"
        "  'cached': {'ttft_p50_ms': round(m_hit['ttft']['p50']*1e3, 1),\n"
        "             'ttft_p90_ms': round(m_hit['ttft']['p90']*1e3, 1),\n"
        "             'tbt_p50_ms': round(m_hit['tbt']['p50']*1e3, 1),\n"
        "             'wall_s': round(wall_hit, 2),\n"
        "             'tokens_per_sec': round(N_USERS*PNEW/wall_hit, 1),\n"
        "             'executable_calls':\n"
        "                 int(m_hit['executable_calls'])},\n"
        "  'cold': {'ttft_p50_ms': round(m_cold['ttft']['p50']*1e3, 1),\n"
        "           'ttft_p90_ms': round(m_cold['ttft']['p90']*1e3, 1),\n"
        "           'tbt_p50_ms': round(m_cold['tbt']['p50']*1e3, 1),\n"
        "           'wall_s': round(wall_cold, 2),\n"
        "           'tokens_per_sec': round(N_USERS*PNEW/wall_cold, 1),\n"
        "           'executable_calls': int(m_cold['executable_calls'])},\n"
        "  'compile_count_ok': int(m_hit['compile_count']) <= 2,\n"
        "  # the ISSUE 7 acceptance gates, recorded as booleans\n"
        "  'savings_ge_30pct': 100.0 * saved / prompt_toks >= 30.0,\n"
        "  'ttft_p90_better_than_cold':\n"
        "      m_hit['ttft']['p90'] < m_cold['ttft']['p90'],\n"
        "}\n"
        "res = {\n"
        "  'model': {'hidden': H, 'layers': L, 'heads': NH,\n"
        "            'kv_heads': NKV, 'vocab': V},\n"
        "  'prompt_lens': lens, 'max_new_tokens': new,\n"
        "  'page_size': eng.pool.page_size,\n"
        "  'chunk_size': eng.scheduler.chunk,\n"
        "  'prefill_rows': eng.scheduler.prefill_rows,\n"
        "  'token_budget': eng.scheduler.token_budget,\n"
        "  'dense': {'tokens_per_sec': round(n_tok / dense_cold, 1),\n"
        "            'tokens_per_sec_steady': round(n_tok / dense_warm, 1),\n"
        "            'wall_s': round(dense_cold, 2),\n"
        "            'wall_s_steady': round(dense_warm, 2),\n"
        "            'kv_bytes_per_req': dense_bytes_per_req,\n"
        "            'recompiles': 1},\n"
        "  'unified': {\n"
        "    # cold = first trace incl. XLA compile (the v1-comparable\n"
        "    # numbers); steady = best-of-3 warm replay of the same trace\n"
        "    'cold': {'tokens_per_sec': round(n_tok / cold_wall, 1),\n"
        "             'wall_s': round(cold_wall, 2),\n"
        "             'ttft_p90_ms': round(mc['ttft']['p90'] * 1e3, 1),\n"
        "             'executable_calls': int(mc['executable_calls']),\n"
        "             'preemptions': int(mc['preemptions'])},\n"
        "    'steady': {'tokens_per_sec': round(n_tok / warm_wall, 1),\n"
        "               'wall_s': round(warm_wall, 2),\n"
        "               'ttft_p90_ms': round(m['ttft']['p90'] * 1e3, 1),\n"
        "               'tbt_p50_ms': round(m['tbt']['p50'] * 1e3, 1),\n"
        "               'tbt_p90_ms': round(m['tbt']['p90'] * 1e3, 1),\n"
        "               'ttft_buckets': m['ttft_buckets'],\n"
        "               'tbt_buckets': m['tbt_buckets'],\n"
        "               'executable_calls': int(m['executable_calls']),\n"
        "               'decode_steps': int(m['decode_steps']),\n"
        "               'prefill_chunks': int(m['prefill_chunks'])},\n"
        "    'kv_bytes_per_req_mean': int(np.mean(paged_bytes)),\n"
        "    'kv_bytes_per_req': paged_bytes,\n"
        "    'compile_count': int(m['compile_count']),\n"
        "    'host_logit_fetches': int(m['host_logit_fetches'])},\n"
        "  'prefix_cache': shared,\n"
        "  'spec_decode': spec_decode,\n"
        "  'mla': mla,\n"
        "  'obs': obs_res,\n"
        "}\n"
        "res['kv_bytes_ratio_dense_vs_paged'] = round(\n"
        "    dense_bytes_per_req / np.mean(paged_bytes), 2)\n"
        "res['steady_speedup_vs_dense'] = round(\n"
        "    dense_warm / warm_wall, 2)\n"
        "# the contract the CI guard pins: ONE executable (+ optional\n"
        "# warmup) over the whole mixed trace -- vs the v1 bucket grid\n"
        "res['compile_count_ok'] = m['compile_count'] <= 2\n"
        "print(json.dumps(res))\n"
        "# the obs acceptance gate, AFTER the headline JSON is out so a\n"
        "# noisy host can never cost the serving numbers: the no-op\n"
        "# tracer path must be free\n"
        "if obs_delta is not None:\n"
        "    assert obs_delta < 2.0, (\n"
        "        f'disabled-tracer overhead {obs_delta:.2f}% >= 2%')\n"
        "else:\n"
        "    assert 'error' not in obs_res, (\n"
        "        'obs section failed: ' + str(obs_res))\n"
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    try:
        proc = subprocess.run([sys.executable, "-c", code], env=env,
                              capture_output=True, text=True,
                              timeout=1200)
        lines = proc.stdout.strip().splitlines()
        if not lines:
            return {"error": f"rc={proc.returncode}: "
                             f"{proc.stderr.strip()[-400:]}"}
        result = json.loads(lines[-1])
        if proc.returncode != 0:
            # the post-print obs gate tripped: headline numbers are
            # intact, but surface the failed gate loudly
            result["obs_gate_error"] = proc.stderr.strip()[-200:]
    except Exception as e:  # never fail the headline bench on this
        return {"error": f"{type(e).__name__}: {e}"}
    # trace-plane numbers (tracer overhead + reconciliation table,
    # ISSUE 9) live in their own BENCH_OBS.json next to the trace
    # artifact pointer; BENCH_SERVING.json keeps the serving trajectory
    obs_res = result.pop("obs", None)
    if obs_res is not None:
        try:
            obs_path = os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "BENCH_OBS.json")
            with open(obs_path, "w") as fh:
                json.dump(obs_res, fh, indent=1)
        except Exception:
            pass
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_SERVING.json")
    try:
        prev = {}
        try:
            with open(out_path) as fh:
                prev = json.load(fh)
        except Exception:
            pass
        # keep the bucketed-engine trajectory: the first refreeze nests
        # the old numbers under "v1"; later refreezes carry it forward
        if "v1" in prev:
            result["v1"] = prev["v1"]
        elif "paged" in prev:
            result["v1"] = prev
        with open(out_path, "w") as fh:
            json.dump(result, fh, indent=1)
    except Exception:
        pass
    return result


def bench_router_bench() -> dict:
    """Serving-cluster heavy-traffic bench (ISSUE 11): Poisson arrivals,
    Zipf-shared prefixes, and a burst phase that forces preemption +
    prefix-cache eviction, driven through ``serving.cluster`` three
    ways — ONE replica (the scale-up ceiling), N=3 replicas with
    prefix-aware placement, and N=3 with seeded random placement (the
    baseline prefix-aware routing must beat).  Freezes TTFT/TBT
    p50/p99 under load per configuration into ``BENCH_ROUTER.json``
    with the acceptance booleans (prefix-aware beats random on cache
    hit rate AND TTFT p99 at N>=3), plus a disaggregated
    prefill/decode run recording the priced KV-page handoff totals
    (payload bytes + alpha-beta predicted wire seconds — the CPU-honest
    stand-in for hardware page streaming).

    All four clusters share ONE compiled unified-step program (the
    cluster's own fleet-sharing mechanism, reused across configs), so
    compile cost is paid once and the walls compare engines, not XLA.
    """
    code = (
        "import os, sys, json, time\n"
        f"sys.path.insert(0, {os.path.dirname(os.path.abspath(__file__))!r})\n"
        "import numpy as np\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from hetu_tpu.models import GPTConfig\n"
        "from hetu_tpu.serving import EngineCluster\n"
        "H = int(os.environ.get('HETU_TPU_ROUTER_BENCH_HIDDEN', '64'))\n"
        "L = int(os.environ.get('HETU_TPU_ROUTER_BENCH_LAYERS', '2'))\n"
        "V, NH, NKV = 512, 8, 4\n"
        "cfg = GPTConfig(vocab_size=V, hidden_size=H, num_layers=L,\n"
        "                num_heads=NH, num_kv_heads=NKV, max_seq_len=512,\n"
        "                sp=False, dropout=0.0, position='rotary',\n"
        "                norm='rmsnorm', activation='silu',\n"
        "                tie_embeddings=True)\n"
        "hd, f = cfg.head_dim, cfg.ffn_size\n"
        "rng = np.random.RandomState(0)\n"
        "def w(*s):\n"
        "    return (rng.randn(*s) * 0.02).astype(np.float32)\n"
        "state = {'wte.weight': w(V, H), 'ln_f.weight': np.ones(H, np.float32)}\n"
        "for i in range(L):\n"
        "    state[f'h{i}.ln_1.weight'] = np.ones(H, np.float32)\n"
        "    state[f'h{i}.ln_2.weight'] = np.ones(H, np.float32)\n"
        "    state[f'h{i}.attn.qkv.weight'] = w((NH + 2 * NKV) * hd, H)\n"
        "    state[f'h{i}.attn.out.weight'] = w(H, NH * hd)\n"
        "    state[f'h{i}.mlp.up.weight'] = w(f, H)\n"
        "    state[f'h{i}.mlp.down.weight'] = w(H, f)\n"
        "\n"
        "# -- the heavy-traffic trace: Zipf-shared headers, Poisson\n"
        "# interarrivals, a 5x burst phase in the middle third --------\n"
        "PS, NEW, HDR, TAIL = 8, 8, 32, 8\n"
        "K_HEADERS, N_REQ = 4, 36\n"
        "zipf_w = 1.0 / np.arange(1, K_HEADERS + 1) ** 1.1\n"
        "zipf_w /= zipf_w.sum()\n"
        "headers = [rng.randint(1, V, size=HDR).tolist()\n"
        "           for _ in range(K_HEADERS)]\n"
        "trace = []            # (arrival offset s, prompt)\n"
        "t = 0.0\n"
        "for i in range(N_REQ):\n"
        "    burst = N_REQ // 3 <= i < 2 * N_REQ // 3\n"
        "    t += float(rng.exponential(0.004 if burst else 0.02))\n"
        "    hdr = headers[int(rng.choice(K_HEADERS, p=zipf_w))]\n"
        "    trace.append((t, hdr + rng.randint(1, V, size=TAIL).tolist()))\n"
        "SHAPES = dict(page_size=PS, max_batch=4, chunk_size=16,\n"
        "              prefill_rows=1, max_model_len=120)\n"
        "\n"
        "def run_cluster(n, policy, mode='replicated', num_prefill=1,\n"
        "                fn=None):\n"
        "    cl = EngineCluster(state, cfg, num_replicas=n, mode=mode,\n"
        "                       num_prefill=num_prefill, policy=policy,\n"
        "                       name=f'rb_{mode}_{policy}_{n}',\n"
        "                       coordinator=False, num_pages=16,\n"
        "                       step_fn=fn, seed=1, **SHAPES)\n"
        "    # warm: compile + every header into some cache (identical\n"
        "    # treatment for every config -- the deltas are pure policy)\n"
        "    for h in headers:\n"
        "        cl.add_request(h + [1, 2], 2)\n"
        "    cl.run()\n"
        "    t0 = time.monotonic()\n"
        "    reqs = [cl.add_request(p, NEW, arrival_time=t0 + dt)\n"
        "            for dt, p in trace]\n"
        "    cl.run()\n"
        "    wall = time.monotonic() - t0\n"
        "    ms = cl.metrics_summary()\n"
        "    ttft, tbt = cl.histograms['ttft'], cl.histograms['tbt']\n"
        "    out = {\n"
        "      'replicas': n, 'policy': policy, 'mode': mode,\n"
        "      'wall_s': round(wall, 2),\n"
        "      'tokens_per_sec': round(N_REQ * NEW / wall, 1),\n"
        "      'ttft_p50_ms': round(ttft.percentile(50) * 1e3, 1),\n"
        "      'ttft_p99_ms': round(ttft.percentile(99) * 1e3, 1),\n"
        "      'tbt_p50_ms': round(tbt.percentile(50) * 1e3, 1),\n"
        "      'tbt_p99_ms': round(tbt.percentile(99) * 1e3, 1),\n"
        "      'hit_rate': round(float(ms['prefix_cache_hit_rate']), 3),\n"
        "      'prefill_tokens_saved':\n"
        "          int(ms['prefix_cache_tokens_saved']),\n"
        "      'preemptions': int(ms['preemptions']),\n"
        "      'cache_evictions': int(ms['prefix_cache_evictions']),\n"
        "      'reroutes': int(ms['cluster_reroutes']),\n"
        "      'handoffs': int(ms['cluster_handoffs']),\n"
        "      'handoff_payload_bytes': int(ms['handoff_payload_bytes']),\n"
        "      'handoff_predicted_wire_s':\n"
        "          round(float(ms['handoff_predicted_s']), 6),\n"
        "      'completed': int(ms['cluster_requests_completed']),\n"
        "    }\n"
        "    fn_out = cl.replicas[0].engine._compiled['unified']\n"
        "    cl.close()\n"
        "    return out, fn_out\n"
        "\n"
        "single, fn = run_cluster(1, 'prefix')\n"
        "prefix3, fn = run_cluster(3, 'prefix', fn=fn)\n"
        "random3, fn = run_cluster(3, 'random', fn=fn)\n"
        "disagg, fn = run_cluster(3, 'prefix', mode='disaggregated',\n"
        "                         num_prefill=1, fn=fn)\n"
        "res = {\n"
        "  'model': {'hidden': H, 'layers': L, 'vocab': V},\n"
        "  'trace': {'requests': N_REQ, 'headers': K_HEADERS,\n"
        "            'zipf_exponent': 1.1, 'header_tokens': HDR,\n"
        "            'tail_tokens': TAIL, 'max_new_tokens': NEW,\n"
        "            'poisson_mean_interarrival_s': 0.02,\n"
        "            'burst_mean_interarrival_s': 0.004,\n"
        "            'burst_phase': 'middle third'},\n"
        "  'single_replica': single,\n"
        "  'prefix_routing_3x': prefix3,\n"
        "  'random_routing_3x': random3,\n"
        "  'disaggregated_3x': disagg,\n"
        "  # acceptance gates (ISSUE 11), recorded as booleans\n"
        "  'prefix_beats_random_hit_rate':\n"
        "      prefix3['hit_rate'] > random3['hit_rate'],\n"
        "  'prefix_beats_random_ttft_p99':\n"
        "      prefix3['ttft_p99_ms'] < random3['ttft_p99_ms'],\n"
        "  'burst_forced_pressure': (prefix3['preemptions']\n"
        "      + prefix3['cache_evictions'] + random3['preemptions']\n"
        "      + random3['cache_evictions']) > 0,\n"
        "  'no_request_lost': all(c['completed'] == N_REQ + 4 for c in\n"
        "      (single, prefix3, random3, disagg)),\n"
        "}\n"
        "print(json.dumps(res))\n"
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    try:
        proc = subprocess.run([sys.executable, "-c", code], env=env,
                              capture_output=True, text=True,
                              timeout=1200)
        lines = proc.stdout.strip().splitlines()
        if not lines:
            return {"error": f"rc={proc.returncode}: "
                             f"{proc.stderr.strip()[-400:]}"}
        result = json.loads(lines[-1])
    except Exception as e:  # never fail the bench driver on this
        return {"error": f"{type(e).__name__}: {e}"}
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_ROUTER.json")
    try:
        with open(out_path, "w") as fh:
            json.dump(result, fh, indent=1)
    except Exception:
        pass
    return result


def bench_chaos_bench() -> dict:
    """Fault-plane bench (ISSUE 13): goodput and TTFT p99 under a FIXED
    fault schedule (decode-replica crash + transport drop/dup/delay)
    vs the fault-free run of the same trace, recovery time from the
    kill to the first re-routed token, and the elastic trainer's MTTR
    for an injected worker death — frozen into ``BENCH_CHAOS.json``
    with the acceptance booleans ``no_request_lost``,
    ``bitwise_survivors``, ``recovery_under_2s`` and
    ``loss_curve_continues``.

    Runs in a subprocess (cpu-pinned, 8 virtual devices for the
    trainer half) like the other bench targets, so a wedged backend
    can never hang the driver."""
    code = (
        "import os, sys, json, time\n"
        f"sys.path.insert(0, {os.path.dirname(os.path.abspath(__file__))!r})\n"
        "import numpy as np\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from hetu_tpu.models import GPTConfig\n"
        "from hetu_tpu.serving import EngineCluster\n"
        "from hetu_tpu.fault import (ChaosController, FaultEvent,\n"
        "                            FaultPlan)\n"
        "H, L, V, NH, NKV = 64, 2, 512, 8, 4\n"
        "cfg = GPTConfig(vocab_size=V, hidden_size=H, num_layers=L,\n"
        "                num_heads=NH, num_kv_heads=NKV, max_seq_len=512,\n"
        "                sp=False, dropout=0.0, position='rotary',\n"
        "                norm='rmsnorm', activation='silu',\n"
        "                tie_embeddings=True)\n"
        "hd, f = cfg.head_dim, cfg.ffn_size\n"
        "rng = np.random.RandomState(0)\n"
        "def w(*s):\n"
        "    return (rng.randn(*s) * 0.02).astype(np.float32)\n"
        "state = {'wte.weight': w(V, H),\n"
        "         'ln_f.weight': np.ones(H, np.float32)}\n"
        "for i in range(L):\n"
        "    state[f'h{i}.ln_1.weight'] = np.ones(H, np.float32)\n"
        "    state[f'h{i}.ln_2.weight'] = np.ones(H, np.float32)\n"
        "    state[f'h{i}.attn.qkv.weight'] = w((NH + 2 * NKV) * hd, H)\n"
        "    state[f'h{i}.attn.out.weight'] = w(H, NH * hd)\n"
        "    state[f'h{i}.mlp.up.weight'] = w(f, H)\n"
        "    state[f'h{i}.mlp.down.weight'] = w(H, f)\n"
        "PS, NEW, N_REQ = 8, 8, 24\n"
        "KILL_AT_S = 0.12\n"
        "SHAPES = dict(page_size=PS, max_batch=4, chunk_size=16,\n"
        "              prefill_rows=1, max_model_len=120)\n"
        "trace = []\n"
        "t = 0.0\n"
        "for i in range(N_REQ):\n"
        "    t += float(rng.exponential(0.01))\n"
        "    trace.append((t, rng.randint(1, V, size=24).tolist()))\n"
        "\n"
        "def run(name, plan=None, fn=None):\n"
        "    cl = EngineCluster(state, cfg, num_replicas=3,\n"
        "                       mode='disaggregated', num_prefill=1,\n"
        "                       name=name, coordinator=False,\n"
        "                       num_pages=16, step_fn=fn, seed=1,\n"
        "                       **SHAPES)\n"
        "    cl.add_request(trace[0][1], 2)   # warm/compile\n"
        "    cl.run()\n"
        "    chaos = None\n"
        "    if plan is not None:\n"
        "        chaos = ChaosController(plan)\n"
        "        cl.chaos = chaos\n"
        "    t0 = time.monotonic()\n"
        "    reqs = [cl.add_request(p, NEW, arrival_time=t0 + dt)\n"
        "            for dt, p in trace]\n"
        "    # the crash is triggered at a fixed TRACE-TIME offset (a\n"
        "    # wall-clock trace reaches any given step index in\n"
        "    # microseconds while the backlog waits on arrivals, so a\n"
        "    # step-keyed kill would always beat the traffic); the\n"
        "    # transport faults stay on the deterministic attempt\n"
        "    # ordinals of the FaultPlan\n"
        "    kill_ts = None\n"
        "    while cl.has_work:\n"
        "        cl.step()\n"
        "        if plan is not None and kill_ts is None \\\n"
        "                and time.monotonic() - t0 > KILL_AT_S:\n"
        "            cl.kill_replica(1)\n"
        "            kill_ts = time.monotonic()\n"
        "    wall = time.monotonic() - t0\n"
        "    ms = cl.metrics_summary()\n"
        "    ttft = cl.histograms['ttft']\n"
        "    out = {\n"
        "      'wall_s': round(wall, 2),\n"
        "      'goodput_tok_per_s': round(N_REQ * NEW / wall, 1),\n"
        "      'ttft_p50_ms': round(ttft.percentile(50) * 1e3, 1),\n"
        "      'ttft_p99_ms': round(ttft.percentile(99) * 1e3, 1),\n"
        "      'completed': int(ms['cluster_requests_completed']) - 1,\n"
        "      'replica_deaths': int(ms['replica_deaths']),\n"
        "      'requests_rerouted': int(ms['requests_rerouted']),\n"
        "      'handoff_retries': int(ms['handoff_retries']),\n"
        "      'handoffs_restaged': int(ms['handoffs_restaged']),\n"
        "      'stale_completions_dropped':\n"
        "          int(ms['stale_completions_dropped']),\n"
        "      'duplicate_deliveries_dropped':\n"
        "          int(ms['duplicate_deliveries_dropped']),\n"
        "      'requests_shed': int(ms['requests_shed']),\n"
        "    }\n"
        "    outs = {r.req_id: list(r.out_tokens) for r in reqs}\n"
        "    # recovery time: kill instant -> first token of a\n"
        "    # re-routed request delivered after it\n"
        "    rec_s = None\n"
        "    if kill_ts is not None:\n"
        "        cand = [r.token_times[0] for r in reqs\n"
        "                if r.n_reroutes > 0 and r.token_times\n"
        "                and r.token_times[0] >= kill_ts]\n"
        "        if cand:\n"
        "            rec_s = min(cand) - kill_ts\n"
        "    fn_out = cl.replicas[0].engine._compiled['unified']\n"
        "    cl.close()\n"
        "    return out, outs, rec_s, fn_out\n"
        "\n"
        "free, free_outs, _, fn = run('cb_free')\n"
        "# the fixed fault schedule: kill decode replica 1 (the first\n"
        "# least-loaded pick, so it holds adopted work) mid-trace, drop\n"
        "# the first injection attempt, dup + delay two more\n"
        "plan = FaultPlan(\n"
        "    transport={0: ('drop', 0.0), 2: ('dup', 0.0),\n"
        "               3: ('delay', 0.02)})\n"
        "chaos, chaos_outs, rec_s, fn = run('cb_chaos', plan, fn)\n"
        "\n"
        "# -- trainer MTTR: injected worker death, dp8 -> dp4 ---------\n"
        "import hetu_tpu as ht\n"
        "from jax.sharding import PartitionSpec as P\n"
        "from hetu_tpu.elastic import (FaultTolerantTrainer, TrainBuild,\n"
        "                              WorkerMonitor)\n"
        "from hetu_tpu.graph import ctor\n"
        "from hetu_tpu.models import GPTLMHeadModel, llama_config\n"
        "from hetu_tpu.parallel import create_mesh\n"
        "def build_fn(dp, devices):\n"
        "    ctor._seed_counter[0] = 777\n"
        "    mesh = create_mesh({'dp': dp}, devices[:dp])\n"
        "    tcfg = llama_config(vocab_size=64, hidden_size=32,\n"
        "                        num_layers=1, num_heads=4,\n"
        "                        max_seq_len=16, sp=False)\n"
        "    gctx = ht.graph('define_and_run', create_new=True,\n"
        "                    mesh=mesh)\n"
        "    g = gctx.__enter__()\n"
        "    ids = ht.parallel_placeholder('int32', (8, 16),\n"
        "                                  pspec=P('dp', None),\n"
        "                                  name='ids')\n"
        "    labels = ht.parallel_placeholder('int32', (8, 16),\n"
        "                                     pspec=P('dp', None),\n"
        "                                     name='labels')\n"
        "    model = GPTLMHeadModel(tcfg)\n"
        "    loss = model(ids, labels)\n"
        "    opt = ht.optim.AdamOptimizer(lr=1e-2, zero=2,\n"
        "                                 grad_comm='fp32',\n"
        "                                 flat_state=True)\n"
        "    train_op = opt.minimize(loss)\n"
        "    drng = np.random.RandomState(0)\n"
        "    IDS = drng.randint(0, 64, (8, 16)).astype(np.int32)\n"
        "    feed = {ids: IDS, labels: np.roll(IDS, -1, axis=1)}\n"
        "    def step_fn(step):\n"
        "        out = g.run(loss, [loss, train_op], feed)\n"
        "        return float(np.asarray(out[0]))\n"
        "    return TrainBuild(graph=g, model=model, optimizer=opt,\n"
        "                      step_fn=step_fn,\n"
        "                      close=lambda: gctx.__exit__(None, None,\n"
        "                                                  None))\n"
        "devices = jax.devices()[:8]\n"
        "STEPS = 8\n"
        "ref_build = build_fn(8, devices)\n"
        "ref = [ref_build.step_fn(i) for i in range(STEPS)]\n"
        "ref_build.close()\n"
        "mon = WorkerMonitor(4, devices, ttl=0.3,\n"
        "                    heartbeat_interval=0.05)\n"
        "trainer = FaultTolerantTrainer(build_fn, devices, monitor=mon,\n"
        "                               checkpoint_dir='/tmp/cb_ck',\n"
        "                               checkpoint_every=2)\n"
        "tplan = FaultPlan(events=[FaultEvent(step=5,\n"
        "                  kind='worker_death', target=3)])\n"
        "losses = trainer.train(STEPS, fault_plan=tplan)\n"
        "mon.close(); trainer.close()\n"
        "rec = trainer.recoveries[0] if trainer.recoveries else {}\n"
        "loss_ok = bool(np.allclose(losses, ref, rtol=1e-6))\n"
        "\n"
        "# -- numeric sentry + durable generations (ISSUE 14) ---------\n"
        "# a seeded plan mixing numeric and process faults: grad_nan\n"
        "# skips, shard_corrupt poisons the newest generation, the\n"
        "# loss_spike rewind must fall back past it, then a worker\n"
        "# death re-plans dp8 -> dp4 on the verified restore path\n"
        "import shutil\n"
        "shutil.rmtree('/tmp/cb_nm', ignore_errors=True)\n"
        "TABLE = np.random.RandomState(42).randint(\n"
        "    0, 64, (64, 8, 16)).astype(np.int32)\n"
        "def build_sentry(dp, devices):\n"
        "    ctor._seed_counter[0] = 777\n"
        "    mesh = create_mesh({'dp': dp}, devices[:dp])\n"
        "    tcfg = llama_config(vocab_size=64, hidden_size=32,\n"
        "                        num_layers=1, num_heads=4,\n"
        "                        max_seq_len=16, sp=False)\n"
        "    gctx = ht.graph('define_and_run', create_new=True,\n"
        "                    mesh=mesh)\n"
        "    g = gctx.__enter__()\n"
        "    ids = ht.parallel_placeholder('int32', (8, 16),\n"
        "                                  pspec=P('dp', None),\n"
        "                                  name='ids')\n"
        "    labels = ht.parallel_placeholder('int32', (8, 16),\n"
        "                                     pspec=P('dp', None),\n"
        "                                     name='labels')\n"
        "    model = GPTLMHeadModel(tcfg)\n"
        "    loss = model(ids, labels)\n"
        "    opt = ht.optim.AdamOptimizer(lr=1e-2, zero=2,\n"
        "                                 grad_comm='fp32',\n"
        "                                 flat_state=True, sentry=True)\n"
        "    train_op = opt.minimize(loss)\n"
        "    def step_fn(cursor):\n"
        "        b = TABLE[cursor % 64]\n"
        "        out = g.run(loss, [loss, train_op],\n"
        "                    {ids: b, labels: np.roll(b, -1, axis=1)})\n"
        "        return float(np.asarray(out[0]))\n"
        "    return TrainBuild(graph=g, model=model, optimizer=opt,\n"
        "                      step_fn=step_fn,\n"
        "                      close=lambda: gctx.__exit__(None, None,\n"
        "                                                  None))\n"
        "mon2 = WorkerMonitor(4, devices, ttl=0.3,\n"
        "                     heartbeat_interval=0.05)\n"
        "tr2 = FaultTolerantTrainer(build_sentry, devices, monitor=mon2,\n"
        "                           checkpoint_dir='/tmp/cb_nm',\n"
        "                           checkpoint_every=2,\n"
        "                           keep_checkpoints=3, rewind_after=2)\n"
        "nplan = FaultPlan(events=[\n"
        "    FaultEvent(step=2, kind='grad_nan', target=0),\n"
        "    FaultEvent(step=3, kind='grad_nan', target=1),\n"
        "    FaultEvent(step=6, kind='shard_corrupt', target=0),\n"
        "    FaultEvent(step=6, kind='loss_spike', target=0),\n"
        "    FaultEvent(step=8, kind='worker_death', target=3)])\n"
        "NSTEPS = 10\n"
        "nlosses = tr2.train(NSTEPS, fault_plan=nplan)\n"
        "mon2.close()\n"
        "nms = tr2.metrics_summary()\n"
        "cursors = tr2.committed_cursors()\n"
        "rewind = next((r for r in tr2.recoveries\n"
        "               if r.get('kind') == 'numeric_rewind'), {})\n"
        "tr2.close()\n"
        "nref_build = build_sentry(8, devices)\n"
        "nref = [nref_build.step_fn(c) for c in cursors]\n"
        "nref_build.close()\n"
        "numeric = {\n"
        "  'steps': NSTEPS, 'attempts': nms['attempts'],\n"
        "  'skip_rate': round(nms['steps_skipped']\n"
        "                     / max(1, nms['attempts']), 3),\n"
        "  'anomalies': nms['sentry_anomalies'],\n"
        "  'rewinds': nms['rewinds'],\n"
        "  'rewind_mttr_s': round(rewind.get('mttr_s', -1.0), 3),\n"
        "  'restore_fallbacks': nms['restore_fallbacks'],\n"
        "  'checkpoints_written': nms['checkpoints_written'],\n"
        "  'worker_recoveries': nms['worker_recoveries'],\n"
        "}\n"
        "clean_bitwise = nlosses[:8] == nref[:8]\n"
        "numeric_loss_ok = bool(np.allclose(nlosses, nref, rtol=1e-6))\n"
        "\n"
        "res = {\n"
        "  'model': {'hidden': H, 'layers': L, 'vocab': V},\n"
        "  'trace': {'requests': N_REQ, 'max_new_tokens': NEW,\n"
        "            'mean_interarrival_s': 0.01},\n"
        "  'fault_schedule': {'crash':\n"
        "                         'decode replica 1 @ trace t+0.12s',\n"
        "                     'transport': 'drop@0, dup@2, delay@3'},\n"
        "  'fault_free': free,\n"
        "  'chaos': chaos,\n"
        "  'recovery_s': None if rec_s is None else round(rec_s, 3),\n"
        "  'trainer': {'steps': STEPS, 'death_at_step': 5,\n"
        "              'resumed_from_step':\n"
        "                  rec.get('resumed_from_step'),\n"
        "              'dp_after': rec.get('dp'),\n"
        "              'mttr_s': round(rec.get('mttr_s', -1.0), 3)},\n"
        "  'numeric': numeric,\n"
        "  # acceptance booleans (ISSUE 13)\n"
        "  'no_request_lost':\n"
        "      free['completed'] == N_REQ and\n"
        "      chaos['completed'] == N_REQ,\n"
        "  'bitwise_survivors': chaos_outs == free_outs,\n"
        "  'recovery_under_2s': rec_s is not None and rec_s < 2.0,\n"
        "  'loss_curve_continues': loss_ok,\n"
        "  # acceptance booleans (ISSUE 14: numeric sentry + durable\n"
        "  # generations under a mixed numeric/process fault plan)\n"
        "  'clean_steps_bitwise': bool(clean_bitwise),\n"
        "  'rewind_under_3s': 0 < rewind.get('mttr_s', -1.0) < 3.0,\n"
        "  'corrupt_restore_falls_back':\n"
        "      nms['restore_fallbacks'] >= 1,\n"
        "  'numeric_loss_curve_continues': numeric_loss_ok,\n"
        "}\n"
        "print(json.dumps(res))\n"
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (flags +
                            " --xla_force_host_platform_device_count=8"
                            ).strip()
    try:
        proc = subprocess.run([sys.executable, "-c", code], env=env,
                              capture_output=True, text=True,
                              timeout=1200)
        lines = proc.stdout.strip().splitlines()
        if not lines:
            return {"error": f"rc={proc.returncode}: "
                             f"{proc.stderr.strip()[-400:]}"}
        result = json.loads(lines[-1])
    except Exception as e:  # never fail the bench driver on this
        return {"error": f"{type(e).__name__}: {e}"}
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_CHAOS.json")
    try:
        with open(out_path, "w") as fh:
            json.dump(result, fh, indent=1)
    except Exception:
        pass
    return result


def bench_slo_bench() -> dict:
    """SLO traffic-plane bench (ISSUE 17): a synthetic diurnal trace
    (trough -> interactive-heavy peak -> trough, Poisson interarrivals,
    mixed priority classes) through the managed cluster — priority
    scheduling + replica autoscaler — vs the SAME trace through an
    unmanaged static fleet, plus the host-RAM KV tier's hit-vs-recompute
    pricing on a shared-prefix workload.  Frozen into ``BENCH_SLO.json``
    with the acceptance booleans ``zero_class_inversions``,
    ``interactive_ttft_p99_under_target``,
    ``goodput_recovers_after_scale_event``,
    ``host_tier_hit_cheaper_than_recompute`` (both sides priced by the
    planner's own formulas) and ``temp0_bitwise_vs_unmanaged``.

    Runs in a cpu-pinned subprocess like the other bench targets; both
    clusters and the host-tier engine share ONE compiled unified-step
    program, so the walls compare traffic planes, not XLA."""
    code = (
        "import os, sys, json, time\n"
        f"sys.path.insert(0, {os.path.dirname(os.path.abspath(__file__))!r})\n"
        "import numpy as np\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from hetu_tpu.models import GPTConfig\n"
        "from hetu_tpu.serving import Engine, EngineCluster\n"
        "from hetu_tpu.serving.slo import (Autoscaler, DEFAULT_TARGETS,\n"
        "                                  SLO_CLASSES)\n"
        "H, L, V, NH, NKV = 64, 2, 512, 8, 4\n"
        "cfg = GPTConfig(vocab_size=V, hidden_size=H, num_layers=L,\n"
        "                num_heads=NH, num_kv_heads=NKV, max_seq_len=512,\n"
        "                sp=False, dropout=0.0, position='rotary',\n"
        "                norm='rmsnorm', activation='silu',\n"
        "                tie_embeddings=True)\n"
        "hd, f = cfg.head_dim, cfg.ffn_size\n"
        "rng = np.random.RandomState(0)\n"
        "def w(*s):\n"
        "    return (rng.randn(*s) * 0.02).astype(np.float32)\n"
        "state = {'wte.weight': w(V, H),\n"
        "         'ln_f.weight': np.ones(H, np.float32)}\n"
        "for i in range(L):\n"
        "    state[f'h{i}.ln_1.weight'] = np.ones(H, np.float32)\n"
        "    state[f'h{i}.ln_2.weight'] = np.ones(H, np.float32)\n"
        "    state[f'h{i}.attn.qkv.weight'] = w((NH + 2 * NKV) * hd, H)\n"
        "    state[f'h{i}.attn.out.weight'] = w(H, NH * hd)\n"
        "    state[f'h{i}.mlp.up.weight'] = w(f, H)\n"
        "    state[f'h{i}.mlp.down.weight'] = w(H, f)\n"
        "PS, NEW = 8, 8\n"
        "SHAPES = dict(page_size=PS, max_batch=4, chunk_size=16,\n"
        "              prefill_rows=1, max_model_len=120)\n"
        "\n"
        "# -- the diurnal trace: trough (batch-heavy, sparse) -> peak\n"
        "# (interactive-heavy, 8x denser) -> trough -------------------\n"
        "trace = []            # (arrival offset s, prompt, class)\n"
        "t = 0.0\n"
        "def phase(n, rate, probs):\n"
        "    global t\n"
        "    for _ in range(n):\n"
        "        t += float(rng.exponential(rate))\n"
        "        c = SLO_CLASSES[int(rng.choice(3, p=probs))]\n"
        "        trace.append((t, rng.randint(1, V, size=16).tolist(),\n"
        "                      c))\n"
        "phase(8, 0.04, [0.125, 0.25, 0.625])     # night trough\n"
        "phase(24, 0.0005, [0.625, 0.25, 0.125])  # daytime peak\n"
        "phase(8, 0.04, [0.125, 0.25, 0.625])     # evening trough\n"
        "N_REQ = len(trace)\n"
        "\n"
        "def run(name, auto, fn=None):\n"
        "    cl = EngineCluster(state, cfg, num_replicas=2, name=name,\n"
        "                       coordinator=False, num_pages=32,\n"
        "                       step_fn=fn, seed=1, max_queue_depth=2,\n"
        "                       autoscaler=auto, **SHAPES)\n"
        "    # warm/compile request rides in class batch: best-effort,\n"
        "    # no TTFT target for its compile wall to distort\n"
        "    cl.add_request(trace[0][1], 2, slo_class='batch')\n"
        "    cl.run()\n"
        "    t0 = time.monotonic()\n"
        "    reqs = [cl.add_request(p, NEW, arrival_time=t0 + dt,\n"
        "                           slo_class=c) for dt, p, c in trace]\n"
        "    prod = []   # (tokens this step, active replicas after)\n"
        "    while cl.has_work:\n"
        "        n = cl.step()\n"
        "        prod.append((n, cl.gauges['replicas_active'].value))\n"
        "    wall = time.monotonic() - t0\n"
        "    ms = cl.metrics_summary()\n"
        "    outs = {r.req_id - reqs[0].req_id: list(r.out_tokens)\n"
        "            for r in reqs}\n"
        "    fn_out = cl.replicas[0].engine._compiled['unified']\n"
        "    cl.close()\n"
        "    return ms, outs, prod, wall, fn_out, reqs\n"
        "\n"
        "auto = Autoscaler(min_replicas=1, max_replicas=2,\n"
        "                  backlog_high=3, backlog_low=0,\n"
        "                  hysteresis_steps=2, cooldown_steps=8)\n"
        "ms, m_outs, prod, wall, fn, reqs = run('slo_managed', auto)\n"
        "sms, s_outs, _, s_wall, fn, _sr = run('slo_static', None, fn)\n"
        "\n"
        "# goodput around scale events: after the LAST scale-up the\n"
        "# grown fleet must actually produce (and the trace complete)\n"
        "up_steps = [i for i in range(1, len(prod))\n"
        "            if prod[i][1] > prod[i - 1][1]]\n"
        "tok_after_up = (sum(n for n, _a in prod[up_steps[-1]:])\n"
        "                if up_steps else 0)\n"
        "completed = int(ms['cluster_requests_completed']) - 1\n"
        "# per-class tails straight from the trace's requests (the\n"
        "# cluster histograms also hold the warm/compile request)\n"
        "per_class = {}\n"
        "for c in SLO_CLASSES:\n"
        "    rs = [r for r in reqs if r.slo_class == c and r.token_times]\n"
        "    ttfts = [r.token_times[0] - r.submit_time for r in rs]\n"
        "    tbts = [b - a for r in rs\n"
        "            for a, b in zip(r.token_times, r.token_times[1:])]\n"
        "    per_class[c] = {\n"
        "        'requests': len(rs),\n"
        "        'ttft_p99_ms': round(float(np.percentile(ttfts, 99))\n"
        "                             * 1e3, 1) if ttfts else None,\n"
        "        'tbt_p99_ms': round(float(np.percentile(tbts, 99))\n"
        "                            * 1e3, 1) if tbts else None}\n"
        "target_s = DEFAULT_TARGETS['interactive']['ttft_s']\n"
        "\n"
        "# -- host tier: evict -> refetch vs recompute pricing --------\n"
        "eng = Engine(state, cfg, num_pages=32, name='slo_host',\n"
        "             step_fn=fn, host_tier=True, **SHAPES)\n"
        "header = rng.randint(1, V, size=40).tolist()   # 5 full pages\n"
        "r1 = eng.add_request(header + [7, 8], max_new_tokens=4)\n"
        "eng.run()\n"
        "eng.prefix_cache.evict(32)        # the cold sweep\n"
        "r2 = eng.add_request(header + [9, 10], max_new_tokens=4)\n"
        "eng.run()\n"
        "cached_tok = eng.finished[r2.req_id].cached_tokens\n"
        "ht_ = eng.host_tier\n"
        "refetch_s = ht_.predicted_s('refetch')\n"
        "# recompute price, SAME planner formulas: forward prefill of\n"
        "# the refetched span through every layer at the chip roofline.\n"
        "# Priced twice — at this bench's toy width (where recompute is\n"
        "# nearly free, so the tier would lose) and at the paper's\n"
        "# serving scale (H=4096, 32 layers, GQA 8 kv-heads x 128),\n"
        "# where the FLOPs/KV-bytes ratio the tier exists for holds;\n"
        "# the acceptance boolean keys off the deployment scale\n"
        "from hetu_tpu.planner.cost_model import (ChipSpec, ClusterSpec,\n"
        "                                         collective_time,\n"
        "                                         transformer_layer_spec)\n"
        "chip = ChipSpec()\n"
        "def recompute_price(hidden, ffn, layers):\n"
        "    spec = transformer_layer_spec(1, max(1, cached_tok),\n"
        "                                  hidden, ffn, 2)\n"
        "    return layers * max(\n"
        "        spec.flops / (chip.peak_flops * chip.mxu_efficiency),\n"
        "        spec.act_io_bytes / chip.hbm_bw)\n"
        "HR, LR, KVH, HDR = 4096, 32, 8, 128\n"
        "ref_kv_bytes = cached_tok * 2 * KVH * HDR * 2 * LR\n"
        "refetch_ref_s = collective_time('ppermute',\n"
        "                                float(ref_kv_bytes), 2,\n"
        "                                ClusterSpec())\n"
        "recompute_ref_s = recompute_price(HR, 4 * HR, LR)\n"
        "host = {\n"
        "  'evictions': ht_.evictions, 'hits': ht_.hits,\n"
        "  'hit_rate': round(ht_.hits / max(1, ht_.evictions), 3),\n"
        "  'refetched_tokens': int(cached_tok),\n"
        "  'refetch_bytes': int(sum(r['payload_bytes']\n"
        "                           for r in ht_.records\n"
        "                           if r['dir'] == 'refetch')),\n"
        "  'refetch_predicted_s': refetch_s,\n"
        "  'recompute_predicted_s': recompute_price(H, f, L),\n"
        "  'ref_scale': {'hidden': HR, 'layers': LR,\n"
        "                'kv_heads': KVH, 'head_dim': HDR,\n"
        "                'refetch_bytes': int(ref_kv_bytes),\n"
        "                'refetch_predicted_s': refetch_ref_s,\n"
        "                'recompute_predicted_s': recompute_ref_s},\n"
        "}\n"
        "\n"
        "res = {\n"
        "  'model': {'hidden': H, 'layers': L, 'vocab': V},\n"
        "  'trace': {'requests': N_REQ, 'max_new_tokens': NEW,\n"
        "            'phases': 'trough(8)/peak(24)/trough(8)',\n"
        "            'peak_interarrival_s': 0.0005,\n"
        "            'trough_interarrival_s': 0.04},\n"
        "  'managed': {'wall_s': round(wall, 2),\n"
        "              'goodput_tok_per_s':\n"
        "                  round(N_REQ * NEW / wall, 1),\n"
        "              'completed': completed,\n"
        "              'scale_ups': int(ms['scale_ups']),\n"
        "              'scale_downs': int(ms['scale_downs']),\n"
        "              'class_inversions': int(ms['class_inversions']),\n"
        "              'per_class': per_class},\n"
        "  'static': {'wall_s': round(s_wall, 2),\n"
        "             'goodput_tok_per_s':\n"
        "                 round(N_REQ * NEW / s_wall, 1),\n"
        "             'completed':\n"
        "                 int(sms['cluster_requests_completed']) - 1},\n"
        "  'host_tier': host,\n"
        "  'interactive_ttft_target_ms': target_s * 1e3,\n"
        "  # acceptance booleans (ISSUE 17)\n"
        "  'zero_class_inversions': int(ms['class_inversions']) == 0,\n"
        "  'interactive_ttft_p99_under_target':\n"
        "      per_class['interactive']['ttft_p99_ms']\n"
        "      < target_s * 1e3,\n"
        "  'goodput_recovers_after_scale_event':\n"
        "      int(ms['scale_ups']) >= 1 and tok_after_up > 0\n"
        "      and completed == N_REQ,\n"
        "  'host_tier_hit_cheaper_than_recompute':\n"
        "      ht_.hits >= 1 and refetch_ref_s < recompute_ref_s,\n"
        "  'temp0_bitwise_vs_unmanaged': m_outs == s_outs,\n"
        "}\n"
        "print(json.dumps(res))\n"
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    try:
        proc = subprocess.run([sys.executable, "-c", code], env=env,
                              capture_output=True, text=True,
                              timeout=1200)
        lines = proc.stdout.strip().splitlines()
        if not lines:
            return {"error": f"rc={proc.returncode}: "
                             f"{proc.stderr.strip()[-400:]}"}
        result = json.loads(lines[-1])
    except Exception as e:  # never fail the bench driver on this
        return {"error": f"{type(e).__name__}: {e}"}
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_SLO.json")
    try:
        with open(out_path, "w") as fh:
            json.dump(result, fh, indent=1)
    except Exception:
        pass
    return result


def _probe_backend(timeout_s: float = 180.0) -> str:
    """Probe the default backend in a SUBPROCESS with a timeout: a wedged
    TPU runtime hangs on init (round-3 postmortem: BENCH_r03 rc=1 /
    MULTICHIP_r03 rc=124) — it must never hang the bench itself.
    Returns the platform string, or "cpu" on hang/failure."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=timeout_s)
        if proc.returncode == 0 and proc.stdout.strip():
            return proc.stdout.strip().splitlines()[-1]
    except Exception:
        pass
    return "cpu"


# Deliberately TRACKED in git (not .gitignore'd like PROGRESS.jsonl):
# the cache is the hardware-evidence trail — when the axon relay is
# wedged at capture time, the CPU-fallback bench surfaces the last real
# TPU measurement from here, clearly labeled with its timestamp.
_CACHE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_CACHE.json")


def _load_cache():
    try:
        with open(_CACHE_PATH) as f:
            return json.load(f)
    except Exception:
        return None


def _store_cache(result) -> None:
    try:
        with open(_CACHE_PATH, "w") as f:
            json.dump({"cached_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                                  time.gmtime()),
                       "result": result}, f, indent=1)
    except Exception:
        pass


def main():
    # subcommands run ONE suite and print its JSON (the default
    # argv-less invocation stays the headline training bench):
    #   python bench.py serving_microbench   (writes BENCH_SERVING.json)
    #   python bench.py comm_microbench
    if len(sys.argv) > 1:
        sub = sys.argv[1]
        fns = {"serving_microbench": bench_serving_microbench,
               "comm_microbench": bench_comm_microbench,
               "lint_graph": bench_lint_graph,
               "protocol_lint": bench_protocol_lint,
               "schedule_lint": bench_schedule_lint,
               "mem_lint": bench_mem_lint,
               "cost_lint": bench_cost_lint,
               "router_bench": bench_router_bench,
               "chaos_bench": bench_chaos_bench,
               "slo_bench": bench_slo_bench}
        if sub not in fns:
            print(json.dumps({"error": f"unknown subcommand {sub!r}; "
                                       f"have {sorted(fns)}"}))
            raise SystemExit(2)
        print(json.dumps(fns[sub]()))
        return

    platform = _probe_backend()
    import jax
    if platform == "cpu":
        # hardware backend unavailable/hung: pin cpu so the bench still
        # produces a valid (clearly-labeled) JSON line
        jax.config.update("jax_platforms", "cpu")
    on_tpu = jax.devices()[0].platform == "tpu"

    gpt = bench_gpt2(on_tpu)
    bert = bench_bert(on_tpu)
    scaling = bench_scaling_virtual(8)
    mpmd = bench_mpmd_dispatch_overhead()
    comm_micro = bench_comm_microbench()

    mfu = gpt["mfu"]
    result = {
        "metric": "gpt2_tokens_per_sec_per_chip",
        "value": round(gpt["tokens_per_sec"], 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": {
            "step_time_s": round(gpt["step_time_s"], 4),
            "mfu": round(mfu, 4),
            "mfu_formula": "(6*n_matmul + 6*L*S*H_causal_attn)*tok/s "
                           "/ peak; embedding gathers excluded",
            "params": gpt["params"],
            "params_matmul": gpt["params_matmul"],
            "platform": jax.devices()[0].platform,
            "device_kind": getattr(jax.devices()[0], "device_kind", "?"),
            "batch": gpt["batch"], "seq": gpt["seq"],
            "planner_plan": gpt["planner_plan"],
            "num_micro_batches": gpt["num_micro_batches"],
            "remat": gpt["remat"],
            "bert_samples_per_sec": round(bert["samples_per_sec"], 2),
            "bert_step_time_s": round(bert["step_time_s"], 4),
            "bert_batch": bert["batch"], "bert_seq": bert["seq"],
            "scaling_virtual8": scaling,
            "mpmd_pp2_dispatch": mpmd,
            "comm_microbench": comm_micro,
        },
    }
    if on_tpu:
        _store_cache(result)
    else:
        # the axon relay wedges when a TPU client is killed (hangs on
        # init rather than raising; round-3 postmortem): surface the
        # last REAL TPU measurement, clearly labeled, so transient
        # relay wedges don't erase hardware evidence
        cache = _load_cache()
        if cache is not None:
            result["extra"]["last_tpu_result"] = cache
    print(json.dumps(result))


if __name__ == "__main__":
    main()
