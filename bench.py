"""Benchmark: GPT-2 training throughput on the available accelerator.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Metric is GPT-2 (124M-class) training tokens/sec/chip (BASELINE.json north
star).  vs_baseline reports measured MFU relative to the 40%-MFU target
(1.0 == 40% MFU), since the reference repo publishes no raw numbers
(BASELINE.md).
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def peak_flops_per_chip() -> float:
    """bf16 peak FLOP/s for the local accelerator generation."""
    import jax
    d = jax.devices()[0]
    kind = getattr(d, "device_kind", "").lower()
    if "v5 lite" in kind or "v5e" in kind:
        return 197e12
    if "v5p" in kind or "v5" in kind:
        return 459e12
    if "v4" in kind:
        return 275e12
    if "v6" in kind or "trillium" in kind:
        return 918e12
    return 197e12  # conservative default (also used for CPU smoke runs)


def main():
    import jax
    import jax.numpy as jnp
    import hetu_tpu as ht
    from hetu_tpu import optim
    from hetu_tpu.models import GPTConfig, GPTLMHeadModel

    on_tpu = jax.devices()[0].platform == "tpu"
    # GPT-2 small-class config; trimmed when benching on CPU fallback.
    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                        num_heads=12, max_seq_len=1024, sp=False,
                        dtype="bfloat16", position="learned",
                        activation="gelu", norm="layernorm")
        batch, seq, steps, warmup = 32, 1024, 10, 3
    else:
        cfg = GPTConfig(vocab_size=1024, hidden_size=256, num_layers=4,
                        num_heads=8, max_seq_len=256, sp=False,
                        dtype="float32")
        batch, seq, steps, warmup = 4, 256, 5, 2

    with ht.graph("define_and_run", create_new=True) as g:
        ids = ht.placeholder("int32", (batch, seq), name="input_ids")
        labels = ht.placeholder("int32", (batch, seq), name="labels")
        model = GPTLMHeadModel(cfg)
        loss = model(ids, labels, seq_len=seq)
        train_op = optim.AdamOptimizer(lr=1e-4, weight_decay=0.01).minimize(loss)

        rng = np.random.RandomState(0)
        IDS = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
        L = np.roll(IDS, -1, axis=1)

        def _sync():
            # block_until_ready can be a no-op under remote-relay PJRT
            # backends; force a real host fetch of one element of every
            # updated tensor class: a param (waits for the optimizer update)
            arrs = list(g._var_data.values())
            for arr in (arrs[0], arrs[-1]):
                np.asarray(arr.ravel()[0])

        for _ in range(warmup):
            g.run(loss, [loss, train_op], {ids: IDS, labels: L})
            _sync()
        t0 = time.perf_counter()
        for _ in range(steps):
            g.run(loss, [loss, train_op], {ids: IDS, labels: L})
        _sync()
        dt = (time.perf_counter() - t0) / steps

    n_params = sum(
        int(np.prod(t.concrete_shape())) for t in g._var_tensors.values())
    # Honest matmul-FLOP accounting: embedding tables are gathers, not
    # matmuls — exclude wte/wpe from the 6N term.  (lm_head is untied here
    # and IS a matmul, so it stays in n_matmul.)  Attention scores/values
    # add 12*L*S*H per token for full attention; causal halves it to
    # 6*L*S*H (fwd=2*S*H per layer causal, bwd=2x fwd).
    n_matmul = sum(
        int(np.prod(t.concrete_shape())) for t in g._var_tensors.values()
        if not (t.name and ("wte" in t.name or "wpe" in t.name)))
    tokens_per_step = batch * seq
    tokens_per_sec = tokens_per_step / dt
    n_chips = 1  # bench runs single-chip
    tps_per_chip = tokens_per_sec / n_chips
    attn_flops_per_token = 6.0 * cfg.num_layers * seq * cfg.hidden_size
    flops_per_token = 6.0 * n_matmul + attn_flops_per_token
    mfu = flops_per_token * tokens_per_sec / peak_flops_per_chip()
    result = {
        "metric": "gpt2_tokens_per_sec_per_chip",
        "value": round(tps_per_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": {
            "step_time_s": round(dt, 4),
            "mfu": round(mfu, 4),
            "mfu_formula": "(6*n_matmul + 6*L*S*H_causal_attn)*tok/s "
                           "/ peak; embedding gathers excluded",
            "params": n_params,
            "params_matmul": n_matmul,
            "platform": jax.devices()[0].platform,
            "device_kind": getattr(jax.devices()[0], "device_kind", "?"),
            "batch": batch, "seq": seq,
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
